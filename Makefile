# Developer/CI entry points. Tier-1 tests invoke lint-collectives via
# tests/test_analysis.py::test_cli_clean_on_shipped_code as well, so the
# analyzer gates both paths.

PY ?= python

.PHONY: test lint-collectives chaos-smoke metrics-smoke overlap-smoke guard-smoke driver-smoke topo-smoke quant-smoke trace-smoke tune-smoke zero-smoke sim-smoke selfdrive-smoke llm-smoke reshard-smoke serve-smoke tpfuse-smoke ci

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
		--continue-on-collection-errors -p no:cacheprovider

# Collective-safety static analysis (docs/static_analysis.md): Pass 1
# over the example train steps, Pass 2 over the runtime + fault/guard/
# metrics/journal sources, Pass 3 over the full compositor plan grid,
# Pass 4 over the shipped train-step variants, Pass 5 over the reference
# sharding-rule table.
lint-collectives:
	HVD_CI_SKIP_CHAOS=1 HVD_CI_SKIP_METRICS=1 HVD_CI_SKIP_OVERLAP=1 HVD_CI_SKIP_GUARD=1 HVD_CI_SKIP_DRIVER=1 HVD_CI_SKIP_TOPO=1 HVD_CI_SKIP_QUANT=1 HVD_CI_SKIP_TRACE=1 HVD_CI_SKIP_TUNE=1 HVD_CI_SKIP_ZERO=1 HVD_CI_SKIP_SIM=1 HVD_CI_SKIP_SELFDRIVE=1 HVD_CI_SKIP_LLM=1 HVD_CI_SKIP_RESHARD=1 HVD_CI_SKIP_SERVE=1 HVD_CI_SKIP_TPFUSE=1 bash tools/ci_checks.sh

# Seeded fault-injection smoke (docs/fault_tolerance.md): worker kill +
# slow rank + dropped control-plane burst, recovery asserted, <120s CPU.
chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/chaos_smoke.py

# Metrics smoke (docs/metrics.md): 2-rank job with HOROVOD_METRICS=1,
# GET /metrics scraped off the driver and validated, <60s CPU.
metrics-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/metrics_smoke.py

# Structural overlap verification (docs/overlap.md): compile the MLP +
# transformer phase-B programs with overlap on/off on the virtual CPU
# mesh and assert >=3 independent, scheduler-interleaved all-reduce
# groups in the streamed build, <60s CPU.
overlap-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/tpu_profile_overlap.py --structural --assert-overlap

# Data-plane integrity smoke (docs/fault_tolerance.md): 2-rank seeded
# nan+corrupt plan — sentinel detection + digest heal asserted, event
# log byte-identical across two runs, <15s CPU.
guard-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/guard_smoke.py

# Control-plane HA smoke (docs/fault_tolerance.md): seeded driver kill
# mid-training + journal resume (--resume) + in-place worker reattach,
# two runs with byte-identical normalized event logs, <90s CPU.
driver-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/driver_smoke.py

# Topology-compositor smoke (docs/topology.md): plan dumps for 1/2/4-slice
# (and a three-level) synthetic topologies, byte-identical across two
# runs, hierarchical DCN bytes < flat, <10s CPU, no backend.
topo-smoke:
	$(PY) tools/topo_smoke.py

# Quantized-wire smoke (docs/overlap.md "Quantized wire compression"):
# 2-rank streamed-quantized step bitwise-equal to the post-hoc quantized
# step, EF residual threaded and live, every collective-permute payload
# s8, event log byte-identical across two runs, <15s CPU.
quant-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/quant_smoke.py

# Fleet-tracing smoke (docs/timeline.md "Fleet tracing"): 2-rank run with
# a seeded rank-1 delay fault — merged Perfetto trace with per-rank +
# driver lanes and clock metadata, hvd_step_skew_seconds /
# hvd_straggler_total{rank="1"} on /metrics, flight-recorder dumps from
# an injected guard abort rendered as an aligned postmortem, normalized
# summary byte-identical across two runs, ~2x15s CPU.
trace-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/trace_smoke.py

# Compiled-path offline-tuner smoke (docs/autotune.md "Compiled-path
# offline tuning"): two tools/autotune_compiled.py runs byte-identical,
# the tuned mlp3 step bitwise-equal to the untuned (and hand-set) step,
# modeled cost <= default with a strict free-objective win on the
# transformer program, stale-signature fallback warned loudly, <60s CPU.
tune-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/tune_smoke.py

# Streamed-ZeRO-1 smoke (docs/overlap.md "Streamed ZeRO-1"): 2-rank
# streamed-zero1+quantized step bitwise-equal to the post-hoc zero1
# step, shard-local update verified against the gathered (replicated
# DP) reference, sharded EF live, digest shard-aware, event log
# byte-identical across two runs, <15s CPU.
zero-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/zero_smoke.py

# Fleet-simulator smoke (docs/simulation.md): two predict runs over
# 256/1024/4096 ranks byte-identical, two-level strictly beating flat
# at 1024 simulated ranks, a calibration fitted from a known-constants
# simulated trace recovering them (replay ratios ~1), and a real 2-rank
# traced run replayed with bounded per-hop divergence, ~30s CPU.
sim-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/sim_smoke.py

# Self-driving-fleet smoke (docs/fault_tolerance.md "Self-driving
# fleet"): two seeded chronic-delay runs on 2 ranks + 1 hot spare —
# slowness quarantine fires, the spare promotes in the re-formation
# bump, a drift-triggered re-plan publishes and every rank adopts,
# training converges bitwise to the uninterrupted run — with the
# normalized decision logs byte-identical across runs and the
# re-planned config's simulated step time strictly below the
# incumbent's on the drifted calibration, ~45s CPU.
selfdrive-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/selfdrive_smoke.py

# Composed DP x TP smoke (docs/parallelism.md "Composed DP x TP fast
# path"): the shipped GPT rule table preflights clean against the real
# transformer tree on a 2x2 mesh, the composed step trains with
# streamed ZeRO-1 + int8 wire on the DP axis, per-axis wire bytes are
# nonzero on BOTH axes (model = plain psums only), and the normalized
# event log is byte-identical across two runs, <30s CPU.
llm-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/llm_smoke.py

# Elastic-reshard chaos smoke (docs/fault_tolerance.md "Elastic
# resharding"): f32 and int8 zero1 runs on a 4-rank virtual mesh each
# survive a quarantine shrink to 2 ranks and a spare-promotion grow
# back to 4 — gathered state bitwise-identical across every reshard
# edge, f32 finals bitwise vs the uninterrupted reference, int8 within
# quantization tolerance with live EF, hvd_reshard_* metered, event
# log byte-identical across two runs, <25s CPU.
reshard-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/reshard_smoke.py

# Serving chaos smoke (docs/serving.md): a 2-replica CPU serving job
# (TP-sharded across 2 virtual devices) under a seeded mid-batch
# kill_replica + request drop — every request answered exactly once
# (in-flight batch re-queued), normalized request logs byte-identical
# across two seeded runs, hvd_request_latency_seconds/queue-depth
# nonzero, request spans rendered via tools/trace_merge.py, <30s CPU.
serve-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/serve_smoke.py

# Fused-TP collective-matmul smoke (docs/parallelism.md "Fused TP
# overlap"): 2x2 fused step == classic to <=5e-7, fused forward HLO
# free of model-axis all-reduces with exactly the predicted chunked
# ring collective-permutes, the tuner's TP term pinning a fused chunk
# count strictly below the exposed-psum constant on the transformer
# program, normalized log byte-identical across two runs, <90s CPU.
tpfuse-smoke:
	JAX_PLATFORMS=cpu $(PY) tools/tpfuse_smoke.py

ci: lint-collectives chaos-smoke metrics-smoke overlap-smoke guard-smoke driver-smoke topo-smoke quant-smoke trace-smoke tune-smoke zero-smoke sim-smoke selfdrive-smoke llm-smoke reshard-smoke serve-smoke tpfuse-smoke test
