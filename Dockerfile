# horovod_tpu runtime image — role parity with the reference's
# Dockerfile.cpu/Dockerfile.gpu (reference builds MPI+NCCL+frameworks; the
# TPU build needs only the jax TPU stack plus the native control-plane
# toolchain).
#
# Build:  docker build -t horovod-tpu .
# Run  :  docker run --privileged horovod-tpu \
#             python -m horovod_tpu.run -np 4 python examples/keras_mnist.py
# (TPU VMs: --privileged exposes /dev/accel*; on GKE use the TPU device
# plugin instead.)
FROM python:3.12-slim-bookworm

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make git openssh-client \
    && rm -rf /var/lib/apt/lists/*

# jax[tpu] pulls libtpu via the Google releases index.
RUN pip install --no-cache-dir \
        'jax[tpu]' -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
        flax optax orbax-checkpoint chex einops numpy

# Framework bindings are optional extras; install the ones you use.
ARG WITH_TF=0
ARG WITH_TORCH=0
RUN if [ "$WITH_TF" = "1" ]; then pip install --no-cache-dir tensorflow-cpu; fi
RUN if [ "$WITH_TORCH" = "1" ]; then \
        pip install --no-cache-dir torch --index-url https://download.pytorch.org/whl/cpu; fi

WORKDIR /horovod_tpu
COPY . .
# Build the native control-plane core and install the package.
RUN make -C cpp && pip install --no-cache-dir -e .

# Launcher entrypoint (hvdrun analogue of horovodrun).
ENTRYPOINT []
CMD ["python", "-m", "horovod_tpu.run", "--help"]
