"""horovod_tpu.spark — Spark integration (gated).

The reference runs the training stack inside Spark executors
(``horovod/spark/__init__.py:36-235``: driver service collects task host
hashes, launches ranks through the Spark task service, returns per-task
results). PySpark is not installed in this environment, so the module is
import-gated; when PySpark is present, ``run(fn)`` drives the same flow as
the reference by mapping a barrier-stage job onto the ``horovod_tpu.run``
launcher primitives (slot allocation from executor hosts, env plumbing,
pickled fn shipping, per-task result collection).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional

try:
    import pyspark  # noqa: F401

    _SPARK_AVAILABLE = True
except ImportError:
    _SPARK_AVAILABLE = False

_MSG = (
    "PySpark is not installed in this environment. horovod_tpu.spark.run() "
    "requires pyspark; use horovod_tpu.run.run() (process fan-out) or "
    "hvdrun for non-Spark clusters."
)


def run(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    num_proc: Optional[int] = None,
    env: Optional[dict] = None,
    verbose: int = 1,
) -> List[Any]:
    """Run ``fn`` on ``num_proc`` Spark tasks (reference
    ``horovod.spark.run`` signature)."""
    if not _SPARK_AVAILABLE:
        raise ImportError(_MSG)
    import socket

    from pyspark import SparkContext, TaskContext

    from ..run import launcher
    from ..run.http_server import KVStoreClient, KVStoreServer

    kwargs = kwargs or {}
    sc = SparkContext.getOrCreate()
    if num_proc is None:
        num_proc = max(int(sc.defaultParallelism), 1)

    # Rendezvous KV on the driver: tasks register their hosts, then wait
    # for their rank env and run fn (the reference's driver/task service
    # handshake collapsed onto the HTTP KV store).
    server = KVStoreServer()
    port = server.start()
    driver_addr = socket.gethostbyname(socket.gethostname())

    import pickle

    fn_blob = pickle.dumps((fn, args, kwargs))

    def task(index):
        client = KVStoreClient(driver_addr, port)
        client.put("hosts", str(index), socket.gethostname().encode())
        slot_blob = client.wait("slots", str(index), timeout=120)
        slot_env = pickle.loads(slot_blob)
        import os

        os.environ.update(slot_env)
        f, a, kw = pickle.loads(fn_blob)
        result = f(*a, **kw)
        client.put("results", str(index), pickle.dumps(result))
        return [index]

    import threading

    def allocator():
        client = KVStoreClient("127.0.0.1", port)
        hosts = {}
        while len(hosts) < num_proc:
            for i in range(num_proc):
                v = client.get("hosts", str(i))
                if v is not None:
                    hosts[i] = v.decode()
        host_counts: dict = {}
        for i in sorted(hosts):
            host_counts[hosts[i]] = host_counts.get(hosts[i], 0) + 1
        slots = launcher.allocate(list(host_counts.items()), num_proc)
        controller_port = launcher._free_port()
        jax_port = launcher._free_port()
        by_host: dict = {}
        for i in sorted(hosts):
            h = hosts[i]
            slot = slots[len(by_host.setdefault("_all", []))]
            by_host["_all"].append(i)
            env = launcher.build_rank_env(
                slot, {}, hosts[0], controller_port,
                f"{hosts[0]}:{jax_port}",
            )
            client.put("slots", str(i), pickle.dumps(env))

    t = threading.Thread(target=allocator, daemon=True)
    t.start()
    try:
        sc.parallelize(range(num_proc), num_proc).barrier().mapPartitions(
            lambda it: task(next(it))
        ).collect()
        client = KVStoreClient("127.0.0.1", port)
        return [
            pickle.loads(client.wait("results", str(i), timeout=60))
            for i in range(num_proc)
        ]
    finally:
        server.stop()


def __getattr__(name):
    if not _SPARK_AVAILABLE and name not in ("run", "_SPARK_AVAILABLE"):
        raise ImportError(_MSG)
    raise AttributeError(name)
