"""horovod_tpu.spark — Spark integration (gated).

The reference runs the training stack inside Spark executors
(``horovod/spark/__init__.py:36-235``: driver service collects task host
hashes, launches ranks through the Spark task service, returns per-task
results). PySpark is not installed in this environment, so the module is
import-gated; when PySpark is present, ``run(fn)`` drives the same flow as
the reference by mapping a barrier-stage job onto the ``horovod_tpu.run``
launcher primitives (slot allocation from executor hosts, env plumbing,
per-task result collection).
"""

from __future__ import annotations

import time
from typing import Any, Callable, List, Optional

try:
    import pyspark  # noqa: F401

    _SPARK_AVAILABLE = True
except ImportError:
    _SPARK_AVAILABLE = False

_MSG = (
    "PySpark is not installed in this environment. horovod_tpu.spark.run() "
    "requires pyspark; use horovod_tpu.run.run() (process fan-out) or "
    "hvdrun for non-Spark clusters."
)

_ERROR_KEY = "__hvd_allocator_error__"


def run(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    num_proc: Optional[int] = None,
    env: Optional[dict] = None,
    verbose: int = 1,
) -> List[Any]:
    """Run ``fn`` on ``num_proc`` Spark tasks (reference
    ``horovod.spark.run`` signature). ``env`` is the base environment
    merged under the per-rank HOROVOD_* variables on every task."""
    if not _SPARK_AVAILABLE:
        raise ImportError(_MSG)
    import pickle
    import socket

    from pyspark import SparkContext

    from ..run import launcher
    from ..run.http_server import KVStoreClient, KVStoreServer

    kwargs = kwargs or {}
    base_env = dict(env or {})
    sc = SparkContext.getOrCreate()
    if num_proc is None:
        num_proc = max(int(sc.defaultParallelism), 1)

    # Rendezvous KV on the driver: tasks register their hosts, then wait
    # for their rank env and run fn (the reference's driver/task service
    # handshake collapsed onto the HTTP KV store).
    server = KVStoreServer()
    port = server.start()
    driver_addr = socket.gethostbyname(socket.gethostname())

    # fn/args/kwargs ride inside the task closure so Spark's cloudpickle
    # serializes them (stdlib pickle rejects lambdas and local functions,
    # which are the common Spark-notebook case).
    def task(index):
        import os
        import pickle as _p

        # The driver's resolved address may not be routable from every
        # executor network namespace (and on a single-host test cluster
        # hostname resolution itself can stall); the first successful PUT
        # pins the working address, falling back to loopback for
        # driver-local tasks.
        client = None
        last = None
        for addr in (driver_addr, "127.0.0.1"):
            cand = KVStoreClient(addr, port)
            try:
                cand.put("hosts", str(index),
                         socket.gethostname().encode())
                client = cand
                break
            except Exception as e:  # noqa: BLE001
                last = e
        if client is None:
            raise RuntimeError(
                f"cannot reach driver KV at {driver_addr}:{port}: {last}"
            )
        slot_blob = client.wait("slots", str(index), timeout=120)
        slot_env = _p.loads(slot_blob)
        if _ERROR_KEY in slot_env:
            raise RuntimeError(
                f"slot allocation failed on the driver: {slot_env[_ERROR_KEY]}"
            )
        os.environ.update(slot_env)
        result = fn(*args, **kwargs)
        client.put("results", str(index), _p.dumps(result))
        return [index]

    import threading

    alloc_error: list = []

    def allocator():
        client = KVStoreClient("127.0.0.1", port)
        try:
            hosts: dict = {}
            while len(hosts) < num_proc:
                progress = False
                for i in range(num_proc):
                    if i in hosts:
                        continue
                    v = client.get("hosts", str(i))
                    if v is not None:
                        hosts[i] = v.decode()
                        progress = True
                if not progress:
                    time.sleep(0.1)
            host_counts: dict = {}
            for i in sorted(hosts):
                host_counts[hosts[i]] = host_counts.get(hosts[i], 0) + 1
            slots = launcher.allocate(list(host_counts.items()), num_proc)
            # allocate() groups slots by host; hand each task index a slot
            # on the host it actually runs on.
            slots_by_host: dict = {}
            for slot in slots:
                slots_by_host.setdefault(slot.hostname, []).append(slot)
            controller_port = launcher._free_port()
            jax_port = launcher._free_port()
            for i in sorted(hosts):
                slot = slots_by_host[hosts[i]].pop(0)
                rank_env = launcher.build_rank_env(
                    slot, dict(base_env), hosts[0], controller_port,
                    f"{hosts[0]}:{jax_port}",
                )
                client.put("slots", str(i), pickle.dumps(rank_env))
        except Exception as e:  # propagate: fail tasks fast, re-raise on driver
            alloc_error.append(e)
            blob = pickle.dumps({_ERROR_KEY: repr(e)})
            for i in range(num_proc):
                try:
                    client.put("slots", str(i), blob)
                except Exception:
                    pass

    t = threading.Thread(target=allocator, daemon=True)
    t.start()
    try:
        sc.parallelize(range(num_proc), num_proc).barrier().mapPartitions(
            lambda it: task(next(it))
        ).collect()
        if alloc_error:
            raise alloc_error[0]
        client = KVStoreClient("127.0.0.1", port)
        return [
            pickle.loads(client.wait("results", str(i), timeout=60))
            for i in range(num_proc)
        ]
    except Exception:
        if alloc_error:
            raise alloc_error[0]
        raise
    finally:
        server.stop()
