"""Keras callbacks — parity with ``horovod/_keras/callbacks.py:20-181``:
BroadcastGlobalVariables, MetricAverage, LearningRateSchedule/Warmup with
momentum correction.

Real ``keras.callbacks.Callback`` subclasses: Keras 3's CallbackList only
dispatches the hooks the base class declares (``on_train_batch_end`` etc.),
so a duck-typed object's legacy ``on_batch_end`` silently never fires —
which under multi-rank training means the initial broadcast never happens
and ranks train from different inits.
"""

from __future__ import annotations

from typing import Optional

import tensorflow as tf

_Callback = tf.keras.callbacks.Callback


class BroadcastGlobalVariablesCallback(_Callback):
    """Broadcast model + optimizer state from root at the end of the first
    batch (after Keras has built the optimizer slots), so all ranks train
    identically (reference ``_keras/callbacks.py:20-45``)."""

    def __init__(self, root_rank: int = 0, device=""):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done or self.model is None:
            return
        from ..tensorflow import broadcast_variables

        broadcast_variables(self.model.variables, self.root_rank)
        opt = getattr(self.model, "optimizer", None)
        if opt is not None and getattr(opt, "variables", None):
            vars_ = opt.variables if not callable(opt.variables) \
                else opt.variables()
            broadcast_variables(vars_, self.root_rank)
        self.broadcast_done = True

    def on_train_batch_end(self, batch, logs=None):
        # Keras 3 dispatches the train-specific hook, not on_batch_end.
        self.on_batch_end(batch, logs)


class MetricAverageCallback(_Callback):
    """Average epoch metrics over ranks at epoch end (reference
    ``_keras/callbacks.py:46-84``)."""

    def __init__(self, device=""):
        super().__init__()

    def on_epoch_end(self, epoch, logs=None):
        if logs is None:
            return
        import numpy as np

        from .. import allreduce as _np_allreduce

        for k, v in list(logs.items()):
            if isinstance(v, (int, float, np.floating)):
                logs[k] = float(
                    np.asarray(
                        _np_allreduce(
                            np.asarray(v, dtype=np.float64),
                            average=True,
                            name=f"metric.{k}",
                        )
                    )
                )


class LearningRateScheduleCallback(_Callback):
    """Multiply the LR by ``multiplier`` within an epoch range (reference
    ``_keras/callbacks.py:86-133``); with ``staircase`` the multiplier is a
    function of epoch."""

    def __init__(self, initial_lr: float, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 momentum_correction: bool = True, steps_per_epoch=None):
        super().__init__()
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        if callable(multiplier):
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier
        self._restore_momentum = None

    def _in_range(self, epoch) -> bool:
        return epoch >= self.start_epoch and (
            self.end_epoch is None or epoch < self.end_epoch
        )

    def _set_lr(self, lr: float) -> None:
        opt = self.model.optimizer
        if hasattr(opt, "learning_rate"):
            try:
                opt.learning_rate = lr
            except Exception:
                opt.learning_rate.assign(lr)

    def _adjust_momentum(self, lr_ratio: float) -> None:
        # Momentum correction (reference :120-133): scale momentum when LR
        # changes mid-training so velocity stays consistent.
        opt = self.model.optimizer
        if not self.momentum_correction or not hasattr(opt, "momentum"):
            return
        if self._restore_momentum is None:
            self._restore_momentum = float(
                opt.momentum if not callable(opt.momentum) else opt.momentum()
            )
        try:
            opt.momentum = self._restore_momentum * lr_ratio
        except Exception:
            pass

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.model is None or not self._in_range(epoch):
            return
        if self.staircase:
            new_lr = self.initial_lr * self.multiplier(epoch)
            self._set_lr(new_lr)

    def on_batch_begin(self, batch, logs=None):
        if self.model is None or self.staircase \
                or not self._in_range(self.current_epoch) \
                or not self.steps_per_epoch:
            return
        frac_epoch = self.current_epoch + batch / self.steps_per_epoch
        self._set_lr(self.initial_lr * self.multiplier(frac_epoch))

    def on_train_batch_begin(self, batch, logs=None):
        self.on_batch_begin(batch, logs)


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual LR warmup over the first epochs: scales from 1/size -> 1.0
    of the target LR (reference ``_keras/callbacks.py:134-181``)."""

    def __init__(self, initial_lr: float, warmup_epochs: int = 5,
                 momentum_correction: bool = True, steps_per_epoch=None,
                 verbose: int = 0):
        from .. import size

        self.warmup_epochs = warmup_epochs
        self.verbose = verbose
        n = size()

        def multiplier(epoch):
            # epoch may be fractional; ramp 1/n -> 1 across warmup_epochs
            progress = min(max(epoch / max(warmup_epochs, 1e-9), 0.0), 1.0)
            return 1.0 / n + progress * (1.0 - 1.0 / n)

        super().__init__(
            initial_lr, multiplier, start_epoch=0, end_epoch=warmup_epochs,
            staircase=False, momentum_correction=momentum_correction,
            steps_per_epoch=steps_per_epoch,
        )
