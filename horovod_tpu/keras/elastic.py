"""Elastic API for the Keras binding (upstream
``horovod.tensorflow.keras.elastic``): ``run``/``KerasState`` plus the
three state-keeping callbacks that make ``model.fit`` resumable across
world re-formations.

Usage (mirrors upstream):

```python
import horovod_tpu.keras as hvd
import horovod_tpu.keras.elastic as elastic

state = elastic.KerasState(model, batch=0, epoch=0)

@elastic.run
def train(state):
    model.fit(
        x, y,
        initial_epoch=state.epoch, epochs=total_epochs,
        callbacks=[
            # Update callbacks FIRST so each commit snapshots the
            # already-advanced counters (commit last, as upstream
            # documents).
            elastic.UpdateBatchStateCallback(state),
            elastic.UpdateEpochStateCallback(state),
            elastic.CommitStateCallback(state, batches_per_commit=50),
        ],
    )

train(state)
```
"""

from __future__ import annotations

from ..elastic import (  # noqa: F401
    HostsUpdatedInterrupt,
    ObjectState,
    State,
    TensorFlowKerasState,
    run,
)

# Upstream names it KerasState inside the keras module.
KerasState = TensorFlowKerasState

__all__ = [
    "run",
    "State",
    "ObjectState",
    "KerasState",
    "TensorFlowKerasState",
    "CommitStateCallback",
    "UpdateBatchStateCallback",
    "UpdateEpochStateCallback",
    "HostsUpdatedInterrupt",
]


def _callback_base():
    import tensorflow as tf

    return tf.keras.callbacks.Callback


class _LazyCallback:
    """Build the tf.keras Callback subclass on first instantiation so
    importing this module never requires tensorflow."""

    _cls = None

    def __new__(cls, *args, **kwargs):
        if cls._cls is None:
            cls._cls = cls._build()
        return cls._cls(*args, **kwargs)


class CommitStateCallback(_LazyCallback):
    """``state.commit()`` every ``batches_per_commit`` batches (and at
    every epoch end) — the commit is also where membership changes
    surface (``HostsUpdatedInterrupt`` out of ``fit``, caught by
    ``run``)."""

    @staticmethod
    def _build():
        Base = _callback_base()

        class _CommitStateCallback(Base):
            def __init__(self, state, batches_per_commit: int = 100):
                super().__init__()
                self._state = state
                self._every = max(1, int(batches_per_commit))
                self._counter = 0

            def on_batch_end(self, batch, logs=None):
                self._counter += 1
                if self._counter % self._every == 0:
                    self._state.commit()

            def on_epoch_end(self, epoch, logs=None):
                self._state.commit()

        return _CommitStateCallback


class UpdateBatchStateCallback(_LazyCallback):
    """Track ``state.batch`` through fit (reset to 0 at epoch end)."""

    @staticmethod
    def _build():
        Base = _callback_base()

        class _UpdateBatchStateCallback(Base):
            def __init__(self, state):
                super().__init__()
                self._state = state

            def on_batch_end(self, batch, logs=None):
                self._state.batch = batch + 1

            def on_epoch_end(self, epoch, logs=None):
                self._state.batch = 0

        return _UpdateBatchStateCallback


class UpdateEpochStateCallback(_LazyCallback):
    """Track ``state.epoch`` through fit (feed it back as
    ``initial_epoch`` after a re-formation)."""

    @staticmethod
    def _build():
        Base = _callback_base()

        class _UpdateEpochStateCallback(Base):
            def __init__(self, state):
                super().__init__()
                self._state = state

            def on_epoch_end(self, epoch, logs=None):
                self._state.epoch = epoch + 1

        return _UpdateEpochStateCallback
