"""horovod_tpu.keras — Keras binding.

API parity with ``horovod/keras/__init__.py`` + ``horovod/_keras/``:
``DistributedOptimizer`` wrapper, broadcast/metric-average/LR-schedule
callbacks, and ``load_model`` that rewraps saved optimizers.
"""

from __future__ import annotations

from .. import (  # noqa: F401
    Adasum,
    Average,
    Sum,
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from ..tensorflow import (
    DistributedOptimizer as _TfDistributedOptimizer,
    allreduce as _tf_allreduce,
    broadcast_variables,
)
from ..tensorflow.compression import Compression

from . import callbacks  # noqa: E402,F401  (import after basics)


def DistributedOptimizer(optimizer, name=None,  # noqa: N802
                         device_dense="", device_sparse="",
                         compression=Compression.none, op=None):
    return _TfDistributedOptimizer(
        optimizer, name=name, device_dense=device_dense,
        device_sparse=device_sparse, compression=compression, op=op,
    )


def allreduce(value, name=None, average=True):
    """Average a value (tensor or scalar) across ranks — used by metric
    averaging (reference ``horovod/keras/__init__.py``)."""
    import numpy as np
    import tensorflow as tf

    tensor = tf.convert_to_tensor(value)
    return _tf_allreduce(tensor, average=average, name=name)


def allgather(value, name=None):
    from ..tensorflow import allgather as _ag

    return _ag(value, name)


def broadcast(value, root_rank, name=None):
    from ..tensorflow import broadcast as _bc

    return _bc(value, root_rank, name)


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a Keras model and wrap its optimizer in DistributedOptimizer
    (reference ``_keras/__init__.py:111+``)."""
    import tensorflow as tf

    model = tf.keras.models.load_model(
        filepath, custom_objects=custom_objects, compile=True
    )
    if getattr(model, "optimizer", None) is not None:
        wrapped = DistributedOptimizer(model.optimizer,
                                       compression=compression)
        model.compile(
            optimizer=wrapped,
            loss=model.loss,
        )
    return model
