"""horovod_tpu.keras — Keras binding.

API parity with ``horovod/keras/__init__.py`` + ``horovod/_keras/``:
``DistributedOptimizer`` wrapper, broadcast/metric-average/LR-schedule
callbacks, and ``load_model`` that rewraps saved optimizers.
"""

from __future__ import annotations

from .. import (  # noqa: F401
    Adasum,
    Average,
    Sum,
    init,
    is_initialized,
    local_rank,
    local_size,
    rank,
    shutdown,
    size,
)
from ..tensorflow import (
    DistributedOptimizer as _TfDistributedOptimizer,
    allreduce as _tf_allreduce,
    broadcast_variables,
)
from ..tensorflow.compression import Compression

from . import callbacks  # noqa: E402,F401  (import after basics)


def DistributedOptimizer(optimizer, name=None,  # noqa: N802
                         device_dense="", device_sparse="",
                         compression=Compression.none,
                         sparse_as_dense=False, op=None):
    return _TfDistributedOptimizer(
        optimizer, name=name, device_dense=device_dense,
        device_sparse=device_sparse, compression=compression,
        sparse_as_dense=sparse_as_dense, op=op,
    )


def allreduce(value, name=None, average=True):
    """Average a value (tensor or scalar) across ranks — used by metric
    averaging (reference ``horovod/keras/__init__.py``)."""
    import numpy as np
    import tensorflow as tf

    tensor = tf.convert_to_tensor(value)
    return _tf_allreduce(tensor, average=average, name=name)


def allgather(value, name=None):
    from ..tensorflow import allgather as _ag

    return _ag(value, name)


def broadcast(value, root_rank, name=None):
    from ..tensorflow import broadcast as _bc

    return _bc(value, root_rank, name)


def _deserialize_compile_arg(key, value):
    """Turn a saved compile-config entry (possibly a serialized keras object
    or a nested list/dict of them) back into something ``compile`` accepts."""
    import tensorflow as tf

    if isinstance(value, dict) and "class_name" in value:
        mod = tf.keras.losses if key == "loss" else tf.keras.metrics
        return mod.deserialize(value)
    if isinstance(value, (list, tuple)):
        return [_deserialize_compile_arg(key, v) for v in value]
    if isinstance(value, dict):
        return {k: _deserialize_compile_arg(key, v) for k, v in value.items()}
    return value


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a Keras model with its optimizer re-wrapped as a
    DistributedOptimizer (reference ``_keras/__init__.py:111+``: every known
    optimizer class name is remapped to a distributed subclass so the saved
    optimizer config — including one saved *from* a wrapped optimizer, which
    serializes under the base class name — deserializes directly into the
    wrapper)."""
    import tensorflow as tf

    from ..tensorflow import _make_distributed_optimizer_class

    opt_classes = set()
    for attr in dir(tf.keras.optimizers):
        obj = getattr(tf.keras.optimizers, attr, None)
        if (isinstance(obj, type)
                and issubclass(obj, tf.keras.optimizers.Optimizer)
                and obj is not tf.keras.optimizers.Optimizer):
            opt_classes.add(obj)
    if custom_optimizers:
        opt_classes.update(custom_optimizers)

    hvd_objects = {
        cls.__name__: _make_distributed_optimizer_class(
            cls, compression=compression
        )
        for cls in opt_classes
    }
    if custom_objects:
        hvd_objects.update(custom_objects)

    model = tf.keras.models.load_model(
        filepath, custom_objects=hvd_objects, compile=True
    )
    opt = getattr(model, "optimizer", None)
    if opt is not None and not getattr(type(opt), "_hvd_distributed", False):
        # An optimizer deserialized through user custom_objects (not one of
        # the remapped classes) still needs the distributed wrapper. Carry
        # over the full saved compile config (metrics, loss_weights, ...) —
        # re-compiling with only loss would silently drop them.
        dist_opt = DistributedOptimizer(opt, compression=compression)
        try:
            cfg = dict(model.get_compile_config() or {})
            kwargs = {}
            for key in ("loss", "metrics", "weighted_metrics", "loss_weights"):
                if cfg.get(key) is not None:
                    kwargs[key] = _deserialize_compile_arg(key, cfg[key])
            kwargs.setdefault("loss", model.loss)
            model.compile(optimizer=dist_opt, **kwargs)
        except Exception:  # pragma: no cover - keras version drift
            model.compile(optimizer=dist_opt, loss=model.loss)
    return model
