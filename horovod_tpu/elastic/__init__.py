"""Elastic training: fault tolerance + dynamically changing membership.

Later-reference parity (``horovod.elastic``, added upstream in v0.20 — not
present in the v0.18.2 reference tree, like the ProcessSet and grouped-op
APIs this build already ships): a training loop wrapped in
``@hvd.elastic.run`` survives worker failures and host set changes by
rolling back to the last committed ``State`` and re-forming the world with
the surviving/new workers.

TPU-native design — generation-based world re-formation, no process
restart:

- The elastic driver (``hvdrun --min-np/--max-np/--host-discovery-script``,
  ``run/elastic_driver.py``) publishes each world *generation* (membership,
  rank assignments, and FRESH control-plane + JAX-coordinator endpoints) in
  its HTTP KV rendezvous store.
- Workers re-rendezvous IN PROCESS: tear down the JAX distributed client
  and the XLA backend caches (``jax.distributed.shutdown()`` +
  ``xla_bridge._clear_backends()``), update the ``HOROVOD_*`` env from the
  new generation, and ``hvd.init()`` again. Weights stay in host memory
  (the committed ``State``); nothing is re-spawned, so recovery cost is one
  re-rendezvous + one recompilation at the new world size.
- ``State.check_host_updates()`` reaches cross-rank agreement with a tiny
  allreduce before interrupting, so every live rank raises
  ``HostsUpdatedInterrupt`` at the same step (upstream's notification
  agreement, re-expressed as the collective it always was).

Failure semantics: a crashed peer surfaces on survivors as
``HorovodInternalError`` (transport abort or stall shutdown) → ``run``
restores the last commit and rejoins the next generation. A graceful
membership change (host added/removed by discovery) surfaces as
``HostsUpdatedInterrupt`` → current in-memory state is KEPT (no rollback)
and re-synced from the new rank 0.
"""

from __future__ import annotations

import contextlib
import copy
import functools
import json
import logging
import os
import sys
import time
from typing import Any, Callable, Dict, List, Optional

from .. import guard as _guard
from .. import metrics as _metrics
from .. import trace as _trace
from ..fault import injector as _fault_injector
from ..fault import preemption as _preemption
from ..fault.preemption import PreemptionInterrupt  # noqa: F401 (re-export)

logger = logging.getLogger("horovod_tpu.elastic")

__all__ = [
    "run",
    "State",
    "ObjectState",
    "JaxState",
    "TorchState",
    "TensorFlowState",
    "TensorFlowKerasState",
    "HostsUpdatedInterrupt",
    "PlanUpdatedInterrupt",
    "PreemptionInterrupt",
    "adopted_replan",
    "adopted_step_kwargs",
    "apply_serve_scale",
    "note_zero1_layout",
]


class HostsUpdatedInterrupt(Exception):
    """Raised inside the training function when the driver published a new
    world generation (host added/removed). The in-memory state is kept;
    ``run`` re-rendezvouses and re-syncs it."""


class PlanUpdatedInterrupt(Exception):
    """Raised inside the training function — on EVERY rank, at the same
    commit boundary (the adoption rides the host-check agreement
    allreduce) — when the driver published a live re-plan notice
    (docs/fault_tolerance.md "Self-driving fleet"). The world is
    unchanged: no rollback, no re-rendezvous; ``run`` re-enters the
    training function so it rebuilds its step from
    :func:`adopted_step_kwargs` (a ``make_train_step`` rebuilt from
    ``tune.tuned_step_kwargs`` — never a mid-step knob flip)."""

    def __init__(self, notice: Dict[str, Any]):
        self.notice = dict(notice)
        super().__init__(
            f"live re-plan #{notice.get('id')} adopted: "
            f"{notice.get('config')}"
        )


# --------------------------------------------------------------- context
class _ElasticContext:
    """Worker-side view of the elastic rendezvous (driver KV store)."""

    def __init__(self) -> None:
        from ..run.http_server import KVStoreClient

        self.worker_id = os.environ["HOROVOD_ELASTIC_WORKER_ID"]
        self.gen = int(os.environ.get("HOROVOD_ELASTIC_GEN", "1"))
        # Driver-epoch fencing baseline (docs/fault_tolerance.md
        # "Control-plane availability"): the incarnation of the driver
        # that spawned this worker. A resumed driver presents a HIGHER
        # epoch (reattach); anything lower is a stale driver that lost a
        # supervisor race and must be rejected.
        try:
            self.epoch = int(os.environ.get("HOROVOD_DRIVER_EPOCH", "0"))
        except ValueError:
            self.epoch = 0
        # Rank holding the authoritative state for the current generation
        # (a survivor after a re-formation; see ElasticDriver._publish).
        # From env at spawn (a respawned worker joins mid-job and never
        # goes through apply() for its first generation), then updated by
        # apply() on every re-formation.
        self.sync_root = int(
            os.environ.get("HOROVOD_ELASTIC_SYNC_ROOT", "0")
        )
        addr = os.environ["HOROVOD_ELASTIC_KV_ADDR"]
        port = int(os.environ["HOROVOD_ELASTIC_KV_PORT"])
        self._kv = KVStoreClient(addr, port)
        self.timeout = float(
            os.environ.get("HOROVOD_ELASTIC_TIMEOUT", "600")
        )
        # Consecutive failed control-plane probes; at the threshold the
        # driver is declared lost and this rank votes to park.
        self._probe_failures = 0
        try:
            self.lost_threshold = max(1, int(os.environ.get(
                "HOROVOD_DRIVER_LOST_PROBES", "3")))
        except ValueError:
            self.lost_threshold = 3
        self._parks = 0
        # Live re-plan bookkeeping (docs/fault_tolerance.md
        # "Self-driving fleet"): the last ADOPTED notice id, the last
        # EXAMINED id (so a rejected stale notice is not re-litigated
        # every commit), and the validated doc awaiting the commit-
        # boundary agreement.
        self.replan_id = 0
        self._replan_seen = 0
        self._pending_replan: Optional[Dict[str, Any]] = None

    def fetch_world(self, strict: bool = False) -> Optional[Dict[str, Any]]:
        raw = self._kv.get("elastic", "world", strict=strict)
        if raw is None:
            return None
        return json.loads(raw.decode())

    def fetch_driver(self, strict: bool = False) -> Optional[Dict[str, Any]]:
        """The driver's identity doc on the KV plane: epoch (fencing
        token), generation, liveness beat."""
        raw = self._kv.get("elastic", "driver", strict=strict)
        if raw is None:
            return None
        return json.loads(raw.decode())

    def fetch_replan(self, strict: bool = False) -> Optional[Dict[str, Any]]:
        """The driver's live re-plan notice, if one is published."""
        raw = self._kv.get("elastic", "replan", strict=strict)
        if raw is None:
            return None
        doc = json.loads(raw.decode())
        return doc if isinstance(doc, dict) else None

    def check_replan(self) -> bool:
        """Examine the published re-plan notice (one KV read per
        commit). A fresh, valid notice is stashed for the commit-
        boundary agreement; a STALE one — epoch below this worker's
        fencing baseline (a fenced driver's plans are as untrustworthy
        as its worlds) or a generation that is not the current one — is
        rejected loudly, exactly once per notice id. Returns True while
        a validated notice awaits adoption."""
        try:
            doc = self.fetch_replan()
        except Exception:  # noqa: BLE001 - driver briefly unreachable
            return self._pending_replan is not None
        if not doc:
            return self._pending_replan is not None
        try:
            nid = int(doc.get("id", 0))
            epoch = int(doc.get("epoch", 0) or 0)
            gen = int(doc.get("gen", -1))
        except (TypeError, ValueError):
            return self._pending_replan is not None
        if nid <= self.replan_id or nid <= self._replan_seen:
            return self._pending_replan is not None
        if gen > self.gen:
            # Stamped for a generation this worker has not joined yet
            # (the driver re-stamps notices across re-formations): not
            # stale, just early — leave it unexamined; it becomes
            # adoptable right after the rejoin commits the new gen.
            return self._pending_replan is not None
        reason = None
        if epoch < self.epoch:
            reason = "stale-epoch"
        elif gen < self.gen:
            reason = "stale-generation"
        if reason is not None:
            self._replan_seen = nid
            if _metrics.ACTIVE:
                _metrics.TAP.inc("hvd_replan_rejected_total",
                                 reason=reason)
            logger.error(
                "elastic: rejecting re-plan notice #%s (%s: notice "
                "epoch %s / gen %s vs acknowledged epoch %s / current "
                "gen %s)", nid, reason, epoch, gen, self.epoch, self.gen,
            )
            return self._pending_replan is not None
        self._replan_seen = nid
        self._pending_replan = doc
        return True

    def take_pending_replan(self) -> Dict[str, Any]:
        """The notice to adopt after the fleet AGREED at a commit
        boundary. A rank whose own KV read raced the publish (it got
        the agreement bit from a peer) re-fetches here; if the notice
        is unreachable the adoption cannot be completed consistently
        and the caller degrades to the rollback path."""
        doc = self._pending_replan
        if doc is None:
            for _ in range(3):
                try:
                    doc = self.fetch_replan(strict=True)
                except Exception:  # noqa: BLE001 - retried below
                    doc = None
                if doc:
                    break
                time.sleep(0.2)
        if doc is None:
            import horovod_tpu as hvd

            raise hvd.HorovodInternalError(
                "elastic: the fleet agreed to adopt a re-plan notice "
                "this rank cannot fetch; rolling back to stay consistent"
            )
        self._pending_replan = None
        self.replan_id = max(self.replan_id, int(doc.get("id", 0)))
        return doc

    def probe_driver(self):
        """One strict probe of the control plane for the park loop:
        (driver_doc, world_doc), or (None, None) while the driver is
        unreachable."""
        try:
            return self.fetch_driver(strict=True), self.fetch_world(
                strict=True
            )
        except Exception:  # noqa: BLE001 - endpoint down
            return None, None

    def commit_probe(self):
        """Per-commit control-plane probe. Returns
        ``(updated, driver_lost, new_epoch)``:

        - ``updated`` — a newer world generation is published;
        - ``driver_lost`` — ``lost_threshold`` consecutive probes failed
          (dead driver), or the plane is served by a STALE driver epoch
          (split brain — park and wait to be fenced through);
        - ``new_epoch`` — the driver restarted (epoch advanced) while
          publishing the SAME generation: the fleet never broke, so this
          rank can reattach in place, no parking and no collective."""
        try:
            world = self.fetch_world(strict=True)
            driver = self.fetch_driver(strict=True)
        except Exception:  # noqa: BLE001 - endpoint down
            self._probe_failures += 1
            return False, self._probe_failures >= self.lost_threshold, None
        self._probe_failures = 0
        updated = bool(world) and int(world["gen"]) > self.gen
        if driver is not None:
            try:
                epoch = int(driver.get("epoch", 0) or 0)
            except (TypeError, ValueError):
                epoch = 0
            if epoch < self.epoch:
                # A fenced driver's world/generation claims are not
                # trustworthy either: treat as loss, the park loop keeps
                # rejecting it until a current driver answers.
                if _metrics.ACTIVE:
                    _metrics.TAP.inc("hvd_worker_driver_fenced_total")
                return False, True, None
            if (epoch > self.epoch and not updated
                    and world is not None
                    and int(world["gen"]) == self.gen):
                return False, False, epoch
        return updated, False, None

    def reattach(self, epoch: int) -> None:
        """Adopt the resumed driver: accept its (higher) epoch,
        re-register under it, and carry on — same generation, same
        process, no rollback."""
        self.epoch = int(epoch)
        self._probe_failures = 0
        self.signal_attach()
        if _trace.ACTIVE:
            _trace.TAP.event(
                "hvd_worker_reattach", cat="elastic",
                gen=self.gen, epoch=self.epoch,
            )
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_worker_reattaches_total")
        if _fault_injector.ACTIVE:
            _fault_injector.record_event(
                "driver", 1, "reattach",
                f"gen={self.gen} epoch={self.epoch}",
            )
        logger.warning(
            "elastic: reattached to resumed driver (generation %s, "
            "epoch %s)", self.gen, self.epoch,
        )

    def signal_attach(self) -> None:
        """Re-register with a resumed driver: the adoption machinery
        (ElasticDriver._poll_adopted) matches the generation + epoch and
        uses the pid for local liveness supervision."""
        try:
            self._kv.put(
                "elastic", f"attach.{self.worker_id}",
                f"{self.gen}:{self.epoch}:{os.getpid()}".encode(),
            )
        except Exception:  # noqa: BLE001 - advisory signal
            pass

    def signal_done(self) -> None:
        """Tell the driver this worker's training function returned.
        A resumed driver has no process handle on adopted workers, so a
        clean exit would otherwise be invisible to it."""
        try:
            self._kv.put(
                "elastic", f"done.{self.worker_id}",
                str(self.gen).encode(),
            )
        except Exception:  # noqa: BLE001 - advisory signal
            pass

    def confirm_joined(self) -> None:
        """Tell the driver this worker completed a state sync in its
        current generation — from then on it holds live training state
        and is a valid sync_root for future re-formations."""
        try:
            self._kv.put(
                "elastic", f"joined.{self.worker_id}",
                str(self.gen).encode(),
            )
        except Exception:  # noqa: BLE001 - advisory signal
            pass

    def signal_rejoin(self) -> None:
        """Tell the driver this worker abandoned its current generation
        (rollback with every process still alive — stall shutdown,
        transient control-plane error). The driver responds by bumping
        the generation even though membership did not change; without
        this, every rank would wait out the full elastic timeout for a
        bump that nothing else triggers."""
        try:
            self._kv.put(
                "elastic", f"rejoin.{self.worker_id}",
                str(self.gen).encode(),
            )
        except Exception:  # noqa: BLE001 - advisory signal
            pass

    def poll_updated(self) -> bool:
        """True when the driver has published a newer generation than the
        one this worker is part of."""
        try:
            world = self.fetch_world()
        except Exception:  # noqa: BLE001 - driver briefly unreachable
            return False
        return bool(world) and int(world["gen"]) > self.gen

    def apply(self, world: Dict[str, Any]) -> bool:
        """Point the ``HOROVOD_*`` env at this generation's assignment.
        Returns False when this worker is not a member of the new world.
        Deliberately does NOT advance ``self.gen`` — the caller commits
        the generation only after ``hvd.init()`` succeeds, so a transient
        init failure retries the SAME still-live generation instead of
        waiting forever for a bump the driver has no reason to publish."""
        a = world["assignments"].get(self.worker_id)
        if a is None:
            return False
        os.environ.update(
            {
                "HOROVOD_RANK": str(a["rank"]),
                "HOROVOD_SIZE": str(world["size"]),
                "HOROVOD_LOCAL_RANK": str(a["local_rank"]),
                "HOROVOD_LOCAL_SIZE": str(a["local_size"]),
                "HOROVOD_CROSS_RANK": str(a["cross_rank"]),
                "HOROVOD_CROSS_SIZE": str(a["cross_size"]),
                "HOROVOD_CONTROLLER_ADDR": world["controller_addr"],
                "HOROVOD_CONTROLLER_PORT": str(world["controller_port"]),
                "HOROVOD_JAX_COORDINATOR": world["jax_coordinator"],
                "HOROVOD_ELASTIC_GEN": str(world["gen"]),
            }
        )
        self.sync_root = int(world.get("sync_root", 0))
        # The generation doc is epoch-stamped: joining it acknowledges
        # its driver, raising this worker's fencing baseline.
        try:
            self.epoch = max(self.epoch, int(world.get("epoch", 0) or 0))
        except (TypeError, ValueError):
            pass
        return True


_context: Optional[_ElasticContext] = None


def _ctx() -> Optional[_ElasticContext]:
    global _context
    if _context is None and os.environ.get("HOROVOD_ELASTIC") == "1":
        _context = _ElasticContext()
    return _context


# ------------------------------------------- driver-loss park/reattach
class DriverWatch:
    """Pure classification core of the worker-side park/reconnect state
    machine (unit-testable without a fleet): given what a parked rank
    currently observes on the KV plane, decide its next move.

    - ``wait``     — no driver answering (or no world yet): keep parking.
    - ``fenced``   — a driver is answering but with an epoch LOWER than
      one this worker has already acknowledged: a stale incarnation that
      lost a supervisor race. Rejected; keep parking for the real one.
    - ``reattach`` — a current-or-newer epoch republished the SAME
      generation this rank is part of: the fleet never broke, resume in
      place (``epoch_seen`` carries the epoch to adopt).
    - ``rejoin``   — the returning driver published a DIFFERENT
      generation: this rank's world is gone; degrade to the existing
      membership-interrupt path (state kept, re-sync, or respawn)."""

    def __init__(self, gen: int, epoch: int):
        self.gen = int(gen)
        self.epoch = int(epoch)
        self.epoch_seen: Optional[int] = None
        self.fenced = 0

    def classify(self, driver_doc, world_doc) -> str:
        if not isinstance(driver_doc, dict):
            return "wait"
        try:
            epoch = int(driver_doc.get("epoch", 0) or 0)
        except (TypeError, ValueError):
            return "wait"
        if epoch < self.epoch:
            self.fenced += 1
            return "fenced"
        if not isinstance(world_doc, dict):
            return "wait"
        try:
            gen = int(world_doc.get("gen", -1))
        except (TypeError, ValueError):
            return "wait"
        if gen == self.gen:
            self.epoch_seen = epoch
            return "reattach"
        return "rejoin"


# Cross-rank outcome agreement codes, ordered by severity (the fleet
# adopts the MAX so no rank resumes into a world a peer abandoned).
PARK_OUTCOMES = {"reattach": 0, "rejoin": 1, "dead": 2}


def _park_and_reattach(ctx: _ElasticContext, state=None) -> None:
    """Driver-loss handling, entered at a commit boundary once the fleet
    AGREED (via the host-check allreduce) that the driver is gone:
    training state is held, collectives are quiesced, and every rank
    polls the KV plane with the bounded-backoff machinery until a
    current-epoch driver answers. Same generation back → reattach in
    place; new generation → the existing rollback/rejoin path; no driver
    within the elastic timeout → collective failure (rollback, and in
    respawn mode persist-and-exit so a future driver finds the
    snapshots)."""
    import numpy as np

    import horovod_tpu as hvd

    from ..fault.backoff import Backoff

    ctx._parks += 1
    if _trace.ACTIVE:
        _trace.TAP.event(
            "hvd_worker_park", cat="elastic", gen=ctx.gen, epoch=ctx.epoch,
        )
    if _metrics.ACTIVE:
        _metrics.TAP.inc("hvd_worker_parks_total")
    if _fault_injector.ACTIVE:
        _fault_injector.record_event(
            "driver", ctx._parks, "park", f"gen={ctx.gen}"
        )
    logger.warning(
        "elastic: driver unreachable; parked at the commit boundary "
        "(state held, collectives quiesced; gen %s, epoch %s)",
        ctx.gen, ctx.epoch,
    )
    watch = DriverWatch(ctx.gen, ctx.epoch)
    backoff = Backoff.from_env()
    deadline = time.monotonic() + ctx.timeout
    attempt = 0
    fenced_logged = False
    outcome = "dead"
    while time.monotonic() <= deadline:
        driver_doc, world_doc = ctx.probe_driver()
        got = watch.classify(driver_doc, world_doc)
        if got in ("reattach", "rejoin"):
            outcome = got
            break
        if got == "fenced":
            if _metrics.ACTIVE:
                _metrics.TAP.inc("hvd_worker_driver_fenced_total")
            if not fenced_logged:
                fenced_logged = True
                if _fault_injector.ACTIVE:
                    _fault_injector.record_event(
                        "driver", ctx._parks, "fenced",
                        f"epoch={driver_doc.get('epoch')}<{ctx.epoch}",
                    )
                logger.error(
                    "elastic: rejecting stale driver (epoch %s < "
                    "acknowledged %s); waiting for a current one",
                    driver_doc.get("epoch"), ctx.epoch,
                )
        time.sleep(backoff.delay(min(attempt, 5)))
        attempt += 1
    # Outcome agreement: a rank must not resume into a world a peer has
    # abandoned (or vice versa) — adopt the most severe observation.
    code = PARK_OUTCOMES[outcome]
    if hvd.is_initialized() and hvd.size() > 1:
        agreed = int(np.asarray(hvd.allreduce(
            np.asarray([code], np.int32), op=hvd.Max,
            name="hvd.elastic.parkagree",
        ))[0])
    else:
        agreed = code
    if agreed == PARK_OUTCOMES["reattach"]:
        ctx.reattach(watch.epoch_seen if watch.epoch_seen is not None
                     else ctx.epoch)
        return
    if agreed == PARK_OUTCOMES["rejoin"]:
        raise HostsUpdatedInterrupt(
            "driver resumed with a new world generation; rejoining"
        )
    raise hvd.HorovodInternalError(
        f"elastic: no current driver within {ctx.timeout:g}s of parking "
        f"(last known generation {ctx.gen}, epoch {ctx.epoch})"
    )


# ------------------------------------------------------- live re-plan
_adopted_replan: Optional[Dict[str, Any]] = None


def _adopt_replan(ctx: _ElasticContext) -> None:
    """Commit-boundary re-plan adoption, after the fleet AGREED via the
    host-check allreduce: record the notice, then interrupt the training
    function so it rebuilds its step — a generation-style state
    transition (state kept, no rollback, no re-rendezvous), never a
    mid-step knob flip."""
    global _adopted_replan
    doc = ctx.take_pending_replan()
    _adopted_replan = doc
    if _metrics.ACTIVE:
        _metrics.TAP.inc("hvd_replan_adoptions_total")
    if _trace.ACTIVE:
        _trace.TAP.event(
            "hvd_replan_adopt", cat="elastic",
            id=int(doc.get("id", 0)), gen=ctx.gen,
        )
        # The new plan invalidates the noted correlation ids; the
        # rebuilt step re-notes its own.
        _trace.TAP.note_plan(
            topo_algorithm=doc.get("config", {}).get("topo_algorithm"),
            wire_dtype=doc.get("config", {}).get("wire_dtype"),
        )
    if _fault_injector.ACTIVE:
        _fault_injector.record_event(
            "driver", int(doc.get("id", 0)), "replan-adopt",
            f"id={doc.get('id')}",
        )
    logger.warning(
        "elastic: adopting live re-plan #%s at the commit boundary "
        "(%s); rebuilding the train step", doc.get("id"),
        doc.get("config"),
    )
    raise PlanUpdatedInterrupt(doc)


def adopted_replan() -> Optional[Dict[str, Any]]:
    """The last live re-plan notice this worker adopted (None before
    any). Plain data: ``{"id", "gen", "epoch", "trigger", "config",
    "modeled", ...}``."""
    return dict(_adopted_replan) if _adopted_replan else None


def adopted_step_kwargs() -> Optional[Dict[str, Any]]:
    """The ``make_train_step`` knob values the adopted re-plan maps to,
    via the SAME ``tune.tuned_step_kwargs`` translation a pinned
    ``tuned.json`` uses — so a re-planned step is bitwise-identical to
    the same knobs passed by hand. None before any adoption; training
    loops splat it when (re)building their step:

    .. code-block:: python

        kwargs = hvd.elastic.adopted_step_kwargs() or {}
        step = hvd.make_train_step(loss_fn, opt, **kwargs)
    """
    if _adopted_replan is None:
        return None
    from ..tune import TunedConfig, tuned_step_kwargs

    cfg = TunedConfig(
        knobs=dict(_adopted_replan.get("config") or {}),
        signature={}, objectives={}, baseline={},
        program="live-replan",
    )
    return tuned_step_kwargs(cfg)


# --------------------------------------------------------- hot spares
SPARE_POLL_S = 0.5


def maybe_wait_as_spare() -> bool:
    """The spare gate (docs/fault_tolerance.md "Self-driving fleet"):
    a worker spawned with ``HOROVOD_ELASTIC_SPARE=1`` holds HERE —
    before any backend or rank plumbing exists — heartbeating
    ``spare.<wid>`` on the KV plane until the driver's EXPLICIT
    ``promote.<wid>`` signal names a generation whose published world
    assigns this worker id. (The world doc alone is not enough: in
    respawn mode the first publish after a membership change is only
    the drain notification — joining it would wedge the spare on a
    doomed generation's endpoints.) Promotion applies the assignment
    env exactly like a re-rendezvous and returns True; ``hvd.init()``
    then proceeds as a normal member of that generation (the driver
    counted one generation bump, not a respawn).

    Exit conditions: the driver stops answering for the elastic timeout
    (fleet gone → exit 0), or a NEWER driver epoch appears (a resumed
    driver respawns its own spares; a stale pool must not race it for
    slots → exit 0)."""
    if os.environ.get("HOROVOD_ELASTIC_SPARE") != "1":
        return False
    from ..run.http_server import KVStoreClient

    wid = os.environ["HOROVOD_ELASTIC_WORKER_ID"]
    addr = os.environ["HOROVOD_ELASTIC_KV_ADDR"]
    port = int(os.environ["HOROVOD_ELASTIC_KV_PORT"])
    try:
        spawn_epoch = int(os.environ.get("HOROVOD_DRIVER_EPOCH", "0") or 0)
    except ValueError:
        spawn_epoch = 0
    try:
        timeout = float(os.environ.get("HOROVOD_ELASTIC_TIMEOUT", "600"))
    except ValueError:
        timeout = 600.0
    kv = KVStoreClient(addr, port)
    logger.warning(
        "elastic: spare %s parked at the spare gate (awaiting "
        "promotion)", wid,
    )
    beat = 0
    last_seen = time.monotonic()
    while True:
        world = driver = None
        promote_gen = None
        try:
            raw = kv.get("elastic", "world")
            world = json.loads(raw.decode()) if raw else None
            raw = kv.get("elastic", "driver")
            driver = json.loads(raw.decode()) if raw else None
            raw = kv.get("elastic", f"promote.{wid}")
            if raw:
                promote_gen = int(raw.decode())
        except Exception:  # noqa: BLE001 - driver briefly unreachable
            pass
        if driver is not None:
            last_seen = time.monotonic()
            try:
                epoch = int(driver.get("epoch", 0) or 0)
            except (TypeError, ValueError):
                epoch = 0
            if spawn_epoch and epoch > spawn_epoch:
                logger.warning(
                    "elastic: spare %s superseded (driver epoch %s > "
                    "spawn epoch %s); exiting — the resumed driver "
                    "spawns its own pool", wid, epoch, spawn_epoch,
                )
                sys.exit(0)
        elif time.monotonic() - last_seen > timeout:
            logger.warning(
                "elastic: spare %s saw no driver for %gs; exiting",
                wid, timeout,
            )
            sys.exit(0)
        assignments = (world or {}).get("assignments") or {}
        if (promote_gen is not None and wid in assignments
                and int((world or {}).get("gen", -1)) == promote_gen):
            a = assignments[wid]
            os.environ.update({
                "HOROVOD_RANK": str(a["rank"]),
                "HOROVOD_SIZE": str(world["size"]),
                "HOROVOD_LOCAL_RANK": str(a["local_rank"]),
                "HOROVOD_LOCAL_SIZE": str(a["local_size"]),
                "HOROVOD_CROSS_RANK": str(a["cross_rank"]),
                "HOROVOD_CROSS_SIZE": str(a["cross_size"]),
                "HOROVOD_CONTROLLER_ADDR": world["controller_addr"],
                "HOROVOD_CONTROLLER_PORT": str(world["controller_port"]),
                "HOROVOD_JAX_COORDINATOR": world["jax_coordinator"],
                "HOROVOD_ELASTIC_GEN": str(world["gen"]),
                "HOROVOD_ELASTIC_SYNC_ROOT": str(
                    world.get("sync_root", 0)
                ),
                "HOROVOD_DRIVER_EPOCH": str(
                    world.get("epoch", spawn_epoch)
                ),
            })
            os.environ.pop("HOROVOD_ELASTIC_SPARE", None)
            if _metrics.ACTIVE:
                _metrics.TAP.inc("hvd_spare_activations_total")
            if _fault_injector.ACTIVE:
                _fault_injector.record_event(
                    "driver", int(world["gen"]), "spare-adopt",
                    f"worker={wid}",
                )
            logger.warning(
                "elastic: spare %s promoted into generation %s as rank "
                "%s", wid, world["gen"], a["rank"],
            )
            return True
        beat += 1
        try:
            kv.put("elastic", f"spare.{wid}", str(beat).encode())
        except Exception:  # noqa: BLE001 - advisory heartbeat
            pass
        time.sleep(SPARE_POLL_S)


def apply_serve_scale(engine, decision):
    """Apply a serving autoscale verdict with the elastic verbs
    (docs/serving.md "Autoscale"): scale-out is the spare-promotion
    verb — a fresh DP serving replica joins the fleet — and scale-in
    the quarantine-shrink verb — the newest replica drains its current
    batch and retires. Event-logged through the fault injector's
    deterministic ledger like every other membership change, so a chaos
    diff sees serving resizes next to kills and promotions.

    Returns the replica index added/retired, or None when the engine
    refused (e.g. retiring the last replica)."""
    if decision is None:
        return None
    if decision.action == "scale-out":
        idx = engine.add_replica()
        verb = "serve-promote"
    else:
        idx = engine.retire_replica()
        verb = "serve-retire"
    if idx is None:
        return None
    if _fault_injector.ACTIVE:
        _fault_injector.record_event(
            "replica", idx, verb,
            f"reason={decision.reason} depth={decision.depth:.1f} "
            f"burn={decision.slo_burn:.3f}",
        )
    logger.warning(
        "elastic: serving %s replica %s (%s: depth=%.1f burn=%.3f)",
        "scale-out to" if verb == "serve-promote" else "scale-in of",
        idx, decision.reason, decision.depth, decision.slo_burn,
    )
    return idx


def _jax_distributed_initialize(coord: str, num: int, pid: int) -> None:
    """Stand up the JAX distributed runtime for an elastic world. Unlike
    ``jax.distributed.initialize``:

    - The coordination SERVICE is never created here — it lives in the
      elastic DRIVER process (one per world generation), so no worker is
      special: any worker, including generation rank 0, can crash without
      taking the coordination plane down with it (the reference's elastic
      driver owns the rendezvous for the same reason).
    - The client is failure-tolerant: ``recoverable=True`` (peer death is
      swallowed by the agent and surfaces as failed collectives, which the
      runtime turns into ``HorovodInternalError`` → rollback) and
      ``shutdown_on_destruction=False`` — OBJECT DESTRUCTION never issues
      the ShutdownTask RPC; the explicit, graceful
      ``_jax_distributed_teardown`` shuts the client down instead (safe
      because the driver-hosted service is alive to answer), which stops
      the error-poll/heartbeat threads before the channel dies under
      them. No ``missed_heartbeat_callback`` — the pybind functional
      bridge std::bad_cast-aborts when the agent's error-poll thread
      invokes a Python callback (jaxlib 0.9), and the driver-hosted
      service keeps heartbeats answerable for stragglers anyway."""
    from jax._src import distributed as _dist
    from jax._src.lib import _jax as _jaxlib

    state = _dist.global_state
    if state.client is not None:
        raise RuntimeError("jax distributed runtime is already initialized")
    init_timeout = int(float(
        os.environ.get("HOROVOD_ELASTIC_INIT_TIMEOUT", "120")
    ))
    heartbeat = int(float(
        os.environ.get("HOROVOD_ELASTIC_HEARTBEAT_S", "10")
    ))
    state.client = _jaxlib.get_distributed_runtime_client(
        coord, pid, init_timeout=init_timeout, use_compression=True,
        heartbeat_timeout=heartbeat,
        shutdown_on_destruction=False, recoverable=True,
    )
    logger.info("elastic: connecting to coordination service %s", coord)
    state.client.connect()
    state.process_id = pid
    state.num_processes = num
    state.coordinator_address = coord


def _jax_distributed_teardown() -> None:
    """Leave the current world. The client's background error-poll and
    heartbeat threads treat a dying channel as FATAL (client.h), so the
    client must be shut down gracefully BEFORE the object is dropped —
    safe here because the coordination service lives in the always-alive
    driver (a live endpoint to answer the ShutdownTask RPC) and the
    recoverable flag waives the shutdown barrier; a short-lived failure
    of that RPC is swallowed rather than escalated."""
    from jax._src import distributed as _dist

    state = _dist.global_state
    if state.preemption_sync_manager is not None:
        try:
            state.preemption_sync_manager.shutdown()
        except Exception:  # noqa: BLE001
            pass
        state.preemption_sync_manager = None
    if state.client is not None:
        try:
            state.client.shutdown()
        except Exception as exc:  # noqa: BLE001 - half-dead world
            logger.info("elastic: client shutdown reported %s", exc)
    state.client = None
    if state.service is not None:
        try:
            state.service.shutdown()
        except Exception:  # noqa: BLE001
            pass
        state.service = None


def _reset_jax_world() -> None:
    """Tear down the JAX distributed client and backend caches so the next
    ``hvd.init()`` can stand up a DIFFERENT world size in this process.
    (Validated: surviving processes of an N-world re-form an M-world and
    produce correct collectives after this reset.)"""
    import jax

    try:
        _jax_distributed_teardown()
    except Exception:  # noqa: BLE001 - not initialized / already gone
        pass
    try:
        jax.clear_caches()
    except Exception:  # noqa: BLE001
        pass
    try:
        from jax._src import xla_bridge as _xb

        _xb._clear_backends()
    except Exception as exc:  # noqa: BLE001 - jax internals moved
        logger.warning("could not clear XLA backends: %s", exc)


# ------------------------------------------------- rejoin-mode selection
# Exit status a worker uses to ask the driver for a fresh process instead
# of re-forming the world in-process. Must match REJOIN_EXIT_CODE in
# run/elastic_driver.py (kept as literals on both sides so the launcher
# never has to import this — jax-loading — module).
REJOIN_EXIT_CODE = 79

_rejoin_mode: Optional[str] = None


def _inprocess_rejoin_supported() -> bool:
    """In-process world re-formation rides three private JAX surfaces:
    the ``jax_enable_recoverability`` config flag (a dead peer surfaces
    on survivors as a catchable collective error, not a fatal
    coordination abort), ``xla_bridge._clear_backends`` (the next
    ``hvd.init()`` can stand up a different world size in this process),
    and the ``jax._src.lib._jax`` distributed-runtime factories (the
    recoverable client here, the driver-hosted coordination service in
    ``run/elastic_driver.py`` — older jaxlibs keep them under a
    different module name and without the ``recoverable`` kwarg). Any of
    these can vanish or move in a minor upgrade — probe them up front
    instead of finding out mid-crash-recovery."""
    try:
        import jax
        from jax._src import xla_bridge as _xb
        from jax._src.lib import _jax as _jaxlib
    except Exception:  # noqa: BLE001 - jax internals moved wholesale
        return False
    if not callable(getattr(_xb, "_clear_backends", None)):
        return False
    for factory in (
        "get_distributed_runtime_service", "get_distributed_runtime_client"
    ):
        if not callable(getattr(_jaxlib, factory, None)):
            return False
    try:
        # Attribute access raises if the flag no longer exists.
        jax.config.jax_enable_recoverability  # noqa: B018
    except Exception:  # noqa: BLE001
        return False
    return True


def rejoin_mode() -> str:
    """Active recovery mode: ``'inprocess'`` (generation-based world
    re-formation without process death — the fast path) or ``'respawn'``
    (the worker persists its last commit and exits with
    ``REJOIN_EXIT_CODE``; the driver respawns the slot and the fresh
    process resumes from the snapshot — upstream's restart semantics,
    used as the fallback when the private JAX surfaces the in-process
    path needs are absent). ``HOROVOD_ELASTIC_REJOIN_MODE`` forces
    either; the elastic driver resolves the mode once and exports it so
    every worker agrees."""
    global _rejoin_mode
    if _rejoin_mode is None:
        forced = os.environ.get(
            "HOROVOD_ELASTIC_REJOIN_MODE", "auto"
        ).lower()
        if forced == "inprocess" and not _inprocess_rejoin_supported():
            # Honoring the pin anyway would fatal-abort the first
            # crash recovery (the private JAX surfaces are absent);
            # degrade loudly instead.
            logger.warning(
                "elastic: HOROVOD_ELASTIC_REJOIN_MODE=inprocess but this "
                "jax lacks the required private surfaces; falling back "
                "to 'respawn'"
            )
            _rejoin_mode = "respawn"
        elif forced in ("inprocess", "respawn"):
            _rejoin_mode = forced
        else:
            _rejoin_mode = (
                "inprocess" if _inprocess_rejoin_supported() else "respawn"
            )
        logger.info("elastic: rejoin mode '%s'", _rejoin_mode)
    return _rejoin_mode


def _persist_path() -> Optional[str]:
    """Per-slot snapshot file in the driver-shared state dir. Keyed by
    worker id (host:local_rank), so a respawn of the same slot — on the
    same host, hence the same local filesystem — finds its predecessor's
    last commit."""
    d = os.environ.get("HOROVOD_ELASTIC_STATE_DIR")
    wid = os.environ.get("HOROVOD_ELASTIC_WORKER_ID")
    if not d or not wid:
        return None
    safe = wid.replace(":", "_").replace("/", "_")
    return os.path.join(d, f"{safe}.state.pkl")


def _persist_state_and_exit(state: "State", ctx: _ElasticContext) -> None:
    """Respawn-mode rejoin: snapshot the state to disk, signal the
    driver, and exit with the rejoin status. Never returns."""
    import pickle

    path = _persist_path()
    if path is not None:
        try:
            state.save()
            payload = _persist_payload(state)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                pickle.dump(payload, f)
            os.replace(tmp, path)
        except Exception as exc:  # noqa: BLE001 - degrade, don't hang
            logger.warning(
                "elastic: could not persist state (%s); the respawn will "
                "re-sync from a peer's snapshot instead", exc
            )
    else:
        logger.warning(
            "elastic: no HOROVOD_ELASTIC_STATE_DIR/WORKER_ID; respawn "
            "resumes from peers' snapshots only"
        )
    # The rejoin signal both tells the driver this generation is
    # abandoned and keeps its reconcile loop re-arming until a fresh
    # generation is actually published.
    ctx.signal_rejoin()
    logger.info(
        "elastic: exiting for respawn (status %d)", REJOIN_EXIT_CODE
    )
    # os._exit: the world is half-dead; a graceful interpreter shutdown
    # can hang joining runtime threads that are blocked on dead peers.
    os._exit(REJOIN_EXIT_CODE)


def _maybe_restore_persisted(state: "State") -> bool:
    """Respawn-mode startup: resume from this slot's persisted last
    commit, if any. Runs before the first sync so a restored snapshot is
    what a sync_root broadcasts (every rank's last commit is the same
    step — commits reach cross-rank agreement before returning).
    Returns True when a snapshot was restored."""
    import pickle

    path = _persist_path()
    if path is None or not os.path.exists(path):
        return False
    try:
        with open(path, "rb") as f:
            payload = pickle.load(f)
    except Exception as exc:  # noqa: BLE001 - torn write, stale format
        # Quarantine the broken snapshot instead of warning and
        # re-reading the same bytes every generation: renamed aside it
        # can never be retried (or mistaken for live state by a later
        # respawn), while staying on disk for post-mortem.
        quarantined = f"{path}.corrupt"
        try:
            os.replace(path, quarantined)
            logger.error(
                "elastic: unreadable persisted state (%s); quarantined "
                "to %s — this slot resumes from a peer's snapshot",
                exc, quarantined,
            )
        except OSError as mv_exc:
            logger.warning(
                "elastic: unreadable persisted state (%s); could not "
                "quarantine it either (%s)", exc, mv_exc,
            )
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_elastic_snapshot_quarantined_total")
        return False
    # Layout preflight BEFORE any state is applied: a world-size change
    # between save and restore either reshards the snapshot's sharded
    # zero1 state here or fails with an error naming both layouts —
    # never the deep zero.py axis-size ValueError mid-step.
    payload = _preflight_snapshot_layout(state, payload, path)
    _apply_payload(state, payload)
    state.restore()
    logger.info("elastic: restored persisted state from %s", path)
    return True


def _warn_if_unrestored(restored_any: bool) -> None:
    """Respawn-mode data-loss guard (advisor finding): a restart at
    generation > 1 means a previous world made progress, so when NO rank
    restored a snapshot the job is silently starting over from step 0.
    Shout about it — or, with ``HOROVOD_ELASTIC_REQUIRE_SNAPSHOT`` set,
    fail the worker instead of losing data quietly."""
    if restored_any:
        return
    try:
        gen = int(os.environ.get("HOROVOD_ELASTIC_GEN", "1") or 1)
    except ValueError:
        gen = 1
    if gen <= 1:
        return  # a genuine from-scratch start
    msg = (
        f"elastic: restart generation {gen} found no restored snapshot "
        "on ANY rank — training resumes from step 0 and all progress "
        "since the last commit is LOST. Check that "
        "HOROVOD_ELASTIC_STATE_DIR survives respawns (shared or "
        "host-local persistent storage)."
    )
    if os.environ.get(
        "HOROVOD_ELASTIC_REQUIRE_SNAPSHOT", ""
    ).strip().lower() in ("1", "true", "yes", "on"):
        raise RuntimeError(
            msg + " Failing because HOROVOD_ELASTIC_REQUIRE_SNAPSHOT is "
            "set."
        )
    logger.error(msg)
    if _metrics.ACTIVE:
        _metrics.TAP.inc("hvd_elastic_unrestored_restarts_total")


def _elect_restored_sync_root(ctx: _ElasticContext, restored: bool) -> None:
    """Respawn-mode guard against silent progress loss: the driver picks
    a sync_root before workers spawn, so it cannot know which slots will
    actually find a snapshot (rank 0's host may be a fresh replacement,
    or its pickle may be torn). A tiny allgather of per-rank restored
    flags re-elects the sync source onto the first rank that DID restore
    — identical on every rank, so the broadcast stays consistent — and
    only keeps the driver's choice when nobody restored (a genuine
    from-scratch restart)."""
    import horovod_tpu as hvd

    if hvd.size() <= 1:
        _warn_if_unrestored(restored)
        return
    flags = hvd.allgather_object(bool(restored), name="hvd.elastic.snap")
    _warn_if_unrestored(any(flags))
    if not flags[ctx.sync_root] and any(flags):
        new_root = flags.index(True)
        logger.info(
            "elastic: sync root %d has no snapshot; re-electing rank %d "
            "(restored)", ctx.sync_root, new_root,
        )
        ctx.sync_root = new_root


def _persist_payload(state: "State") -> Dict[str, Any]:
    """Everything a ``save()`` produced, generically: every ``_saved*``
    attribute. ObjectState keeps the tracked dict in ``_saved``;
    subclasses add their own snapshot attrs (TorchState
    ``_saved_model``/``_saved_opt``, TensorFlowState ``_saved_vars``,
    TensorFlowKerasState ``_saved_weights``/``_saved_opt_vars``) — an
    allowlist here would silently drop any of them and a respawn would
    resume with reinitialized weights under a restored step counter.

    The snapshot is stamped with its world layout (``__layout__``: the
    saving world size plus any attached ZeRO-1 bucket layouts) so a
    restore at a DIFFERENT world size can preflight the mismatch and
    route sharded state through ``parallel/reshard`` instead of dying
    at the zero.py axis-size raise mid-step. Older readers ignore the
    key (``_apply_payload`` only consumes ``_saved*``)."""
    payload = {
        k: v for k, v in vars(state).items() if k.startswith("_saved")
    }
    payload["__layout__"] = _snapshot_layout_stamp(state)
    return payload


def _zero1_shard_dims(payload: Dict[str, Any]) -> Dict[str, int]:
    """``{payload_key/tree_path: leading shard count}`` for every
    Zero1State found inside the ``_saved*`` snapshot values."""
    try:
        from ..parallel.zero import Zero1State
    except Exception:  # noqa: BLE001 - jax-free install
        return {}

    dims: Dict[str, int] = {}

    def scan(prefix: str, node: Any) -> None:
        if isinstance(node, Zero1State):
            for leaf in _tree_leaves(node.opt):
                shape = getattr(leaf, "shape", ())
                if len(shape) >= 1:
                    dims[prefix] = int(shape[0])
                    return
            dims[prefix] = 0
            return
        if isinstance(node, dict):
            for k, v in node.items():
                scan(f"{prefix}/{k}" if prefix else str(k), v)
        elif isinstance(node, (list, tuple)):
            for i, v in enumerate(node):
                scan(f"{prefix}/{i}" if prefix else str(i), v)

    for key, value in payload.items():
        if key.startswith("_saved"):
            scan(key, value)
    return dims


def _tree_leaves(node: Any):
    import jax

    return jax.tree.leaves(node)


def _snapshot_layout_stamp(state: "State") -> Dict[str, Any]:
    try:
        world = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
    except ValueError:
        world = 1
    layouts = getattr(state, "zero1_layout", None) or {}
    serialized = {}
    for attr, lay in dict(layouts).items():
        serialized[str(attr)] = (
            lay.to_dict() if hasattr(lay, "to_dict") else dict(lay)
        )
    return {"world": world, "zero1_layout": serialized}


def note_zero1_layout(state: "State", attr: str, layout: Any) -> None:
    """Attach the ZeRO-1 bucket layout of tracked attribute ``attr``
    (from ``parallel/reshard.zero1_layout_from_params``) to ``state`` so
    elastic snapshots and in-process resizes can reshard it across a
    world-shape change. Without a layout, a resize with sharded state
    refuses loudly instead of silently corrupting shard offsets."""
    layouts = getattr(state, "zero1_layout", None)
    if layouts is None:
        layouts = {}
        state.zero1_layout = layouts
    layouts[str(attr)] = layout


def _preflight_snapshot_layout(state: "State",
                               payload: Dict[str, Any],
                               path: str) -> Dict[str, Any]:
    """Respawn-mode layout preflight: a snapshot persisted at one world
    size restoring into a DIFFERENT one used to surface as a deep
    ``zero.py`` ValueError ("optimizer state is sharded N ways...") on
    the first post-restore step. Instead: compare the snapshot's
    recorded layout against the new generation here, reshard every
    Zero1State through ``parallel/reshard`` when a bucket layout is
    available, and otherwise raise an error naming BOTH layouts."""
    dims = _zero1_shard_dims(payload)
    stamp = payload.get("__layout__") or {}
    snap_world = stamp.get("world")
    try:
        cur = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
    except ValueError:
        cur = 1
    if not dims:
        return payload  # replicated snapshot: any world size fits
    mismatched = {k: n for k, n in dims.items() if n != cur}
    if not mismatched:
        return payload
    layouts = dict(stamp.get("zero1_layout") or {})
    if not layouts:
        raise RuntimeError(
            f"elastic: snapshot {path} holds ZeRO-1 state sharded for "
            f"a different world: snapshot layout (world="
            f"{snap_world if snap_world is not None else '?'}, shards "
            f"{dims}) vs new generation layout (world={cur}) — and no "
            f"bucket layout was recorded to reshard it. Attach one with "
            f"hvd.elastic.note_zero1_layout(state, attr, "
            f"zero1_layout_from_params(...)) before the first commit, "
            f"or restore from a sharded checkpoint "
            f"(docs/fault_tolerance.md 'Elastic resharding')."
        )
    from ..parallel import reshard as _reshard

    out = dict(payload)
    for key in list(out):
        if not key.startswith("_saved"):
            continue
        value = out[key]
        if not isinstance(value, dict):
            continue
        new_value = dict(value)
        for attr, sub in value.items():
            attr_dims = _zero1_shard_dims({"_saved": {attr: sub}})
            if not attr_dims:
                continue
            lay = layouts.get(str(attr))
            if lay is None:
                raise RuntimeError(
                    f"elastic: snapshot {path} attr {attr!r} holds "
                    f"ZeRO-1 state sharded {sorted(set(attr_dims.values()))}"
                    f" ways (snapshot world="
                    f"{snap_world if snap_world is not None else '?'}) "
                    f"but the new generation has world={cur} and no "
                    f"bucket layout was recorded for {attr!r} "
                    f"(known: {sorted(layouts)}) — attach one with "
                    f"hvd.elastic.note_zero1_layout."
                )
            resharded, reports = _reshard.reshard_zero1_tree(
                sub, cur, layouts={"": lay}, trigger="snapshot-restore",
            )
            new_value[attr] = resharded
            for rep in reports:
                logger.info(
                    "elastic: resharded snapshot attr %r zero1 state "
                    "%d->%d shards (%d bytes)", attr, rep["n_old"],
                    rep["n_new"], rep["moved_bytes"],
                )
        out[key] = new_value
    # Re-stamp for the world we just resharded into.
    new_layouts = {
        a: _reshard.Zero1Layout.from_dict(l).relayout(cur).to_dict()
        for a, l in layouts.items()
    }
    out["__layout__"] = {"world": cur, "zero1_layout": new_layouts}
    state.zero1_layout = {
        a: _reshard.Zero1Layout.from_dict(l) for a, l in new_layouts.items()
    }
    return out


def _apply_payload(state: "State", payload: Dict[str, Any]) -> None:
    if "tracked" in payload and "_saved" not in payload:
        payload = dict(payload)
        payload["_saved"] = payload.pop("tracked")  # pre-r5 layout
    for k, v in payload.items():
        if k.startswith("_saved"):
            setattr(state, k, v)


def _clear_persisted() -> None:
    path = _persist_path()
    if path is not None and os.path.exists(path):
        try:
            os.remove(path)
        except OSError:
            pass


def _rejoin(ctx: _ElasticContext) -> None:
    """Leave the current (broken or stale) world and join the next
    generation: wait for the driver to publish gen > current with this
    worker in it, then re-init. A worker dropped from the new world exits
    cleanly (the driver also terminates it as a backstop)."""
    import horovod_tpu as hvd

    ctx.signal_rejoin()
    try:
        hvd.shutdown()
    except Exception:  # noqa: BLE001 - already torn down
        pass
    _reset_jax_world()
    deadline = time.monotonic() + ctx.timeout
    while True:
        if time.monotonic() > deadline:
            raise RuntimeError(
                "elastic: no usable world generation within "
                f"{ctx.timeout}s (last known gen {ctx.gen})"
            )
        world = None
        try:
            world = ctx.fetch_world()
        except Exception:  # noqa: BLE001 - driver briefly unreachable
            pass
        if not world or int(world["gen"]) <= ctx.gen:
            time.sleep(0.2)
            continue
        if not ctx.apply(world):
            # Scaled down past this worker: graceful departure.
            logger.info(
                "elastic: worker %s not in generation %s; exiting",
                ctx.worker_id, world["gen"],
            )
            sys.exit(0)
        try:
            hvd.init()
            ctx.gen = int(world["gen"])  # committed only on success
            if _trace.ACTIVE:
                # Ranks are renumbered in the new generation: restart
                # the step ledger so the driver's skew attribution never
                # compares step indices across a resize (a removed rank
                # must not be charged for a stranger's steps).
                _trace.TAP.reset_steps()
            # A re-plan notice is generation-scoped; whatever was
            # pending died with the old world.
            ctx._pending_replan = None
            # A resumed driver supervising adopted workers has no
            # process handle on this rank: the attach signal (stamped
            # with the generation + acknowledged epoch) is how it learns
            # the rejoin landed.
            ctx.signal_attach()
            if _metrics.ACTIVE:
                _metrics.TAP.inc("hvd_elastic_rejoins_total")
            return
        except Exception as exc:  # noqa: BLE001 - racing another bump
            logger.warning(
                "elastic: init at gen %s failed (%s); retrying",
                world["gen"], exc,
            )
            try:
                hvd.shutdown()
            except Exception:  # noqa: BLE001
                pass
            _reset_jax_world()
            time.sleep(0.5)


_sync_root_override: Optional[int] = None


def _sync_root() -> int:
    """Rank whose state is authoritative for the current generation: a
    survivor of the previous world (published by the driver), so a fresh
    respawn that happened to land on rank 0 can never broadcast its
    just-constructed state over everyone's progress. The digest guard's
    heal path overrides it transiently (``_sync_root_as``) to
    re-broadcast from the agreeing quorum's reference rank."""
    if _sync_root_override is not None:
        return _sync_root_override
    ctx = _ctx()
    return ctx.sync_root if ctx is not None else 0


@contextlib.contextmanager
def _sync_root_as(root: int):
    """Temporarily force the sync root (digest-guard healing): every rank
    enters this context with the SAME root, so the broadcasts stay
    collective."""
    global _sync_root_override
    prev = _sync_root_override
    _sync_root_override = int(root)
    try:
        yield
    finally:
        _sync_root_override = prev


# ----------------------------------------------------------------- state
class State:
    """Base class for elastic state (upstream ``horovod.elastic.State``):
    ``commit()`` snapshots + checks for membership changes,
    ``restore()`` rolls back to the last commit, ``sync()`` aligns all
    ranks to rank 0's state after a re-rendezvous."""

    def __init__(self) -> None:
        self._reset_callbacks: List[Callable[[], None]] = []
        # Commit counter for the parameter-digest guard
        # (HOROVOD_GUARD_DIGEST_STEPS; docs/fault_tolerance.md).
        self._guard_commits = 0

    def register_reset_callbacks(
        self, callbacks: List[Callable[[], None]]
    ) -> None:
        """Callbacks invoked after each world re-formation (learning-rate
        rescale, dataset re-partition, ...)."""
        self._reset_callbacks.extend(callbacks)

    def on_reset(self) -> None:
        for cb in self._reset_callbacks:
            cb()

    def commit(self) -> None:
        if _trace.ACTIVE:
            # Fleet-tracing step boundary (docs/timeline.md "Step
            # spans"): one commit == one training step for loops that
            # commit per step, so the inter-commit window doubles as the
            # step span feeding the driver's skew attribution — unless a
            # wrap_step tap already records real step spans.
            _trace.TAP.commit_step()
        if _fault_injector.ACTIVE:
            # Chaos tap: one commit == one training step; kill/preempt
            # actions with at_step target this counter.
            _fault_injector.fault_point("step")
        if _guard.ACTIVE:
            # Digest agreement BEFORE save(): a silently diverged replica
            # must never become the rollback point. Heals in place (the
            # heal's sync() snapshots) or raises for the elastic rollback.
            self._guard_check_digest()
        self.save()
        self.check_host_updates()

    def _guard_check_digest(self) -> None:
        """Periodic cross-rank parameter-digest agreement
        (docs/fault_tolerance.md "Data-plane integrity"): every
        ``HOROVOD_GUARD_DIGEST_STEPS`` commits, hash the tracked state,
        allgather the digests (bytes, not payloads), and on mismatch
        self-heal — re-broadcast from the agreeing quorum's reference
        rank, or roll back to the last commit when no quorum exists."""
        steps = _guard.digest_steps()
        if steps <= 0:
            return
        self._guard_commits += 1
        if self._guard_commits % steps:
            return
        import horovod_tpu as hvd

        if not hvd.is_initialized() or hvd.size() <= 1:
            return
        from ..guard import digest as _digest

        mine = _digest.state_digest(self)
        digests = hvd.allgather_object(mine, name="hvd.guard.digest")
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_guard_digest_checks_total")
        ok, ref, outliers = _digest.find_quorum(
            digests,
            no_quorum=_guard.no_quorum_action(),
            sync_root=_sync_root(),
        )
        if ok:
            return
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_guard_digest_mismatches_total")
        rt = getattr(hvd, "_runtime", None)
        tl = getattr(rt, "timeline", None)
        if tl is not None and getattr(tl, "initialized", False):
            tl.metadata(
                "hvd_guard_digest_mismatch",
                {"outliers": outliers, "reference": ref},
            )
        if ref is None:
            _guard.record_guard_event(
                "digest-rollback", f"outliers={outliers}"
            )
            if _metrics.ACTIVE:
                _metrics.TAP.inc("hvd_guard_rollbacks_total")
            raise hvd.HorovodInternalError(
                "parameter digest mismatch across ranks "
                f"{outliers} with no agreeing quorum "
                "(HOROVOD_GUARD_DIGEST_STEPS guard); rolling back to the "
                "last commit"
            )
        _guard.record_guard_event(
            "digest-heal", f"ref={ref} outliers={outliers}"
        )
        logger.error(
            "digest guard: ranks %s diverged from the quorum; healing by "
            "re-broadcast from rank %d", outliers, ref,
        )
        # Heal: every rank (agreeing and diverged alike) re-syncs from
        # the reference — the broadcasts are collective. sync() also
        # save()s, so the healed state becomes the new rollback point.
        with _sync_root_as(ref):
            self.sync()
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_guard_heals_total")

    # Per-rank vote bits for the commit-time agreement allreduce: each
    # rank ORs its local observations into one int32 and the fleet
    # agrees with op=Max (same idiom as the park-outcome agreement).
    # The decision ladder only acts on the strongest signal present, so
    # Max losing weaker bits is harmless — and unlike a weighted Sum the
    # scheme is rank-count independent (no overflow band to outgrow).
    # Ordered by severity: a pending re-plan is the WEAKEST signal (a
    # membership change, preemption, or driver loss each makes the
    # notice moot — the next generation re-plans on fresh evidence).
    _REPLAN_BIT = 1
    _UPDATED_BIT = 2
    _PREEMPT_BIT = 4
    _LOST_BIT = 8

    def check_host_updates(self) -> None:
        """Raise ``HostsUpdatedInterrupt`` on EVERY rank when any rank has
        seen a newer world generation — agreement by allreduce so no rank
        runs ahead into a collective its peers abandoned. A pending
        preemption notice rides the same agreement: the preempted rank
        raises ``PreemptionInterrupt`` (drain + rejoin with the state just
        committed), its peers a plain membership interrupt.

        The same probe doubles as the driver heartbeat/epoch check
        (docs/fault_tolerance.md "Control-plane availability"): when any
        rank has lost the driver, ALL ranks park at this commit boundary
        (state held, collectives quiesced) and reconnect/reattach; a
        driver that restarted without ever dropping off (epoch advanced,
        same generation) is reattached in place — a purely local act."""
        ctx = _ctx()
        if ctx is None:
            return
        import numpy as np

        import horovod_tpu as hvd

        preempted = _preemption.preemption_requested()
        updated, lost, new_epoch = ctx.commit_probe()
        replan = ctx.check_replan()
        if new_epoch is not None and not (lost or updated or preempted):
            ctx.reattach(new_epoch)
        flag = np.asarray(
            [(self._LOST_BIT if lost else 0)
             | (self._PREEMPT_BIT if preempted else 0)
             | (self._UPDATED_BIT if updated else 0)
             | (self._REPLAN_BIT if replan else 0)],
            np.int32,
        )
        if hvd.size() > 1:
            flag = np.asarray(
                hvd.allreduce(flag, op=hvd.Max, name="hvd.elastic.hostcheck")
            )
        agreed = int(flag[0])
        if preempted:
            raise PreemptionInterrupt(
                _preemption.preemption_reason() or "preemption notice"
            )
        if agreed >= self._LOST_BIT:
            _park_and_reattach(ctx, self)
            return
        if agreed >= self._PREEMPT_BIT:
            raise HostsUpdatedInterrupt(
                "a peer rank received a preemption notice; re-forming "
                "the world"
            )
        if agreed >= self._UPDATED_BIT:
            raise HostsUpdatedInterrupt(
                "host membership changed; re-forming the world"
            )
        if agreed >= self._REPLAN_BIT:
            _adopt_replan(ctx)

    # subclass responsibilities
    def save(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError

    def sync(self) -> None:
        raise NotImplementedError


class ObjectState(State):
    """State over arbitrary picklable attributes
    (``ObjectState(batch=0, epoch=0)``); sync ships rank 0's values with
    the object-allgather wire."""

    def __init__(self, **kwargs: Any) -> None:
        super().__init__()
        self._tracked = sorted(kwargs)
        for k, v in kwargs.items():
            setattr(self, k, v)
        # Snapshot through the subclass's save() (JaxState needs its
        # device_get host copies, not deepcopied device arrays): a
        # rollback can happen before the first commit — e.g. a peer dies
        # during the initial sync — and restore() must already hold
        # backend-independent state.
        self._saved: Dict[str, Any] = {}
        self.save()

    def save(self) -> None:
        self._saved = {
            k: copy.deepcopy(getattr(self, k)) for k in self._tracked
        }

    def restore(self) -> None:
        for k, v in self._saved.items():
            self._assign(k, copy.deepcopy(v))

    def _assign(self, key: str, new: Any) -> None:
        """Bind ``new`` as the value of tracked attribute ``key``,
        mutating the existing object IN PLACE when it is a mutable
        container or a plain instance of the same class.

        External references must stay valid across rollbacks and
        re-formations: the documented ``DataLoader(sampler=sampler)``
        pattern (torch/elastic.py) holds the sampler object directly, so
        rebinding the attribute to a freshly-unpickled copy would leave
        the loader iterating stale state while commits snapshot the new
        object. The upstream reference mutates samplers in place via its
        state handlers for exactly this reason
        (ref: horovod/common/elastic.py state-handler design).

        ``new`` is always a throwaway (an unpickled wire copy or a
        deepcopy of a snapshot), so adopting its internals is safe.
        """
        cur = getattr(self, key, None)
        if cur is new:
            return
        if cur is not None and type(cur) is type(new):
            if isinstance(cur, dict):
                cur.clear()
                cur.update(new)
                return
            if isinstance(cur, list):
                cur[:] = new
                return
            if isinstance(cur, set):
                cur.clear()
                cur.update(new)
                return
            d_cur = getattr(cur, "__dict__", None)
            d_new = getattr(new, "__dict__", None)
            if isinstance(d_cur, dict) and isinstance(d_new, dict):
                d_cur.clear()
                d_cur.update(d_new)
                return
        setattr(self, key, new)

    @staticmethod
    def _is_sampler(v: Any) -> bool:
        # Duck-typed ElasticSampler (torch/elastic.py) — its processed
        # set is PER-RANK state that must union across ranks, not be
        # overwritten by the sync source's copy.
        return hasattr(v, "processed") and hasattr(v, "record_batch")

    def sync(self) -> None:
        import horovod_tpu as hvd

        if hvd.size() > 1:
            sampler_keys = [
                k for k in self._tracked
                if self._is_sampler(getattr(self, k))
            ]
            # Capture every rank's processed indices BEFORE the broadcast
            # overwrites the samplers (upstream's SamplerStateHandler
            # unions the same way): each rank trained a disjoint shard,
            # so resume-without-repeat needs the union.
            merged = (
                hvd.allgather_object(
                    {k: sorted(getattr(self, k).processed)
                     for k in sampler_keys},
                    name="hvd.elastic.sampsync",
                )
                if sampler_keys else []
            )
            values = {k: getattr(self, k) for k in self._tracked}
            synced = hvd.broadcast_object(
                values, root_rank=_sync_root(),
                name="hvd.elastic.objsync",
            )
            for k, v in synced.items():
                self._assign(k, v)
            for k in sampler_keys:
                s = getattr(self, k)
                s.processed = set().union(
                    *[set(m[k]) for m in merged]
                )
                s._local_order = []
        self.save()


def _broadcast_skipping_rank_local(hvd, tree: Any, root: int) -> Any:
    """Broadcast an array pytree from ``root`` WITHOUT clobbering
    rank-local nodes: Zero1State shard rows and EF residuals are
    distinct per rank by construction (the same leaves
    ``guard/digest.strip_rank_local`` excludes from cross-rank
    agreement), so a whole-tree broadcast would overwrite every rank's
    shards with the root's. Replicated leaves broadcast as before; an
    EFState's ``inner`` (cross-rank optimizer state) still syncs, only
    its ``residual`` stays local."""
    import jax

    try:
        from ..ops.quantized import EFState
        from ..parallel.zero import Zero1State
    except Exception:  # noqa: BLE001 - partial install
        return hvd.broadcast_variables(tree, root_rank=root)

    def is_rank_local(n: Any) -> bool:
        return isinstance(n, (Zero1State, EFState))

    leaves, treedef = jax.tree.flatten(tree, is_leaf=is_rank_local)
    if not any(is_rank_local(l) for l in leaves):
        return hvd.broadcast_variables(tree, root_rank=root)
    plain_idx = [i for i, l in enumerate(leaves) if not is_rank_local(l)]
    if plain_idx:
        synced = hvd.broadcast_variables(
            [leaves[i] for i in plain_idx], root_rank=root
        )
        for i, v in zip(plain_idx, synced):
            leaves[i] = v
    for i, l in enumerate(leaves):
        if isinstance(l, EFState) and l.inner is not None:
            leaves[i] = EFState(
                inner=_broadcast_skipping_rank_local(hvd, l.inner, root),
                residual=l.residual,
            )
    return jax.tree.unflatten(treedef, leaves)


class JaxState(ObjectState):
    """State whose attributes are JAX pytrees (params, opt_state, plus
    plain counters). Array-leaf pytrees sync with fused tensor broadcasts
    (``broadcast_variables``); everything else rides the object wire.
    Saves are host-side snapshots (``jax.device_get``) so a rollback
    survives device-state teardown across generations."""

    def save(self) -> None:
        import jax

        self._saved = {
            k: jax.device_get(getattr(self, k)) for k in self._tracked
        }

    def sync(self) -> None:
        import jax

        import horovod_tpu as hvd

        if hvd.size() > 1:
            arrays = {}
            objects = {}
            for k in self._tracked:
                v = getattr(self, k)
                leaves = jax.tree.leaves(v)
                if leaves and all(hasattr(l, "shape") for l in leaves):
                    arrays[k] = v
                else:
                    # Plain counters / mixed pytrees ride the object wire.
                    objects[k] = v
            root = _sync_root()
            for k in sorted(arrays):
                setattr(
                    self, k,
                    _broadcast_skipping_rank_local(
                        hvd, arrays[k], root
                    ),
                )
            if objects:
                synced = hvd.broadcast_object(
                    objects, root_rank=root, name="hvd.elastic.objsync"
                )
                for k, v in synced.items():
                    self._assign(k, v)
        self.save()


class TorchState(ObjectState):
    """State over a torch model + optimizer (plus plain counters):
    upstream ``horovod.torch.elastic.TorchState`` role. save/restore use
    ``state_dict`` deep copies; sync broadcasts rank 0's parameters and
    optimizer state with the existing torch binding."""

    def __init__(self, model=None, optimizer=None, **kwargs: Any) -> None:
        self.model = model
        self.optimizer = optimizer
        # ObjectState.__init__ takes the initial snapshot via save().
        super().__init__(**kwargs)

    def save(self) -> None:
        super().save()
        if self.model is not None:
            self._saved_model = copy.deepcopy(self.model.state_dict())
        if self.optimizer is not None:
            self._saved_opt = copy.deepcopy(self.optimizer.state_dict())

    def _reset_optimizer_handles(self) -> None:
        # A DistributedOptimizer's in-flight allreduce handles reference
        # a dead world after a rollback; a failure raised OUTSIDE its own
        # synchronize() (e.g. a logging allreduce between backward and
        # step) leaves them set, and the next zero_grad() would refuse.
        reset = getattr(self.optimizer, "reset", None)
        if callable(reset):
            reset()

    def restore(self) -> None:
        super().restore()
        self._reset_optimizer_handles()
        if self.model is not None:
            self.model.load_state_dict(self._saved_model)
        if self.optimizer is not None:
            self.optimizer.load_state_dict(self._saved_opt)

    def sync(self) -> None:
        import horovod_tpu as hvd

        self._reset_optimizer_handles()
        if hvd.size() > 1:
            import horovod_tpu.torch as hvd_torch

            root = _sync_root()
            if self.model is not None:
                hvd_torch.broadcast_parameters(
                    self.model.state_dict(), root_rank=root
                )
            if self.optimizer is not None:
                hvd_torch.broadcast_optimizer_state(
                    self.optimizer, root_rank=root
                )
        super().sync()


class TensorFlowState(ObjectState):
    """State over raw ``tf.Variable`` collections (upstream
    ``horovod.tensorflow.elastic.TensorFlowState`` role): pass the
    variables plus plain counters. ``variables`` may be a CALLABLE
    (e.g. ``lambda: model.trainable_variables``) so lazily built
    variables are picked up at every save/restore/sync; a plain list is
    frozen at construction — commit after the model is built, or a
    count mismatch is warned about and the optimizer-style half-restore
    skipped. sync broadcasts the sync root's values through the TF
    binding's ``broadcast_variables``."""

    def __init__(self, variables=None, **kwargs: Any) -> None:
        self.variables = (
            variables if callable(variables)
            else list(variables) if variables is not None else []
        )
        super().__init__(**kwargs)

    def _vars(self) -> list:
        return list(self.variables() if callable(self.variables)
                    else self.variables)

    def save(self) -> None:
        super().save()
        import numpy as np

        self._saved_vars = [np.array(v) for v in self._vars()]

    def restore(self) -> None:
        cur = self._vars()
        if len(cur) != len(self._saved_vars):
            # Nothing is rolled back — counters included: a half-restore
            # (old counters, new weights) would silently re-apply
            # training on already-trained weights if this rank becomes
            # the sync root.
            logger.warning(
                "elastic: variable count changed since the last snapshot "
                "(%d saved vs %d now); NOTHING was rolled back — "
                "commit() after the model is built, or pass a callable "
                "so new variables are tracked",
                len(self._saved_vars), len(cur),
            )
            return
        super().restore()
        for var, val in zip(cur, self._saved_vars):
            var.assign(val)

    def sync(self) -> None:
        import horovod_tpu as hvd

        cur = self._vars()
        if hvd.size() > 1 and cur:
            from ..tensorflow import broadcast_variables as _tf_bcast

            _tf_bcast(cur, root_rank=_sync_root())
        super().sync()


class TensorFlowKerasState(ObjectState):
    """State over a Keras model (plus plain counters): upstream
    ``horovod.elastic.TensorFlowKerasState`` role. save/restore use
    weight-array copies; sync broadcasts rank 0's weights (and the
    optimizer's variables when it exposes any) with the numpy wire."""

    def __init__(self, model, optimizer=None, **kwargs: Any) -> None:
        self.model = model
        self.optimizer = optimizer or getattr(model, "optimizer", None)
        # ObjectState.__init__ takes the initial snapshot via save().
        super().__init__(**kwargs)

    @staticmethod
    def _opt_vars(optimizer):
        # Keras 3 exposes .variables; tf-keras 2 .weights.
        for attr in ("variables", "weights"):
            v = getattr(optimizer, attr, None)
            if v:
                return list(v)
        return []

    def save(self) -> None:
        super().save()
        import numpy as np

        self._saved_weights = [
            np.array(w) for w in self.model.get_weights()
        ]
        if self.optimizer is not None:
            self._saved_opt_vars = [
                np.array(v) for v in self._opt_vars(self.optimizer)
            ]

    def restore(self) -> None:
        super().restore()
        self.model.set_weights(self._saved_weights)
        if self.optimizer is not None:
            ovars = self._opt_vars(self.optimizer)
            if len(ovars) == len(self._saved_opt_vars):
                for var, val in zip(ovars, self._saved_opt_vars):
                    var.assign(val)
            else:
                # Keras builds slot variables lazily; a snapshot taken
                # before the first apply cannot restore them. The weights
                # ARE rolled back — warn that momentum/iteration state is
                # not, instead of silently half-restoring.
                logger.warning(
                    "elastic: optimizer variable count changed since the "
                    "last snapshot (%d saved vs %d now); optimizer state "
                    "was NOT rolled back — commit() after the first "
                    "optimizer step to make it restorable",
                    len(self._saved_opt_vars), len(ovars),
                )

    def sync(self) -> None:
        import numpy as np

        import horovod_tpu as hvd

        if hvd.size() > 1:
            root = _sync_root()
            synced = hvd.broadcast_variables(
                [np.asarray(w) for w in self.model.get_weights()],
                root_rank=root,
            )
            self.model.set_weights([np.asarray(w) for w in synced])
            if self.optimizer is not None:
                ovars = self._opt_vars(self.optimizer)
                if ovars:
                    vals = hvd.broadcast_variables(
                        [np.asarray(v) for v in ovars], root_rank=root
                    )
                    for var, val in zip(ovars, vals):
                        var.assign(np.asarray(val))
        super().sync()


def _is_collective_failure(exc: BaseException) -> bool:
    """True when ``exc`` is (or wraps) a failed collective. Framework
    runtimes re-raise our op failures under their own exception types —
    a TF async op kernel fails a ``tf.function`` step with
    ``tf.errors.InternalError`` carrying the collective's message — so
    the elastic wrapper matches on origin + message, not only on
    ``HorovodInternalError`` (upstream's TF elastic does the same)."""
    import horovod_tpu as hvd

    if isinstance(exc, hvd.HorovodInternalError):
        return True
    if type(exc).__module__.partition(".")[0] == "tensorflow":
        # Only failures our own runtime emits into failed op kernels — a
        # deterministic user error inside a horovod-named op (shape
        # mismatch, unregistered op) must SURFACE, not spin the rollback
        # loop forever. Every graph-op failure carries the stable
        # [hvd-collective-failure] prefix (graph_ops.finish_error); the
        # remaining substrings cover enqueue-time raises that reach TF
        # before an op kernel exists.
        msg = str(exc)
        return ("[hvd-collective-failure]" in msg
                or "Horovod control plane" in msg
                or "Horovod has been shut down" in msg
                or "lost a peer rank" in msg
                or "lost the coordinator" in msg
                # Enqueue raced the teardown of a dying world:
                or "core is not running" in msg
                or "Horovod runtime is shut down" in msg)
    return False


# ------------------------------------------------------------------- run
def run(func: Callable) -> Callable:
    """Decorator making ``func(state, *args)`` elastic (upstream
    ``hvd.elastic.run``). On ``HorovodInternalError`` (peer failure) the
    state rolls back to the last commit; on ``HostsUpdatedInterrupt``
    (graceful membership change) it is kept. Either way the worker
    re-rendezvouses with the next world generation, re-syncs from the new
    rank 0, fires reset callbacks, and re-enters ``func``.

    Outside an elastic launch (no ``--host-discovery-script``/``--min-np``)
    the wrapper is a plain call."""

    @functools.wraps(func)
    def wrapper(state: State, *args: Any, **kwargs: Any) -> Any:
        import horovod_tpu as hvd

        ctx = _ctx()
        if ctx is None:
            return func(state, *args, **kwargs)
        mode = rejoin_mode()
        if os.environ.get(
            "HOROVOD_PREEMPTION_GRACEFUL", "1"
        ).strip().lower() not in ("0", "false", "no", "off"):
            # SIGTERM is the platform's maintenance/preemption notice:
            # turn it into a graceful drain (commit → drain → rejoin)
            # instead of an instant death. The driver's SIGKILL escalation
            # still bounds a worker that never reaches another commit.
            _preemption.install_sigterm_handler()
        if mode == "respawn":
            restored = _maybe_restore_persisted(state)
            _elect_restored_sync_root(ctx, restored)
        while True:
            try:
                state.sync()
                # From here this worker holds live state: eligible as a
                # future generation's sync source.
                ctx.confirm_joined()
                result = func(state, *args, **kwargs)
                # A resumed (adopting) driver cannot see this process
                # exit; the done signal is its completion record.
                ctx.signal_done()
                if mode == "respawn":
                    # Clean finish: a leftover snapshot must not
                    # resurrect into an unrelated later job on this slot.
                    _clear_persisted()
                return result
            except PlanUpdatedInterrupt as exc:
                # A live re-plan is NOT a membership change: the world
                # (and the committed state) is intact, so no rollback,
                # no re-rendezvous, no reset callbacks — re-enter the
                # training function so it rebuilds its step from
                # adopted_step_kwargs(). The loop-top sync() keeps the
                # re-entry collective (every rank adopted at the same
                # commit boundary).
                logger.warning(
                    "elastic: %s; re-entering the training function", exc
                )
                continue
            except HostsUpdatedInterrupt:
                if _metrics.ACTIVE:
                    _metrics.TAP.inc("hvd_elastic_host_interrupts_total")
                logger.info(
                    "elastic: membership change; rejoining with current "
                    "state"
                )
            except PreemptionInterrupt as exc:
                # The notice was observed inside commit(): the state is
                # already saved. Keep it (no rollback), drain the
                # in-flight collectives with the runtime teardown below
                # (_persist_state_and_exit / _rejoin both shut the
                # runtime down), and rejoin through the elastic path.
                if _metrics.ACTIVE:
                    _metrics.TAP.inc("hvd_elastic_preemptions_total")
                logger.warning(
                    "elastic: preemption notice (%s); draining and "
                    "rejoining with the just-committed state", exc,
                )
                _preemption.clear()
            except Exception as exc:  # noqa: BLE001 - filtered below
                if not _is_collective_failure(exc):
                    raise
                if _metrics.ACTIVE:
                    _metrics.TAP.inc("hvd_elastic_rollbacks_total")
                logger.warning(
                    "elastic: collective failure (%s); rolling back to the "
                    "last commit and rejoining", exc,
                )
                state.restore()
            if mode == "respawn":
                _persist_state_and_exit(state, ctx)  # never returns
            try:
                old_size = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
            except ValueError:
                old_size = 1
            _rejoin(ctx)
            try:
                new_size = int(os.environ.get("HOROVOD_SIZE", "1") or 1)
            except ValueError:
                new_size = 1
            if new_size != old_size:
                _reshard_state_for_world(state, old_size, new_size)
            state.on_reset()

    return wrapper


def _reshard_state_for_world(state: State, old_size: int,
                             new_size: int) -> None:
    """In-process resize (quarantine shrink, spare-promotion grow,
    scale-in/out): re-stack every tracked Zero1State attribute — and its
    host snapshot — onto the new world size via ``parallel/reshard``,
    instead of letting the first post-resize step die at the zero.py
    axis-size raise. Needs the bucket layouts attached via
    :func:`note_zero1_layout`; sharded state without one refuses loudly
    naming both layouts."""
    try:
        from ..parallel.zero import Zero1State  # noqa: F401 - probe
    except Exception:  # noqa: BLE001 - jax-free install: nothing sharded
        return

    tracked = list(getattr(state, "_tracked", []))
    sharded = []
    for attr in tracked:
        dims = _zero1_shard_dims({"_saved": {attr: getattr(state, attr)}})
        if any(n != new_size for n in dims.values()):
            sharded.append(attr)
    if not sharded:
        return
    layouts = dict(getattr(state, "zero1_layout", None) or {})
    missing = [a for a in sharded if str(a) not in layouts]
    if missing:
        raise RuntimeError(
            f"elastic: world resized {old_size}->{new_size} but tracked "
            f"state {missing} holds ZeRO-1 shards laid out for "
            f"{old_size} ranks and no bucket layout was attached to "
            f"reshard them — call hvd.elastic.note_zero1_layout(state, "
            f"attr, zero1_layout_from_params(...)) at setup "
            f"(docs/fault_tolerance.md 'Elastic resharding')."
        )
    from ..parallel import reshard as _reshard

    for attr in sharded:
        lay = layouts[str(attr)]
        if not hasattr(lay, "relayout"):
            lay = _reshard.Zero1Layout.from_dict(lay)
        if lay.n_shards != old_size:
            # The layout tracks the last reshard, not necessarily the
            # last generation — trust the state's actual leading dims.
            lay = lay.relayout(old_size)
        new_value, reports = _reshard.reshard_zero1_tree(
            getattr(state, attr), new_size, layouts={"": lay},
            trigger="resize",
        )
        setattr(state, attr, new_value)
        saved = getattr(state, "_saved", None)
        if isinstance(saved, dict) and attr in saved:
            saved[attr], _ = _reshard.reshard_zero1_tree(
                saved[attr], new_size, layouts={"": lay},
                trigger="resize",
            )
        layouts[str(attr)] = lay.relayout(new_size)
        for rep in reports:
            logger.info(
                "elastic: resharded %r zero1 state %d->%d shards for "
                "the new generation (%d bytes)", attr, rep["n_old"],
                rep["n_new"], rep["moved_bytes"],
            )
    state.zero1_layout = layouts
