"""Fault-injection taps.

``fault_point(site)`` is called from a handful of fixed places in the
runtime, the launcher control plane, and the elastic driver.  With no plan
loaded (the production default) the module-level :data:`ACTIVE` flag is
False and instrumented call sites skip the call entirely — zero overhead.
With ``HOROVOD_FAULT_PLAN`` set, each hit advances a per-site counter,
matches the plan's actions against (site, counter, rank, worker,
generation), and executes whatever the plan schedules: sleep, raise
:class:`InjectedFault`, deliver a preemption notice, or kill the process.

Every executed injection is appended to the event log — in memory always,
and to the file named by ``HOROVOD_FAULT_EVENT_LOG`` when set.  Event
lines carry only deterministic fields (sequence number, site, hit count,
action) so logs from two runs of the same plan can be compared directly.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

from .plan import (
    DRIVER_KINDS,
    FAULT_PLAN_ENV,
    FaultAction,
    FaultPlan,
    PAYLOAD_KINDS,
)

FAULT_EVENT_LOG_ENV = "HOROVOD_FAULT_EVENT_LOG"


class InjectedFault(ConnectionError):
    """A fault injected by the active plan (dropped control-plane message,
    severed connection).  Subclasses ConnectionError so the production
    retry/backoff paths treat it exactly like a real transport failure."""


class ReplicaKilled(InjectedFault):
    """A ``kill_replica`` fault: the serving replica that hit the
    ``replica`` tap mid-batch must abort. The serve engine catches this
    at the replica loop boundary, re-queues every in-flight request of
    the aborted batch, and retires the replica — the exactly-once
    invariant the chaos harness asserts (docs/serving.md)."""


ACTIVE = False

_lock = threading.Lock()
_plan: Optional[FaultPlan] = None
_counters: Dict[str, int] = {}
_events: List[dict] = []
_seq = 0


def install_plan(plan: Optional[FaultPlan]) -> None:
    """Activate ``plan`` for this process (None deactivates)."""
    global ACTIVE, _plan, _seq
    with _lock:
        _plan = plan
        _counters.clear()
        _events.clear()
        _seq = 0
        ACTIVE = plan is not None


def activate_from_env() -> Optional[FaultPlan]:
    """(Re)load the plan from ``HOROVOD_FAULT_PLAN``; returns it."""
    install_plan(FaultPlan.from_env())
    return _plan


def reset() -> None:
    install_plan(None)


def active_plan() -> Optional[FaultPlan]:
    return _plan


def events() -> List[dict]:
    with _lock:
        return list(_events)


def _identity() -> tuple:
    env = os.environ
    rank = env.get("HOROVOD_RANK")
    gen = env.get("HOROVOD_ELASTIC_GEN")
    return (
        int(rank) if rank is not None and rank.isdigit() else None,
        env.get("HOROVOD_ELASTIC_WORKER_ID"),
        int(gen) if gen is not None and gen.isdigit() else None,
    )


def record_event(site: str, hit: int, action: str, detail: str = "") -> dict:
    """Append one deterministic event line (also used by the driver for
    its own scheduled injections)."""
    global _seq
    rank, _, _ = _identity()
    with _lock:
        _seq += 1
        ev = {
            "seq": _seq,
            "site": site,
            "hit": hit,
            "action": action,
            "detail": detail,
            # Per-process identity: a shared event-log file interleaves
            # ranks nondeterministically, but each rank's OWN (rank, seq)
            # subsequence is deterministic — that's what chaos runs diff.
            "rank": rank,
        }
        _events.append(ev)
        path = os.environ.get(FAULT_EVENT_LOG_ENV, "")
        # The file append stays under the lock: released first, a second
        # thread could write its (higher-seq) line before this one, and
        # this rank's (rank, seq) subsequence in the shared log — the
        # thing chaos runs diff byte-for-byte — would invert.
        if path:
            try:
                with open(path, "a") as f:
                    f.write(json.dumps(ev, sort_keys=True) + "\n")
            except OSError:
                pass
    return ev


def _execute(action: FaultAction, site: str, hit: int,
             name: Optional[str]) -> Optional[str]:
    detail = name or ""
    if action.kind == "delay":
        record_event(site, hit, "delay", detail)
        time.sleep(action.seconds)
        return None
    if action.kind == "drop":
        record_event(site, hit, "drop", detail)
        raise InjectedFault(
            f"injected fault: dropped {site} message"
            + (f" ({name})" if name else "")
        )
    if action.kind == "duplicate":
        record_event(site, hit, "duplicate", detail)
        return "duplicate"
    if action.kind == "preempt":
        record_event(site, hit, "preempt", detail)
        from . import preemption

        preemption.request_preemption("fault plan: simulated maintenance")
        return None
    if action.kind == "kill_replica":
        record_event(site, hit, "kill_replica", detail)
        raise ReplicaKilled(
            f"injected fault: replica killed mid-batch ({site} hit {hit})"
        )
    if action.kind == "kill":
        record_event(site, hit, "kill", f"exit={action.exit_code}")
        # Flush anything buffered — the event log write above already
        # hit disk (opened in append mode per line).
        try:
            import sys

            sys.stdout.flush()
            sys.stderr.flush()
        except Exception:  # noqa: BLE001
            pass
        os._exit(action.exit_code)
    return None


def fault_point(site: str, name: Optional[str] = None) -> Optional[str]:
    """Advance ``site``'s hit counter and execute any scheduled faults.

    Returns a directive string for actions the call site must implement
    itself (currently only ``"duplicate"``), else None.  Raises
    :class:`InjectedFault` for dropped messages and never returns for
    kills."""
    plan = _plan
    if plan is None:
        return None
    with _lock:
        hit = _counters.get(site, 0) + 1
        _counters[site] = hit
    rank, worker, gen = _identity()
    directive = None
    for action in plan.actions:
        if action.site != site:
            continue
        if action.kind in PAYLOAD_KINDS:
            continue  # payload faults run through payload_fault()
        if action.kind in DRIVER_KINDS:
            continue  # driver faults fire in the driver's own loop
        if not action.matches_process(rank, worker, gen):
            continue
        if not action.in_window(hit):
            continue
        if not plan.decide(action, rank):
            continue
        out = _execute(action, site, hit, name)
        directive = out or directive
    return directive


def _mutate_payload(plan: FaultPlan, action: FaultAction, site: str,
                    hit: int, name: str, tensor, rank):
    """Apply one corrupt/nan action to a tensor payload. Returns a
    mutated COPY (numpy) — the original array is never written through."""
    import numpy as np

    arr = np.array(np.asarray(tensor), copy=True)
    if arr.size == 0:
        return tensor
    rng = plan._stream(action, rank)
    if action.kind == "nan":
        if not np.issubdtype(arr.dtype, np.floating):
            return tensor  # integer payloads have no NaN to inject
        idx = (action.element if action.element is not None
               else rng.randrange(arr.size)) % arr.size
        arr.flat[idx] = np.nan
        record_event(site, hit, "nan", f"{name}[{idx}]")
        return arr
    # corrupt: flip one bit of one element — the SDC model. Flips land in
    # the element's raw bytes, so exponent/sign corruption is possible
    # (exactly the silent-divergence class the digest guard exists for).
    itemsize = arr.dtype.itemsize
    idx = (action.element if action.element is not None
           else rng.randrange(arr.size)) % arr.size
    bit = (action.bit if action.bit is not None
           else rng.randrange(8 * itemsize)) % (8 * itemsize)
    view = arr.reshape(-1).view(np.uint8)
    view[idx * itemsize + bit // 8] ^= np.uint8(1 << (bit % 8))
    record_event(site, hit, "corrupt", f"{name}[{idx}] bit {bit}")
    return arr


def payload_fault(site: str, name: str, tensor):
    """Advance the payload hit counters and apply any scheduled payload
    mutations (``corrupt`` / ``nan``) to ``tensor``. Returns the tensor
    (a mutated numpy copy when a fault fired, the original otherwise).
    Call sites gate on :data:`ACTIVE`; sites: ``payload`` (collective
    input at submission), ``output`` (this rank's collective result).

    An action with a ``tensor`` name pattern is windowed over its OWN
    (site, pattern) counter — it counts only matching payloads, so
    internal collectives (digest agreement, elastic sync) passing the
    same tap never shift the schedule. Patternless actions use the
    site-global counter."""
    import fnmatch

    plan = _plan
    if plan is None or tensor is None:
        return tensor
    patterns = sorted({
        a.tensor for a in plan.actions
        if a.kind in PAYLOAD_KINDS and a.site == site
        and a.tensor is not None
        and fnmatch.fnmatchcase(name, a.tensor)
    })
    with _lock:
        hit = _counters.get(site, 0) + 1
        _counters[site] = hit
        pattern_hits = {}
        for p in patterns:
            key = f"{site}|{p}"
            pattern_hits[p] = _counters.get(key, 0) + 1
            _counters[key] = pattern_hits[p]
    rank, worker, gen = _identity()
    out = tensor
    for action in plan.actions:
        if action.site != site or action.kind not in PAYLOAD_KINDS:
            continue
        if action.tensor is not None:
            if action.tensor not in pattern_hits:
                continue
            window_hit = pattern_hits[action.tensor]
        else:
            window_hit = hit
        if not action.matches_process(rank, worker, gen):
            continue
        if not action.in_window(window_hit):
            continue
        if not plan.decide(action, rank):
            continue
        out = _mutate_payload(
            plan, action, site, window_hit, name, out, rank
        )
    return out


def step(name: Optional[str] = None) -> None:
    """Mark one training step (``State.commit`` calls this; non-elastic
    loops may call it directly).  No-op without an active plan."""
    if ACTIVE:
        fault_point("step", name)


# Load at import so worker processes spawned with HOROVOD_FAULT_PLAN in
# their environment are armed without any code changes.
if os.environ.get(FAULT_PLAN_ENV, "").strip():
    try:
        activate_from_env()
    except Exception:  # noqa: BLE001 - a malformed plan must not
        # take down production init; it is surfaced by the chaos tools.
        import logging

        logging.getLogger("horovod_tpu.fault").exception(
            "could not load %s", FAULT_PLAN_ENV
        )
