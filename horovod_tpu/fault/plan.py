"""Deterministic, seeded fault plans.

A fault plan is a JSON document (inline in ``HOROVOD_FAULT_PLAN`` or a path
to a file) describing *exactly which failures to inject where*:

.. code-block:: json

    {
      "seed": 1234,
      "faults": [
        {"kind": "kill",      "rank": 2, "at_step": 3, "exit_code": 43},
        {"kind": "delay",     "rank": 1, "site": "enqueue",
         "seconds": 0.05, "after": 2, "count": 20},
        {"kind": "drop",      "site": "kv",  "frac": 0.5,
         "after": 5, "count": 8},
        {"kind": "duplicate", "site": "rpc", "frac": 0.1},
        {"kind": "preempt",   "worker": "localhost:1", "after_s": 2.0},
        {"kind": "preempt",   "rank": 0, "at_step": 4}
      ]
    }

Determinism is the whole point: probabilistic actions (``frac``) draw from
a ``random.Random`` stream keyed by ``(seed, site, rank)``, so the n-th tap
hit at a site makes the same drop/keep decision in every run with the same
seed.  :meth:`FaultPlan.canonical_schedule` serializes the fully-resolved
plan — including the first decisions of every probabilistic stream — to
canonical bytes, which the elastic driver writes to its event log so two
runs with the same seed can be diffed byte-for-byte.

Action fields
-------------

``kind``
    ``kill`` | ``delay`` | ``drop`` | ``duplicate`` | ``preempt`` |
    ``corrupt`` | ``nan`` | ``kill_driver`` | ``restart_driver`` |
    ``kill_replica``.
    ``kill_replica`` is a *serving-plane* fault (docs/serving.md): it
    aborts a serving replica in the middle of a batch dispatch (site
    ``replica``), exercising the engine's exactly-once re-queue of
    every in-flight request.
    ``corrupt``/``nan`` are *payload* faults exercising the data-plane
    integrity guard (docs/fault_tolerance.md): ``corrupt`` bit-flips one
    element of a tensor payload (silent data corruption), ``nan``
    poisons one element of a floating-point gradient.
    ``kill_driver``/``restart_driver`` are *control-plane* faults
    executed by the elastic driver itself ``after_s`` seconds into its
    run: a hard ``os._exit`` of the driver process (resume with
    ``horovodrun --resume``) and an in-process simulated crash-restart
    (KV blackout → journal replay → epoch bump → port reclaim). Scoped
    by ``epoch`` (default: first driver incarnation only), so a resumed
    driver never re-executes its own death.
``site``
    Tap the action applies to: ``step`` (one training step, i.e. one
    ``State.commit``), ``enqueue``/``response`` (runtime collective
    submission/completion), ``rpc`` (launcher control-plane send),
    ``kv`` (rendezvous KV request), ``spawn`` (driver worker spawn),
    ``payload`` (a collective's INPUT tensor at submission — where a
    ``nan`` models a diverged kernel) and ``output`` (a collective's
    result on THIS rank only — where a ``corrupt`` models SDC that makes
    replicas silently diverge). Serving adds ``request`` (one inference
    request at admission; carries only ``drop``/``delay``) and
    ``replica`` (one batch dispatch on a serving replica; carries only
    ``kill_replica``).
    Defaults: kill/preempt → ``step``, delay → ``enqueue``,
    drop/duplicate → ``rpc``, nan → ``payload``, corrupt → ``output``,
    kill_replica → ``replica``.
``rank`` / ``worker`` / ``gen``
    Selectors; omitted means "any". ``rank`` matches ``HOROVOD_RANK``,
    ``worker`` matches ``HOROVOD_ELASTIC_WORKER_ID``, ``gen`` matches
    ``HOROVOD_ELASTIC_GEN`` (scoping a fault to the first world generation
    is the standard way to keep a kill from re-firing after recovery).
``at_step`` / ``after`` / ``count`` / ``frac``
    Trigger window over the site's hit counter: ``at_step`` fires exactly
    at that count (kill/preempt), ``after``+``count`` bound a window
    (delay/drop/duplicate), ``frac`` makes the action probabilistic inside
    its window.
``every`` / ``until``
    Chronic-slowness shape (``delay`` only): ``every`` fires the action
    on every N-th in-window hit (``every: 1`` = every hit — a persistent
    straggler; ``every: 3`` = periodic hiccups), ``until`` bounds the
    window by an absolute hit count (an alternative to ``count``, which
    is relative to ``after``). Both validated at parse time; the seeded
    decision stream advances only on firing hits, so the recurring form
    is exactly as byte-reproducible as the single-shot one, and the
    fleet simulator (``sim/core.py``) draws the same schedule.
``seconds`` / ``exit_code`` / ``after_s``
    Parameters: delay duration, kill exit status, and (driver-side
    preempt) seconds after spawn at which the driver delivers the
    simulated maintenance notice (SIGTERM) to the worker.
``element`` / ``bit`` / ``tensor``
    Payload-fault targeting: the flat element index to poison, (for
    ``corrupt``) which bit of that element to flip, and a tensor-name
    pattern (``fnmatch`` syntax, e.g. ``"grad"`` or ``"grad.*"``).
    ``element``/``bit`` omitted → drawn from the action's seeded decision
    stream, deterministic per (seed, action, rank) without hand-pinning.
    With ``tensor`` set, the trigger window counts only MATCHING payloads
    at the site (its own counter), so ``at_step`` means "the K-th time
    THIS tensor passes the tap" — internal collectives (digest
    agreement, elastic sync) don't perturb the schedule.
"""

from __future__ import annotations

import json
import os
import random
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

FAULT_PLAN_ENV = "HOROVOD_FAULT_PLAN"

_KINDS = ("kill", "delay", "drop", "duplicate", "preempt", "corrupt", "nan",
          "kill_driver", "restart_driver", "kill_replica")
_SITES = ("step", "enqueue", "response", "rpc", "kv", "spawn",
          "payload", "output", "driver", "request", "replica")
# Payload faults mutate tensors; only these sites carry one.
PAYLOAD_KINDS = ("corrupt", "nan")
PAYLOAD_SITES = ("payload", "output")
# Driver faults execute in the ELASTIC DRIVER's supervision loop (site
# ``driver``), never at worker taps: ``kill_driver`` hard-kills the
# driver process ``after_s`` seconds into its run (the control-plane
# SPOF model — resume via ``horovodrun --resume`` or a supervisor);
# ``restart_driver`` simulates the full crash-restart cycle in-process
# (KV blackout → journal replay → epoch bump → port reclaim →
# republish) so a single job exercises park/reattach. Both are scoped
# by the ``epoch`` selector (default: the FIRST driver incarnation
# only) so a resumed driver does not re-execute its own death.
DRIVER_KINDS = ("kill_driver", "restart_driver")
DRIVER_KILL_EXIT_CODE = 67
# Serving-plane faults (docs/serving.md "Chaos semantics"): the
# ``request`` site taps one inference request at admission (``drop`` =
# the request is discarded and answered as dropped, ``delay`` = queueing
# latency injected before batching), and the ``replica`` site taps one
# batch dispatch on a serving replica — ``kill_replica`` aborts the
# replica mid-batch, exercising the engine's exactly-once re-queue of
# every in-flight request. Validated kind<->site like driver faults so a
# plan cannot silently schedule a serving fault at a training tap.
REQUEST_KINDS = ("drop", "delay")
REPLICA_KINDS = ("kill_replica",)
_DEFAULT_SITE = {
    "kill": "step",
    "preempt": "step",
    "delay": "enqueue",
    "drop": "rpc",
    "duplicate": "rpc",
    "corrupt": "output",
    "nan": "payload",
    "kill_driver": "driver",
    "restart_driver": "driver",
    "kill_replica": "replica",
}
# How many leading decisions of each probabilistic stream the canonical
# schedule materializes (enough to make drop bursts diffable without
# unbounded output).
_SCHEDULE_DECISIONS = 64


@dataclass
class FaultAction:
    kind: str
    site: str
    rank: Optional[int] = None
    worker: Optional[str] = None
    gen: Optional[int] = None
    at_step: Optional[int] = None
    after: int = 0
    count: Optional[int] = None
    every: Optional[int] = None    # delay: fire on every N-th in-window hit
    until: Optional[int] = None    # delay: absolute last hit of the window
    frac: float = 1.0
    seconds: float = 0.0
    exit_code: int = 43
    after_s: Optional[float] = None
    element: Optional[int] = None  # payload faults: flat index to poison
    bit: Optional[int] = None      # corrupt: bit of that element to flip
    tensor: Optional[str] = None   # payload faults: name pattern (fnmatch)
    epoch: Optional[int] = None    # driver faults: driver incarnation
    index: int = 0  # position in the plan; part of the stream key

    @staticmethod
    def from_dict(d: Dict[str, Any], index: int) -> "FaultAction":
        kind = str(d.get("kind", "")).lower()
        if kind not in _KINDS:
            raise ValueError(
                f"fault plan action {index}: unknown kind {kind!r} "
                f"(expected one of {_KINDS})"
            )
        site = str(d.get("site", _DEFAULT_SITE[kind])).lower()
        if site not in _SITES:
            raise ValueError(
                f"fault plan action {index}: unknown site {site!r} "
                f"(expected one of {_SITES})"
            )
        if (kind in DRIVER_KINDS) != (site == "driver"):
            raise ValueError(
                f"fault plan action {index}: kind {kind!r} and site "
                f"{site!r} do not match — driver faults "
                f"({'/'.join(DRIVER_KINDS)}) execute only at the "
                "'driver' site (the elastic driver's supervision loop)"
            )
        if (kind in REPLICA_KINDS) != (site == "replica"):
            raise ValueError(
                f"fault plan action {index}: kind {kind!r} and site "
                f"{site!r} do not match — replica faults "
                f"({'/'.join(REPLICA_KINDS)}) execute only at the "
                "'replica' site (a serving replica's batch dispatch)"
            )
        if site == "request" and kind not in REQUEST_KINDS:
            raise ValueError(
                f"fault plan action {index}: kind {kind!r} is not a "
                f"request fault — the 'request' site (one inference "
                f"request at admission) carries only "
                f"{'/'.join(REQUEST_KINDS)}"
            )
        every = None if d.get("every") is None else int(d["every"])
        until = None if d.get("until") is None else int(d["until"])
        if (every is not None or until is not None) and kind != "delay":
            raise ValueError(
                f"fault plan action {index}: every/until describe the "
                f"chronic-slowness shape and apply only to 'delay' "
                f"actions, not {kind!r}"
            )
        if every is not None and every < 1:
            raise ValueError(
                f"fault plan action {index}: every must be >= 1 "
                f"(got {every})"
            )
        after = int(d.get("after", 0))
        if until is not None and until <= after:
            raise ValueError(
                f"fault plan action {index}: until ({until}) must be "
                f"> after ({after}) — the window would be empty"
            )
        return FaultAction(
            kind=kind,
            site=site,
            rank=None if d.get("rank") is None else int(d["rank"]),
            worker=d.get("worker"),
            gen=None if d.get("gen") is None else int(d["gen"]),
            at_step=(
                None if d.get("at_step") is None else int(d["at_step"])
            ),
            after=after,
            count=None if d.get("count") is None else int(d["count"]),
            every=every,
            until=until,
            frac=float(d.get("frac", 1.0)),
            seconds=float(d.get("seconds", 0.0)),
            exit_code=int(d.get(
                "exit_code",
                DRIVER_KILL_EXIT_CODE if kind == "kill_driver" else 43,
            )),
            after_s=(
                None if d.get("after_s") is None else float(d["after_s"])
            ),
            element=(
                None if d.get("element") is None else int(d["element"])
            ),
            bit=None if d.get("bit") is None else int(d["bit"]),
            tensor=d.get("tensor"),
            epoch=None if d.get("epoch") is None else int(d["epoch"]),
            index=index,
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"kind": self.kind, "site": self.site}
        for k in ("rank", "worker", "gen", "at_step", "count", "every",
                  "until", "after_s", "element", "bit", "tensor", "epoch"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        if self.after:
            out["after"] = self.after
        if self.frac != 1.0:
            out["frac"] = self.frac
        if self.seconds:
            out["seconds"] = self.seconds
        if self.kind == "kill":
            out["exit_code"] = self.exit_code
        return out

    def matches_driver_epoch(self, epoch: int) -> bool:
        """Driver-fault scoping: an action with no explicit ``epoch``
        targets ONLY the first driver incarnation — otherwise a resumed
        driver, armed with the same plan from its environment, would
        faithfully re-execute the very crash it just recovered from."""
        return epoch == (self.epoch if self.epoch is not None else 1)

    def matches_process(self, rank: Optional[int], worker: Optional[str],
                        gen: Optional[int]) -> bool:
        if self.rank is not None and self.rank != rank:
            return False
        if self.worker is not None and self.worker != worker:
            return False
        if self.gen is not None and gen is not None and self.gen != gen:
            return False
        return True

    def in_window(self, hit: int) -> bool:
        """Window test over the site's 1-based hit counter. ``every``
        makes a hit in-window only on the action's period (the decision
        stream advances only on in-window hits, so the chronic form
        stays byte-reproducible), ``until`` closes the window at an
        absolute hit count."""
        if self.at_step is not None:
            return hit == self.at_step
        if hit <= self.after:
            return False
        if self.until is not None and hit > self.until:
            return False
        if self.count is not None and hit > self.after + self.count:
            return False
        if self.every is not None and (hit - self.after - 1) % self.every:
            return False
        return True


class FaultPlan:
    """A parsed plan plus its per-action deterministic decision streams."""

    def __init__(self, seed: int, actions: List[FaultAction]):
        self.seed = int(seed)
        self.actions = actions
        self._streams: Dict[tuple, random.Random] = {}

    # ------------------------------------------------------------- parse
    @staticmethod
    def from_json(text: str) -> "FaultPlan":
        doc = json.loads(text)
        if not isinstance(doc, dict):
            raise ValueError("fault plan must be a JSON object")
        actions = [
            FaultAction.from_dict(a, i)
            for i, a in enumerate(doc.get("faults", []))
        ]
        return FaultPlan(int(doc.get("seed", 0)), actions)

    @staticmethod
    def from_env(env: Optional[Dict[str, str]] = None) -> Optional["FaultPlan"]:
        """Load the plan named by ``HOROVOD_FAULT_PLAN`` (inline JSON when
        the value starts with ``{``, otherwise a file path). Returns None
        when the variable is unset/empty."""
        raw = (env or os.environ).get(FAULT_PLAN_ENV, "").strip()
        if not raw:
            return None
        if raw.startswith("{"):
            return FaultPlan.from_json(raw)
        with open(raw, "r") as f:
            return FaultPlan.from_json(f.read())

    # -------------------------------------------------------- decisions
    def _stream(self, action: FaultAction, rank: Optional[int]) -> random.Random:
        key = (action.index, action.site, rank if rank is not None else -1)
        rng = self._streams.get(key)
        if rng is None:
            rng = random.Random(
                f"{self.seed}:{action.index}:{action.site}:{key[2]}"
            )
            self._streams[key] = rng
        return rng

    def decide(self, action: FaultAction, rank: Optional[int]) -> bool:
        """Deterministic probabilistic decision for one in-window hit."""
        if action.frac >= 1.0:
            return True
        return self._stream(action, rank).random() < action.frac

    def decision_trace(self, action: FaultAction, rank: Optional[int],
                       n: int) -> List[bool]:
        """First ``n`` decisions of an action's stream for ``rank`` —
        computed on a FRESH stream so the trace is a pure function of
        (seed, action, rank), independent of how often ``decide`` ran."""
        rng = random.Random(
            f"{self.seed}:{action.index}:{action.site}:"
            f"{rank if rank is not None else -1}"
        )
        if action.frac >= 1.0:
            return [True] * n
        return [rng.random() < action.frac for _ in range(n)]

    # --------------------------------------------------------- schedule
    def canonical_schedule(self) -> str:
        """Fully-resolved schedule as canonical JSON text: the actions in
        plan order plus, for each probabilistic action, the first
        decisions of its stream for the ranks it can select. Byte-for-byte
        reproducible for a given plan — the driver writes these bytes to
        its event log, which is what the chaos suite diffs across runs."""
        resolved = []
        for a in self.actions:
            entry: Dict[str, Any] = a.to_dict()
            if a.frac < 1.0:
                ranks = [a.rank] if a.rank is not None else [None]
                entry["decisions"] = {
                    str(r if r is not None else "*"): [
                        1 if d else 0
                        for d in self.decision_trace(
                            a, r, _SCHEDULE_DECISIONS
                        )
                    ]
                    for r in ranks
                }
            resolved.append(entry)
        return json.dumps(
            {"seed": self.seed, "schedule": resolved},
            sort_keys=True, separators=(",", ":"),
        )
