"""Deterministic fault injection + the recovery machinery it exercises.

Two halves (docs/fault_tolerance.md):

- **Injection** (:mod:`.plan`, :mod:`.injector`): a seeded fault schedule
  from ``HOROVOD_FAULT_PLAN`` — kill worker N at step K, delay a rank's
  submissions, drop/duplicate control-plane messages, deliver a simulated
  TPU maintenance notice — executed at fixed taps in the runtime, the
  launcher control plane, and the elastic driver.  Zero overhead when the
  env var is unset.
- **Recovery** (:mod:`.backoff`, :mod:`.preemption`): bounded retry with
  exponential backoff + deterministic jitter for control-plane RPCs, and
  the graceful-preemption drain path (notice → commit → drain → rejoin).
"""

from .backoff import (  # noqa: F401
    Backoff,
    retry_call,
    HOROVOD_FAULT_SEED,
    HOROVOD_RPC_BACKOFF_BASE_S,
    HOROVOD_RPC_BACKOFF_JITTER,
    HOROVOD_RPC_BACKOFF_MAX_S,
    HOROVOD_RPC_RETRIES,
)
from . import injector  # noqa: F401  (live ACTIVE flag: injector.ACTIVE)
from .injector import (  # noqa: F401
    FAULT_EVENT_LOG_ENV,
    InjectedFault,
    activate_from_env,
    active_plan,
    events,
    fault_point,
    install_plan,
    payload_fault,
    record_event,
    reset,
    step,
)
from .plan import FAULT_PLAN_ENV, FaultAction, FaultPlan  # noqa: F401
from .preemption import (  # noqa: F401
    PreemptionInterrupt,
    clear as clear_preemption,
    install_sigterm_handler,
    preemption_requested,
    request_preemption,
)

__all__ = [
    "Backoff",
    "FAULT_EVENT_LOG_ENV",
    "FAULT_PLAN_ENV",
    "FaultAction",
    "FaultPlan",
    "InjectedFault",
    "PreemptionInterrupt",
    "activate_from_env",
    "active_plan",
    "clear_preemption",
    "events",
    "fault_point",
    "install_plan",
    "install_sigterm_handler",
    "payload_fault",
    "preemption_requested",
    "record_event",
    "request_preemption",
    "reset",
    "retry_call",
    "step",
]
