"""Bounded retry with exponential backoff + deterministic jitter.

Used by the launcher control plane (``run/network.py``) and the rendezvous
KV client (``run/http_server.py``) so a dropped or delayed control-plane
message costs one backoff, not a job.  Jitter draws from a seeded
``random.Random`` so chaos runs are reproducible: with
``HOROVOD_FAULT_SEED`` set, the exact sleep sequence is a pure function of
the seed and the knobs.
"""

from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass, field
from typing import Callable, Optional, Tuple, Type

HOROVOD_RPC_RETRIES = "HOROVOD_RPC_RETRIES"
HOROVOD_RPC_BACKOFF_BASE_S = "HOROVOD_RPC_BACKOFF_BASE_S"
HOROVOD_RPC_BACKOFF_MAX_S = "HOROVOD_RPC_BACKOFF_MAX_S"
HOROVOD_RPC_BACKOFF_JITTER = "HOROVOD_RPC_BACKOFF_JITTER"
HOROVOD_FAULT_SEED = "HOROVOD_FAULT_SEED"


@dataclass
class Backoff:
    """Retry budget: ``retries`` attempts AFTER the first, sleeping
    ``base * multiplier**i`` (capped at ``max_s``) plus up to
    ``jitter`` fraction of that delay between attempts."""

    retries: int = 3
    base_s: float = 0.05
    max_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.1
    seed: Optional[int] = None
    _rng: random.Random = field(default=None, repr=False)  # type: ignore

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)

    @staticmethod
    def from_env(env=None) -> "Backoff":
        e = env or os.environ

        def _f(name, default):
            try:
                return float(e.get(name, "") or default)
            except ValueError:
                return default

        seed = e.get(HOROVOD_FAULT_SEED, "").strip()
        return Backoff(
            retries=int(_f(HOROVOD_RPC_RETRIES, 3)),
            base_s=_f(HOROVOD_RPC_BACKOFF_BASE_S, 0.05),
            max_s=_f(HOROVOD_RPC_BACKOFF_MAX_S, 2.0),
            jitter=_f(HOROVOD_RPC_BACKOFF_JITTER, 0.1),
            seed=int(seed) if seed.lstrip("-").isdigit() else None,
        )

    def delay(self, attempt: int) -> float:
        """Sleep before retry ``attempt`` (0-based)."""
        d = min(self.max_s, self.base_s * (self.multiplier ** attempt))
        if self.jitter:
            d += d * self.jitter * self._rng.random()
        return d


def retry_call(
    fn: Callable,
    *,
    retryable: Tuple[Type[BaseException], ...] = (OSError, EOFError),
    backoff: Optional[Backoff] = None,
    describe: str = "",
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Call ``fn()`` with up to ``backoff.retries`` retries on ``retryable``
    exceptions; re-raises the last error once the budget is spent, with
    the attempt count appended so logs show the retry history."""
    bo = backoff or Backoff()
    last: Optional[BaseException] = None
    for attempt in range(bo.retries + 1):
        try:
            return fn()
        except retryable as exc:  # noqa: PERF203 - retry loop
            last = exc
            if attempt >= bo.retries:
                break
            d = bo.delay(attempt)
            if on_retry is not None:
                on_retry(attempt, exc, d)
            sleep(d)
    assert last is not None
    raise type(last)(
        f"{last} [{describe + ': ' if describe else ''}gave up after "
        f"{bo.retries + 1} attempts]"
    ) from last
