"""Graceful preemption: notice → commit → drain → rejoin.

A TPU maintenance event / spot preemption arrives as a notice (SIGTERM
from the platform, or a simulated notice from the fault plan) some grace
period before the hardware goes away.  The recovery contract:

1. the notice sets a process-wide flag (nothing is interrupted mid-step);
2. the next ``State.commit()`` observes the flag, reaches cross-rank
   agreement through the same allreduce that powers
   ``HostsUpdatedInterrupt``, and raises :class:`PreemptionInterrupt` on
   the preempted rank (peers see a plain membership-change interrupt);
3. the elastic wrapper keeps the just-committed state (no rollback),
   drains in-flight collectives via the runtime shutdown, and rejoins
   through the existing elastic path — persist-and-respawn when in-process
   re-formation is unsupported, in-process re-rendezvous otherwise.

``install_sigterm_handler`` is chained: the previous handler still runs,
so launcher-driven termination semantics are preserved.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

logger = logging.getLogger("horovod_tpu.fault")


class PreemptionInterrupt(Exception):
    """Raised inside the training function on the preempted rank after
    cross-rank agreement; the elastic wrapper drains and rejoins with the
    state that was just committed."""


_flag = threading.Event()
_reason: Optional[str] = None
_installed = False
_prev_handler = None


def request_preemption(reason: str = "") -> None:
    """Deliver a (possibly simulated) preemption notice to this process."""
    global _reason
    _reason = reason or "preemption notice"
    if not _flag.is_set():
        logger.warning(
            "preemption notice received (%s); will drain at the next "
            "commit", _reason,
        )
        try:
            # Flight recorder (docs/timeline.md): a SIGTERM'd worker may
            # be gone before the graceful drain completes — persist the
            # last moments the instant the notice lands. No-op when
            # tracing is disabled.
            from .. import trace as _trace

            if _trace.ACTIVE:
                _trace.TAP.flight_dump(f"preempt:{_reason}")
        except Exception:  # noqa: BLE001 - the notice path must not die
            pass
    _flag.set()


def preemption_requested() -> bool:
    return _flag.is_set()


def preemption_reason() -> str:
    return _reason or ""


def clear() -> None:
    global _reason
    _flag.clear()
    _reason = None


def _on_sigterm(signum, frame):  # noqa: ARG001
    request_preemption("SIGTERM")
    if callable(_prev_handler):
        _prev_handler(signum, frame)


def install_sigterm_handler() -> bool:
    """Install the notice handler (main thread only — signal.signal's own
    constraint).  Idempotent; returns True when installed/active."""
    global _installed, _prev_handler
    if _installed:
        return True
    if threading.current_thread() is not threading.main_thread():
        logger.warning(
            "cannot install SIGTERM preemption handler off the main "
            "thread; preemption notices must be delivered via "
            "request_preemption()"
        )
        return False
    prev = signal.signal(signal.SIGTERM, _on_sigterm)
    _prev_handler = prev if prev not in (
        signal.SIG_DFL, signal.SIG_IGN, None
    ) else None
    _installed = True
    return True
