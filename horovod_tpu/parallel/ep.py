"""Expert parallelism (Mixture-of-Experts) over an ``expert`` mesh axis.

TPU-native extension beyond the reference framework: the reference's op set
has no alltoall at all (``horovod/common/message.h:48-50`` — allreduce,
allgather, broadcast only) and no model-structure code (SURVEY.md §2.3), so
MoE training is impossible there. Here expert parallelism composes with the
data axis on one mesh: tokens are routed top-1 (Switch style) with a static
capacity so every shape stays compile-time constant, dispatched to expert
owners with ``lax.all_to_all`` riding ICI, transformed by the local expert
FFNs in one batched einsum (MXU-friendly), and combined back.

Design notes (the GShard/Switch dispatch pattern, re-derived for shard_map):
 - dispatch/combine are dense one-hot tensors ``[tokens, experts, capacity]``
   — no gathers with data-dependent shapes, so XLA tiles everything.
 - per-device expert compute is a single ``[E_local, n_send*C, D]`` batched
   matmul — large, static, bfloat16-friendly.
 - the auxiliary load-balancing loss is the standard mean(gates)*mean(mask)
   dot product per expert, summed over experts, scaled by E.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..common.compat import axis_size as _axis_size
from .mesh import DATA_AXIS, EXPERT_AXIS


class MoEParams(NamedTuple):
    """Parameters of one MoE FFN layer.

    ``w_router`` is replicated; ``w_in``/``w_out`` hold only the experts
    owned by this device along the ``expert`` axis (shard_map view) —
    globally they are sharded ``P(expert_axis)`` on dim 0.
    """

    w_router: jax.Array  # [D, E_total]
    w_in: jax.Array      # [E_local, D, H]
    w_out: jax.Array     # [E_local, H, D]


def init_moe_params(
    rng: jax.Array,
    *,
    d_model: int,
    d_hidden: int,
    num_experts: int,
    num_expert_shards: int,
    dtype=jnp.float32,
) -> MoEParams:
    """Initialize *global* MoE params (callers shard w_in/w_out over the
    expert axis; dim 0 of both is the global expert count)."""
    if num_experts % num_expert_shards:
        raise ValueError(
            f"num_experts={num_experts} not divisible by "
            f"expert shards={num_expert_shards}"
        )
    kr, ki, ko = jax.random.split(rng, 3)
    scale_in = 1.0 / jnp.sqrt(d_model)
    scale_out = 1.0 / jnp.sqrt(d_hidden)
    return MoEParams(
        w_router=(jax.random.normal(kr, (d_model, num_experts)) * scale_in
                  ).astype(dtype),
        w_in=(jax.random.normal(ki, (num_experts, d_model, d_hidden))
              * scale_in).astype(dtype),
        w_out=(jax.random.normal(ko, (num_experts, d_hidden, d_model))
               * scale_out).astype(dtype),
    )


def moe_ffn(
    params: MoEParams,
    x: jax.Array,
    *,
    expert_axis: str = EXPERT_AXIS,
    capacity_factor: float = 1.25,
    activation: Callable = jax.nn.gelu,
) -> Tuple[jax.Array, jax.Array]:
    """Apply the expert-parallel MoE FFN to local tokens ``x`` ``[S, D]``.

    Must run inside ``shard_map`` with a mesh that has ``expert_axis``.
    Returns ``(y [S, D], aux_loss scalar)``. Every device routes its own
    S tokens over ALL ``E_total`` experts; token shards travel to the
    expert's owner via all_to_all and come back combined.
    """
    n_exp = _axis_size(expert_axis)
    e_local, d_model, _ = params.w_in.shape
    e_total = e_local * n_exp
    s_tokens = x.shape[0]
    # Static capacity per (expert, source-device): how many of this
    # device's tokens one expert may accept this step. Overflow tokens
    # drop to the residual path (standard Switch behavior).
    capacity = max(1, int(capacity_factor * s_tokens / e_total))

    # --- routing (top-1 / Switch) ---
    logits = x @ params.w_router  # [S, E_total]
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_index = jnp.argmax(gates, axis=-1)              # [S]
    gate = jnp.take_along_axis(
        gates, expert_index[:, None], axis=-1
    )[:, 0]                                                # [S]

    # Position of each token within its expert's capacity buffer.
    onehot = jax.nn.one_hot(expert_index, e_total, dtype=jnp.float32)
    position = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [S, E_total]
    keep = (position < capacity) & (onehot > 0)
    pos = jnp.where(keep, position, 0.0).astype(jnp.int32)

    # Load-balancing auxiliary loss (Switch eq. 4).
    frac_tokens = jnp.mean(onehot, axis=0)
    frac_probs = jnp.mean(gates, axis=0)
    aux_loss = e_total * jnp.sum(frac_tokens * frac_probs)

    # Dense dispatch/combine tensors [S, E_total, C].
    pos_onehot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)
    dispatch = pos_onehot * keep.astype(jnp.float32)[..., None]
    combine = dispatch * gate[:, None, None]

    # [S, E, C] x [S, D] -> [E, C, D]: each expert's capacity buffer.
    expert_in = jnp.einsum("sec,sd->ecd", dispatch, x.astype(jnp.float32))

    # --- all_to_all: send each expert-shard group to its owner ---
    # [E_total, C, D] -> [n_exp, E_local, C, D]; peer p owns experts
    # [p*E_local, (p+1)*E_local).
    expert_in = expert_in.reshape(n_exp, e_local, capacity, d_model)
    # After the exchange dim 0 indexes the *source* device.
    expert_in = lax.all_to_all(
        expert_in, expert_axis, split_axis=0, concat_axis=0, tiled=False
    )  # [n_exp, E_local, C, D]

    # --- expert compute: one batched matmul over local experts ---
    # Fold (source-device, capacity) into one token dim per expert.
    h = jnp.einsum(
        "pecd,edh->pech", expert_in.astype(x.dtype), params.w_in
    )
    h = activation(h)
    out = jnp.einsum("pech,ehd->pecd", h, params.w_out)

    # --- return trip + combine ---
    out = lax.all_to_all(
        out.astype(jnp.float32), expert_axis,
        split_axis=0, concat_axis=0, tiled=False,
    )  # [n_exp, E_local, C, D] with dim 0 = owner again
    out = out.reshape(e_total, capacity, d_model)
    y = jnp.einsum("sec,ecd->sd", combine, out)
    return y.astype(x.dtype), aux_loss


def expert_sharding_specs(tree, expert_axis: str = EXPERT_AXIS):
    """PartitionSpecs for a pytree: ``MoEParams.w_in``/``w_out`` leaves
    shard over ``expert_axis`` (dim 0 = global expert id), everything else
    replicated. Works for params and for optimizer state that mirrors the
    param structure (optax momentum etc.)."""
    def spec(path, _):
        return P(expert_axis) if _is_expert_leaf(path) else P()

    return jax.tree_util.tree_map_with_path(spec, tree)


def _is_expert_leaf(path) -> bool:
    return any(getattr(p, "name", None) in ("w_in", "w_out") for p in path)


def make_ep_train_step(
    loss_fn: Callable,
    optimizer,
    mesh: Mesh,
    params,
    opt_state,
    *,
    batch_spec=None,
    data_axis: str = DATA_AXIS,
    expert_axis: str = EXPERT_AXIS,
    aux_loss_weight: float = 0.01,
    donate: bool = True,
):
    """Build a jitted DP x EP train step.

    ``loss_fn(params, batch) -> (task_loss, aux_loss)`` runs on the local
    batch shard and calls :func:`moe_ffn` somewhere inside. ``params`` /
    ``opt_state`` are example pytrees (structure only) where
    ``MoEParams.w_in``/``w_out`` are sharded ``P(expert_axis)`` and
    everything else is replicated. The batch dim shards over BOTH axes by
    default (``P((data, expert))`` — every device holds distinct tokens;
    the expert group exchanges real work via all_to_all rather than
    duplicating it). Gradients of replicated params reduce over both axes;
    expert-sharded gradients reduce over ``data`` only (each expert shard
    has exactly one owner per data replica).
    """
    if batch_spec is None:
        batch_spec = P((data_axis, expert_axis))
    from ..jax import _shard_map

    def step(params, opt_state, batch):
        def total_loss(p):
            task, aux = loss_fn(p, batch)
            return task + aux_loss_weight * aux, (task, aux)

        (_, (task, aux)), grads = jax.value_and_grad(
            total_loss, has_aux=True
        )(params)

        def reduce_grad(path, g):
            g = lax.pmean(g, data_axis)
            if _is_expert_leaf(path):
                # The all_to_all transpose already SUMMED cotangents from
                # every device in the expert group into the owner's shard;
                # divide so expert grads share the replicated params' scale
                # (grad of the loss pmean'd over both axes).
                g = g / _axis_size(expert_axis)
            else:
                g = lax.pmean(g, expert_axis)
            return g

        grads = jax.tree_util.tree_map_with_path(reduce_grad, grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda a, u: a + u, params, updates)
        return params, opt_state, lax.pmean(task, (data_axis, expert_axis))

    param_specs = expert_sharding_specs(params, expert_axis)
    opt_specs = expert_sharding_specs(opt_state, expert_axis)
    fn = _shard_map(
        step, mesh,
        in_specs=(param_specs, opt_specs, batch_spec),
        out_specs=(param_specs, opt_specs, P()),
    )
    return jax.jit(fn, donate_argnums=(0, 1) if donate else ())
