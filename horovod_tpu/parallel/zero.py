"""ZeRO-1 optimizer-state sharding over the data axis.

TPU-native extension beyond the reference (whose optimizer state is fully
replicated, ``horovod/torch/__init__.py:381-435`` role): the optimizer
state lives sharded 1/N per device, the gradient allreduce becomes a
reduce-scatter, each rank updates only its parameter shard, and the
updated shards are all-gathered back — the ZeRO stage-1 schedule (Rajbhandari
et al., 2019) expressed as three XLA collectives inside one jitted step:

    flat(grads) --psum_scatter--> g_shard          (ICI ring, 1/N bytes out)
    tx.update(g_shard, state_shard, p_shard)       (compute on 1/N params)
    flat(params') <--all_gather-- p_shard'         (ICI ring)

Memory per device: optimizer state + one params copy of updates shrink by
the data-axis size (Adam: 8 bytes/param -> 8/N). Wire bytes match plain
DP's reduce-scatter + all-gather decomposition of the ring allreduce, so
there is no communication penalty.

The parameter pytree is flattened to one vector (padded to a multiple of
the axis size), so element-wise optax transforms (sgd, momentum, adam,
adamw with scalar weight decay, ...) track plain DP to numerical
tolerance (tested; psum_scatter vs psum reduction order leaves no
bitwise guarantee). Transforms that need per-parameter tree structure
(per-layer masking, lars/lamb trust ratios) need the replicated path
instead.
"""

from __future__ import annotations

from typing import Any, Callable

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from .mesh import DATA_AXIS

__all__ = ["init_zero1_state", "make_zero1_train_step", "zero1_update"]


def _flat_meta(params, n_shards: int, block: int = 1):
    flat, unravel = ravel_pytree(params)
    total = flat.shape[0]
    per = -(-total // n_shards)
    per = -(-per // block) * block  # quantized wire: BLOCK-aligned shards
    return flat, unravel, total, per * n_shards, per


def _block(quantized: bool) -> int:
    if not quantized:
        return 1
    from ..ops.quantized import BLOCK

    return BLOCK


def init_zero1_state(optimizer, params, n_shards: int,
                     quantized: bool = False):
    """Per-shard optimizer states, stacked on a leading [n_shards] axis
    (the axis ``make_zero1_train_step`` shards over the mesh). Each
    shard's state is ``optimizer.init`` of that rank's flat parameter
    slice, so stateful transforms (momentum, Adam moments) start exactly
    as they would on the full vector."""
    flat, _, total, padded, k = _flat_meta(
        params, n_shards, _block(quantized)
    )
    flat = jnp.pad(flat, (0, padded - total))
    states = [
        optimizer.init(lax.dynamic_slice(flat, (r * k,), (k,)))
        for r in range(n_shards)
    ]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def zero1_update(optimizer, params, state, grads, *,
                 axis_name: str = DATA_AXIS, n_shards: int,
                 quantized: bool = False):
    """The ZeRO-1 update inside an existing shard_map/pmap context:
    reduce-scatter ``grads`` (averaged over the axis), optax-update this
    rank's flat parameter shard against its 1/N ``state`` (un-stacked, as
    produced by ``init_zero1_state`` rows), all-gather the new params.
    Returns ``(new_params, new_state)``. Use ``make_zero1_train_step`` for
    the packaged whole-step version."""
    import optax

    flat_p, unravel, total, padded, k = _flat_meta(
        params, n_shards, _block(quantized)
    )
    flat_g, _ = ravel_pytree(grads)
    flat_g = jnp.pad(flat_g, (0, padded - total))
    flat_p = jnp.pad(flat_p, (0, padded - total))

    if quantized:
        # int8-wire ring reduce-scatter (ops/quantized.py): the shard
        # length is BLOCK-aligned by _flat_meta, and rank r receives
        # exactly its chunk r, so the composition with the sharded
        # update/all-gather below is layout-free.
        from ..ops.quantized import quantized_ring_reduce_scatter

        g_shard = quantized_ring_reduce_scatter(
            flat_g, axis_name=axis_name, average=True
        )
    else:
        g_shard = lax.psum_scatter(flat_g, axis_name, tiled=True) / n_shards
    idx = lax.axis_index(axis_name)
    p_shard = lax.dynamic_slice(flat_p, (idx * k,), (k,))

    updates, new_state = optimizer.update(g_shard, state, p_shard)
    new_p_shard = optax.apply_updates(p_shard, updates)

    new_flat = lax.all_gather(new_p_shard, axis_name, tiled=True)
    return unravel(new_flat[:total]), new_state


def make_zero1_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    donate: bool = True,
    quantized: bool = False,
):
    """Build the jitted ZeRO-1 step: ``step(params, state, batch) ->
    (params, state, loss)``. ``params`` replicated, ``state`` from
    ``init_zero1_state`` (sharded over ``axis_name``), ``batch`` sharded
    on dim0, gradient averaging over the axis."""
    from ..jax import _shard_map

    n = int(mesh.shape[axis_name])

    def body(params, state_stacked, batch):
        state = jax.tree.map(lambda s: s[0], state_stacked)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        new_params, new_state = zero1_update(
            optimizer, params, state, grads,
            axis_name=axis_name, n_shards=n, quantized=quantized,
        )
        loss = lax.pmean(loss, axis_name)
        return (
            new_params,
            jax.tree.map(lambda s: s[None], new_state),
            loss,
        )

    fn = jax.jit(
        _shard_map(
            body, mesh,
            in_specs=(P(), P(axis_name), P(axis_name)),
            out_specs=(P(), P(axis_name), P()),
        ),
        donate_argnums=(0, 1) if donate else (),
    )
    return fn
