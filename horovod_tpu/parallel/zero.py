"""ZeRO-1 optimizer-state sharding over the data axis.

TPU-native extension beyond the reference (whose optimizer state is fully
replicated, ``horovod/torch/__init__.py:381-435`` role): the optimizer
state lives sharded 1/N per device, the gradient allreduce becomes a
reduce-scatter, each rank updates only its parameter shard, and the
updated shards are all-gathered back — the ZeRO stage-1 schedule (Rajbhandari
et al., 2019) expressed as three XLA collectives inside one jitted step:

    flat(grads) --psum_scatter--> g_shard          (ICI ring, 1/N bytes out)
    tx.update(g_shard, state_shard, p_shard)       (compute on 1/N params)
    flat(params') <--all_gather-- p_shard'         (ICI ring)

Memory per device: optimizer state + one params copy of updates shrink by
the data-axis size (Adam: 8 bytes/param -> 8/N). Wire bytes match plain
DP's reduce-scatter + all-gather decomposition of the ring allreduce, so
there is no communication penalty.

The parameter pytree is flattened to one vector (padded to a multiple of
the axis size), so element-wise optax transforms (sgd, momentum, adam,
adamw with scalar weight decay, ...) track plain DP to numerical
tolerance (tested; psum_scatter vs psum reduction order leaves no
bitwise guarantee). Transforms that need per-parameter tree structure
(per-layer masking, lars/lamb trust ratios) need the replicated path
instead.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from .mesh import DATA_AXIS

__all__ = [
    "Zero1State",
    "init_zero1_state",
    "init_zero1_stream_state",
    "make_zero1_train_step",
    "zero1_posthoc_reduce",
    "zero1_stream_update",
    "zero1_update",
]


def _flat_meta(params, n_shards: int, block: int = 1):
    flat, unravel = ravel_pytree(params)
    total = flat.shape[0]
    per = -(-total // n_shards)
    per = -(-per // block) * block  # quantized wire: BLOCK-aligned shards
    return flat, unravel, total, per * n_shards, per


def _block(quantized: bool) -> int:
    if not quantized:
        return 1
    from ..ops.quantized import BLOCK

    return BLOCK


def init_zero1_state(optimizer, params, n_shards: int,
                     quantized: bool = False):
    """Per-shard optimizer states, stacked on a leading [n_shards] axis
    (the axis ``make_zero1_train_step`` shards over the mesh). Each
    shard's state is ``optimizer.init`` of that rank's flat parameter
    slice, so stateful transforms (momentum, Adam moments) start exactly
    as they would on the full vector."""
    flat, _, total, padded, k = _flat_meta(
        params, n_shards, _block(quantized)
    )
    flat = jnp.pad(flat, (0, padded - total))
    states = [
        optimizer.init(lax.dynamic_slice(flat, (r * k,), (k,)))
        for r in range(n_shards)
    ]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def _check_axis_shards(axis_name, n_shards: int, where: str) -> None:
    """A silent mismatch between the bound axis size and the shard count
    the state was built for misaligns every shard offset; fail loudly."""
    from ..common.compat import axis_size

    live = axis_size(axis_name)
    if live != n_shards:
        raise ValueError(
            f"{where}: optimizer state is sharded {n_shards} ways but "
            f"the bound axis {axis_name!r} has size {live} — the shard "
            f"offsets would silently misalign; rebuild the state for "
            f"this mesh"
        )


def zero1_update(optimizer, params, state, grads, *,
                 axis_name: str = DATA_AXIS, n_shards: int,
                 quantized: bool = False):
    """The ZeRO-1 update inside an existing shard_map/pmap context:
    reduce-scatter ``grads`` (averaged over the axis), optax-update this
    rank's flat parameter shard against its 1/N ``state`` (un-stacked, as
    produced by ``init_zero1_state`` rows), all-gather the new params.
    Returns ``(new_params, new_state)``. Use ``make_zero1_train_step`` for
    the packaged whole-step version."""
    import optax

    _check_axis_shards(axis_name, n_shards, "zero1_update")
    flat_p, unravel, total, padded, k = _flat_meta(
        params, n_shards, _block(quantized)
    )
    flat_g, _ = ravel_pytree(grads)
    flat_g = jnp.pad(flat_g, (0, padded - total))
    flat_p = jnp.pad(flat_p, (0, padded - total))

    if quantized:
        # int8-wire ring reduce-scatter (ops/quantized.py): the shard
        # length is BLOCK-aligned by _flat_meta, and rank r receives
        # exactly its chunk r, so the composition with the sharded
        # update/all-gather below is layout-free.
        from ..ops.quantized import quantized_ring_reduce_scatter

        g_shard = quantized_ring_reduce_scatter(
            flat_g, axis_name=axis_name, average=True
        )
    else:
        g_shard = lax.psum_scatter(flat_g, axis_name, tiled=True) / n_shards
    idx = lax.axis_index(axis_name)
    p_shard = lax.dynamic_slice(flat_p, (idx * k,), (k,))

    updates, new_state = optimizer.update(g_shard, state, p_shard)
    new_p_shard = optax.apply_updates(p_shard, updates)

    new_flat = lax.all_gather(new_p_shard, axis_name, tiled=True)
    return unravel(new_flat[:total]), new_state


def make_zero1_train_step(
    loss_fn: Callable[[Any, Any], jax.Array],
    optimizer,
    mesh: Mesh,
    *,
    axis_name: str = DATA_AXIS,
    donate: bool = True,
    quantized: bool = False,
):
    """Build the jitted ZeRO-1 step: ``step(params, state, batch) ->
    (params, state, loss)``. ``params`` replicated, ``state`` from
    ``init_zero1_state`` (sharded over ``axis_name``), ``batch`` sharded
    on dim0, gradient averaging over the axis."""
    from ..jax import _shard_map

    n = int(mesh.shape[axis_name])

    def body(params, state_stacked, batch):
        state = jax.tree.map(lambda s: s[0], state_stacked)
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        new_params, new_state = zero1_update(
            optimizer, params, state, grads,
            axis_name=axis_name, n_shards=n, quantized=quantized,
        )
        loss = lax.pmean(loss, axis_name)
        return (
            new_params,
            jax.tree.map(lambda s: s[None], new_state),
            loss,
        )

    fn = jax.jit(
        _shard_map(
            body, mesh,
            in_specs=(P(), P(axis_name), P(axis_name)),
            out_specs=(P(), P(axis_name), P()),
        ),
        donate_argnums=(0, 1) if donate else (),
    )
    return fn


# --- streamed ZeRO-1: per-bucket shard layout --------------------------------
#
# The whole-flat-vector schedule above reduce-scatters AFTER the backward
# completes, so it can never overlap with compute. The streamed variant
# (docs/overlap.md "Streamed ZeRO-1") re-expresses ZeRO-1 over the SAME
# bucket partition the overlap fast path streams: each
# ``stream_param_groups`` bucket runs reduce-scatter inside the
# custom_vjp backward (``ops/fusion.fused_reduce_scatter``), each rank
# keeps only its shard's cotangents per bucket, the optimizer state is
# sharded per bucket, and the updated shards all-gather back. The bucket
# layout round-trips exactly through ``ops/fusion.plan_buckets`` — the
# backward and the update derive it from the same planners, so the shard
# a rank updates is bitwise the shard its backward reduced.


class Zero1State(NamedTuple):
    """Streamed-ZeRO-1 optimizer state: per-group, per-bucket optax
    states stacked on a leading ``[n_shards]`` axis (``opt["g<gi>"]
    ["b<bi>"]``), plus the optional SHARDED error-feedback residuals for
    the quantized wire (``ef`` mirrors ``opt``'s keys with f32
    ``[n_shards, k]`` leaves; None without EF). Shard rows are RANK-LOCAL
    by construction — each rank holds and updates only its row — so the
    guard's cross-rank digest agreement hashes only the structure, never
    the bytes (``guard/digest.strip_rank_local``)."""

    opt: Any
    ef: Any


def _zero1_groups(params, threshold_bytes, first_bucket_bytes):
    """Resolve the streamed group partition: returns ``(items, finish)``
    where ``items`` is ``[(label, sub_params)]`` in group order and
    ``finish(new_subs)`` rebuilds the full tree from the per-group
    results (``new_subs`` keyed by label)."""
    from ..ops import fusion as F

    children, rebuild, groups = F.zero1_group_layout(
        params, threshold_bytes, first_bucket_bytes
    )
    if children is None:
        def finish_single(new_subs):
            return new_subs["g0"]

        return [("g0", params)], finish_single

    items = []
    membership = []
    for gi, group in enumerate(groups):
        items.append((f"g{gi}", {str(i): children[i] for i in group}))
        membership.append(group)

    def finish(new_subs):
        out = list(children)
        for gi, group in enumerate(membership):
            sub = new_subs[f"g{gi}"]
            for i in group:
                out[i] = sub[str(i)]
        return rebuild(out)

    return items, finish


def init_zero1_stream_state(
    optimizer,
    params,
    n_shards: int,
    *,
    threshold_bytes: Optional[int] = None,
    first_bucket_bytes: Optional[int] = None,
    quantized: bool = False,
    error_feedback: Optional[bool] = None,
) -> Zero1State:
    """Build the :class:`Zero1State` for ``make_train_step(zero1=True)``:
    for every streamed group and fusion bucket, ``optimizer.init`` of
    each rank's packed parameter shard, stacked on a leading
    ``[n_shards]`` axis (shard the leading axis over the data axis /
    hierarchy tuple). Non-float and zero-length buckets carry no state
    (the update passes them through). ``error_feedback`` (default: on
    for the quantized wire) adds the zero sharded residuals."""
    from ..ops import fusion as F

    use_ef = bool(quantized) if error_feedback is None else bool(error_feedback)
    if use_ef and not quantized:
        raise ValueError("error_feedback=True requires quantized=True")
    items, _ = _zero1_groups(params, threshold_bytes, first_bucket_bytes)
    threshold = F.default_threshold_bytes(threshold_bytes)
    opt: Dict[str, Dict[str, Any]] = {}
    ef: Dict[str, Dict[str, Any]] = {}
    for label, sub in items:
        leaves = jax.tree.leaves(sub)
        g_opt: Dict[str, Any] = {}
        g_ef: Dict[str, Any] = {}
        for bi, bucket in enumerate(F.plan_buckets(leaves, threshold)):
            packed = F.pack_bucket([leaves[i] for i in bucket])
            total = packed.shape[0]
            if total == 0 or not jnp.issubdtype(packed.dtype, jnp.floating):
                continue
            k = F.zero1_shard_len(total, n_shards, quantized)
            buf = jnp.pad(packed, (0, n_shards * k - total))
            states = [
                optimizer.init(lax.dynamic_slice(buf, (r * k,), (k,)))
                for r in range(n_shards)
            ]
            g_opt[f"b{bi}"] = jax.tree.map(
                lambda *xs: jnp.stack(xs), *states
            )
            if use_ef:
                g_ef[f"b{bi}"] = jnp.zeros((n_shards, k), jnp.float32)
        opt[label] = g_opt
        if use_ef:
            ef[label] = g_ef
    return Zero1State(opt=opt, ef=ef if use_ef else None)


def zero1_posthoc_reduce(
    grads,
    *,
    op=None,
    axis_name: Any = DATA_AXIS,
    threshold_bytes: Optional[int] = None,
    first_bucket_bytes: Optional[int] = None,
    quantized: bool = False,
    ef: Any = None,
    label: str = "zero1-posthoc",
):
    """Post-hoc form of the streamed-zero1 reduction: the SAME group
    partition and per-bucket reduce-scatter the backward rule runs,
    applied to an already-computed gradient tree. Returns
    ``(shard_images, new_ef)`` — bitwise identical to the streamed
    path's output (one reduction, two call sites)."""
    from ..common.types import ReduceOp
    from ..ops import fusion as F

    op = ReduceOp.AVERAGE if op is None else op
    items, finish = _zero1_groups(
        grads, threshold_bytes, first_bucket_bytes
    )
    threshold = F.default_threshold_bytes(threshold_bytes)
    new_subs: Dict[str, Any] = {}
    new_ef: Dict[str, Any] = {}
    for gi, (glabel, sub) in enumerate(items):
        sub_ef = None
        if ef is not None:
            if glabel not in ef:
                raise ValueError(
                    f"sharded EF residual is missing group {glabel!r} — "
                    f"build it with init_zero1_stream_state"
                )
            sub_ef = ef[glabel]
        images, sub_new_ef = F.fused_reduce_scatter(
            sub,
            op=op,
            axis_name=axis_name,
            threshold_bytes=threshold,
            quantized=quantized,
            ef=sub_ef,
            label=f"{label}:{glabel}",
        )
        new_subs[glabel] = images
        if sub_new_ef is not None:
            new_ef[glabel] = sub_new_ef
    return finish(new_subs), (new_ef if ef is not None else None)


def zero1_stream_update(
    optimizer,
    params,
    opt_buckets,
    grads,
    *,
    axis_name: Any = DATA_AXIS,
    n_shards: int,
    threshold_bytes: Optional[int] = None,
    first_bucket_bytes: Optional[int] = None,
    quantized: bool = False,
):
    """The shard-local update against the bucketized shard layout:
    ``grads`` are SHARD IMAGES (from the streamed backward or
    :func:`zero1_posthoc_reduce`), ``opt_buckets`` is this rank's row of
    ``Zero1State.opt``. Per bucket: re-pack the image (recovering the
    reduce-scattered shard bitwise), slice this rank's parameter shard,
    optax-update it against the bucket's 1/N state, and all-gather the
    updated shards back into the full parameter layout (hierarchical
    all-gather on an axis tuple — only the 1/L shard crosses DCN).
    Returns ``(new_params, new_opt_buckets)``. Padding is proven
    zero-contribution: padded tails never leave the gather (the image is
    truncated to the bucket's true length before unpacking)."""
    import optax

    from ..ops import fusion as F

    axes = F._axes_of(axis_name)
    _check_axis_shards(
        axes if len(axes) > 1 else axes[0], n_shards, "zero1_stream_update"
    )
    items, finish = _zero1_groups(params, threshold_bytes, first_bucket_bytes)
    g_items, _ = _zero1_groups(grads, threshold_bytes, first_bucket_bytes)
    threshold = F.default_threshold_bytes(threshold_bytes)
    idx = F.zero1_axis_rank(axes if len(axes) > 1 else axes[0])
    ag_payload = 0
    new_subs: Dict[str, Any] = {}
    new_opt: Dict[str, Dict[str, Any]] = {}
    for (glabel, sub_p), (_, sub_g) in zip(items, g_items):
        p_leaves, treedef = jax.tree.flatten(sub_p)
        g_leaves = jax.tree.leaves(sub_g)
        states = opt_buckets.get(glabel, {})
        results = list(p_leaves)
        g_opt: Dict[str, Any] = {}
        for bi, bucket in enumerate(F.plan_buckets(p_leaves, threshold)):
            bkey = f"b{bi}"
            packed_p = F.pack_bucket([p_leaves[i] for i in bucket])
            total = packed_p.shape[0]
            if (
                total == 0
                or not jnp.issubdtype(packed_p.dtype, jnp.floating)
            ):
                continue  # no shard state: parameters pass through
            if bkey not in states:
                raise ValueError(
                    f"zero1 optimizer state is missing bucket "
                    f"{glabel}/{bkey} — the state was built for a "
                    f"different partition (threshold/first-bucket/"
                    f"quantized knobs must match init_zero1_stream_state)"
                )
            packed_g = F.pack_bucket([g_leaves[i] for i in bucket])
            k = F.zero1_shard_len(total, n_shards, quantized)
            pad = n_shards * k - total
            buf_p = jnp.pad(packed_p, (0, pad))
            buf_g = jnp.pad(packed_g, (0, pad))
            g_shard = lax.dynamic_slice(buf_g, (idx * k,), (k,))
            p_shard = lax.dynamic_slice(buf_p, (idx * k,), (k,))
            updates, new_state = optimizer.update(
                g_shard, states[bkey], p_shard
            )
            new_p_shard = optax.apply_updates(p_shard, updates)
            if len(axes) > 1:
                from ..topo import compositor as _compositor

                full = _compositor.lower_allgather(
                    new_p_shard, axes, algorithm="two-level"
                )
            else:
                full = lax.all_gather(new_p_shard, axes[0], tiled=True)
            ag_payload += n_shards * k * np.dtype(packed_p.dtype).itemsize
            unpacked = F.unpack_bucket(
                full[:total], [p_leaves[i].shape for i in bucket]
            )
            for i, r in zip(bucket, unpacked):
                results[i] = r
            g_opt[bkey] = new_state
        stale = set(states) - set(g_opt)
        if stale:
            raise ValueError(
                f"zero1 optimizer state carries buckets {sorted(stale)} "
                f"the live partition of group {glabel!r} does not — "
                f"stale shard layout"
            )
        new_subs[glabel] = jax.tree.unflatten(treedef, results)
        new_opt[glabel] = g_opt
    if ag_payload:
        # Per-axis attribution (trace-time): the parameter all-gather is
        # always full precision — replicas must stay exact.
        F.record_axis_wire_bytes(ag_payload, axis_name, "all_gather")
    return finish(new_subs), new_opt
