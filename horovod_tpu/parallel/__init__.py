"""Parallelism toolbox: mesh construction (``mesh``), DP x TP sharding
rules (``rules``), ZeRO-1 optimizer-state sharding (``zero``), expert
parallelism (``ep``), and elastic resharding across world-shape changes
(``reshard``). Submodules import jax lazily where they can — the reshard
planning half and this package root stay importable on a jax-free host
(fleet simulator, capacity tooling)."""

from . import reshard  # noqa: F401 (jax-free planning half)

__all__ = ["reshard"]
