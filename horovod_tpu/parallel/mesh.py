"""Device-mesh construction.

The mesh is the TPU-native analogue of the reference's communicator set
(``horovod/common/mpi/mpi_context.cc:149-158`` global/local/cross comms): a
named axis of the mesh *is* a communicator, and XLA lowers collectives over
it to ICI (intra-slice) or DCN (inter-slice) transfers automatically when the
axis ordering follows the physical topology.

Conventions:
 - ``data`` — the data-parallel axis (Horovod's world communicator).
 - ``local`` / ``cross`` — the two-level split used by hierarchical ops
   (ICI within a slice, DCN across slices), mirroring the reference's
   NCCL-local + MPI-cross structure (``nccl_operations.cc:151-346``).
 - ``model`` / ``seq`` / ``expert`` — extension axes for TP/SP/EP.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

from .. import metrics as _metrics

logger = logging.getLogger("horovod_tpu")

DATA_AXIS = "data"
LOCAL_AXIS = "local"
CROSS_AXIS = "cross"
POD_AXIS = "pod"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"


def parse_axes(spec: str) -> Dict[str, int]:
    """Parse a ``"data:4,model:2"`` style axis spec. ``-1`` means "fill"."""
    axes: Dict[str, int] = {}
    if not spec:
        return axes
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, n = part.split(":", 1)
            axes[name.strip()] = int(n)
        else:
            axes[part] = -1
    return axes


def build_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh over ``devices`` with the given named axis sizes.

    With no spec, a single ``data`` axis spans every device — the pure-DP
    configuration that matches the reference's world communicator. At most
    one axis may be ``-1`` (filled with the remaining device count). Device
    order follows ``mesh_utils.create_device_mesh`` so ICI neighbours stay
    adjacent on TPU.
    """
    devices = list(devices if devices is not None else jax.devices())
    ndev = len(devices)
    if not axes:
        axes = {DATA_AXIS: ndev}
    axes = dict(axes)

    fill_axes = [k for k, v in axes.items() if v == -1]
    if len(fill_axes) > 1:
        raise ValueError(f"At most one mesh axis may be -1 (fill): {axes}")
    known = 1
    for k, v in axes.items():
        if v != -1:
            known *= v
    if fill_axes:
        if ndev % known != 0:
            raise ValueError(
                f"Cannot fill axis {fill_axes[0]}: {ndev} devices not divisible "
                f"by {known}"
            )
        axes[fill_axes[0]] = ndev // known
    total = int(np.prod(list(axes.values())))
    if total != ndev:
        raise ValueError(
            f"Mesh axes {axes} require {total} devices but {ndev} are available"
        )

    shape = tuple(axes.values())
    names = tuple(axes.keys())
    try:
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(
            shape, devices=devices, allow_split_physical_axes=True
        )
    except Exception as exc:  # noqa: BLE001 - degrade, but LOUDLY
        # The naive reshape keeps every collective correct but loses the
        # physical ICI adjacency create_device_mesh preserves — on a real
        # pod that silently turns "local" hops into cross-chip traffic,
        # so this fallback must never pass unnoticed.
        logger.warning(
            "mesh_utils.create_device_mesh failed for shape %s (%s: %s); "
            "falling back to a bare device reshape — ICI adjacency is NOT "
            "preserved and hierarchical lowerings may ride the wrong links",
            dict(zip(names, shape)), type(exc).__name__, exc,
        )
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_mesh_fallback_total",
                             error=type(exc).__name__)
        dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, names)


def build_hierarchical_mesh(
    local_size: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Two-level ``(cross, local)`` mesh for hierarchical allreduce.

    ``local`` spans the chips inside one slice/host (ICI) and ``cross``
    spans slices (DCN) — the direct analogue of the reference's
    NCCLHierarchicalAllreduce structure (``nccl_operations.cc:151-346``).
    """
    devices = list(devices if devices is not None else jax.devices())
    ndev = len(devices)
    if ndev % local_size != 0:
        raise ValueError(f"{ndev} devices not divisible by local_size={local_size}")
    return build_mesh(
        {CROSS_AXIS: ndev // local_size, LOCAL_AXIS: local_size}, devices
    )


def build_three_level_mesh(
    pod_size: int,
    cross_size: int,
    local_size: int,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Three-level ``(pod, cross, local)`` mesh: ``local`` rides ICI
    within a slice, ``cross`` rides DCN between slices of one pod, and
    ``pod`` rides the (slower) inter-pod DCN — the hierarchy the
    compositor's three-level plans lower over (docs/topology.md). Rank
    layout is ``rank = pod*(cross*local) + cross*local + local``, the
    outer-major order every hierarchical lowering assumes."""
    devices = list(devices if devices is not None else jax.devices())
    ndev = len(devices)
    if ndev != pod_size * cross_size * local_size:
        raise ValueError(
            f"{ndev} devices != pod {pod_size} x cross {cross_size} x "
            f"local {local_size}"
        )
    return build_mesh(
        {POD_AXIS: pod_size, CROSS_AXIS: cross_size, LOCAL_AXIS: local_size},
        devices,
    )


def hierarchy_axes(mesh: Mesh) -> Tuple[str, ...]:
    """The mesh's hierarchy axis tuple, outermost first — () when the
    mesh has no (cross, local) grid to compose over."""
    if LOCAL_AXIS not in mesh.axis_names or CROSS_AXIS not in mesh.axis_names:
        return ()
    axes = [CROSS_AXIS, LOCAL_AXIS]
    if POD_AXIS in mesh.axis_names:
        axes.insert(0, POD_AXIS)
    return tuple(axes)


def data_axis_size(mesh: Mesh) -> int:
    return int(mesh.shape[DATA_AXIS]) if DATA_AXIS in mesh.axis_names else 1
