"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference framework has no sequence-parallel support (SURVEY.md §2.3 —
its op set is allreduce/allgather/broadcast only); long-context parallelism
is a TPU-native extension of this framework, built on the same mesh
machinery as the data plane.

Two schemes, both SPMD over a named ``seq`` axis:

- **Ring attention** (`ring_attention`): K/V blocks rotate around the ring
  via ``lax.ppermute`` while each device keeps its Q shard, accumulating
  attention with the online-softmax (flash) recurrence — memory per device
  is O(T/n), communication overlaps with compute on ICI, and arbitrary
  context lengths scale linearly with the ring size.
- **Ulysses** (`ulysses_attention`): ``all_to_all`` re-shards from
  sequence-sharded to head-sharded, runs dense local attention, and
  re-shards back — cheaper at moderate T when heads >= ring size.

Causality is handled with global-position masks; blocks that are entirely
masked are skipped numerically by the online-softmax guard (they contribute
exp(-inf)=0).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..common.compat import axis_size as _axis_size
from .mesh import SEQ_AXIS


def _block_attn(q, k, v, bias, m_prev, l_prev, o_prev, scale):
    """One online-softmax accumulation step.

    q: [B, Tq, H, D]; k/v: [B, Tk, H, D]; bias: [Tq, Tk] additive mask.
    Carries m (rowmax), l (denominator), o (unnormalized numerator).
    """
    compute = jnp.float32
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(compute), k.astype(compute)
    ) * scale
    scores = scores + bias[None, None, :, :]
    m_cur = jnp.max(scores, axis=-1)  # [B, H, Tq]
    m_new = jnp.maximum(m_prev, m_cur)
    # Guard fully-masked rows (m == -inf): keep them at zero contribution.
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(scores - safe_m[..., None])
    p = jnp.where(jnp.isfinite(scores), p, 0.0)
    corr = jnp.where(
        jnp.isfinite(m_prev), jnp.exp(m_prev - safe_m), 0.0
    )
    l_new = l_prev * corr + jnp.sum(p, axis=-1)
    o_new = o_prev * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v.astype(compute)
    )
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash: bool = True,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Blockwise ring attention over a named mesh axis (call inside
    shard_map). q/k/v: [batch, seq_local, heads, head_dim], sequence-sharded
    on ``axis_name``. Returns [batch, seq_local, heads, head_dim].

    The per-block compute is the Pallas flash kernel
    (``ops/pallas_attention.flash_attention_block``): each ring step runs
    the fused block on the resident K/V shard, and the returned
    ``(o_unnorm, m, l)`` triples are merged with the standard online-softmax
    log-sum-exp combination. ``use_flash=False`` falls back to the dense
    jnp block (kept for A/B numerics testing).
    """
    n = _axis_size(axis_name)
    # Only materialize the rank when a code path consumes it: a dead
    # axis_index survives shard_map lowering as a PartitionId HLO, which
    # the SPMD partitioner rejects (the non-causal flash kernel never
    # reads the block offset).
    rank = (
        lax.axis_index(axis_name) if (causal or not use_flash)
        else jnp.int32(0)
    )
    B, T, H, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    # Ring: after s steps this rank holds the K/V block originally owned by
    # rank (rank - s) mod n. Source i sends to (i+1) mod n each step.
    perm = [(i, (i + 1) % n) for i in range(n)]

    if use_flash:
        from ..ops.pallas_attention import _NEG_INF, flash_attention_block

        # Fold heads into the kernel batch axis once; K/V rotate folded.
        fold = lambda x: x.transpose(0, 2, 1, 3).reshape(B * H, T, D)
        qf, kf, vf = fold(q), fold(k), fold(v)
        m0 = jnp.full((B * H, T), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((B * H, T), jnp.float32)
        o0 = jnp.zeros((B * H, T, D), jnp.float32)

        def step(carry, s):
            k_blk, v_blk, m, l, o = carry
            src = (rank - s) % n
            # K block's global origin minus Q's: positions k_pos + delta.
            delta = ((src - rank) * T).astype(jnp.float32)
            o_s, m_s, l_s = flash_attention_block(
                qf, k_blk, v_blk, delta, sm_scale=scale, causal=causal,
                interpret=interpret,
            )
            # Online-softmax merge of two partial blocks (finite -1e30
            # sentinel: fully-masked blocks contribute exp(-huge) = 0).
            m_new = jnp.maximum(m, m_s)
            c = jnp.exp(m - m_new)
            c_s = jnp.exp(m_s - m_new)
            o = o * c[..., None] + o_s * c_s[..., None]
            l = l * c + l_s * c_s
            # Rotate for the next step. XLA schedules this ppermute
            # concurrently with the block compute on TPU (collective-compute
            # overlap on ICI).
            k_nxt = lax.ppermute(k_blk, axis_name, perm)
            v_nxt = lax.ppermute(v_blk, axis_name, perm)
            return (k_nxt, v_nxt, m_new, l, o), None

        (k_f, v_f, m, l, o), _ = lax.scan(
            step, (kf, vf, m0, l0, o0), jnp.arange(n)
        )
        l = jnp.where(l == 0.0, 1.0, l)
        out = (o / l[..., None]).astype(q.dtype)      # [BH, T, D]
        return out.reshape(B, H, T, D).transpose(0, 2, 1, 3)

    q_offset = rank * T
    compute = jnp.float32
    m0 = jnp.full((B, H, T), -jnp.inf, compute)
    l0 = jnp.zeros((B, H, T), compute)
    o0 = jnp.zeros((B, H, T, D), compute)
    q_pos = q_offset + jnp.arange(T)

    def step(carry, s):
        k_blk, v_blk, m, l, o = carry
        src = (rank - s) % n
        k_pos = src * T + jnp.arange(T)
        if causal:
            bias = jnp.where(
                k_pos[None, :] > q_pos[:, None], -jnp.inf, 0.0
            ).astype(compute)
        else:
            bias = jnp.zeros((T, T), compute)
        m, l, o = _block_attn(q, k_blk, v_blk, bias, m, l, o, scale)
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        return (k_nxt, v_nxt, m, l, o), None

    (k_f, v_f, m, l, o), _ = lax.scan(
        step, (k, v, m0, l0, o0), jnp.arange(n)
    )
    l = jnp.where(l == 0.0, 1.0, l)
    out = (o / l[..., None]).astype(q.dtype)  # [B, H, T, D]
    return jnp.transpose(out, (0, 2, 1, 3))


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = SEQ_AXIS,
    causal: bool = False,
    scale: Optional[float] = None,
    use_flash: bool = True,
) -> jax.Array:
    """Ulysses all-to-all sequence parallelism (call inside shard_map):
    re-shard [B, T/n, H, D] -> [B, T, H/n, D], local attention over the
    full sequence (the Pallas flash kernel by default), then re-shard
    back. Requires heads % axis_size == 0."""
    n = _axis_size(axis_name)
    B, T, H, D = q.shape
    if H % n != 0:
        raise ValueError(f"ulysses needs heads ({H}) divisible by axis ({n})")

    def seq_to_heads(x):
        # [B, Tl, H, D] -> [B, Tl*n(=T), H/n, D]
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    scale_v = scale if scale is not None else 1.0 / math.sqrt(D)
    if use_flash:
        from ..ops.pallas_attention import flash_attention_bthd

        out = flash_attention_bthd(
            qg, kg, vg, causal=causal, sm_scale=scale_v
        )
        return heads_to_seq(out)
    Tg = qg.shape[1]
    compute = jnp.float32
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", qg.astype(compute), kg.astype(compute)
    ) * scale_v
    if causal:
        pos = jnp.arange(Tg)
        scores = jnp.where(
            pos[None, None, None, :] > pos[None, None, :, None],
            -jnp.inf, scores,
        )
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, vg.astype(compute))
    return heads_to_seq(out.astype(q.dtype))


def reference_attention(q, k, v, *, causal: bool = False,
                        scale: Optional[float] = None):
    """Dense single-device reference (for tests)."""
    B, T, H, D = q.shape
    scale_v = scale if scale is not None else 1.0 / math.sqrt(D)
    compute = jnp.float32
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(compute), k.astype(compute)
    ) * scale_v
    if causal:
        pos = jnp.arange(T)
        scores = jnp.where(
            pos[None, None, None, :] > pos[None, None, :, None],
            -jnp.inf, scores,
        )
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(compute))
    return out.astype(q.dtype)
