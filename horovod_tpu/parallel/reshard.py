"""Elastic resharding: sharded fast-path state survives world-shape changes.

Every robustness mechanism that changes the world shape (slowness
quarantine, hot-spare promotion, elastic scale-in/out) used to assume
state is replicated — ``parallel/zero.py`` raises loudly on any
axis-size mismatch and checkpoints carried no layout metadata. This
module closes that gap: given an old layout (mesh axes/sizes + a
sharding-spec tree or a :class:`Zero1Layout` bucket layout) and a new
one, it computes and executes the redistribution —

- re-partitioning ZeRO-1 bucket shards ``[n_old, k_old] -> [n_new,
  k_new]`` across a changed data-axis size,
- re-slicing TP-sharded leaves per the rules engine's specs on the new
  mesh (checkpoint restore assembles global leaves from per-rank shard
  payloads via the same interval math),
- folding-or-zeroing error-feedback residuals with an explicit counter
  and a warning — never silent loss.

The module has two halves:

PLANNING (pure, no jax import at module scope): shard-interval
arithmetic (:func:`shard_intervals`, :func:`transfer_plan`),
redistribution bytes-on-wire accounting (:func:`plan_bytes`,
:func:`resize_redistribution`), layout descriptions
(:class:`BucketLayout`, :class:`Zero1Layout`, :class:`LayoutManifest`)
and rank-coordinate / leaf-slice math (:func:`rank_coords`,
:func:`leaf_slices`) mirroring ``parallel/rules.local_shard_tree``
host-side. Everything here runs on a laptop or inside the fleet
simulator with no accelerator runtime.

EXECUTION (imports jax lazily): :func:`zero1_layout_from_params`
derives the live bucket layout from the SAME planners the streamed step
uses (``ops/fusion``), and :func:`reshard_zero1_state` re-stacks a host
:class:`~horovod_tpu.parallel.zero.Zero1State` onto a new shard count —
property: ``gather(reshard(state)) == gather(state)`` bitwise for every
exact dtype (the payload bytes are moved, never recomputed).

Observability: each executed reshard increments
``hvd_reshard_total{trigger=...}`` and ``hvd_reshard_bytes_total
{axis=...}`` and emits an ``hvd_reshard`` span on the trace lanes
(docs/fault_tolerance.md "Elastic resharding").
"""

from __future__ import annotations

import hashlib
import json
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

logger = logging.getLogger("horovod_tpu.reshard")

__all__ = [
    "BucketLayout",
    "LayoutManifest",
    "ReshardPlan",
    "ShardMove",
    "Zero1Layout",
    "leaf_slices",
    "plan_bytes",
    "plan_zero1_reshard",
    "rank_coords",
    "reshard_zero1_state",
    "reshard_zero1_tree",
    "resize_redistribution",
    "shard_intervals",
    "shard_len",
    "transfer_plan",
    "zero1_layout_from_params",
]

# Mirrors ops/quantized.BLOCK without importing the jax-side module: the
# int8 wire scales per 256-element block, so quantized shard lengths are
# BLOCK-aligned (cross-checked against ops/fusion.zero1_shard_len in
# tests/test_reshard.py).
_BLOCK = 256

MANIFEST_SCHEMA = 1


def shard_len(total: int, n_shards: int, quantized: bool = False) -> int:
    """Per-shard length for a ``total``-element vector split ``n_shards``
    ways — the pure mirror of ``ops/fusion.zero1_shard_len`` (ceil
    division, BLOCK-aligned when the bucket rides the quantized wire)."""
    if n_shards <= 0:
        raise ValueError(f"n_shards must be positive, got {n_shards}")
    k = -(-max(int(total), 1) // int(n_shards))
    if quantized:
        k = -(-k // _BLOCK) * _BLOCK
    return k


def shard_intervals(total: int, n_shards: int, k: int) -> List[Tuple[int, int]]:
    """Half-open global intervals ``[start, end)`` of REAL (un-padded)
    elements each shard row holds: row ``r`` covers ``[r*k, r*k+k)``
    clipped to ``[0, total)``. Rows past the data are empty intervals."""
    out = []
    for r in range(int(n_shards)):
        start = min(r * k, total)
        out.append((start, min(start + k, total)))
    return out


@dataclass(frozen=True)
class ShardMove:
    """One contiguous slice movement in a reshard: ``length`` elements
    starting at global offset ``start`` travel from row ``src`` (local
    offset ``src_off``) to row ``dst`` (local offset ``dst_off``)."""

    src: int
    dst: int
    src_off: int
    dst_off: int
    start: int
    length: int


def transfer_plan(total: int, n_old: int, k_old: int,
                  n_new: int, k_new: int) -> List[ShardMove]:
    """The slice-level redistribution plan from an ``[n_old, k_old]``
    row layout to ``[n_new, k_new]``: for every new row, the old-row
    slices that cover its global interval, in global order. The plan is
    exhaustive and disjoint — every real element moves exactly once —
    which the property tests assert by executing it."""
    old_iv = shard_intervals(total, n_old, k_old)
    moves: List[ShardMove] = []
    for dst, (ds, de) in enumerate(shard_intervals(total, n_new, k_new)):
        if ds >= de:
            continue
        for src, (ss, se) in enumerate(old_iv):
            lo, hi = max(ds, ss), min(de, se)
            if lo >= hi:
                continue
            moves.append(ShardMove(
                src=src, dst=dst, src_off=lo - ss, dst_off=lo - ds,
                start=lo, length=hi - lo,
            ))
    return moves


def plan_bytes(moves: Sequence[ShardMove], itemsize: int) -> Tuple[int, int]:
    """``(moved_bytes, local_bytes)`` for a transfer plan: elements whose
    source and destination row differ cross the wire on a real fleet;
    same-row elements are local copies (possibly at a shifted offset)."""
    moved = sum(m.length for m in moves if m.src != m.dst) * int(itemsize)
    local = sum(m.length for m in moves if m.src == m.dst) * int(itemsize)
    return moved, local


@dataclass(frozen=True)
class BucketLayout:
    """Shard layout of ONE fusion bucket: ``total`` real elements of
    ``dtype``, held as ``n_shards`` rows of ``k`` (``n*k - total`` pad)."""

    total: int
    k: int
    dtype: str

    def to_dict(self) -> dict:
        return {"total": self.total, "k": self.k, "dtype": self.dtype}

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "BucketLayout":
        return cls(total=int(d["total"]), k=int(d["k"]),
                   dtype=str(d["dtype"]))


@dataclass
class Zero1Layout:
    """The full streamed-ZeRO-1 shard layout: which fusion bucket holds
    how many elements of what dtype at which per-row length. Derived
    from the live params by :func:`zero1_layout_from_params` (execution
    half) and carried in checkpoints / elastic snapshots so a restore at
    a DIFFERENT world size can plan the redistribution without the
    original params in hand."""

    n_shards: int
    quantized: bool
    buckets: Dict[str, Dict[str, BucketLayout]] = field(default_factory=dict)

    def bucket_items(self) -> List[Tuple[str, str, BucketLayout]]:
        out = []
        for g in sorted(self.buckets):
            for b in sorted(self.buckets[g]):
                out.append((g, b, self.buckets[g][b]))
        return out

    def total_elements(self) -> int:
        return sum(bl.total for _, _, bl in self.bucket_items())

    def relayout(self, n_new: int) -> "Zero1Layout":
        """Same buckets/totals/dtypes on a new shard count: each
        bucket's row length is re-derived by the SAME rule the streamed
        step will apply at the new world size."""
        return Zero1Layout(
            n_shards=int(n_new), quantized=self.quantized,
            buckets={
                g: {
                    b: BucketLayout(
                        total=bl.total,
                        k=shard_len(bl.total, n_new, self.quantized),
                        dtype=bl.dtype,
                    )
                    for b, bl in sub.items()
                }
                for g, sub in self.buckets.items()
            },
        )

    def to_dict(self) -> dict:
        return {
            "n_shards": self.n_shards,
            "quantized": self.quantized,
            "buckets": {
                g: {b: bl.to_dict() for b, bl in sub.items()}
                for g, sub in self.buckets.items()
            },
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "Zero1Layout":
        return cls(
            n_shards=int(d["n_shards"]),
            quantized=bool(d["quantized"]),
            buckets={
                g: {b: BucketLayout.from_dict(bl) for b, bl in sub.items()}
                for g, sub in dict(d["buckets"]).items()
            },
        )

    def describe(self) -> str:
        n_buckets = len(self.bucket_items())
        return (
            f"zero1[n_shards={self.n_shards}, quantized={self.quantized}, "
            f"{n_buckets} buckets, {self.total_elements()} elements]"
        )


@dataclass
class ReshardPlan:
    """The executable redistribution from one :class:`Zero1Layout` to
    another: per-bucket slice moves plus the bytes-on-wire accounting
    the fleet simulator prices (one state copy per optimizer slot rides
    the same plan)."""

    old: Zero1Layout
    new: Zero1Layout
    moves: Dict[Tuple[str, str], List[ShardMove]]
    moved_bytes: int
    local_bytes: int

    def summary(self) -> dict:
        return {
            "n_old": self.old.n_shards,
            "n_new": self.new.n_shards,
            "buckets": len(self.moves),
            "elements": self.old.total_elements(),
            "moved_bytes": self.moved_bytes,
            "local_bytes": self.local_bytes,
        }


def _dtype_itemsize(dtype: str) -> int:
    sizes = {
        "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
        "float32": 4, "int32": 4, "uint32": 4,
        "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
        "int8": 1, "uint8": 1, "bool": 1,
    }
    try:
        return sizes[str(dtype)]
    except KeyError:
        raise ValueError(f"unknown dtype {dtype!r} in bucket layout")


def plan_zero1_reshard(old: Zero1Layout, new: Zero1Layout) -> ReshardPlan:
    """Plan the redistribution between two ZeRO-1 layouts. The layouts
    must describe the SAME parameter partition (identical group/bucket
    keys, totals, and dtypes) — a mismatch means the two worlds bucketed
    different params and no byte-moving plan can reconcile them."""
    if bool(old.quantized) != bool(new.quantized):
        raise ValueError(
            f"cannot reshard across wire formats: old layout "
            f"quantized={old.quantized}, new quantized={new.quantized} — "
            f"shard lengths are BLOCK-aligned only on the quantized wire"
        )
    old_keys = [(g, b) for g, b, _ in old.bucket_items()]
    new_keys = [(g, b) for g, b, _ in new.bucket_items()]
    if old_keys != new_keys:
        raise ValueError(
            f"bucket partitions differ: old has {old_keys}, new has "
            f"{new_keys} — the layouts were built for different params"
        )
    moves: Dict[Tuple[str, str], List[ShardMove]] = {}
    moved = local = 0
    for g, b, obl in old.bucket_items():
        nbl = new.buckets[g][b]
        if obl.total != nbl.total or obl.dtype != nbl.dtype:
            raise ValueError(
                f"bucket {g}/{b} mismatch: old total={obl.total} "
                f"dtype={obl.dtype}, new total={nbl.total} "
                f"dtype={nbl.dtype} — the layouts were built for "
                f"different params"
            )
        plan = transfer_plan(
            obl.total, old.n_shards, obl.k, new.n_shards, nbl.k
        )
        moves[(g, b)] = plan
        m, l = plan_bytes(plan, _dtype_itemsize(obl.dtype))
        moved += m
        local += l
    return ReshardPlan(
        old=old, new=new, moves=moves, moved_bytes=moved, local_bytes=local
    )


def resize_redistribution(elements: int, itemsize: int, n_old: int,
                          n_new: int, *, quantized: bool = False,
                          copies: int = 1) -> dict:
    """Bytes-on-wire accounting for resizing one sharded vector of
    ``elements`` items from ``n_old`` to ``n_new`` rows — the pure
    pricing primitive the fleet simulator and the selfdrive re-plan
    ladder use (``copies`` = number of state vectors riding the same
    layout: e.g. Adam's mu+nu+EF ride the param partition 3x)."""
    k_old = shard_len(elements, n_old, quantized)
    k_new = shard_len(elements, n_new, quantized)
    plan = transfer_plan(elements, n_old, k_old, n_new, k_new)
    moved, local = plan_bytes(plan, itemsize)
    return {
        "elements": int(elements),
        "n_old": int(n_old),
        "n_new": int(n_new),
        "k_old": k_old,
        "k_new": k_new,
        "copies": int(copies),
        "moved_bytes": moved * int(copies),
        "local_bytes": local * int(copies),
        "total_bytes": int(elements) * int(itemsize) * int(copies),
    }


# --- rank-coordinate / leaf-slice math (pure mirror of rules engine) --------


def rank_coords(mesh_axes: Sequence[Tuple[str, int]], rank: int
                ) -> Dict[str, int]:
    """Axis coordinates of flat ``rank`` on a row-major mesh described
    as an ordered ``[(axis, size), ...]`` list — the pure mirror of
    ``Mesh.devices`` indexing for checkpoint shard assembly."""
    world = 1
    for _, size in mesh_axes:
        world *= int(size)
    if not 0 <= rank < world:
        raise ValueError(f"rank {rank} out of range for mesh {mesh_axes}")
    coords: Dict[str, int] = {}
    rem = int(rank)
    for axis, size in reversed(list(mesh_axes)):
        coords[axis] = rem % int(size)
        rem //= int(size)
    return coords


def _spec_dims(spec: Any) -> List[Tuple[str, ...]]:
    """Normalize a per-leaf spec (as serialized in the manifest: a list
    with one entry per array dim, each entry None, an axis name, or a
    list of axis names) to a tuple-of-axis-tuples."""
    dims: List[Tuple[str, ...]] = []
    for entry in (spec or []):
        if entry is None:
            dims.append(())
        elif isinstance(entry, str):
            dims.append((entry,))
        else:
            dims.append(tuple(entry))
    return dims


def leaf_slices(spec: Any, shape: Sequence[int],
                mesh_sizes: Mapping[str, int],
                coords: Mapping[str, int]) -> Tuple[slice, ...]:
    """The index slices of one rank's shard of a leaf with global
    ``shape`` under ``spec`` — the jax-free mirror of
    ``parallel/rules.local_shard_tree`` (axes absent from ``mesh_sizes``
    contribute size 1, i.e. replicated)."""
    dims = _spec_dims(spec)
    out: List[slice] = []
    for d, dim_size in enumerate(shape):
        axes = dims[d] if d < len(dims) else ()
        idx, sz = 0, 1
        for a in axes:
            a_sz = int(mesh_sizes.get(a, 1))
            idx = idx * a_sz + (int(coords.get(a, 0)) % a_sz)
            sz *= a_sz
        if sz == 1:
            out.append(slice(0, dim_size))
            continue
        if dim_size % sz:
            raise ValueError(
                f"dim {d} of shape {tuple(shape)} not divisible by "
                f"mesh extent {sz} for spec {spec!r}"
            )
        shard = dim_size // sz
        out.append(slice(idx * shard, (idx + 1) * shard))
    return tuple(out)


# --- the layout manifest (checkpoint metadata) ------------------------------


@dataclass
class LayoutManifest:
    """Mesh/layout metadata written next to a sharded checkpoint so a
    restore at a DIFFERENT world shape can plan the redistribution: the
    ordered mesh axes, the rules-table id that produced the specs, one
    entry per (non-zero1) leaf with its global shape/dtype/spec, and the
    :class:`Zero1Layout` of every Zero1State node keyed by tree path.
    ``axes_hash`` fingerprints (mesh, rules) so mismatches are named,
    not guessed."""

    mesh_axes: List[Tuple[str, int]]
    leaves: List[dict]
    zero1: Dict[str, dict] = field(default_factory=dict)
    rules_id: Optional[str] = None
    step: int = 0
    schema: int = MANIFEST_SCHEMA

    @property
    def world(self) -> int:
        w = 1
        for _, size in self.mesh_axes:
            w *= int(size)
        return w

    @property
    def axes_hash(self) -> str:
        blob = json.dumps(
            {"mesh_axes": [[a, int(s)] for a, s in self.mesh_axes],
             "rules_id": self.rules_id},
            sort_keys=True,
        ).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def describe(self) -> str:
        axes = ", ".join(f"{a}={s}" for a, s in self.mesh_axes)
        return (
            f"mesh({axes}) rules={self.rules_id or '-'} "
            f"hash={self.axes_hash} leaves={len(self.leaves)} "
            f"zero1_nodes={len(self.zero1)}"
        )

    def to_json(self) -> str:
        return json.dumps(
            {
                "schema": self.schema,
                "mesh_axes": [[a, int(s)] for a, s in self.mesh_axes],
                "rules_id": self.rules_id,
                "axes_hash": self.axes_hash,
                "step": self.step,
                "leaves": self.leaves,
                "zero1": self.zero1,
            },
            sort_keys=True, indent=1,
        )

    @classmethod
    def from_json(cls, text: str) -> "LayoutManifest":
        doc = json.loads(text)
        schema = int(doc.get("schema", -1))
        if schema != MANIFEST_SCHEMA:
            raise ValueError(
                f"checkpoint layout manifest schema {schema} is not the "
                f"supported schema {MANIFEST_SCHEMA}"
            )
        man = cls(
            mesh_axes=[(str(a), int(s)) for a, s in doc["mesh_axes"]],
            leaves=list(doc["leaves"]),
            zero1={str(k): dict(v) for k, v in doc.get("zero1", {}).items()},
            rules_id=doc.get("rules_id"),
            step=int(doc.get("step", 0)),
        )
        recorded = doc.get("axes_hash")
        if recorded and recorded != man.axes_hash:
            raise ValueError(
                f"checkpoint layout manifest axes_hash {recorded} does "
                f"not match its own mesh/rules content ({man.axes_hash}) "
                f"— the manifest is torn or hand-edited"
            )
        return man


def spec_to_list(spec: Any) -> Optional[List[Any]]:
    """Serialize a ``PartitionSpec``-like per-leaf spec to the manifest
    form: one entry per array dim — ``None``, an axis name, or a list of
    axis names. ``None`` spec means replicated."""
    if spec is None:
        return None
    out: List[Any] = []
    for entry in tuple(spec):
        if entry is None:
            out.append(None)
        elif isinstance(entry, str):
            out.append(entry)
        else:
            out.append([str(a) for a in entry])
    return out


def build_manifest(tree: Any, mesh_axes: Sequence[Tuple[str, int]], *,
                   specs: Optional[Mapping[str, Any]] = None,
                   zero1_layouts: Optional[Mapping[str, Any]] = None,
                   zero1_axis: str = "data",
                   rules_id: Optional[str] = None,
                   step: int = 0) -> LayoutManifest:
    """Build the :class:`LayoutManifest` for a sharded checkpoint of
    ``tree``: one entry per non-zero1 leaf (flatten order, Zero1State
    nodes stop the flatten) with its global shape/dtype and sharding
    spec (``specs`` maps tree path -> PartitionSpec; unlisted leaves are
    replicated), plus the :class:`Zero1Layout` of every Zero1State node
    (``zero1_layouts`` maps path -> layout; a bare layout is accepted
    when the tree holds exactly one node)."""
    import jax

    import numpy as np

    from .rules import _key_name

    flat = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=_is_zero1_state
    )[0]
    leaves: List[dict] = []
    zero1: Dict[str, dict] = {}
    for path, leaf in flat:
        name = "/".join(_key_name(k) for k in path)
        if _is_zero1_state(leaf):
            if isinstance(zero1_layouts, Zero1Layout):
                layout = zero1_layouts
            elif zero1_layouts is not None:
                layout = zero1_layouts.get(name)
            else:
                layout = None
            if layout is None:
                raise ValueError(
                    f"tree holds a Zero1State at {name!r} but no layout "
                    f"was provided for it — derive one with "
                    f"zero1_layout_from_params(...) and pass "
                    f"zero1_layouts={{{name!r}: layout}}"
                )
            if isinstance(layout, Zero1Layout):
                layout = layout.to_dict()
            else:
                layout = dict(layout)
            layout["axis"] = zero1_axis
            zero1[name] = layout
            continue
        arr = np.asarray(jax.device_get(leaf))
        spec = specs.get(name) if specs else None
        leaves.append({
            "path": name,
            "dtype": str(arr.dtype),
            "shape": [int(d) for d in arr.shape],
            "spec": spec_to_list(spec),
        })
    return LayoutManifest(
        mesh_axes=[(str(a), int(s)) for a, s in mesh_axes],
        leaves=leaves, zero1=zero1, rules_id=rules_id, step=int(step),
    )


# --- execution half (lazy jax/numpy) ----------------------------------------


def zero1_layout_from_params(params: Any, n_shards: int, *,
                             threshold_bytes: Optional[int] = None,
                             first_bucket_bytes: Optional[int] = None,
                             quantized: bool = False) -> Zero1Layout:
    """Derive the live :class:`Zero1Layout` from the params via the SAME
    planners ``init_zero1_stream_state`` walks (``ops/fusion``): group
    partition, per-group fusion buckets, per-bucket totals/dtypes, and
    the per-row shard length at ``n_shards``. Buckets that carry no
    optimizer state (zero-length or non-float) are skipped, exactly as
    the init skips them."""
    import jax
    import jax.numpy as jnp

    from ..ops import fusion as F
    from .zero import _zero1_groups

    items, _ = _zero1_groups(params, threshold_bytes, first_bucket_bytes)
    threshold = F.default_threshold_bytes(threshold_bytes)
    layout = Zero1Layout(n_shards=int(n_shards), quantized=bool(quantized))
    for label, sub in items:
        leaves = jax.tree.leaves(sub)
        buckets: Dict[str, BucketLayout] = {}
        for bi, bucket in enumerate(F.plan_buckets(leaves, threshold)):
            total = sum(int(leaves[i].size) for i in bucket)
            dtype = jnp.result_type(*(leaves[i] for i in bucket)) \
                if bucket else jnp.float32
            if total == 0 or not jnp.issubdtype(dtype, jnp.floating):
                continue
            buckets[f"b{bi}"] = BucketLayout(
                total=total,
                k=shard_len(total, n_shards, quantized),
                dtype=str(jnp.dtype(dtype)),
            )
        layout.buckets[label] = buckets
    return layout


def _resplit_rows(rows, total: int, n_new: int, k_new: int,
                  moves: Sequence[ShardMove]):
    """Execute a transfer plan on a host ``[n_old, k_old]`` array:
    returns ``[n_new, k_new]`` with every real element placed per the
    plan and the pad region zeroed. Bitwise — bytes move, nothing is
    recomputed."""
    import numpy as np

    rows = np.asarray(rows)
    out = np.zeros((int(n_new), int(k_new)), dtype=rows.dtype)
    for m in moves:
        out[m.dst, m.dst_off:m.dst_off + m.length] = \
            rows[m.src, m.src_off:m.src_off + m.length]
    return out


def _is_zero1_state(node: Any) -> bool:
    from .zero import Zero1State

    return isinstance(node, Zero1State)


def reshard_zero1_state(state: Any, n_new: int, *,
                        layout: Optional[Zero1Layout] = None,
                        params: Any = None,
                        threshold_bytes: Optional[int] = None,
                        first_bucket_bytes: Optional[int] = None,
                        quantized: Optional[bool] = None,
                        ef_policy: str = "fold",
                        trigger: str = "manual",
                        axis: str = "data") -> Tuple[Any, dict]:
    """Re-stack a host :class:`~horovod_tpu.parallel.zero.Zero1State`
    from its current shard count onto ``n_new`` shards. Returns
    ``(new_state, report)``.

    The bucket layout comes from ``layout`` (e.g. deserialized from a
    checkpoint manifest or elastic snapshot) or is derived live from
    ``params`` via :func:`zero1_layout_from_params`. Per-bucket optax
    leaves move by the transfer plan: ``[n_old, k_old]`` vector leaves
    are re-split bitwise, per-shard scalar leaves (e.g. Adam's step
    count, identical across rows by construction) are re-tiled, and
    anything else raises naming the leaf. Error-feedback residuals
    follow ``ef_policy``: ``"fold"`` moves each residual element with
    its parameter (pad-region mass, zero by construction, is counted
    and warned about if nonzero); ``"zero"`` resets the residuals and
    reports the discarded mass loudly. Either way the report carries
    ``ef_dropped_elements`` / ``ef_dropped_mass`` — never silent loss."""
    import numpy as np

    import jax

    from .. import metrics as _metrics
    from .. import trace as _trace
    from .zero import Zero1State

    if not _is_zero1_state(state):
        raise TypeError(
            f"reshard_zero1_state expects a Zero1State, got "
            f"{type(state).__name__}"
        )
    if ef_policy not in ("fold", "zero"):
        raise ValueError(
            f"ef_policy must be 'fold' or 'zero', got {ef_policy!r}"
        )
    if layout is None:
        if params is None:
            raise ValueError(
                "reshard_zero1_state needs the bucket layout: pass "
                "layout= (from zero1_layout_from_params / a checkpoint "
                "manifest / an elastic snapshot) or params= to derive it"
            )
        layout = zero1_layout_from_params(
            params, _state_n_shards(state),
            threshold_bytes=threshold_bytes,
            first_bucket_bytes=first_bucket_bytes,
            quantized=bool(quantized) if quantized is not None
            else state.ef is not None,
        )
    elif isinstance(layout, Mapping):
        layout = Zero1Layout.from_dict(layout)

    n_old = layout.n_shards
    live_n = _state_n_shards(state)
    if live_n is not None and live_n != n_old:
        raise ValueError(
            f"layout says n_shards={n_old} but the state's leading axis "
            f"is {live_n} — the layout describes a different world"
        )
    new_layout = layout.relayout(n_new)
    plan = plan_zero1_reshard(layout, new_layout)

    report = dict(plan.summary())
    report.update({
        "trigger": trigger, "axis": axis, "ef_policy": ef_policy,
        "ef_dropped_elements": 0, "ef_dropped_mass": 0.0,
    })

    def _reshard_bucket_opt(g: str, b: str, node):
        bl, nbl = layout.buckets[g][b], new_layout.buckets[g][b]
        moves = plan.moves[(g, b)]

        def one(leaf):
            arr = np.asarray(jax.device_get(leaf))
            if arr.ndim >= 1 and arr.shape[0] == n_old:
                if arr.ndim == 2 and arr.shape[1] == bl.k:
                    return _resplit_rows(arr, bl.total, n_new, nbl.k, moves)
                if arr.ndim == 1:
                    # Per-shard scalar state (optax count etc.): every
                    # row saw the same number of updates, so re-tiling
                    # row 0 is exact — verified, not assumed.
                    if arr.size and not (arr == arr[0]).all():
                        raise ValueError(
                            f"bucket {g}/{b}: per-shard scalar state "
                            f"rows disagree ({arr!r}); cannot reshard"
                        )
                    return np.broadcast_to(
                        arr[:1], (int(n_new),)
                    ).copy() if arr.size else arr
            raise ValueError(
                f"bucket {g}/{b}: optimizer-state leaf of shape "
                f"{arr.shape} is neither an [n_shards, k={bl.k}] vector "
                f"nor an [n_shards] scalar stack — this transform's "
                f"state has no defined reshard"
            )

        return jax.tree.map(one, node)

    ef_dropped_elems = 0
    ef_dropped_mass = 0.0

    def _reshard_bucket_ef(g: str, b: str, rows):
        nonlocal ef_dropped_elems, ef_dropped_mass
        bl, nbl = layout.buckets[g][b], new_layout.buckets[g][b]
        arr = np.asarray(jax.device_get(rows))
        flat = arr.reshape(-1)
        pad = flat[bl.total:]
        pad_nonzero = int(np.count_nonzero(pad))
        if ef_policy == "zero":
            nz = int(np.count_nonzero(flat[:bl.total])) + pad_nonzero
            ef_dropped_elems += nz
            ef_dropped_mass += float(np.abs(flat).sum())
            return np.zeros((int(n_new), nbl.k), dtype=arr.dtype)
        if pad_nonzero:
            # Pad-region residual mass has no parameter to ride with —
            # count it, warn, and drop it explicitly.
            ef_dropped_elems += pad_nonzero
            ef_dropped_mass += float(np.abs(pad).sum())
        return _resplit_rows(arr, bl.total, n_new, nbl.k,
                             plan.moves[(g, b)])

    new_opt: Dict[str, Dict[str, Any]] = {}
    for g in state.opt:
        new_opt[g] = {}
        for b in state.opt[g]:
            if g not in layout.buckets or b not in layout.buckets[g]:
                raise ValueError(
                    f"state holds bucket {g}/{b} but the layout does "
                    f"not describe it ({layout.describe()}) — the "
                    f"layout was built for different params"
                )
            new_opt[g][b] = _reshard_bucket_opt(g, b, state.opt[g][b])
    new_ef = None
    if state.ef is not None:
        new_ef = {
            g: {b: _reshard_bucket_ef(g, b, state.ef[g][b])
                for b in state.ef[g]}
            for g in state.ef
        }

    report["ef_dropped_elements"] = ef_dropped_elems
    report["ef_dropped_mass"] = ef_dropped_mass
    if ef_dropped_elems:
        logger.warning(
            "reshard %s->%s shards (trigger=%s): %d EF residual "
            "elements (L1 mass %.3e) could not ride a parameter and "
            "were %s — the next quantized steps re-accumulate the "
            "error from scratch",
            n_old, n_new, trigger, ef_dropped_elems, ef_dropped_mass,
            "zeroed" if ef_policy == "zero" else "dropped",
        )
    if _metrics.ACTIVE:
        _metrics.TAP.inc("hvd_reshard_total", trigger=str(trigger))
        _metrics.TAP.inc("hvd_reshard_bytes_total",
                         value=float(plan.moved_bytes), axis=str(axis))
        if ef_dropped_elems:
            _metrics.TAP.inc("hvd_reshard_ef_dropped_elements_total",
                             value=float(ef_dropped_elems),
                             policy=ef_policy)
    if _trace.ACTIVE:
        _trace.TAP.event(
            "hvd_reshard", cat="elastic", trigger=str(trigger),
            axis=str(axis), n_old=n_old, n_new=int(n_new),
            moved_bytes=plan.moved_bytes,
            ef_dropped_elements=ef_dropped_elems,
        )
    logger.info(
        "resharded zero1 state %d->%d shards (trigger=%s, axis=%s): "
        "%d buckets, %d bytes on the wire, %d local",
        n_old, n_new, trigger, axis, len(plan.moves),
        plan.moved_bytes, plan.local_bytes,
    )
    return Zero1State(opt=new_opt, ef=new_ef), report


def _state_n_shards(state: Any) -> Optional[int]:
    """Leading-axis shard count of a host Zero1State (None if the state
    carries no array leaves)."""
    import jax

    for leaf in jax.tree.leaves(state.opt):
        shape = getattr(leaf, "shape", ())
        if len(shape) >= 1:
            return int(shape[0])
    return None


def reshard_zero1_tree(tree: Any, n_new: int,
                       layouts: Optional[Mapping[str, Any]] = None,
                       **kw) -> Tuple[Any, List[dict]]:
    """Reshard every :class:`Zero1State` node inside an arbitrary
    pytree (e.g. an elastic snapshot payload) to ``n_new`` shards.
    ``layouts`` maps the node's tree path (``named_tree_paths`` form) to
    its :class:`Zero1Layout` (or dict); a single-node tree accepts a
    bare layout under the empty path. Returns the rebuilt tree and the
    per-node reshard reports."""
    import jax

    from .rules import _key_name

    reports: List[dict] = []
    flat = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=_is_zero1_state
    )[0]
    named = [
        ("/".join(_key_name(k) for k in path), leaf)
        for path, leaf in flat
    ]
    replacements: Dict[str, Any] = {}
    for path, node in named:
        if not _is_zero1_state(node):
            continue
        layout = None
        if layouts is not None:
            layout = layouts.get(path)
            if layout is None and len(layouts) == 1 and "" in layouts:
                layout = layouts[""]
        if layout is None and layouts is not None:
            raise ValueError(
                f"no layout recorded for Zero1State at {path!r}; "
                f"known paths: {sorted(layouts)}"
            )
        new_node, report = reshard_zero1_state(
            node, n_new, layout=layout, **kw
        )
        report["path"] = path
        reports.append(report)
        replacements[path] = new_node

    if not replacements:
        return tree, reports

    leaves, treedef = jax.tree.flatten(tree, is_leaf=_is_zero1_state)
    paths = [p for p, _ in named]
    out = [
        replacements.get(paths[i], leaf) for i, leaf in enumerate(leaves)
    ]
    return jax.tree.unflatten(treedef, out), reports
