"""Sequence-parallel (long-context) training step builder.

Combines data parallelism and sequence/context parallelism on one mesh:
the batch dimension shards over ``data`` and the sequence dimension over
``seq``; gradients reduce over BOTH axes (params are replicated). The
attention inside the model must be ring/Ulysses attention bound to the
``seq`` axis (see ``models/transformer.py`` attn_fn).

This is a TPU-native extension beyond the reference framework (which is
model-agnostic DP only, SURVEY.md §2.3) — required for long-context
workloads where one chip cannot hold a full sequence.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..common.types import Average, ReduceOp
from .mesh import DATA_AXIS, SEQ_AXIS


def make_sp_train_step(
    loss_fn: Callable,
    optimizer,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    seq_axis: str = SEQ_AXIS,
    fusion_threshold_bytes: int = 64 * 1024 * 1024,
    donate: bool = True,
):
    """Build a jitted DP×SP train step.

    ``loss_fn(params, tokens, labels, positions) -> scalar`` runs on the
    local [B/nd, T/ns] shard; ``positions`` carries global sequence offsets
    for the shard. Batch arrays are [B, T] sharded P(data, seq).
    """
    import optax

    from ..jax import _shard_map, allreduce_gradients

    axes = (data_axis, seq_axis)

    def step(params, opt_state, tokens, labels):
        B, T = tokens.shape
        seq_idx = lax.axis_index(seq_axis)
        positions = jnp.broadcast_to(
            seq_idx * T + jnp.arange(T), (B, T)
        )
        loss, grads = jax.value_and_grad(loss_fn)(
            params, tokens, labels, positions
        )
        grads = allreduce_gradients(
            grads, op=Average, axis_name=axes,
            fusion_threshold_bytes=fusion_threshold_bytes,
        )
        loss = lax.pmean(loss, axes)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    fn = _shard_map(
        step,
        mesh,
        in_specs=(P(), P(), P(data_axis, seq_axis), P(data_axis, seq_axis)),
        out_specs=P(),
    )
    return jax.jit(fn, donate_argnums=(0, 1) if donate else ())
