"""Pipeline (stage) parallelism: GPipe-style microbatch pipelining over a
``stage`` mesh axis.

TPU-native extension beyond the reference framework (which is
model-agnostic DP only, SURVEY.md §2.3): each device owns one pipeline
stage's parameters; microbatches flow stage -> stage over ``lax.ppermute``
inside a ``lax.scan`` of n_micro + n_stages - 1 ticks (fill + steady +
drain). Because the whole schedule is traced functional code, jax autodiff
derives the backward pipeline (cotangents flow through the ppermute
transpose in the reverse direction) — no hand-written 1F1B schedule is
needed for correctness, and XLA overlaps each tick's compute with the
next's ICI transfer.

Layout: stage parameters enter with a leading [n_stages, ...] dim placed
``P(stage)``; every stage must map activations of one shape to the same
shape (the classic homogeneous-pipeline constraint; embed/head layers
belong on stages 0 / n-1 inside ``stage_fn``).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import DATA_AXIS

STAGE_AXIS = "stage"


def _pvary(x, axis_name):
    """Mark a replicated value as device-varying over ``axis_name`` (vma
    bookkeeping only — the values are unchanged). Needed so the pipeline
    scan's carry has a consistent varying type across iterations."""
    try:
        return lax.pcast(x, (axis_name,), to="varying")
    except AttributeError:  # pragma: no cover - pre-pcast jax
        return lax.pvary(x, (axis_name,))


def pipeline_apply(
    stage_fn: Callable,
    stage_params: Any,
    x_micro: jax.Array,
    *,
    axis_name: str = STAGE_AXIS,
) -> jax.Array:
    """Run microbatches through the pipeline; call inside shard_map.

    ``stage_fn(params, x, stage_index)`` maps [mb, ...] -> [mb, ...] with
    this device's stage params; ``x_micro``: [n_micro, mb, ...] (the full
    input, present on every stage — stage 0 ingests it). Returns the last
    stage's outputs [n_micro, mb, ...] (zeros elsewhere; the caller
    typically psums or masks by stage).
    """
    s = lax.axis_index(axis_name)
    n_stages = lax.axis_size(axis_name)
    x_micro = _pvary(x_micro, axis_name)
    n_micro = x_micro.shape[0]
    ticks = n_micro + n_stages - 1
    state0 = jnp.zeros_like(x_micro[0])
    outs0 = jnp.zeros_like(x_micro)
    # Send each stage's output one hop down the line; stage n-1's output
    # is dropped by the permutation (it exits via `outs`).
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    def tick(carry, t):
        state, outs = carry
        # Stage 0 ingests microbatch t (clamped: beyond n_micro it runs
        # garbage that never reaches an output slot).
        feed = x_micro[jnp.minimum(t, n_micro - 1)]
        x_in = jnp.where(s == 0, feed, state)
        y = stage_fn(stage_params, x_in, s)
        # Last stage emits microbatch t-(n_stages-1) at ticks >= n-1.
        out_idx = t - (n_stages - 1)
        is_emit = jnp.logical_and(s == n_stages - 1, out_idx >= 0)
        outs = lax.dynamic_update_index_in_dim(
            outs,
            jnp.where(is_emit, y, lax.dynamic_index_in_dim(
                outs, jnp.maximum(out_idx, 0), 0, keepdims=False)),
            jnp.maximum(out_idx, 0), 0,
        )
        state_next = lax.ppermute(y, axis_name, perm)
        return (state_next, outs), None

    (state, outs), _ = lax.scan(tick, (state0, outs0), jnp.arange(ticks))
    return outs


def make_pp_train_step(
    loss_fn: Callable,
    stage_fn: Callable,
    optimizer,
    mesh: Mesh,
    *,
    stage_axis: str = STAGE_AXIS,
    data_axis: str = DATA_AXIS,
    donate: bool = True,
):
    """Build a jitted DP×PP train step.

    ``stage_fn(params, x, stage_index)``: one stage's forward.
    ``loss_fn(y_micro, labels_micro) -> scalar``: loss on the pipeline
    output (runs on the last stage's values; every stage computes it on
    the psum-broadcast outputs so the graph stays SPMD).

    Params enter stacked [n_stages, ...] placed P(stage). Batches are
    PRE-SHAPED [n_micro, mb, ...]: dim 0 is the microbatch index
    (unsharded), dim 1 the per-microbatch batch, sharded over ``data`` and
    replicated across stages (in_specs P(None, data)).
    """
    from ..jax import _shard_map
    from ._stacked import stacked_train_update

    def step(params, opt_state, x_micro, y_micro):
        def local_loss(p):
            outs = pipeline_apply(
                stage_fn, p, x_micro, axis_name=stage_axis
            )
            # Outputs live on the last stage; share them so the loss (and
            # its gradient wiring) is SPMD-identical on every stage.
            n_stages = lax.axis_size(stage_axis)
            mask = (lax.axis_index(stage_axis) == n_stages - 1).astype(
                outs.dtype
            )
            outs = lax.psum(outs * mask, stage_axis)
            return loss_fn(outs, y_micro)

        params, opt_state, loss = stacked_train_update(
            optimizer, params, opt_state,
            jax.value_and_grad(local_loss), data_axis,
        )
        loss = lax.pmean(loss, data_axis)
        return params, opt_state, loss

    fn = _shard_map(
        step, mesh, check=True,
        in_specs=(P(stage_axis), P(stage_axis), P(None, data_axis),
                  P(None, data_axis)),
        out_specs=(P(stage_axis), P(stage_axis), P()),
    )
    return jax.jit(fn, donate_argnums=(0, 1) if donate else ())


from ._stacked import init_stacked_state as init_pp_state  # noqa: E402
