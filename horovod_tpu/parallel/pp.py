"""Pipeline (stage) parallelism: GPipe-style microbatch pipelining over a
``stage`` mesh axis.

TPU-native extension beyond the reference framework (which is
model-agnostic DP only, SURVEY.md §2.3): each device owns one pipeline
stage's parameters; microbatches flow stage -> stage over ``lax.ppermute``
inside a ``lax.scan`` of n_micro + n_stages - 1 ticks (fill + steady +
drain). Because the whole schedule is traced functional code, jax autodiff
derives the backward pipeline (cotangents flow through the ppermute
transpose in the reverse direction) — no hand-written 1F1B schedule is
needed for correctness, and XLA overlaps each tick's compute with the
next's ICI transfer.

Two APIs:

- ``make_pp_train_step`` — homogeneous stages: parameters enter with a
  leading [n_stages, ...] dim placed ``P(stage)``; every stage maps one
  activation shape to itself.
- ``make_pp_lm_train_step`` — heterogeneous ends as first-class stages:
  ``embed_fn`` ingests raw tokens on stage 0, ``head_loss_fn`` folds the
  projection + loss on the last stage, and only the hidden activation
  crosses ICI. ``remat=True`` bounds backward memory to the carried
  activations plus one rematerialized tick (``jax.checkpoint`` per tick
  — the memory role of 1F1B, scheduled by the compiler).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..common.compat import assert_replicated, grad_psum, psum_replicated_grad
from ..common.compat import axis_size as _axis_size
from .mesh import DATA_AXIS

STAGE_AXIS = "stage"


def _zeros_with_vma_of(shape, dtype, ref):
    """Zeros of (shape, dtype) carrying ``ref``'s varying-axis type: a
    scan carry must match its body output's vma over every bound axis,
    including axes whose names the callee does not know. The dead
    multiply is DCE'd by XLA."""
    return jnp.zeros(shape, dtype) + jnp.zeros((), dtype) * ref.ravel()[
        0
    ].astype(dtype)


def _pvary(x, axis_name):
    """Mark a replicated value as device-varying over ``axis_name`` (vma
    bookkeeping only — the values are unchanged). Needed so the pipeline
    scan's carry has a consistent varying type across iterations."""
    pcast = getattr(lax, "pcast", None)
    if pcast is not None:
        return pcast(x, (axis_name,), to="varying")
    pvary = getattr(lax, "pvary", None)
    if pvary is not None:
        return pvary(x, (axis_name,))
    # Pre-vma jax: shard_map has no varying-type tracking (check_rep
    # bodies predate it), so there is no bookkeeping to satisfy.
    return x


def _gpipe_scan(axis_name, n_micro, feed, stage_apply, emit, emit0):
    """The one GPipe fill/steady/drain scan both pipeline APIs share.

    - ``feed(i) -> h``: stage 0's input for microbatch i (raw slice or
      embedded tokens);
    - ``stage_apply(h, s) -> h``: this stage's compute;
    - ``emit(outs, idx, y, is_emit) -> outs``: fold the last stage's
      result for microbatch ``idx`` into the accumulator (tensor slot or
      per-microbatch loss).

    Ticks run n_micro + n_stages - 1 times; stage 0 ingests microbatch t
    (clamped past the end: the garbage never reaches an emit slot), the
    last stage emits microbatch t - (n_stages - 1), and each tick's
    output moves one hop down the line over ppermute (stage n-1's hop is
    dropped by the permutation — it exits via ``emit``).
    """
    s = lax.axis_index(axis_name)
    n_stages = _axis_size(axis_name)
    ticks = n_micro + n_stages - 1
    perm = [(i, i + 1) for i in range(n_stages - 1)]
    state0 = jnp.zeros_like(feed(jnp.int32(0)))

    def tick(carry, t):
        state, outs = carry
        x_in = jnp.where(s == 0, feed(jnp.minimum(t, n_micro - 1)), state)
        y = stage_apply(x_in, s)
        out_idx = t - (n_stages - 1)
        idx = jnp.clip(out_idx, 0, n_micro - 1)
        is_emit = jnp.logical_and(s == n_stages - 1, out_idx >= 0)
        outs = emit(outs, idx, y, is_emit)
        state_next = lax.ppermute(y, axis_name, perm)
        return (state_next, outs), None

    (_, outs), _ = lax.scan(tick, (state0, emit0), jnp.arange(ticks))
    return outs


def pipeline_apply(
    stage_fn: Callable,
    stage_params: Any,
    x_micro: jax.Array,
    *,
    axis_name: str = STAGE_AXIS,
) -> jax.Array:
    """Run microbatches through the pipeline; call inside shard_map.

    ``stage_fn(params, x, stage_index)`` maps [mb, ...] -> [mb, ...] with
    this device's stage params; ``x_micro``: [n_micro, mb, ...] (the full
    input, present on every stage — stage 0 ingests it). Returns the last
    stage's outputs [n_micro, mb, ...] (zeros elsewhere; the caller
    typically psums or masks by stage).
    """
    x_micro = _pvary(x_micro, axis_name)

    def emit(outs, idx, y, is_emit):
        prev = lax.dynamic_index_in_dim(outs, idx, 0, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            outs, jnp.where(is_emit, y, prev), idx, 0
        )

    return _gpipe_scan(
        axis_name, x_micro.shape[0],
        lambda i: x_micro[i],
        lambda h, s: stage_fn(stage_params, h, s),
        emit, jnp.zeros_like(x_micro),
    )


def make_pp_train_step(
    loss_fn: Callable,
    stage_fn: Callable,
    optimizer,
    mesh: Mesh,
    *,
    stage_axis: str = STAGE_AXIS,
    data_axis: str = DATA_AXIS,
    donate: bool = True,
):
    """Build a jitted DP×PP train step.

    ``stage_fn(params, x, stage_index)``: one stage's forward.
    ``loss_fn(y_micro, labels_micro) -> scalar``: loss on the pipeline
    output (runs on the last stage's values; every stage computes it on
    the psum-broadcast outputs so the graph stays SPMD).

    Params enter stacked [n_stages, ...] placed P(stage). Batches are
    PRE-SHAPED [n_micro, mb, ...]: dim 0 is the microbatch index
    (unsharded), dim 1 the per-microbatch batch, sharded over ``data`` and
    replicated across stages (in_specs P(None, data)).
    """
    from ..jax import _shard_map
    from ._stacked import stacked_train_update

    def step(params, opt_state, x_micro, y_micro):
        def local_loss(p):
            outs = pipeline_apply(
                stage_fn, p, x_micro, axis_name=stage_axis
            )
            # Outputs live on the last stage; share them so the loss (and
            # its gradient wiring) is SPMD-identical on every stage.
            n_stages = _axis_size(stage_axis)
            mask = (lax.axis_index(stage_axis) == n_stages - 1).astype(
                outs.dtype
            )
            outs = psum_replicated_grad(outs * mask, stage_axis)
            return loss_fn(outs, y_micro)

        params, opt_state, loss = stacked_train_update(
            optimizer, params, opt_state,
            jax.value_and_grad(local_loss), data_axis,
        )
        loss = lax.pmean(loss, data_axis)
        # Old-jax check_rep cannot infer the data-axis replication of the
        # updated shards through optax; no-op on new jax.
        params = assert_replicated(params, data_axis)
        opt_state = assert_replicated(opt_state, data_axis)
        return params, opt_state, loss

    fn = _shard_map(
        step, mesh, check=True,
        in_specs=(P(stage_axis), P(stage_axis), P(None, data_axis),
                  P(None, data_axis)),
        out_specs=(P(stage_axis), P(stage_axis), P()),
    )
    return jax.jit(fn, donate_argnums=(0, 1) if donate else ())


from ._stacked import init_stacked_state  # noqa: E402

init_pp_state = init_stacked_state


# ---------------------------------------------------------------------------
# Heterogeneous pipelines: embed / body / head as first-class stages
# ---------------------------------------------------------------------------

def pipeline_lm_loss(
    embed_fn: Callable,
    stage_fn: Callable,
    head_loss_fn: Callable,
    embed_params: Any,
    stage_params_local: Any,
    head_params: Any,
    tokens_micro: jax.Array,
    labels_micro: jax.Array,
    *,
    axis_name: str = STAGE_AXIS,
    remat: bool = True,
) -> jax.Array:
    """Pipelined forward + loss with heterogeneous ends; call inside
    shard_map with ``axis_name`` bound.

    The wire between stages carries ONLY the hidden activation
    [mb, ...]: stage 0 ingests raw tokens through ``embed_fn`` and the
    last stage folds ``head_loss_fn`` (projection + loss) locally, so
    logits-sized tensors never cross ICI and callers no longer have to
    disguise embed/head as shape-preserving stages (the round-3
    homogeneous-pipeline constraint).

    - ``embed_fn(embed_params, tokens_mb) -> h``      [mb,...] any shape
    - ``stage_fn(stage_params, h, stage_idx) -> h``   shape-preserving
    - ``head_loss_fn(head_params, h, labels_mb) -> scalar``

    ``embed_params``/``head_params`` are replicated across the mesh; under
    a vma-checked shard_map their cotangents are psummed over the stage
    axis automatically, and only the owning stage's branch contributes
    (the ``where`` masks zero the rest), so the replicated update is
    exact. SPMD uniformity means every stage *computes* embed/head each
    tick and masks the result — for projection-dominated models put the
    head inside the last ``stage_fn`` or shard it with TP instead.

    ``remat=True`` wraps each tick's stage compute in ``jax.checkpoint``:
    the backward pass holds the carried activations plus ONE
    rematerialized tick instead of every tick's internals — the memory
    role of a 1F1B schedule, expressed through the compiler (the
    schedule itself stays GPipe fill/steady/drain; autodiff derives the
    reverse pipeline through the ppermute transpose).
    """
    tokens_micro = _pvary(tokens_micro, axis_name)
    labels_micro = _pvary(labels_micro, axis_name)
    n_micro = tokens_micro.shape[0]
    body = jax.checkpoint(stage_fn) if remat else stage_fn
    # The loss carry must inherit the inputs' varying-axis type over
    # EVERY bound axis — stage and the caller's data axis, whose name
    # this function cannot know, so _pvary alone is not enough; derive
    # it from a (DCE'd) embed evaluation instead.
    h_ref = embed_fn(embed_params, tokens_micro[0])
    losses0 = _zeros_with_vma_of((n_micro,), jnp.float32, h_ref)

    def emit(losses, idx, y, is_emit):
        mb_loss = head_loss_fn(
            head_params, y, labels_micro[idx]
        ).astype(jnp.float32)
        prev = lax.dynamic_index_in_dim(losses, idx, 0, keepdims=False)
        return lax.dynamic_update_index_in_dim(
            losses, jnp.where(is_emit, mb_loss, prev), idx, 0
        )

    losses = _gpipe_scan(
        axis_name, n_micro,
        lambda i: embed_fn(embed_params, tokens_micro[i]),
        lambda h, s: body(stage_params_local, h, s),
        emit, losses0,
    )
    # Losses live on the last stage; share so the value (and the gradient
    # wiring) is SPMD-identical everywhere.
    n_stages = _axis_size(axis_name)
    mask = (lax.axis_index(axis_name) == n_stages - 1).astype(losses.dtype)
    losses = psum_replicated_grad(losses * mask, axis_name)
    return losses.mean()


def init_pp_lm_state(optimizer, params):
    """Optimizer state for the heterogeneous layout: ``params`` is a dict
    {"embed", "stages" ([n_stages, ...]-stacked), "head"}; embed/head
    states are replicated like their params, stage states stacked."""
    return {
        "embed": optimizer.init(params["embed"]),
        "stages": init_stacked_state(optimizer, params["stages"]),
        "head": optimizer.init(params["head"]),
    }


def make_pp_lm_train_step(
    embed_fn: Callable,
    stage_fn: Callable,
    head_loss_fn: Callable,
    optimizer,
    mesh: Mesh,
    *,
    stage_axis: str = STAGE_AXIS,
    data_axis: str = DATA_AXIS,
    remat: bool = True,
    donate: bool = True,
):
    """Jitted DP x PP train step over a heterogeneous pipeline.

    ``step(params, opt_state, tokens_micro, labels_micro) ->
    (params, opt_state, loss)`` with ``params`` =
    {"embed", "stages", "head"} (see :func:`pipeline_lm_loss` /
    :func:`init_pp_lm_state`). Batches are [n_micro, mb, ...] with dim 1
    sharded over ``data``.
    """
    import optax

    from ..jax import _shard_map
    from ._stacked import apply_stacked_update

    def step(params, opt_state, tokens_micro, labels_micro):
        nd = _axis_size(data_axis)

        def loss_of(embed_p, stages_local, head_p):
            return pipeline_lm_loss(
                embed_fn, stage_fn, head_loss_fn,
                embed_p, stages_local, head_p,
                tokens_micro, labels_micro,
                axis_name=stage_axis, remat=remat,
            )

        stages_local = jax.tree.map(lambda t: t[0], params["stages"])
        loss, grads = jax.value_and_grad(loss_of, argnums=(0, 1, 2))(
            params["embed"], stages_local, params["head"]
        )
        # New jax: the vma-checked transpose already psummed each
        # gradient over every axis its parameter is invariant on
        # (stage+data for embed/head, data for stage params). Old jax
        # leaves per-rank cotangents — grad_psum reduces them explicitly
        # (identity on new jax). Divide by the data size to average.
        g_embed, g_stages, g_head = grads
        g_embed = grad_psum(g_embed, (stage_axis, data_axis))
        g_head = grad_psum(g_head, (stage_axis, data_axis))
        g_stages = grad_psum(g_stages, (data_axis,))
        g_embed, g_stages, g_head = jax.tree.map(
            lambda g: g / nd, (g_embed, g_stages, g_head)
        )

        new_params, new_state = {}, {}
        up, new_state["embed"] = optimizer.update(
            g_embed, opt_state["embed"], params["embed"]
        )
        new_params["embed"] = optax.apply_updates(params["embed"], up)
        new_params["stages"], new_state["stages"] = apply_stacked_update(
            optimizer, params["stages"], opt_state["stages"], g_stages
        )
        up, new_state["head"] = optimizer.update(
            g_head, opt_state["head"], params["head"]
        )
        new_params["head"] = optax.apply_updates(params["head"], up)
        # Old-jax check_rep cannot infer these replications through
        # optax/scan; no-op on new jax. embed/head are replicated over
        # both axes (P()), stage shards over data only.
        for key, axes in (
            ("embed", (stage_axis, data_axis)),
            ("stages", (data_axis,)),
            ("head", (stage_axis, data_axis)),
        ):
            new_params[key] = assert_replicated(new_params[key], axes)
            new_state[key] = assert_replicated(new_state[key], axes)
        return new_params, new_state, lax.pmean(loss, data_axis)

    pspec = {"embed": P(), "stages": P(stage_axis), "head": P()}
    fn = _shard_map(
        step, mesh, check=True,
        in_specs=(pspec, pspec, P(None, data_axis), P(None, data_axis)),
        out_specs=(pspec, pspec, P()),
    )
    return jax.jit(fn, donate_argnums=(0, 1) if donate else ())
