"""Tensor (model) parallelism: Megatron-style column/row-parallel layers.

TPU-native extension beyond the reference framework (which is
model-agnostic DP only, SURVEY.md §2.3): weight matrices shard over a
``model`` mesh axis and activations stay sharded between the column- and
row-parallel halves of each block, so the only collective per MLP/attention
block is ONE psum on the row-parallel output — the classic Megatron
schedule, expressed with ``shard_map`` + ``lax.psum`` so XLA lays the
reduction onto ICI.

Layout (per device, axis size n):
  - column-parallel: W1 [D, F/n]; y = x @ W1 — output feature-sharded,
    no communication (the gelu runs sharded too);
  - row-parallel: W2 [F/n, D]; z = psum(y @ W2) — one allreduce brings the
    block output back replicated.

The same pair implements attention head sharding (QKV projection is
column-parallel over heads, the output projection row-parallel).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..common.compat import axis_size as _axis_size
from ..common.compat import psum_replicated_grad
from .mesh import DATA_AXIS

MODEL_AXIS = "model"


def _make_block_input_psum_bwd():
    import functools

    @functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
    def f(x, axis_name):
        return x

    def fwd(x, axis_name):
        return x, None

    def bwd(axis_name, _res, ct):
        from ..ops import fusion as _fusion

        # The conjugate psum moves the same activation bytes the forward
        # g-psum moves — charge the model axis (trace-time).
        _fusion.record_axis_wire_bytes(
            ct.size * ct.dtype.itemsize, axis_name, "psum"
        )
        return (lax.psum(ct, axis_name),)

    f.defvjp(fwd, bwd)
    return f


_block_input_psum_bwd = None


def tp_block_input(x: jax.Array, *, axis_name: str = MODEL_AXIS) -> jax.Array:
    """Megatron's ``f`` operator — identity forward, cotangent psum over
    the model axis in the backward: the conjugate of the row-parallel
    ``g`` psum. Apply to a REPLICATED block input right before it feeds
    column-parallel shards; without it, each rank's cotangent for the
    block input carries only its OWN shard's partial, so everything
    upstream (earlier blocks' sharded weights, embeddings) differentiates
    wrong in multi-block stacks.

    On new jax (vma shard_map, ``check_vma=True``) the replication
    tracker inserts exactly this transpose itself and this function is
    the identity — an explicit psum there would double-count."""
    from ..common.compat import needs_explicit_grad_reduce

    if not needs_explicit_grad_reduce():
        return x
    global _block_input_psum_bwd
    if _block_input_psum_bwd is None:
        _block_input_psum_bwd = _make_block_input_psum_bwd()
    return _block_input_psum_bwd(x, axis_name)


def column_parallel(x: jax.Array, w_shard: jax.Array,
                    b_shard=None) -> jax.Array:
    """y = x @ W[:, shard] (+ b[shard]): output is feature-sharded; no
    communication. Call inside shard_map."""
    y = x @ w_shard
    if b_shard is not None:
        y = y + b_shard
    return y


def row_parallel(x_shard: jax.Array, w_shard: jax.Array, b_shard=None, *,
                 axis_name: str = MODEL_AXIS) -> jax.Array:
    """z = psum_i(x_i @ W[shard_i, :] + scatter_i(b_i)): the one collective
    of the Megatron block.

    The bias is genuinely SHARDED ([D/n] per rank, scattered to its offset
    inside the reduction) rather than replicated: a replicated-but-stacked
    bias would be typed device-varying by shard_map's replication checker,
    which flips the psum transpose from pbroadcast back to a sum and
    scales every upstream gradient by the axis size."""
    y = x_shard @ w_shard
    if b_shard is not None:
        n = _axis_size(axis_name)
        f = b_shard.shape[-1]
        if f * n != w_shard.shape[-1]:
            # A full-size bias would silently be added n times (the
            # scatter offset clamps); fail at trace time instead.
            raise ValueError(
                f"row_parallel bias must be the [D/n] shard: got {f} "
                f"features for D={w_shard.shape[-1]} over n={n} shards"
            )
        i = lax.axis_index(axis_name)
        full = jnp.zeros((w_shard.shape[-1],), b_shard.dtype)
        full = lax.dynamic_update_slice(full, b_shard, (i * f,))
        y = y + full
    # Per-axis attribution (trace-time, docs/parallelism.md): the one
    # Megatron psum of this half-block, charged to the MODEL axis so a
    # composed DP x TP program's wire split stays honest. Never
    # bucketized/quantized/re-planned — a plain psum XLA lays onto ICI.
    from ..ops import fusion as _fusion

    _fusion.record_axis_wire_bytes(
        y.size * y.dtype.itemsize, axis_name, "psum"
    )
    # Replicated-cotangent psum: the block output feeds an SPMD-identical
    # loss, so the transpose must be the identity (see compat).
    return psum_replicated_grad(y, axis_name)


# ------------------------------------------------- fused TP overlap
#
# The collective-matmul path (docs/parallelism.md "Fused TP overlap"):
# the residual stream rides token-SHARDED between blocks, the column
# consume is an all-gather-matmul and the row produce a
# matmul-reduce-scatter (ops/collective_matmul.py), so the classic
# exposed psum disappears from the forward — ppermute chains carry the
# chunks while the MXU multiplies. ``psum(y@W) ==
# all_gather(reduce_scatter(y@W))`` over tokens keeps the fused block
# numerically equivalent to the classic one.

_OVERLAP_SCOPE: list = []


def overlap_scope(enabled):
    """Context manager pinning the fused-path selection during a trace
    (the composed builder wraps the user loss in one, so
    ``make_train_step(rules=..., tp_overlap=...)`` reaches every
    ``tp_apply`` call without threading a flag through user code).
    ``enabled=None`` defers to the environment knob."""
    import contextlib

    @contextlib.contextmanager
    def scope():
        _OVERLAP_SCOPE.append(None if enabled is None else bool(enabled))
        try:
            yield
        finally:
            _OVERLAP_SCOPE.pop()

    return scope()


def tp_overlap_enabled(explicit=None) -> bool:
    """Resolve the fused-path switch: an explicit argument wins, then
    the innermost :func:`overlap_scope`, then ``HOROVOD_TP_OVERLAP``."""
    if explicit is not None:
        return bool(explicit)
    for v in reversed(_OVERLAP_SCOPE):
        if v is not None:
            return v
    from ..common import env as _env

    return _env._get_bool(_env.HOROVOD_TP_OVERLAP, False)


def tp_overlap_chunks() -> int:
    """The configured sub-chunk count (0 = auto: one chunk per rank)."""
    from ..common import env as _env

    return _env._get_int(_env.HOROVOD_TP_OVERLAP_CHUNKS, 0)


def tp_scatter_tokens(x: jax.Array, *,
                      axis_name: str = MODEL_AXIS) -> jax.Array:
    """Enter the fused path: slice this rank's token chunk (dim −2) off
    a REPLICATED activation — free of communication forward; the
    backward reassembles and psums the cotangent over the model axis
    (the embedding-boundary conjugate, explicit on old jax exactly like
    :func:`tp_block_input`)."""
    from ..common.compat import needs_explicit_grad_reduce

    n = _axis_size(axis_name)
    tc = x.shape[-2] // n
    if tc * n != x.shape[-2]:
        raise ValueError(
            f"tp_scatter_tokens needs tokens ({x.shape[-2]}) divisible "
            f"by the model-axis size ({n})"
        )
    if not needs_explicit_grad_reduce():
        i = lax.axis_index(axis_name)
        return lax.dynamic_slice_in_dim(x, i * tc, tc, axis=-2)
    global _scatter_tokens_psum_bwd
    if _scatter_tokens_psum_bwd is None:
        _scatter_tokens_psum_bwd = _make_scatter_tokens_psum_bwd()
    return _scatter_tokens_psum_bwd(x, axis_name)


def _make_scatter_tokens_psum_bwd():
    import functools

    @functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
    def f(x, axis_name):
        n = _axis_size(axis_name)
        i = lax.axis_index(axis_name)
        return lax.dynamic_slice_in_dim(
            x, i * (x.shape[-2] // n), x.shape[-2] // n, axis=-2
        )

    def fwd(x, axis_name):
        return f(x, axis_name), None

    def bwd(axis_name, res, ct):
        from ..ops import fusion as _fusion

        n = _axis_size(axis_name)
        shape = list(ct.shape)
        shape[-2] = shape[-2] * n
        i = lax.axis_index(axis_name)
        full = jnp.zeros(tuple(shape), ct.dtype)
        idx = [0] * len(shape)
        idx[-2] = i * ct.shape[-2]
        full = lax.dynamic_update_slice(full, ct, tuple(idx))
        _fusion.record_axis_wire_bytes(
            full.size * full.dtype.itemsize, axis_name, "psum"
        )
        return (lax.psum(full, axis_name),)

    f.defvjp(fwd, bwd)
    return f


_scatter_tokens_psum_bwd = None


def tp_gather_tokens(x_shard: jax.Array, *,
                     axis_name: str = MODEL_AXIS) -> jax.Array:
    """Leave the fused path: all-gather the token chunks (dim −2) back
    to a replicated activation. The backward takes this rank's LOCAL
    cotangent slice — downstream cotangents are replicated-identical
    (the loss is pmean'd over the model axis), so the all_gather's
    psum-scatter transpose would n-fold count; explicit on old jax,
    the vma machinery's job on new jax."""
    from ..common.compat import needs_explicit_grad_reduce

    if not needs_explicit_grad_reduce():
        return lax.all_gather(
            x_shard, axis_name, axis=x_shard.ndim - 2, tiled=True
        )
    global _gather_tokens_slice_bwd
    if _gather_tokens_slice_bwd is None:
        _gather_tokens_slice_bwd = _make_gather_tokens_slice_bwd()
    return _gather_tokens_slice_bwd(x_shard, axis_name)


def _make_gather_tokens_slice_bwd():
    import functools

    @functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
    def f(x_shard, axis_name):
        from ..ops import fusion as _fusion

        n = _axis_size(axis_name)
        _fusion.record_axis_wire_bytes(
            x_shard.size * x_shard.dtype.itemsize * n, axis_name,
            "allgather",
        )
        return lax.all_gather(
            x_shard, axis_name, axis=x_shard.ndim - 2, tiled=True
        )

    def fwd(x_shard, axis_name):
        return f(x_shard, axis_name), None

    def bwd(axis_name, res, ct):
        tc = ct.shape[-2] // _axis_size(axis_name)
        i = lax.axis_index(axis_name)
        return (lax.dynamic_slice_in_dim(ct, i * tc, tc, axis=-2),)

    f.defvjp(fwd, bwd)
    return f


_gather_tokens_slice_bwd = None


def tp_replicated_params(tree: Any, *,
                         axis_name: str = MODEL_AXIS) -> Any:
    """Mark a REPLICATED param subtree consumed by token-sharded compute
    on the fused path (block layernorms): each rank's grad covers only
    its token chunk, so the cotangents psum over the model axis — the
    same conjugate :func:`tp_block_input` provides, applied per leaf."""
    return jax.tree.map(
        lambda leaf: tp_block_input(leaf, axis_name=axis_name), tree
    )


def column_parallel_fused(x_shard: jax.Array, w_shard: jax.Array,
                          b_shard=None, *,
                          axis_name: str = MODEL_AXIS,
                          chunks: int = 0) -> jax.Array:
    """Fused column consume: ``y = all_gather(x_shard over tokens) @
    W[:, shard]`` with the gather chunks riding the bidirectional ring
    while the MXU multiplies — input is the token-sharded residual
    stream, output full-token and feature-sharded (what attention and
    the gelu need)."""
    from ..ops.collective_matmul import all_gather_matmul

    y = all_gather_matmul(
        x_shard, w_shard, axis_name=axis_name, chunks=chunks
    )
    if b_shard is not None:
        y = y + b_shard
    return y


def row_parallel_fused(x_shard: jax.Array, w_shard: jax.Array,
                       b_shard=None, *,
                       axis_name: str = MODEL_AXIS,
                       chunks: int = 0) -> jax.Array:
    """Fused row produce: ``z = reduce_scatter(x @ W[shard, :] over
    tokens)`` — partial products per destination chunk reduced along
    the ring; the classic psum never materializes. Output is the
    token-sharded residual stream
    (``all_gather(row_parallel_fused(...)) == row_parallel(...)``)."""
    from ..ops.collective_matmul import matmul_reduce_scatter

    z = matmul_reduce_scatter(
        x_shard, w_shard, axis_name=axis_name, chunks=chunks
    )
    if b_shard is not None:
        n = _axis_size(axis_name)
        f = b_shard.shape[-1]
        if f * n != w_shard.shape[-1]:
            raise ValueError(
                f"row_parallel_fused bias must be the [D/n] shard: got "
                f"{f} features for D={w_shard.shape[-1]} over n={n} "
                f"shards"
            )
        b_full = lax.all_gather(b_shard, axis_name, axis=0, tiled=True)
        z = z + b_full
    return z


def tp_mlp(params: dict, x: jax.Array, *,
           axis_name: str = MODEL_AXIS,
           activation: Callable = jax.nn.gelu) -> jax.Array:
    """One Megatron MLP block on sharded weights:
    ``params = {"w1": [D, F/n], "b1": [F/n], "w2": [F/n, D], "b2": [D/n]}``
    (every parameter is a true shard — see :func:`row_parallel` on why the
    output bias shards too).
    """
    h = activation(column_parallel(x, params["w1"], params.get("b1")))
    return row_parallel(h, params["w2"], params.get("b2"),
                        axis_name=axis_name)


def tp_attention(params: dict, x: jax.Array, *, head_dim: int,
                 axis_name: str = MODEL_AXIS,
                 causal: bool = True) -> jax.Array:
    """Megatron head-sharded self-attention: the QKV projection is
    column-parallel over heads (each rank holds H/n heads), attention runs
    on the local heads through the Pallas flash kernel, and the output
    projection is row-parallel — again exactly ONE psum per block.

    ``params = {"wqkv": [D, 3*(H/n)*Dh], "wo": [(H/n)*Dh, D],
    "bo": [D/n]}``; ``head_dim`` is static (shapes derive from it).
    """
    from ..ops.pallas_attention import flash_attention_bthd

    B, T, D = x.shape
    qkv = column_parallel(x, params["wqkv"])          # [B, T, 3*Hl*Dh]
    if qkv.shape[-1] % (3 * head_dim):
        raise ValueError(
            f"qkv width {qkv.shape[-1]} is not divisible by 3*head_dim "
            f"({3 * head_dim}); head_dim does not match the sharded weights"
        )
    hl = qkv.shape[-1] // (3 * head_dim)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, hl, head_dim)
    k = k.reshape(B, T, hl, head_dim)
    v = v.reshape(B, T, hl, head_dim)
    a = flash_attention_bthd(q, k, v, causal=causal)
    a = a.reshape(B, T, hl * head_dim)
    return row_parallel(a, params["wo"], params.get("bo"),
                        axis_name=axis_name)


def shard_attention_params(rng, d_model: int, n_heads: int, n_shards: int,
                           dtype=jnp.float32) -> dict:
    """Initialize full attention weights and return head-sharded stacks
    [n, ...] for placement via P(model)."""
    if n_heads % n_shards or d_model % n_heads or d_model % n_shards:
        raise ValueError(
            f"n_heads ({n_heads}) and d_model ({d_model}) must divide by "
            f"n_shards ({n_shards}); d_model by n_heads"
        )
    head_dim = d_model // n_heads
    hl = n_heads // n_shards
    k1, k2 = jax.random.split(rng)
    wqkv = jax.random.normal(k1, (d_model, 3 * d_model), dtype) * (
        d_model ** -0.5
    )
    wo = jax.random.normal(k2, (d_model, d_model), dtype) * (
        d_model ** -0.5
    )
    # Per-shard QKV columns: for each of q/k/v, take that shard's heads.
    wq, wk, wv = jnp.split(wqkv, 3, axis=1)
    f = hl * head_dim

    def col(w, i):
        return w[:, i * f:(i + 1) * f]

    return {
        "wqkv": jnp.stack([
            jnp.concatenate([col(wq, i), col(wk, i), col(wv, i)], axis=1)
            for i in range(n_shards)
        ]),
        "wo": jnp.stack([
            wo[i * f:(i + 1) * f, :] for i in range(n_shards)
        ]),
        "bo": jnp.zeros((n_shards, d_model // n_shards), dtype),
    }


def shard_mlp_params(rng, d_model: int, d_hidden: int, n_shards: int,
                     dtype=jnp.float32) -> dict:
    """Initialize full MLP weights and return them with a leading shard
    dim [n, ...] for placement via P(model) — rank i trains shard i."""
    k1, k2 = jax.random.split(rng)
    w1 = jax.random.normal(k1, (d_model, d_hidden), dtype) * (
        d_model ** -0.5
    )
    w2 = jax.random.normal(k2, (d_hidden, d_model), dtype) * (
        d_hidden ** -0.5
    )
    if d_hidden % n_shards or d_model % n_shards:
        raise ValueError(
            f"d_hidden ({d_hidden}) and d_model ({d_model}) must divide "
            f"by n_shards ({n_shards})"
        )
    f = d_hidden // n_shards
    return {
        "w1": jnp.stack([w1[:, i * f:(i + 1) * f] for i in range(n_shards)]),
        "b1": jnp.zeros((n_shards, f), dtype),
        "w2": jnp.stack([w2[i * f:(i + 1) * f, :] for i in range(n_shards)]),
        "b2": jnp.zeros((n_shards, d_model // n_shards), dtype),
    }


from ._stacked import init_stacked_state as init_tp_state  # noqa: E402


def make_tp_train_step(
    loss_fn: Callable,
    optimizer,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
    donate: bool = True,
):
    """Build a jitted DP×TP train step.

    ``loss_fn(params_shard, batch_shard) -> scalar`` runs on the local
    (batch/nd, weight-shard) pair, calling :func:`tp_mlp`-style layers
    bound to ``model_axis``. Params enter with a leading shard dim
    [n_model, ...] placed P(model); batches [B, ...] placed P(data).

    Gradient reduction: sharded weights reduce over ``data`` only (each
    model rank owns its shard); the loss/replicated stats reduce over both
    axes.
    """
    from ..common.compat import assert_replicated
    from ..jax import _shard_map
    from ._stacked import stacked_train_update

    def step(params, opt_state, batch):
        params, opt_state, loss = stacked_train_update(
            optimizer, params, opt_state,
            jax.value_and_grad(lambda p: loss_fn(p, batch)), data_axis,
        )
        loss = lax.pmean(lax.pmean(loss, data_axis), model_axis)
        # Old-jax check_rep cannot infer the data-axis replication of the
        # updated shards through optax; no-op on new jax.
        params = assert_replicated(params, data_axis)
        opt_state = assert_replicated(opt_state, data_axis)
        return params, opt_state, loss

    fn = _shard_map(
        step, mesh, check=True,
        in_specs=(P(model_axis), P(model_axis), P(data_axis)),
        out_specs=(P(model_axis), P(model_axis), P()),
    )
    return jax.jit(fn, donate_argnums=(0, 1) if donate else ())
