"""Tensor (model) parallelism: Megatron-style column/row-parallel layers.

TPU-native extension beyond the reference framework (which is
model-agnostic DP only, SURVEY.md §2.3): weight matrices shard over a
``model`` mesh axis and activations stay sharded between the column- and
row-parallel halves of each block, so the only collective per MLP/attention
block is ONE psum on the row-parallel output — the classic Megatron
schedule, expressed with ``shard_map`` + ``lax.psum`` so XLA lays the
reduction onto ICI.

Layout (per device, axis size n):
  - column-parallel: W1 [D, F/n]; y = x @ W1 — output feature-sharded,
    no communication (the gelu runs sharded too);
  - row-parallel: W2 [F/n, D]; z = psum(y @ W2) — one allreduce brings the
    block output back replicated.

The same pair implements attention head sharding (QKV projection is
column-parallel over heads, the output projection row-parallel).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..common.compat import axis_size as _axis_size
from ..common.compat import psum_replicated_grad
from .mesh import DATA_AXIS

MODEL_AXIS = "model"


def _make_block_input_psum_bwd():
    import functools

    @functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
    def f(x, axis_name):
        return x

    def fwd(x, axis_name):
        return x, None

    def bwd(axis_name, _res, ct):
        from ..ops import fusion as _fusion

        # The conjugate psum moves the same activation bytes the forward
        # g-psum moves — charge the model axis (trace-time).
        _fusion.record_axis_wire_bytes(
            ct.size * ct.dtype.itemsize, axis_name, "psum"
        )
        return (lax.psum(ct, axis_name),)

    f.defvjp(fwd, bwd)
    return f


_block_input_psum_bwd = None


def tp_block_input(x: jax.Array, *, axis_name: str = MODEL_AXIS) -> jax.Array:
    """Megatron's ``f`` operator — identity forward, cotangent psum over
    the model axis in the backward: the conjugate of the row-parallel
    ``g`` psum. Apply to a REPLICATED block input right before it feeds
    column-parallel shards; without it, each rank's cotangent for the
    block input carries only its OWN shard's partial, so everything
    upstream (earlier blocks' sharded weights, embeddings) differentiates
    wrong in multi-block stacks.

    On new jax (vma shard_map, ``check_vma=True``) the replication
    tracker inserts exactly this transpose itself and this function is
    the identity — an explicit psum there would double-count."""
    from ..common.compat import needs_explicit_grad_reduce

    if not needs_explicit_grad_reduce():
        return x
    global _block_input_psum_bwd
    if _block_input_psum_bwd is None:
        _block_input_psum_bwd = _make_block_input_psum_bwd()
    return _block_input_psum_bwd(x, axis_name)


def column_parallel(x: jax.Array, w_shard: jax.Array,
                    b_shard=None) -> jax.Array:
    """y = x @ W[:, shard] (+ b[shard]): output is feature-sharded; no
    communication. Call inside shard_map."""
    y = x @ w_shard
    if b_shard is not None:
        y = y + b_shard
    return y


def row_parallel(x_shard: jax.Array, w_shard: jax.Array, b_shard=None, *,
                 axis_name: str = MODEL_AXIS) -> jax.Array:
    """z = psum_i(x_i @ W[shard_i, :] + scatter_i(b_i)): the one collective
    of the Megatron block.

    The bias is genuinely SHARDED ([D/n] per rank, scattered to its offset
    inside the reduction) rather than replicated: a replicated-but-stacked
    bias would be typed device-varying by shard_map's replication checker,
    which flips the psum transpose from pbroadcast back to a sum and
    scales every upstream gradient by the axis size."""
    y = x_shard @ w_shard
    if b_shard is not None:
        n = _axis_size(axis_name)
        f = b_shard.shape[-1]
        if f * n != w_shard.shape[-1]:
            # A full-size bias would silently be added n times (the
            # scatter offset clamps); fail at trace time instead.
            raise ValueError(
                f"row_parallel bias must be the [D/n] shard: got {f} "
                f"features for D={w_shard.shape[-1]} over n={n} shards"
            )
        i = lax.axis_index(axis_name)
        full = jnp.zeros((w_shard.shape[-1],), b_shard.dtype)
        full = lax.dynamic_update_slice(full, b_shard, (i * f,))
        y = y + full
    # Per-axis attribution (trace-time, docs/parallelism.md): the one
    # Megatron psum of this half-block, charged to the MODEL axis so a
    # composed DP x TP program's wire split stays honest. Never
    # bucketized/quantized/re-planned — a plain psum XLA lays onto ICI.
    from ..ops import fusion as _fusion

    _fusion.record_axis_wire_bytes(
        y.size * y.dtype.itemsize, axis_name, "psum"
    )
    # Replicated-cotangent psum: the block output feeds an SPMD-identical
    # loss, so the transpose must be the identity (see compat).
    return psum_replicated_grad(y, axis_name)


def tp_mlp(params: dict, x: jax.Array, *,
           axis_name: str = MODEL_AXIS,
           activation: Callable = jax.nn.gelu) -> jax.Array:
    """One Megatron MLP block on sharded weights:
    ``params = {"w1": [D, F/n], "b1": [F/n], "w2": [F/n, D], "b2": [D/n]}``
    (every parameter is a true shard — see :func:`row_parallel` on why the
    output bias shards too).
    """
    h = activation(column_parallel(x, params["w1"], params.get("b1")))
    return row_parallel(h, params["w2"], params.get("b2"),
                        axis_name=axis_name)


def tp_attention(params: dict, x: jax.Array, *, head_dim: int,
                 axis_name: str = MODEL_AXIS,
                 causal: bool = True) -> jax.Array:
    """Megatron head-sharded self-attention: the QKV projection is
    column-parallel over heads (each rank holds H/n heads), attention runs
    on the local heads through the Pallas flash kernel, and the output
    projection is row-parallel — again exactly ONE psum per block.

    ``params = {"wqkv": [D, 3*(H/n)*Dh], "wo": [(H/n)*Dh, D],
    "bo": [D/n]}``; ``head_dim`` is static (shapes derive from it).
    """
    from ..ops.pallas_attention import flash_attention_bthd

    B, T, D = x.shape
    qkv = column_parallel(x, params["wqkv"])          # [B, T, 3*Hl*Dh]
    if qkv.shape[-1] % (3 * head_dim):
        raise ValueError(
            f"qkv width {qkv.shape[-1]} is not divisible by 3*head_dim "
            f"({3 * head_dim}); head_dim does not match the sharded weights"
        )
    hl = qkv.shape[-1] // (3 * head_dim)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, hl, head_dim)
    k = k.reshape(B, T, hl, head_dim)
    v = v.reshape(B, T, hl, head_dim)
    a = flash_attention_bthd(q, k, v, causal=causal)
    a = a.reshape(B, T, hl * head_dim)
    return row_parallel(a, params["wo"], params.get("bo"),
                        axis_name=axis_name)


def shard_attention_params(rng, d_model: int, n_heads: int, n_shards: int,
                           dtype=jnp.float32) -> dict:
    """Initialize full attention weights and return head-sharded stacks
    [n, ...] for placement via P(model)."""
    if n_heads % n_shards or d_model % n_heads or d_model % n_shards:
        raise ValueError(
            f"n_heads ({n_heads}) and d_model ({d_model}) must divide by "
            f"n_shards ({n_shards}); d_model by n_heads"
        )
    head_dim = d_model // n_heads
    hl = n_heads // n_shards
    k1, k2 = jax.random.split(rng)
    wqkv = jax.random.normal(k1, (d_model, 3 * d_model), dtype) * (
        d_model ** -0.5
    )
    wo = jax.random.normal(k2, (d_model, d_model), dtype) * (
        d_model ** -0.5
    )
    # Per-shard QKV columns: for each of q/k/v, take that shard's heads.
    wq, wk, wv = jnp.split(wqkv, 3, axis=1)
    f = hl * head_dim

    def col(w, i):
        return w[:, i * f:(i + 1) * f]

    return {
        "wqkv": jnp.stack([
            jnp.concatenate([col(wq, i), col(wk, i), col(wv, i)], axis=1)
            for i in range(n_shards)
        ]),
        "wo": jnp.stack([
            wo[i * f:(i + 1) * f, :] for i in range(n_shards)
        ]),
        "bo": jnp.zeros((n_shards, d_model // n_shards), dtype),
    }


def shard_mlp_params(rng, d_model: int, d_hidden: int, n_shards: int,
                     dtype=jnp.float32) -> dict:
    """Initialize full MLP weights and return them with a leading shard
    dim [n, ...] for placement via P(model) — rank i trains shard i."""
    k1, k2 = jax.random.split(rng)
    w1 = jax.random.normal(k1, (d_model, d_hidden), dtype) * (
        d_model ** -0.5
    )
    w2 = jax.random.normal(k2, (d_hidden, d_model), dtype) * (
        d_hidden ** -0.5
    )
    if d_hidden % n_shards or d_model % n_shards:
        raise ValueError(
            f"d_hidden ({d_hidden}) and d_model ({d_model}) must divide "
            f"by n_shards ({n_shards})"
        )
    f = d_hidden // n_shards
    return {
        "w1": jnp.stack([w1[:, i * f:(i + 1) * f] for i in range(n_shards)]),
        "b1": jnp.zeros((n_shards, f), dtype),
        "w2": jnp.stack([w2[i * f:(i + 1) * f, :] for i in range(n_shards)]),
        "b2": jnp.zeros((n_shards, d_model // n_shards), dtype),
    }


from ._stacked import init_stacked_state as init_tp_state  # noqa: E402


def make_tp_train_step(
    loss_fn: Callable,
    optimizer,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
    donate: bool = True,
):
    """Build a jitted DP×TP train step.

    ``loss_fn(params_shard, batch_shard) -> scalar`` runs on the local
    (batch/nd, weight-shard) pair, calling :func:`tp_mlp`-style layers
    bound to ``model_axis``. Params enter with a leading shard dim
    [n_model, ...] placed P(model); batches [B, ...] placed P(data).

    Gradient reduction: sharded weights reduce over ``data`` only (each
    model rank owns its shard); the loss/replicated stats reduce over both
    axes.
    """
    from ..common.compat import assert_replicated
    from ..jax import _shard_map
    from ._stacked import stacked_train_update

    def step(params, opt_state, batch):
        params, opt_state, loss = stacked_train_update(
            optimizer, params, opt_state,
            jax.value_and_grad(lambda p: loss_fn(p, batch)), data_axis,
        )
        loss = lax.pmean(lax.pmean(loss, data_axis), model_axis)
        # Old-jax check_rep cannot infer the data-axis replication of the
        # updated shards through optax; no-op on new jax.
        params = assert_replicated(params, data_axis)
        opt_state = assert_replicated(opt_state, data_axis)
        return params, opt_state, loss

    fn = _shard_map(
        step, mesh, check=True,
        in_specs=(P(model_axis), P(model_axis), P(data_axis)),
        out_specs=(P(model_axis), P(model_axis), P()),
    )
    return jax.jit(fn, donate_argnums=(0, 1) if donate else ())
