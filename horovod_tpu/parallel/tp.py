"""Tensor (model) parallelism: Megatron-style column/row-parallel layers.

TPU-native extension beyond the reference framework (which is
model-agnostic DP only, SURVEY.md §2.3): weight matrices shard over a
``model`` mesh axis and activations stay sharded between the column- and
row-parallel halves of each block, so the only collective per MLP/attention
block is ONE psum on the row-parallel output — the classic Megatron
schedule, expressed with ``shard_map`` + ``lax.psum`` so XLA lays the
reduction onto ICI.

Layout (per device, axis size n):
  - column-parallel: W1 [D, F/n]; y = x @ W1 — output feature-sharded,
    no communication (the gelu runs sharded too);
  - row-parallel: W2 [F/n, D]; z = psum(y @ W2) — one allreduce brings the
    block output back replicated.

The same pair implements attention head sharding (QKV projection is
column-parallel over heads, the output projection row-parallel).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import DATA_AXIS

MODEL_AXIS = "model"


def column_parallel(x: jax.Array, w_shard: jax.Array,
                    b_shard=None) -> jax.Array:
    """y = x @ W[:, shard] (+ b[shard]): output is feature-sharded; no
    communication. Call inside shard_map."""
    y = x @ w_shard
    if b_shard is not None:
        y = y + b_shard
    return y


def row_parallel(x_shard: jax.Array, w_shard: jax.Array, b_shard=None, *,
                 axis_name: str = MODEL_AXIS) -> jax.Array:
    """z = psum_i(x_i @ W[shard_i, :] + scatter_i(b_i)): the one collective
    of the Megatron block.

    The bias is genuinely SHARDED ([D/n] per rank, scattered to its offset
    inside the reduction) rather than replicated: a replicated-but-stacked
    bias would be typed device-varying by shard_map's replication checker,
    which flips the psum transpose from pbroadcast back to a sum and
    scales every upstream gradient by the axis size."""
    y = x_shard @ w_shard
    if b_shard is not None:
        n = lax.axis_size(axis_name)
        f = b_shard.shape[-1]
        if f * n != w_shard.shape[-1]:
            # A full-size bias would silently be added n times (the
            # scatter offset clamps); fail at trace time instead.
            raise ValueError(
                f"row_parallel bias must be the [D/n] shard: got {f} "
                f"features for D={w_shard.shape[-1]} over n={n} shards"
            )
        i = lax.axis_index(axis_name)
        full = jnp.zeros((w_shard.shape[-1],), b_shard.dtype)
        full = lax.dynamic_update_slice(full, b_shard, (i * f,))
        y = y + full
    return lax.psum(y, axis_name)


def tp_mlp(params: dict, x: jax.Array, *,
           axis_name: str = MODEL_AXIS,
           activation: Callable = jax.nn.gelu) -> jax.Array:
    """One Megatron MLP block on sharded weights:
    ``params = {"w1": [D, F/n], "b1": [F/n], "w2": [F/n, D], "b2": [D/n]}``
    (every parameter is a true shard — see :func:`row_parallel` on why the
    output bias shards too).
    """
    h = activation(column_parallel(x, params["w1"], params.get("b1")))
    return row_parallel(h, params["w2"], params.get("b2"),
                        axis_name=axis_name)


def shard_mlp_params(rng, d_model: int, d_hidden: int, n_shards: int,
                     dtype=jnp.float32) -> dict:
    """Initialize full MLP weights and return them with a leading shard
    dim [n, ...] for placement via P(model) — rank i trains shard i."""
    k1, k2 = jax.random.split(rng)
    w1 = jax.random.normal(k1, (d_model, d_hidden), dtype) * (
        d_model ** -0.5
    )
    w2 = jax.random.normal(k2, (d_hidden, d_model), dtype) * (
        d_hidden ** -0.5
    )
    if d_hidden % n_shards or d_model % n_shards:
        raise ValueError(
            f"d_hidden ({d_hidden}) and d_model ({d_model}) must divide "
            f"by n_shards ({n_shards})"
        )
    f = d_hidden // n_shards
    return {
        "w1": jnp.stack([w1[:, i * f:(i + 1) * f] for i in range(n_shards)]),
        "b1": jnp.zeros((n_shards, f), dtype),
        "w2": jnp.stack([w2[i * f:(i + 1) * f, :] for i in range(n_shards)]),
        "b2": jnp.zeros((n_shards, d_model // n_shards), dtype),
    }


from ._stacked import init_stacked_state as init_tp_state  # noqa: E402


def make_tp_train_step(
    loss_fn: Callable,
    optimizer,
    mesh: Mesh,
    *,
    data_axis: str = DATA_AXIS,
    model_axis: str = MODEL_AXIS,
    donate: bool = True,
):
    """Build a jitted DP×TP train step.

    ``loss_fn(params_shard, batch_shard) -> scalar`` runs on the local
    (batch/nd, weight-shard) pair, calling :func:`tp_mlp`-style layers
    bound to ``model_axis``. Params enter with a leading shard dim
    [n_model, ...] placed P(model); batches [B, ...] placed P(data).

    Gradient reduction: sharded weights reduce over ``data`` only (each
    model rank owns its shard); the loss/replicated stats reduce over both
    axes.
    """
    from ..jax import _shard_map
    from ._stacked import stacked_train_update

    def step(params, opt_state, batch):
        params, opt_state, loss = stacked_train_update(
            optimizer, params, opt_state,
            jax.value_and_grad(lambda p: loss_fn(p, batch)), data_axis,
        )
        loss = lax.pmean(lax.pmean(loss, data_axis), model_axis)
        return params, opt_state, loss

    fn = _shard_map(
        step, mesh, check=True,
        in_specs=(P(model_axis), P(model_axis), P(data_axis)),
        out_specs=(P(model_axis), P(model_axis), P()),
    )
    return jax.jit(fn, donate_argnums=(0, 1) if donate else ())
