"""Declarative sharding-rules engine: regex -> PartitionSpec tables.

The composed-parallelism fast path (docs/parallelism.md "Composed DP x TP
fast path") places every parameter of a model by TABLE, not by hand: an
ordered sequence of ``(regex, PartitionSpec)`` rules is matched against
each leaf's ``/``-joined tree path and the FIRST hit decides the leaf's
mesh placement (the ``match_partition_rules`` shape from the reference
repos in SNIPPETS.md). Scalars always replicate; a non-scalar leaf no
rule matches is an error, not a silent default — and the whole table is
preflighted by the Pass 5 static validator (``analysis/sharding_rules``)
against the mesh AND the concrete shape table before anything is traced,
so a typo'd axis or a non-divisible dim fails at build time with a named
finding instead of deep inside pjit.

The same table places optimizer state: optax state trees embed the param
tree (``0/mu/block_0/attention/query/kernel``), and ``re.search`` keyed
rules hit the embedded name, so one table drives params, Adam moments,
and anything else shaped like the model.

``make_shard_and_gather_fns`` turns a spec tree into per-leaf jitted
placement/collection functions (shard -> gather round-trips bitwise);
``local_shard_tree`` is the host-side view of ONE mesh coordinate's
shards (what the composed ZeRO-1 state init and the digest tests slice).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from ..analysis.sharding_rules import (
    EXAMPLE_GPT_RULES,
    Rule,
    normalize_spec,
)

__all__ = [
    "GPT_CACHE_RULES",
    "GPT_RULES",
    "NAMED_CACHE_RULES",
    "NAMED_RULES",
    "gather_tree",
    "local_shard_tree",
    "make_shard_and_gather_fns",
    "match_partition_rules",
    "named_tree_paths",
    "preflight_rules",
    "resolve_rules",
    "shard_tree",
    "spec_mentions",
    "tree_shape_table",
]

# The reference DP x TP GPT table — validated against the REAL
# models/transformer.py param tree by Pass 5 (tools/collective_lint.py
# sharding) and trained by the composed fast path.
GPT_RULES: Tuple[Rule, ...] = EXAMPLE_GPT_RULES

NAMED_RULES: Dict[str, Tuple[Rule, ...]] = {"gpt": GPT_RULES}

# Serving decode-state (paged KV-cache) placement, same engine and same
# mesh as the param table (docs/serving.md): cache leaves are named
# ``block_i/attention/cache_k`` / ``cache_v`` with shape
# ``[num_pages, page_size, n_heads, head_dim]``; sharding the HEAD dim
# over "model" makes each TP rank hold exactly the pages of its local
# heads — the decode step's column-parallel q/k/v writes land on the
# local shard with no communication, mirroring Megatron head sharding of
# the q/k/v kernels. Preflighted by Pass 5 against the concrete cache
# tree before the decode step is built (serve/kvcache.py).
GPT_CACHE_RULES: Tuple[Rule, ...] = (
    (r"attention/cache_[kv]$", (None, None, "model", None)),
)

NAMED_CACHE_RULES: Dict[str, Tuple[Rule, ...]] = {"gpt": GPT_CACHE_RULES}


def resolve_rules(rules: Any) -> Sequence[Rule]:
    """A rule table, or the name of a shipped one (``"gpt"``)."""
    if isinstance(rules, str):
        try:
            return NAMED_RULES[rules]
        except KeyError:
            raise ValueError(
                f"unknown named rule table {rules!r}; shipped tables: "
                f"{sorted(NAMED_RULES)}"
            ) from None
    return rules


def _key_name(key: Any) -> str:
    """Render one tree-path key the way flax renders param names."""
    for attr in ("key", "idx", "name"):
        v = getattr(key, attr, None)
        if v is not None:
            return str(v)
    return str(key)


def named_tree_paths(tree: Any) -> List[Tuple[str, Any]]:
    """``[(/-joined path, leaf)]`` in flatten order — the names the rule
    regexes match (flax params: ``block_0/attention/query/kernel``)."""
    import jax

    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [
        ("/".join(_key_name(k) for k in path), leaf)
        for path, leaf in flat
    ]


def tree_shape_table(tree: Any) -> Dict[str, Tuple[int, ...]]:
    """``{name: shape}`` for the Pass 5 validator (arrays or avals)."""
    return {
        name: tuple(int(d) for d in getattr(leaf, "shape", ()))
        for name, leaf in named_tree_paths(tree)
    }


def _is_scalar(leaf: Any) -> bool:
    shape = tuple(getattr(leaf, "shape", ()))
    n = 1
    for d in shape:
        n *= int(d)
    return len(shape) == 0 or n == 1


def match_partition_rules(rules: Any, tree: Any) -> Any:
    """First-match-wins placement: a pytree of ``PartitionSpec`` leaves
    mirroring ``tree``. Scalars replicate unconditionally; a non-scalar
    leaf no rule matches raises (add a catch-all ``(".*", None)`` to
    replicate by default). PartitionSpec-shaped specs (None / axis name /
    tuples) are normalized through the Pass 5 grammar."""
    import jax
    from jax.sharding import PartitionSpec as P

    rules = resolve_rules(rules)
    compiled = []
    for pattern, spec in rules:
        norm = normalize_spec(spec)
        if norm is None:
            raise ValueError(
                f"rule {pattern!r} spec {spec!r} is not "
                f"PartitionSpec-shaped"
            )
        compiled.append((re.compile(pattern), norm))

    def to_spec(norm: Tuple[Tuple[str, ...], ...]) -> Any:
        return P(*(
            (None if not axes else (axes[0] if len(axes) == 1
                                    else tuple(axes)))
            for axes in norm
        ))

    names = iter(named_tree_paths(tree))

    def place(leaf):
        name, _ = next(names)
        if _is_scalar(leaf):
            return P()
        for rx, norm in compiled:
            if rx.search(name) is not None:
                return to_spec(norm)
        raise ValueError(
            f"no sharding rule matches param {name!r} (shape "
            f"{tuple(getattr(leaf, 'shape', ()))}); add a rule or a "
            f"catch-all ('.*', None)"
        )

    return jax.tree.map(place, tree)


def spec_leaves(specs: Any) -> List[Any]:
    """Flatten a spec tree treating ``PartitionSpec`` (a tuple subclass)
    as a LEAF."""
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.tree.leaves(specs, is_leaf=lambda x: isinstance(x, P))


def spec_mentions(spec: Any, axes: Sequence[str]) -> bool:
    """Whether a PartitionSpec shards any dim over one of ``axes``."""
    norm = normalize_spec(spec)
    if not norm:
        return False
    want = set(axes)
    return any(bool(want.intersection(entry)) for entry in norm)


def preflight_rules(rules: Any, mesh: Any, tree: Any,
                    *, suppress: Optional[Sequence[str]] = None) -> None:
    """Pass 5 preflight of ``(rules, mesh, tree)`` — ALWAYS enforced for
    the composed path (not gated on HOROVOD_TPU_STATIC_CHECKS): error
    findings raise :class:`~horovod_tpu.analysis.CollectiveSafetyError`
    naming the rule/param, warnings are logged."""
    import logging

    from ..analysis import CollectiveSafetyError
    from ..analysis.sharding_rules import validate_sharding_rules

    rules = resolve_rules(rules)
    axes = mesh
    shape = getattr(mesh, "shape", None)
    if shape is not None:
        axes = {str(k): int(v) for k, v in dict(shape).items()}
    findings = validate_sharding_rules(
        rules, axes, tree_shape_table(tree), suppress=suppress
    )
    errors = [f for f in findings if f.severity == "error"]
    if errors:
        raise CollectiveSafetyError(errors)
    for f in findings:
        logging.getLogger("horovod_tpu").warning("%s", f.render())


def make_shard_and_gather_fns(
    specs: Any, mesh: Any
) -> Tuple[Any, Any]:
    """Per-leaf jitted placement functions from a spec tree (the
    SNIPPETS.md ``make_shard_and_gather_fns`` shape): ``shard_fns[leaf]``
    constrains the leaf onto its ``NamedSharding(mesh, spec)``;
    ``gather_fns[leaf]`` collects it back fully replicated. Shard →
    gather round-trips BITWISE (pure data movement, tested)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    leaves, treedef = jax.tree.flatten(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    shard_leaves, gather_leaves = [], []
    for spec in leaves:
        sharded = NamedSharding(mesh, spec)
        replicated = NamedSharding(mesh, P())
        shard_leaves.append(jax.jit(lambda x: x, out_shardings=sharded))
        gather_leaves.append(jax.jit(lambda x: x, out_shardings=replicated))
    return (
        jax.tree.unflatten(treedef, shard_leaves),
        jax.tree.unflatten(treedef, gather_leaves),
    )


def shard_tree(tree: Any, specs: Any, mesh: Any) -> Any:
    """Place every leaf of ``tree`` per its spec (device placement only;
    values unchanged)."""
    import jax

    shard_fns, _ = make_shard_and_gather_fns(specs, mesh)
    return jax.tree.map(lambda f, x: f(x), shard_fns, tree)


def gather_tree(tree: Any, specs: Any, mesh: Any) -> Any:
    """Collect every leaf back fully replicated (bitwise inverse of
    :func:`shard_tree`)."""
    import jax

    _, gather_fns = make_shard_and_gather_fns(specs, mesh)
    return jax.tree.map(lambda f, x: f(x), gather_fns, tree)


def local_shard_tree(
    tree: Any,
    specs: Any,
    coords: Mapping[str, Tuple[int, int]],
) -> Any:
    """The host-side view of ONE mesh coordinate's shards: for each leaf,
    slice every dim its spec shards over an axis named in ``coords``
    (``{axis: (index, size)}``) to that coordinate's chunk; dims sharded
    over axes NOT in ``coords`` (and replicated leaves) pass through.
    This is what the composed ZeRO-1 state init uses to build each model
    rank's bucket states, and what the digest tests slice. A dim sharded
    over a mix of named and unnamed axes is rejected (ambiguous chunk)."""
    import jax

    names = iter(named_tree_paths(tree))
    s_leaves = iter(spec_leaves(specs))

    def slice_leaf(leaf):
        name, _ = next(names)
        spec = next(s_leaves)
        norm = normalize_spec(spec) or ()
        out = leaf
        for dim, dim_axes in enumerate(norm):
            hit = [a for a in dim_axes if a in coords]
            if not hit:
                continue
            if len(hit) != len(dim_axes):
                raise ValueError(
                    f"{name!r} dim {dim} shards over {dim_axes} — a mix "
                    f"of sliced ({hit}) and unsliced axes has no "
                    f"well-defined local chunk"
                )
            idx = 0
            total = 1
            for a in dim_axes:
                i, sz = coords[a]
                idx = idx * sz + int(i)
                total *= int(sz)
            size = int(leaf.shape[dim])
            if size % total:
                raise ValueError(
                    f"{name!r} dim {dim} (size {size}) is not divisible "
                    f"by {total}"
                )
            k = size // total
            sl = [slice(None)] * leaf.ndim
            sl[dim] = slice(idx * k, (idx + 1) * k)
            out = out[tuple(sl)]
        return out

    return jax.tree.map(slice_leaf, tree)
