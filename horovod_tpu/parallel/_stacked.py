"""Shared plumbing for shard-stacked parameter layouts (TP's ``[n_model,
...]`` and PP's ``[n_stages, ...]`` leading dims placed over a mesh axis).
"""

from __future__ import annotations

from typing import Callable

import jax
from jax import lax

from ..common.compat import axis_size as _axis_size
from ..common.compat import grad_psum


def init_stacked_state(optimizer, params_stacked):
    """Optimizer state for [n, ...]-stacked params: one state per shard
    (vmapped init), so it places alongside the params' axis spec."""
    return jax.vmap(optimizer.init)(params_stacked)


def apply_stacked_update(optimizer, params, opt_state, grads_local):
    """Unstack -> optimizer.update on this shard's row -> restack.
    ``grads_local`` is already normalized (local layout, no leading shard
    dim). Returns ([1, ...]-restacked params, state)."""
    import optax

    p_local = jax.tree.map(lambda t: t[0], params)
    s_local = jax.tree.map(lambda t: t[0], opt_state)
    updates, s_local = optimizer.update(grads_local, s_local, p_local)
    p_local = optax.apply_updates(p_local, updates)
    return (
        jax.tree.map(lambda t: t[None], p_local),
        jax.tree.map(lambda t: t[None], s_local),
    )


def stacked_train_update(optimizer, params, opt_state, value_and_grad_fn,
                         data_axis: str):
    """One update on stacked shards, inside a vma-checked shard_map:
    strip the leading shard dim, differentiate, normalize the data-axis
    gradient sum, apply, restack.

    Under vma-checked shard_map the transpose ALREADY psums cotangents
    over every axis the parameter is invariant on (the data axis here) —
    an explicit pmean would double-count; dividing by the axis size turns
    that sum into the data-average.
    """
    p_local = jax.tree.map(lambda t: t[0], params)
    loss, grads = value_and_grad_fn(p_local)
    nd = _axis_size(data_axis)
    # Old jax: the checked transpose leaves per-rank cotangents — reduce
    # explicitly (no-op on new jax, whose transpose already psummed).
    grads = grad_psum(grads, data_axis)
    grads = jax.tree.map(lambda g: g / nd, grads)
    new_params, new_state = apply_stacked_update(
        optimizer, params, opt_state, grads
    )
    return new_params, new_state, loss
