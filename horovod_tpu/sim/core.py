"""Deterministic discrete-event simulation of a distributed training step.

The model (docs/simulation.md has the full assumptions list):

- One SPMD step = forward → backward (stream-group segments in reduction
  order) → optimizer. Backward segment ``g`` produces group ``g``'s
  cotangents; its collective becomes *ready* on a rank when that rank
  finishes segments ``0..g``.
- A collective starts when EVERY rank is ready (collectives synchronize)
  and its plan's stages then occupy their hops in schedule order; each
  hop is a serially shared resource (one stage in flight per hop), which
  is what makes a deep stream pipeline back-pressure instead of
  overlapping for free.
- Stage cost is the compositor's own alpha-beta pricing
  (``latency_us * rounds + bytes_on_wire / (bandwidth_gbps * 1e3)``)
  over the — optionally calibrated — interconnect model, so the
  simulator and the planner can never disagree about what a plan costs.
- Seeded ``delay`` faults (``fault/plan.py``, site ``step``) stretch the
  faulted rank's first backward segment of the step — including the
  chronic-slowness shape (``every``/``until``: a persistent or periodic
  straggler); every draw comes from the plan's pure per-(seed, action,
  rank) decision streams, so a simulated incident is byte-reproducible.

Time is simulated microseconds from 0 — no wall clock, no randomness
outside the fault plan — and reports round every float, so a fixed seed
gives byte-identical output across runs (``tests/test_sim.py`` and
``make sim-smoke`` both lock this).
"""

from __future__ import annotations

import json
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..common.types import ReduceOp
from ..fault.plan import FaultPlan
from ..topo.compositor import Plan, candidate_plans, select_plan
from ..topo.model import InterconnectModel

logger = logging.getLogger("horovod_tpu.sim")

SIM_SCHEMA = 1

# Default compute-intensity assumption: microseconds of backward compute
# per MiB of parameter-gradient bytes. Dense layers do ~2 matmul passes
# per parameter in the backward, so compute scales with parameter bytes;
# the absolute constant only shifts the compute/comm balance and is
# overridden by calibration or --compute-us-per-mib. Chosen so a ~1 MiB
# bucket costs about as much compute as a generic-ICI transfer.
DEFAULT_COMPUTE_US_PER_MIB = 120.0

_MIB = float(1 << 20)


@dataclass(frozen=True)
class SimGroup:
    """One stream group, reduction order: ``nbytes`` of gradient payload
    whose producing backward segment takes ``compute_us`` per rank."""

    name: str
    nbytes: int
    compute_us: float

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "nbytes": int(self.nbytes),
            "compute_us": round(float(self.compute_us), 4),
        }


@dataclass(frozen=True)
class SimProgram:
    """The abstract training program a fleet executes: stream groups in
    REDUCTION order (the ``plan_layer_groups`` partition) plus the
    forward and optimizer phases that bracket the backward."""

    name: str
    groups: Tuple[SimGroup, ...]
    forward_us: float = 0.0
    optimizer_us: float = 0.0
    source: str = "layers"
    # Fixed per-step SYNCHRONOUS communication outside the DP staircase
    # — the composed DP x TP program's in-block psums, priced on the
    # innermost (ICI) hop (:func:`tp_fixed_comm_us`). Counts as exposed
    # communication (never as compute), so scaling efficiency stays
    # honest for the composed shape.
    fixed_comm_us: float = 0.0

    @property
    def total_bytes(self) -> int:
        return sum(g.nbytes for g in self.groups)

    @property
    def compute_us(self) -> float:
        """Per-rank compute of one step with communication free — the
        denominator of scaling efficiency."""
        return (
            self.forward_us
            + sum(g.compute_us for g in self.groups)
            + self.optimizer_us
        )

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "source": self.source,
            "forward_us": round(float(self.forward_us), 4),
            "optimizer_us": round(float(self.optimizer_us), 4),
            "fixed_comm_us": round(float(self.fixed_comm_us), 4),
            "total_bytes": int(self.total_bytes),
            "groups": [g.to_dict() for g in self.groups],
        }


@dataclass(frozen=True)
class SimConfig:
    """Lowering knobs, mirroring the tuner's joint space: pinned topo
    algorithm (or ``"auto"`` = per-payload cost selection), wire dtype,
    ZeRO-1 reduction shape, and whether reduction streams inside the
    backward (``overlap=False`` = the post-hoc whole-tree path)."""

    algorithm: str = "auto"
    wire_dtype: str = "f32"
    zero1: bool = False
    overlap: bool = True
    op: ReduceOp = ReduceOp.AVERAGE

    def to_dict(self) -> dict:
        return {
            "algorithm": self.algorithm,
            "wire_dtype": self.wire_dtype,
            "zero1": bool(self.zero1),
            "overlap": bool(self.overlap),
            "op": self.op.name,
        }


def program_from_layers(
    name: str,
    layer_bytes: Sequence[int],
    *,
    fusion_threshold_bytes: int = 64 << 20,
    first_bucket_bytes: int = 1 << 20,
    compute_us_per_mib: float = DEFAULT_COMPUTE_US_PER_MIB,
    forward_fraction: float = 0.5,
    optimizer_us_per_mib: float = 4.0,
    source: str = "layers",
    fixed_comm_us: float = 0.0,
) -> SimProgram:
    """Build the program from per-layer gradient bytes (forward order)
    using the EXACT ``plan_layer_groups`` partition the streamed path
    registers and the tuner prices. Backward compute is apportioned to
    groups proportionally to their parameter bytes (dense-layer FLOPs
    scale with parameter count); the forward costs ``forward_fraction``
    of the backward (fwd ≈ half the training FLOPs of bwd)."""
    from ..ops.fusion import plan_layer_groups

    layer_bytes = [int(b) for b in layer_bytes]
    groups = plan_layer_groups(
        layer_bytes, int(fusion_threshold_bytes), int(first_bucket_bytes)
    )
    sim_groups: List[SimGroup] = []
    for gi, group in enumerate(groups):
        nb = sum(layer_bytes[i] for i in group)
        sim_groups.append(SimGroup(
            name=f"g{gi}",
            nbytes=nb,
            compute_us=(nb / _MIB) * float(compute_us_per_mib),
        ))
    total = sum(g.nbytes for g in sim_groups)
    backward_us = sum(g.compute_us for g in sim_groups)
    return SimProgram(
        name=name,
        groups=tuple(sim_groups),
        forward_us=backward_us * float(forward_fraction),
        optimizer_us=(total / _MIB) * float(optimizer_us_per_mib),
        source=source,
        fixed_comm_us=float(fixed_comm_us),
    )


def tp_fixed_comm_us(
    model: "InterconnectModel",
    psum_bytes: int,
    tp_degree: int,
    psums_per_step: int = 1,
    *,
    overlap: bool = False,
    chunks: int = 1,
    compute_us_per_psum: float = 0.0,
) -> float:
    """Price the composed program's per-step tensor-parallel term: the
    in-block activation psums ride the INNERMOST (fastest, ICI) hop as
    plain ring allreduces over ``tp_degree`` neighbours — never
    bucketized, never re-planned onto DCN (docs/parallelism.md). The
    returned microseconds feed ``SimProgram.fixed_comm_us`` (and
    ``tune(fixed_comm_us=...)``) as a constant every step pays, so the
    simulator's scale predictions and the tuner's knob costs stay honest
    for the composed shape. ``psums_per_step`` counts forward AND
    backward conjugates (2 per Megatron half-block per direction).

    ``overlap=True`` prices the fused collective-matmul path instead
    (docs/parallelism.md "Fused TP overlap"): each psum becomes one
    all_gather_matmul + one matmul_reduce_scatter, ``chunks`` ring
    chunks each, hiding their wire behind ``compute_us_per_psum`` (the
    psum's adjacent matmul time, split across the pair) — only the
    un-hideable remainder (``topo.compositor.collective_matmul_cost_us``:
    ``max(compute, wire) + ramp - compute``) is charged."""
    tp = int(tp_degree)
    if tp <= 1 or psum_bytes <= 0 or psums_per_step <= 0:
        return 0.0
    hop = model.hops[-1]
    if overlap:
        import dataclasses as _dc

        from ..topo.compositor import collective_matmul_cost_us

        inner = _dc.replace(model, hops=(_dc.replace(hop, size=tp),))
        priced = collective_matmul_cost_us(
            inner, int(psum_bytes), chunks=max(int(chunks), 1),
            compute_us=float(compute_us_per_psum) / 2.0,
        )
        return round(
            float(psums_per_step) * 2.0 * priced["exposed_us"], 4
        )
    rounds = 2 * (tp - 1)
    onwire = 2 * (tp - 1) * int(psum_bytes) / tp
    one = hop.latency_us * rounds + onwire / (hop.bandwidth_gbps * 1e3)
    return round(float(psums_per_step) * one, 4)


def program_from_spec(
    spec, config: Optional[Dict] = None, **kw
) -> SimProgram:
    """Program from a tuner :class:`~horovod_tpu.tune.ProgramSpec` —
    same layer granularity, same partition knobs (``config`` may carry
    ``fusion_threshold_bytes`` / ``first_bucket_bytes``)."""
    config = config or {}
    if "fusion_threshold_bytes" in config:
        kw.setdefault(
            "fusion_threshold_bytes", int(config["fusion_threshold_bytes"])
        )
    if "first_bucket_bytes" in config:
        kw.setdefault(
            "first_bucket_bytes", int(config["first_bucket_bytes"])
        )
    kw.setdefault("source", "program-spec")
    return program_from_layers(spec.name, spec.layer_bytes, **kw)


# --------------------------------------------------------------- faults


_SUPPORTED_FAULT_KINDS = ("delay",)


def _delay_matrix(
    plan: Optional[FaultPlan], ranks: int, steps: int
) -> Dict[int, List[float]]:
    """Per-rank per-step delay (us) a seeded fault plan injects,
    computed from the plan's PURE decision traces (independent of call
    order, like ``canonical_schedule``). Only ``delay`` actions
    simulate; other kinds are outside the model and are skipped with a
    loud note — a silently half-applied chaos plan would make the twin
    dishonest. Both the single-shot (``at_step``/``after``+``count``)
    and the chronic-slowness (``every``/``until``) shapes are honored:
    the window test and the decision-stream advance go through the same
    ``FaultAction.in_window`` the injector uses, so a recurring
    straggler stretches exactly the steps the live injector would."""
    delays: Dict[int, List[float]] = {}
    if plan is None:
        return delays
    skipped = sorted({
        a.kind for a in plan.actions if a.kind not in _SUPPORTED_FAULT_KINDS
    })
    if skipped:
        logger.warning(
            "fleet sim: fault plan carries unsupported kind(s) %s — only "
            "%s simulate; the skipped actions do NOT shape this "
            "prediction", skipped, list(_SUPPORTED_FAULT_KINDS),
        )
    for action in plan.actions:
        if action.kind not in _SUPPORTED_FAULT_KINDS:
            continue
        targets = (
            [action.rank] if action.rank is not None else list(range(ranks))
        )
        for r in targets:
            if r >= ranks or not action.matches_process(r, None, None):
                continue
            trace = plan.decision_trace(action, r, steps)
            row = delays.setdefault(r, [0.0] * steps)
            hit_draws = 0
            for s in range(steps):
                # Site hit counters are 1-based (step K = K-th hit);
                # the decision stream advances one draw per IN-WINDOW
                # hit, exactly as the injector consumes it.
                if action.in_window(s + 1):
                    if trace[hit_draws]:
                        row[s] += float(action.seconds) * 1e6
                    hit_draws += 1
    return delays


# ------------------------------------------------------------ the DES


def _group_plans(
    model: InterconnectModel, program: SimProgram, config: SimConfig
) -> List[Tuple[Plan, Optional[Plan]]]:
    """The (reduction plan, optional zero1 all-gather plan) per group —
    pinned algorithm when the compositor offers it at that payload, else
    cost-selected: the same fallback the lowering and the tuner's
    ``plan_for_bucket`` perform."""
    import math

    out: List[Tuple[Plan, Optional[Plan]]] = []
    collective = "reducescatter" if config.zero1 else "allreduce"
    for g in program.groups:
        wire = config.wire_dtype
        cands = candidate_plans(
            model, collective, g.nbytes, op=config.op, wire_dtype=wire
        )
        if config.algorithm != "auto" and config.algorithm in cands:
            plan = cands[config.algorithm]
        else:
            plan = select_plan(
                model, collective, g.nbytes, op=config.op, wire_dtype=wire
            )
        ag = None
        if config.zero1:
            shard = math.ceil(g.nbytes / max(model.size, 1))
            ag = select_plan(model, "allgather", shard, op=config.op)
        out.append((plan, ag))
    return out


@dataclass
class _StageSpan:
    group: int
    primitive: str
    hop: str
    axis: str
    nbytes: int
    rounds: int
    wire_dtype: str
    t0: float
    t1: float


@dataclass
class SimResult:
    """One simulated run: the numbers (stable, rounded) plus enough
    span structure to render Perfetto lanes and feed the replay
    divergence report."""

    ranks: int
    steps: int
    model: InterconnectModel
    program: SimProgram
    config: SimConfig
    seed: int
    step_spans: Dict[int, List[Tuple[int, float, float]]]  # rank -> [(i, t0us, t1us)]
    compute_spans: Dict[int, List[Tuple[str, float, float]]]
    stage_spans: List[_StageSpan] = field(default_factory=list)
    fault_instants: Dict[int, List[Tuple[int, float, float]]] = field(
        default_factory=dict
    )  # rank -> [(step, t_us, delay_us)]
    plans: List[Tuple[Plan, Optional[Plan]]] = field(default_factory=list)
    # Lowest unfaulted rank — the lane every untracked rank mirrors.
    base_rank: int = 0

    # ------------------------------------------------------- aggregates
    @property
    def step_times_us(self) -> List[float]:
        spans = self.step_spans[self.base_rank]
        return [t1 - t0 for _, t0, t1 in spans]

    @property
    def mean_step_us(self) -> float:
        ts = self.step_times_us
        return sum(ts) / len(ts) if ts else 0.0

    @property
    def ideal_step_us(self) -> float:
        return self.program.compute_us

    @property
    def scaling_efficiency(self) -> float:
        """Fraction of the step spent on work that would remain at one
        rank: ``ideal / simulated`` — 1.0 means every wire byte hid
        behind compute."""
        step = self.mean_step_us
        return (self.ideal_step_us / step) if step > 0 else 1.0

    @property
    def exposed_comm_us(self) -> float:
        return max(self.mean_step_us - self.ideal_step_us, 0.0)

    def per_hop_busy_us(self) -> Dict[str, float]:
        """Mean per-step busy time of each hop — the wire-side truth the
        replay divergence compares against measurements."""
        busy: Dict[str, float] = {}
        for s in self.stage_spans:
            if s.hop == "-":
                continue
            busy[s.hop] = busy.get(s.hop, 0.0) + (s.t1 - s.t0)
        return {
            h: v / max(self.steps, 1) for h, v in sorted(busy.items())
        }

    def to_report(self) -> dict:
        """The stable (byte-identical for a fixed seed) summary block
        for one rank count."""
        first_plans = [
            {
                "group": gi,
                "collective": p.collective,
                "algorithm": p.algorithm,
                "wire_dtype": p.wire_dtype,
                "nbytes": int(p.nbytes),
                "cost_us": round(p.cost_us, 4),
                "bytes_per_hop": {
                    k: int(v) for k, v in sorted(p.bytes_per_hop.items())
                },
                **({
                    "ag_algorithm": ag.algorithm,
                    "ag_cost_us": round(ag.cost_us, 4),
                } if ag is not None else {}),
            }
            for gi, (p, ag) in enumerate(self.plans)
        ]
        return {
            "ranks": int(self.ranks),
            "hops": [[h.name, int(h.size)] for h in self.model.hops],
            "steps": int(self.steps),
            "seed": int(self.seed),
            "step_time_us": round(self.mean_step_us, 4),
            "ideal_step_us": round(self.ideal_step_us, 4),
            "exposed_comm_us": round(self.exposed_comm_us, 4),
            "scaling_efficiency": round(self.scaling_efficiency, 6),
            "per_hop_busy_us": {
                k: round(v, 4) for k, v in self.per_hop_busy_us().items()
            },
            "per_group": first_plans,
        }

    # ------------------------------------------------------ trace lanes
    def windows(self, max_ranks: int = 64) -> Dict[int, dict]:
        """Per-rank windows in the ``TraceTap.window()`` shape, so
        ``trace/merge.py`` renders a simulated fleet exactly like a real
        one. Lanes beyond ``max_ranks`` are dropped with a note (a
        4096-lane Perfetto file helps nobody); stage-level comm spans
        ride rank 0's lane (the schedule is global)."""
        n = min(self.ranks, max(int(max_ranks), 1))
        if n < self.ranks:
            logger.info(
                "fleet sim: rendering %d of %d simulated lanes "
                "(raise --trace-ranks to widen)", n, self.ranks,
            )
        out: Dict[int, dict] = {}
        base = self.compute_spans[self.base_rank]
        base_steps = self.step_spans[self.base_rank]
        for r in range(n):
            events: List[dict] = []
            for name, t0, t1 in self.compute_spans.get(r, base):
                events.append({
                    "name": name, "ph": "X", "ts": t0 / 1e6,
                    "dur": (t1 - t0) / 1e6, "cat": "phase", "tid": 0,
                })
            for step, t, d_us in self.fault_instants.get(r, []):
                events.append({
                    "name": "fault:delay", "ph": "i", "ts": t / 1e6,
                    "cat": "fault", "tid": 0,
                    "args": {"step": step, "delay_us": round(d_us, 4)},
                })
            if r == 0:
                for s in self.stage_spans:
                    if s.hop == "-":
                        continue
                    events.append({
                        "name": f"hvd_collective_stage:{s.primitive}",
                        "ph": "X", "ts": s.t0 / 1e6,
                        "dur": (s.t1 - s.t0) / 1e6, "cat": "op", "tid": 1,
                        "args": {
                            "group": s.group, "hop": s.hop,
                            "axis": s.axis, "nbytes": int(s.nbytes),
                            "rounds": int(s.rounds),
                            "wire_dtype": s.wire_dtype,
                        },
                    })
            steps = [
                [i, t0 / 1e6, t1 / 1e6]
                for i, t0, t1 in self.step_spans.get(r, base_steps)
            ]
            out[r] = {
                "schema": 1,
                "rank": r,
                "clock": {
                    "offset_s": 0.0, "rtt_s": 0.0, "estimated": False,
                    "simulated": True,
                },
                "plan": {
                    "topo_algorithm": self.config.algorithm,
                    "wire_dtype": self.config.wire_dtype,
                    "zero1": self.config.zero1,
                    "simulated": True,
                },
                "events": events,
                "steps": steps,
            }
        return out

    def driver_window(self) -> dict:
        """The simulated driver lane: plan instants mirroring what
        ``record_plan`` notes on a live fleet."""
        events = [{
            "name": "hvd_sim_run", "ph": "i", "ts": 0.0, "cat": "driver",
            "args": {
                "ranks": self.ranks, "steps": self.steps,
                "seed": self.seed, **self.config.to_dict(),
            },
        }]
        for gi, (p, ag) in enumerate(self.plans):
            events.append({
                "name": "hvd_sim_plan", "ph": "i", "ts": 0.0,
                "cat": "driver",
                "args": {
                    "group": gi, "collective": p.collective,
                    "algorithm": p.algorithm, "wire_dtype": p.wire_dtype,
                    "nbytes": int(p.nbytes),
                    **({"ag_algorithm": ag.algorithm} if ag else {}),
                },
            })
        return {
            "schema": 1, "rank": -1,
            "clock": {"offset_s": 0.0, "rtt_s": 0.0, "estimated": False},
            "plan": {}, "events": events, "steps": [],
        }


def simulate(
    model: InterconnectModel,
    program: SimProgram,
    config: Optional[SimConfig] = None,
    *,
    steps: int = 4,
    fault_plan: Optional[FaultPlan] = None,
    seed: int = 0,
) -> SimResult:
    """Run the discrete-event simulation. ``seed`` only labels the run
    when no fault plan is given (the fault plan carries its own seed);
    everything else is a pure function of the inputs."""
    config = config or SimConfig()
    steps = max(int(steps), 1)
    n = model.size
    plans = _group_plans(model, program, config)
    delays = _delay_matrix(fault_plan, n, steps)
    faulted = sorted(delays)
    # The representative unfaulted lane (SPMD compute is homogeneous, so
    # one lane stands in for every rank the fault plan never touches).
    base_rank = next(
        (r for r in range(n) if r not in delays), 0
    )

    hop_free: Dict[str, float] = {h.name: 0.0 for h in model.hops}
    by_hop = {h.name: h for h in model.hops}

    step_spans: Dict[int, List[Tuple[int, float, float]]] = {}
    compute_spans: Dict[int, List[Tuple[str, float, float]]] = {}
    fault_instants: Dict[int, List[Tuple[int, float, float]]] = {}
    for r in sorted({base_rank, *faulted}):
        step_spans.setdefault(r, [])
        compute_spans.setdefault(r, [])
    stage_spans: List[_StageSpan] = []

    def stage_cost(stage) -> float:
        if stage.hop == "-":
            return 0.0
        hop = by_hop[stage.hop]
        return (
            hop.latency_us * stage.rounds
            + stage.bytes_on_wire / (hop.bandwidth_gbps * 1e3)
        )

    # Per-tracked-rank current clock: the base lane plus every faulted
    # rank (all other ranks mirror the base lane exactly).
    tracked = sorted({base_rank, *faulted})
    clock = {r: 0.0 for r in tracked}

    for s in range(steps):
        t_begin = {r: clock[r] for r in tracked}
        # Forward (+ the composed program's fixed TP-psum term: ICI
        # time every rank spends synchronously, outside the staircase).
        for r in tracked:
            t0 = clock[r]
            clock[r] = t0 + program.forward_us
            compute_spans[r].append((f"sim_forward:{s}", t0, clock[r]))
        if program.fixed_comm_us > 0.0:
            for r in tracked:
                t0 = clock[r]
                clock[r] = t0 + program.fixed_comm_us
                compute_spans[r].append((f"sim_tp_comm:{s}", t0, clock[r]))
        # Backward segments; a step's injected delay stretches the
        # FIRST segment (the straggler model: the rank falls behind as
        # the backward starts).
        ready: Dict[int, Dict[int, float]] = {}  # group -> rank -> t
        for gi, g in enumerate(program.groups):
            ready[gi] = {}
            for r in tracked:
                extra = 0.0
                if gi == 0 and delays.get(r):
                    extra = delays[r][s]
                    if extra > 0.0:
                        fault_instants.setdefault(r, []).append(
                            (s, clock[r], extra)
                        )
                t0 = clock[r]
                clock[r] = t0 + g.compute_us + extra
                compute_spans[r].append(
                    (f"sim_backward:{s}:g{gi}", t0, clock[r])
                )
                ready[gi][r] = clock[r]
        backward_end = {r: clock[r] for r in tracked}
        # Post-hoc mode: nothing reduces until the whole backward ends.
        if not config.overlap:
            for gi in ready:
                ready[gi] = dict(backward_end)
        # Collectives in reduction order: start at the fleet-wide ready
        # point, stages claim their hops serially.
        comm_done = 0.0
        for gi, (plan, ag) in enumerate(plans):
            start = max(ready[gi].values())
            t = start
            for st in plan.stages:
                if st.hop == "-":
                    continue
                t0 = max(t, hop_free[st.hop])
                t1 = t0 + stage_cost(st)
                hop_free[st.hop] = t1
                stage_spans.append(_StageSpan(
                    group=gi, primitive=st.primitive, hop=st.hop,
                    axis=st.axis, nbytes=st.bytes_on_wire,
                    rounds=st.rounds, wire_dtype=st.wire_dtype,
                    t0=t0, t1=t1,
                ))
                t = t1
            comm_done = max(comm_done, t)
            # ZeRO-1: the parameter all-gather of this group's shard,
            # conservatively exposed after the RS (the tuner's pricing).
            if ag is not None:
                for st in ag.stages:
                    if st.hop == "-":
                        continue
                    t0 = max(t, hop_free[st.hop])
                    t1 = t0 + stage_cost(st)
                    hop_free[st.hop] = t1
                    stage_spans.append(_StageSpan(
                        group=gi, primitive=st.primitive + ":ag",
                        hop=st.hop, axis=st.axis,
                        nbytes=st.bytes_on_wire, rounds=st.rounds,
                        wire_dtype=st.wire_dtype, t0=t0, t1=t1,
                    ))
                    t = t1
                comm_done = max(comm_done, t)
        # Optimizer after the last reduction; the final collective
        # synchronizes, so every rank ends the step together.
        end = max(
            [comm_done] + [backward_end[r] for r in tracked]
        ) + program.optimizer_us
        for r in tracked:
            opt0 = max(comm_done, backward_end[r])
            compute_spans[r].append((f"sim_optimizer:{s}", opt0, end))
            step_spans[r].append((s, t_begin[r], end))
            clock[r] = end

    return SimResult(
        ranks=n, steps=steps, model=model, program=program,
        config=config, seed=int(seed), step_spans=step_spans,
        compute_spans=compute_spans, stage_spans=stage_spans,
        fault_instants=fault_instants, plans=plans,
        base_rank=base_rank,
    )


def straggler_sensitivity(
    model: InterconnectModel,
    program: SimProgram,
    config: Optional[SimConfig] = None,
    *,
    probe_delay_us: float = 1000.0,
    steps: int = 2,
) -> float:
    """How much of a one-rank delay the fleet eats: ``d(step time) /
    d(delay)`` for a probe delay on rank 0. 1.0 = fully synchronous
    (every delayed microsecond is paid by everyone); below 1.0 the
    stream pipeline hid part of the straggler behind wire time the
    fleet was paying anyway."""
    base = simulate(model, program, config, steps=steps)
    probe = FaultPlan.from_json(json.dumps({
        "seed": 0,
        "faults": [{
            "kind": "delay", "rank": 0, "site": "step",
            "seconds": probe_delay_us / 1e6, "after": 0,
        }],
    }))
    delayed = simulate(model, program, config, steps=steps,
                       fault_plan=probe)
    d = (delayed.mean_step_us - base.mean_step_us) / probe_delay_us
    return round(max(d, 0.0), 6)


# ------------------------------------------------------- serving twin
#
# The serving half of the fleet simulator (docs/serving.md "Capacity
# planning"): an open-loop Poisson arrival stream played through the
# EXACT shipping batching policy (serve/batcher.ContinuousBatcher under
# a virtual clock) against an affine batch-service-time model, with the
# request/replica chaos sites of the same seeded fault plans the live
# engine honors. Like the training twin: simulated microseconds from 0,
# no wall clock, every report float rounded — a fixed seed is
# byte-reproducible, which is what lets "what does p99 do at 2x qps?"
# be answered deterministically on a laptop.


@dataclass(frozen=True)
class ServeSimConfig:
    """Knobs of one serving simulation. ``qps`` drives the open-loop
    Poisson arrival process (inter-arrival ~ Exp(qps), independent of
    completions — the arrival stream does not slow down when the fleet
    falls behind, which is exactly what makes overload visible).
    Service time of a dispatched batch is affine:
    ``service_base_us + service_per_request_us * live_slots`` — the
    fixed cost of one compiled decode dispatch plus the marginal cost
    of each occupied slot."""

    qps: float = 50.0
    duration_s: float = 10.0
    replicas: int = 2
    max_batch_size: int = 8
    max_wait_us: int = 2000
    queue_bound: int = 1024
    slo_ms: float = 100.0
    service_base_us: float = 2000.0
    service_per_request_us: float = 500.0
    seed: int = 0

    def to_dict(self) -> dict:
        return {
            "qps": round(float(self.qps), 4),
            "duration_s": round(float(self.duration_s), 4),
            "replicas": int(self.replicas),
            "max_batch_size": int(self.max_batch_size),
            "max_wait_us": int(self.max_wait_us),
            "queue_bound": int(self.queue_bound),
            "slo_ms": round(float(self.slo_ms), 4),
            "service_base_us": round(float(self.service_base_us), 4),
            "service_per_request_us": round(
                float(self.service_per_request_us), 4
            ),
            "seed": int(self.seed),
        }


_SERVE_FAULT_KINDS = ("drop", "delay", "kill_replica")


def simulate_serve(
    config: ServeSimConfig,
    fault_plan: Optional[FaultPlan] = None,
) -> dict:
    """Simulate one serving run; returns the (rounded, sort-keyed
    deterministic) report dict.

    Mechanics mirror the live engine one-for-one: arrivals enter the
    real :class:`~horovod_tpu.serve.batcher.ContinuousBatcher`; the
    earliest-free replica dispatches whenever the policy says a batch
    is ready (max-batch or head-deadline); ``request``-site faults
    resolve at admission in arrival order (``drop`` → answered as
    dropped, ``delay`` → the enqueue slides but the latency clock keeps
    counting from arrival); a ``replica``-site ``kill_replica`` on the
    K-th batch dispatch kills that replica and re-queues its batch at
    the FRONT with original timestamps (the exactly-once re-queue). A
    full queue refuses (outcome ``rejected``), never silently drops.
    """
    import random as _random

    from ..serve.batcher import ContinuousBatcher

    if fault_plan is not None:
        skipped = sorted({
            a.kind for a in fault_plan.actions
            if a.site not in ("request", "replica")
        })
        if skipped:
            logger.warning(
                "serve sim: fault plan carries non-serving action "
                "kind(s) %s — only request/replica-site faults shape "
                "this prediction", skipped,
            )

    # ---- open-loop Poisson arrivals (its own seeded stream).
    rng = _random.Random(config.seed)
    horizon_us = float(config.duration_s) * 1e6
    arrivals: List[Tuple[str, float]] = []
    t = 0.0
    i = 0
    while True:
        t += rng.expovariate(max(float(config.qps), 1e-9)) * 1e6
        if t >= horizon_us:
            break
        arrivals.append((f"req{i}", t))
        i += 1

    # ---- request-site faults resolve at admission, in arrival order
    # (the site's 1-based hit counter IS the arrival index).
    request_actions = [] if fault_plan is None else [
        a for a in fault_plan.actions if a.site == "request"
    ]
    replica_actions = [] if fault_plan is None else [
        a for a in fault_plan.actions if a.site == "replica"
    ]
    arrive_t: Dict[str, float] = {}
    enqueue_t: Dict[str, float] = {}
    outcomes: Dict[str, str] = {}
    admitted: List[Tuple[str, float]] = []
    for hit, (rid, t_arr) in enumerate(arrivals, start=1):
        arrive_t[rid] = t_arr
        t_enq = t_arr
        dropped = False
        for a in request_actions:
            if a.in_window(hit) and fault_plan.decide(a, None):
                if a.kind == "drop":
                    dropped = True
                else:  # delay: queueing latency before batching
                    t_enq += float(a.seconds) * 1e6
        if dropped:
            outcomes[rid] = "dropped"
        else:
            admitted.append((rid, t_enq))
            enqueue_t[rid] = t_enq
    # Delays can reorder the enqueue stream; admission is by ENQUEUE time.
    admitted.sort(key=lambda p: (p[1], p[0]))

    # ---- discrete-event loop: earliest-free live replica dispatches.
    batcher = ContinuousBatcher(
        max_batch_size=config.max_batch_size,
        max_wait_us=config.max_wait_us,
        queue_bound=config.queue_bound,
    )
    replica_free = [0.0] * max(int(config.replicas), 1)
    killed: set = set()
    finish_t: Dict[str, float] = {}
    batches = 0
    occupancy = 0
    requeued = 0
    dispatch_hits = 0
    idx = 0
    inf = float("inf")
    while True:
        live = [r for r in range(len(replica_free)) if r not in killed]
        if not live:
            break
        r = min(live, key=lambda j: (replica_free[j], j))
        t_r = replica_free[r]
        while idx < len(admitted) and admitted[idx][1] <= t_r:
            rid, t_enq = admitted[idx]
            idx += 1
            if not batcher.offer(rid, int(t_enq)):
                outcomes[rid] = "rejected"
        decision = batcher.poll(int(t_r))
        if not decision.ready:
            cand = []
            dl = batcher.next_deadline_us()
            if dl is not None:
                cand.append(float(dl))
            if idx < len(admitted):
                cand.append(admitted[idx][1])
            if not cand:
                break  # drained: no queue, no future arrivals
            replica_free[r] = max(t_r, min(cand))
            continue
        dispatch_hits += 1
        kill = any(
            a.in_window(dispatch_hits) and fault_plan.decide(a, None)
            for a in replica_actions
        )
        if kill:
            for rid in reversed(decision.request_ids):
                batcher.requeue(rid, int(enqueue_t[rid]))
            requeued += len(decision.request_ids)
            killed.add(r)
            continue
        n_live = len(decision.request_ids)
        service = (float(config.service_base_us)
                   + float(config.service_per_request_us) * n_live)
        done = t_r + service
        replica_free[r] = done
        batches += 1
        occupancy += n_live
        for rid in decision.request_ids:
            finish_t[rid] = done
            outcomes[rid] = "ok"

    # ---- report (rounded, canonical).
    lat_ms = sorted(
        (finish_t[rid] - arrive_t[rid]) / 1e3 for rid in finish_t
    )

    def pct(p: float) -> float:
        if not lat_ms:
            return 0.0
        return lat_ms[min(int(p * (len(lat_ms) - 1)), len(lat_ms) - 1)]

    served = sum(1 for o in outcomes.values() if o == "ok")
    slo_viol = sum(1 for v in lat_ms if v > float(config.slo_ms))
    unanswered = len(arrivals) - len(outcomes)
    return {
        "schema": SIM_SCHEMA,
        "config": config.to_dict(),
        "arrivals": len(arrivals),
        "served": served,
        "dropped": sum(1 for o in outcomes.values() if o == "dropped"),
        "rejected": sum(1 for o in outcomes.values() if o == "rejected"),
        "requeued": int(requeued),
        "replicas_killed": len(killed),
        "unanswered": int(unanswered),
        "batches": int(batches),
        "mean_batch_occupancy": round(occupancy / batches, 4) if batches
        else 0.0,
        "latency_ms": {
            "p50": round(pct(0.50), 4),
            "p90": round(pct(0.90), 4),
            "p99": round(pct(0.99), 4),
            "mean": round(sum(lat_ms) / len(lat_ms), 4) if lat_ms else 0.0,
            "max": round(lat_ms[-1], 4) if lat_ms else 0.0,
        },
        "slo_violation_frac": round(slo_viol / served, 4) if served
        else 0.0,
        "throughput_rps": round(
            served / float(config.duration_s), 4
        ) if config.duration_s else 0.0,
    }
