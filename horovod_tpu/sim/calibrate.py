"""Calibration: fit per-hop alpha-beta constants from measured traces.

The FlexLink lesson (PAPERS.md, arXiv:2510.15882): *measure links, don't
assume them*. The interconnect model ships coarse per-generation
defaults that only need to RANK hops for plan selection — but the fleet
simulator and the tuner's pricing are only evidence when the constants
come from observation. This module closes that loop:

- :func:`fit_calibration` consumes the machine-readable per-rank stats
  summary ``tools/trace_merge.py --stats`` emits from PR-10 merged
  trace data and least-squares fits, per hop, ``duration_us =
  latency_us * rounds + bytes / (bandwidth_gbps * 1e3)`` over the
  per-collective (bytes, rounds, duration) samples the trace carries
  (``hvd_collective_stage`` spans name their hop exactly; eager
  ``hvd_response`` / native ``hvd_plan`` spans carry bytes and are
  attributed to the model's bottleneck hop, recorded as such).
- The result persists as a signature-keyed ``calibration.json`` —
  the signature is the interconnect model's (hop name, size) ladder,
  and :func:`apply_calibration` follows the ``tuned.json`` staleness
  discipline: a calibration fitted for a different ladder warns loudly
  ("FALLING BACK") and leaves the generation defaults in place, never
  silently applies stale constants.
- :func:`divergence_report` compares a simulated run against measured
  per-hop time and publishes ``hvd_sim_divergence_ratio{hop}`` so a
  drifting model is an alert, not a quiet lie (docs/simulation.md).
"""

from __future__ import annotations

import hashlib
import json
import logging
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ..topo.model import Hop, InterconnectModel

logger = logging.getLogger("horovod_tpu.sim")

CALIBRATION_VERSION = 1

# Least-squares guards: a fitted bandwidth must stay positive and a
# fitted latency non-negative; degenerate sample sets fall back to the
# ratio estimator (total bytes / total seconds).
_MIN_BANDWIDTH_GBPS = 1e-6


def model_signature(model: InterconnectModel) -> Dict:
    """The staleness key a calibration is valid for: the ordered hop
    NAME ladder plus generation — the identity of the links, NOT their
    sizes (alpha-beta constants are per-link properties, so an ICI
    measurement at 8 ranks prices the ICI hop at 4096) and NOT the cost
    constants (those are what calibration replaces)."""
    sig = {
        "version": CALIBRATION_VERSION,
        "hops": [h.name for h in model.hops],
        "generation": model.generation,
    }
    sig["hash"] = signature_hash(sig)
    return sig


def signature_hash(sig: Dict) -> str:
    body = {k: v for k, v in sig.items() if k != "hash"}
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


@dataclass
class Calibration:
    """Fitted per-hop constants plus the evidence they came from."""

    signature: Dict
    hops: Dict[str, Dict]  # name -> {latency_us, bandwidth_gbps, ...}
    source: str = "fit"
    meta: Dict = field(default_factory=dict)
    version: int = CALIBRATION_VERSION

    def to_dict(self) -> Dict:
        return {
            "version": self.version,
            "signature": dict(self.signature),
            "hops": {k: dict(v) for k, v in sorted(self.hops.items())},
            "source": self.source,
            "meta": dict(self.meta),
        }

    def to_json(self) -> str:
        """Stable serialization (sorted keys, no timestamps) — two fits
        from the same stats diff byte-for-byte."""
        return json.dumps(self.to_dict(), sort_keys=True, indent=1) + "\n"

    @staticmethod
    def from_dict(d: Dict) -> "Calibration":
        return Calibration(
            signature=dict(d.get("signature", {})),
            hops={str(k): dict(v) for k, v in d.get("hops", {}).items()},
            source=str(d.get("source", "fit")),
            meta=dict(d.get("meta", {})),
            version=int(d.get("version", CALIBRATION_VERSION)),
        )

    @property
    def signature_hash(self) -> str:
        h = self.signature.get("hash")
        return str(h) if h else signature_hash(self.signature)


def save_calibration(calib: Calibration, path: str) -> str:
    with open(path, "w") as f:
        f.write(calib.to_json())
    return path


def load_calibration(path: str) -> Calibration:
    with open(path) as f:
        return Calibration.from_dict(json.load(f))


# ----------------------------------------------------------------- fit


def _collect_samples(
    stats: Dict, model: InterconnectModel
) -> Tuple[Dict[str, List[Tuple[float, float, float]]], Dict[str, int]]:
    """Per-hop (bytes, rounds, duration_us) samples from a stats doc.

    ``hvd_collective_stage`` spans (simulated or future native traces)
    name their hop and rounds exactly. ``hvd_response`` / ``hvd_plan``
    spans carry only total bytes; they are attributed to the model's
    bottleneck hop with flat-ring rounds — the attribution counts are
    returned so the calibration records how much of its evidence was
    attributed rather than measured per-hop."""
    hop_names = {h.name for h in model.hops}
    bottleneck = min(model.hops, key=lambda h: h.bandwidth_gbps)
    n = model.size
    ring_rounds = max(2 * (n - 1), 1)
    samples: Dict[str, List[Tuple[float, float, float]]] = {}
    attributed: Dict[str, int] = {}
    for r in sorted(stats.get("ranks", {})):
        for c in stats["ranks"][r].get("collectives", []):
            dur_us = float(c.get("dur_s", 0.0)) * 1e6
            if dur_us <= 0.0:
                continue
            nbytes = float(c.get("nbytes", 0) or 0)
            hop = c.get("hop")
            if hop in hop_names:
                rounds = float(c.get("rounds", 1) or 1)
                samples.setdefault(hop, []).append(
                    (nbytes, rounds, dur_us)
                )
            elif nbytes > 0:
                samples.setdefault(bottleneck.name, []).append(
                    (nbytes * 2 * (n - 1) / max(n, 1), ring_rounds,
                     dur_us)
                )
                attributed[bottleneck.name] = (
                    attributed.get(bottleneck.name, 0) + 1
                )
    return samples, attributed


def _fit_hop(
    samples: List[Tuple[float, float, float]]
) -> Optional[Tuple[float, float]]:
    """Least-squares ``dur = alpha * rounds + beta * bytes`` →
    (latency_us, bandwidth_gbps). Pure python 2x2 normal equations;
    degenerate systems fall back to the ratio estimator (alpha = 0)."""
    if not samples:
        return None
    srr = srb = sbb = srd = sbd = 0.0
    for b, r, d in samples:
        srr += r * r
        srb += r * b
        sbb += b * b
        srd += r * d
        sbd += b * d
    det = srr * sbb - srb * srb
    alpha = beta = None
    if det > 1e-12 * max(srr * sbb, 1.0):
        alpha = (srd * sbb - sbd * srb) / det
        beta = (srr * sbd - srb * srd) / det
    if (
        alpha is None or beta is None
        or beta <= 0.0 or alpha < 0.0
    ):
        # Ratio fallback: all time charged to bandwidth.
        total_b = sum(b for b, _, _ in samples)
        total_d = sum(d for _, _, d in samples)
        if total_b <= 0.0 or total_d <= 0.0:
            return None
        alpha, beta = 0.0, total_d / total_b
    bw = 1.0 / (beta * 1e3)  # us/byte -> GB/s
    return max(alpha, 0.0), max(bw, _MIN_BANDWIDTH_GBPS)


def fit_calibration(
    stats: Dict, model: InterconnectModel, source: str = "fit"
) -> Calibration:
    """Fit per-hop constants for ``model``'s ladder from a
    ``trace_merge --stats`` document. Hops the trace never exercised
    keep their generation defaults and are marked ``calibrated:
    false`` — a calibration never pretends to know a link it never
    saw."""
    samples, attributed = _collect_samples(stats, model)
    hops: Dict[str, Dict] = {}
    for h in model.hops:
        fit = _fit_hop(samples.get(h.name, []))
        if fit is None:
            hops[h.name] = {
                "calibrated": False,
                "latency_us": round(h.latency_us, 6),
                "bandwidth_gbps": round(h.bandwidth_gbps, 6),
                "samples": 0,
                "note": "no samples on this hop; generation default",
            }
            continue
        alpha, bw = fit
        residual = 0.0
        pts = samples[h.name]
        for b, r, d in pts:
            pred = alpha * r + b / (bw * 1e3)
            residual += abs(pred - d)
        hops[h.name] = {
            "calibrated": True,
            "latency_us": round(alpha, 6),
            "bandwidth_gbps": round(bw, 6),
            "samples": len(pts),
            "attributed_samples": int(attributed.get(h.name, 0)),
            "mean_abs_residual_us": round(residual / len(pts), 4),
        }
    return Calibration(
        signature=model_signature(model),
        hops=hops,
        source=source,
        meta={
            "schema_version": int(stats.get("schema_version", 0)),
            "world_size": int(stats.get("world_size", 0)),
            # Provenance only — NOT part of the staleness key (per-link
            # constants transfer across rank counts of the same fabric).
            "fitted_hop_sizes": [
                [h.name, int(h.size)] for h in model.hops
            ],
        },
    )


# --------------------------------------------------------------- apply


def apply_calibration(
    model: InterconnectModel,
    calib: Optional[Calibration],
    where: str = "sim",
    strict: bool = False,
) -> InterconnectModel:
    """Patch ``model``'s cost entries with calibrated constants when the
    signature matches; on a mismatch warn loudly and return the model
    UNCHANGED (``strict=True`` raises instead) — the ``tuned.json``
    staleness discipline: stale constants are never applied silently."""
    if calib is None:
        return model
    live = model_signature(model)
    if calib.signature_hash != live["hash"]:
        msg = (
            f"calibration (signature {calib.signature_hash}, hops "
            f"{calib.signature.get('hops')}) does NOT match this "
            f"model's ladder {live['hops']} (signature {live['hash']}) "
            f"at {where} — FALLING BACK to generation-default "
            "constants. Re-fit with tools/fleet_sim.py --calibrate "
            "against a trace from this topology."
        )
        if strict:
            raise ValueError(msg)
        logger.warning(msg)
        return model
    patched = []
    for h in model.hops:
        entry = calib.hops.get(h.name)
        if not entry or not entry.get("calibrated"):
            patched.append(h)
            continue
        patched.append(Hop(
            name=h.name, axis=h.axis, size=h.size,
            bandwidth_gbps=float(entry["bandwidth_gbps"]),
            latency_us=float(entry["latency_us"]),
        ))
    return InterconnectModel(
        hops=tuple(patched), generation=model.generation,
        eligible=model.eligible, source=model.source + "+calibrated",
    )


def resolve_calibration(calibration: Any) -> Optional[Calibration]:
    """Resolve a ``calibration`` argument: a :class:`Calibration` or
    dict passes through, a path string loads the file, ``None``
    consults ``HOROVOD_CALIBRATION_FILE`` (unreadable env files warn
    instead of raising — the ``resolve_tuned`` contract)."""
    import os

    if isinstance(calibration, Calibration):
        return calibration
    if isinstance(calibration, dict):
        return Calibration.from_dict(calibration)
    if isinstance(calibration, (str, os.PathLike)):
        return load_calibration(os.fspath(calibration))
    if calibration is not None and calibration is not False:
        raise TypeError(
            "calibration= takes a Calibration, a calibration.json "
            f"path, a dict, or None; got {type(calibration).__name__}"
        )
    if calibration is False:
        return None
    from ..common import env as _env

    path = os.environ.get(_env.HOROVOD_CALIBRATION_FILE, "").strip()
    if not path:
        return None
    try:
        return load_calibration(path)
    except Exception as e:  # noqa: BLE001 - env knob must not brick startup
        logger.warning(
            "HOROVOD_CALIBRATION_FILE=%s could not be loaded (%r); "
            "running on generation defaults", path, e,
        )
        return None


# ---------------------------------------------------------- divergence


def divergence_report(
    modeled_per_hop_us: Dict[str, float],
    measured_per_hop_us: Dict[str, float],
    *,
    modeled_step_us: float = 0.0,
    measured_step_us: float = 0.0,
    attribution: str = "per-hop",
) -> Dict:
    """Per-hop model-vs-measured divergence: ratio > 1 means the model
    is pessimistic (predicts more time than observed), < 1 optimistic.
    Published as ``hvd_sim_divergence_ratio{hop}`` (plus the ``step``
    scope) when metrics are armed; hops with no measured time report an
    honest ``null`` instead of a fake 1.0."""
    from .. import metrics as _metrics

    per_hop: Dict[str, Any] = {}
    for hop in sorted(set(modeled_per_hop_us) | set(measured_per_hop_us)):
        modeled = float(modeled_per_hop_us.get(hop, 0.0))
        measured = float(measured_per_hop_us.get(hop, 0.0))
        ratio = (modeled / measured) if measured > 0.0 else None
        per_hop[hop] = {
            "modeled_us": round(modeled, 4),
            "measured_us": round(measured, 4),
            "ratio": None if ratio is None else round(ratio, 6),
        }
        if _metrics.ACTIVE and ratio is not None:
            _metrics.TAP.set(
                "hvd_sim_divergence_ratio", float(ratio), hop=hop
            )
    step_ratio = (
        modeled_step_us / measured_step_us
        if measured_step_us > 0.0 else None
    )
    if _metrics.ACTIVE and step_ratio is not None:
        _metrics.TAP.set(
            "hvd_sim_divergence_ratio", float(step_ratio), hop="step"
        )
    return {
        "attribution": attribution,
        "per_hop": per_hop,
        "step": {
            "modeled_us": round(float(modeled_step_us), 4),
            "measured_us": round(float(measured_step_us), 4),
            "ratio": (
                None if step_ratio is None else round(step_ratio, 6)
            ),
        },
    }


def measured_from_stats(
    stats: Dict, model: InterconnectModel
) -> Dict:
    """Extract the measured quantities a replay compares against:
    per-rank step spans (compute), inter-step gaps (exposed time), and
    per-hop communication time. Per-hop attribution is exact where the
    trace carries hop-labeled stage spans; bytes-only collective spans
    attribute to the model's bottleneck hop (recorded in
    ``attribution``)."""
    ranks = stats.get("ranks", {})
    hop_names = {h.name for h in model.hops}
    bottleneck = min(model.hops, key=lambda h: h.bandwidth_gbps)

    def _median(xs: List[float]) -> float:
        if not xs:
            return 0.0
        xs = sorted(xs)
        return xs[len(xs) // 2]

    compute_us: List[float] = []
    gap_us: List[float] = []
    cycle_us: List[float] = []
    per_hop_exact: Dict[str, float] = {}
    per_hop_attr: Dict[str, float] = {}
    total_bytes = 0.0
    n_steps = 0
    for r in sorted(ranks):
        doc = ranks[r]
        steps = doc.get("steps") or []
        n_steps = max(n_steps, len(steps))
        durs = [(t1 - t0) * 1e6 for _, t0, t1 in steps]
        gaps = [
            (steps[i + 1][1] - steps[i][2]) * 1e6
            for i in range(len(steps) - 1)
        ]
        cycles = [
            (steps[i + 1][2] - steps[i][2]) * 1e6
            for i in range(len(steps) - 1)
        ]
        if durs:
            compute_us.append(_median(durs))
        if gaps:
            gap_us.append(_median(gaps))
        if cycles:
            cycle_us.append(_median(cycles))
        for c in doc.get("collectives", []):
            dur = float(c.get("dur_s", 0.0)) * 1e6
            if dur <= 0.0:
                continue
            hop = c.get("hop")
            if hop in hop_names:
                per_hop_exact[hop] = per_hop_exact.get(hop, 0.0) + dur
            else:
                per_hop_attr[bottleneck.name] = (
                    per_hop_attr.get(bottleneck.name, 0.0) + dur
                )
                # Bytes-only spans carry PAYLOAD bytes (per rank);
                # hop-labeled stage spans carry wire bytes, which are
                # not a payload measure and stay out of this sum.
                total_bytes += float(c.get("nbytes", 0) or 0)
    steps_div = max(n_steps, 1)
    # Hop-labeled stage spans appear once (the schedule is global, rank
    # 0 carries it); bytes-only spans appear once per participating
    # rank — normalize those by the rank count.
    n_ranks = max(len(ranks), 1)
    per_hop_step: Dict[str, float] = {}
    for hop, v in per_hop_exact.items():
        per_hop_step[hop] = per_hop_step.get(hop, 0.0) + v / steps_div
    for hop, v in per_hop_attr.items():
        per_hop_step[hop] = (
            per_hop_step.get(hop, 0.0) + v / steps_div / n_ranks
        )
    return {
        "world_size": len(ranks),
        "steps": n_steps,
        "compute_us": _median(compute_us),
        "gap_us": _median(gap_us),
        "step_us": (
            _median(cycle_us) if cycle_us
            else _median(compute_us) + _median(gap_us)
        ),
        "per_hop_us": {
            k: round(v, 4) for k, v in sorted(per_hop_step.items())
        },
        "bytes_per_step": total_bytes / steps_div / n_ranks,
        "attribution": (
            "per-hop" if not per_hop_attr else
            f"bottleneck-attributed ({bottleneck.name})"
        ),
    }
