"""Calibrated fleet simulator — the 256–4096-rank digital twin.

Real-TPU evidence has been unreachable since round 5, yet the runtime
carries topology plans, a quantized wire, streamed ZeRO-1 and a tuner
whose wins are claimed *at scale*. This package makes those claims
observable from a CPU box by composing three models the repo already
trusts into one deterministic discrete-event simulation of a training
step (HiCCL-style analytic modeling, PAPERS.md arXiv:2408.05962,
promoted to a first-class evidence artifact):

- **Compute** — the structural-overlap staircase: backward compute is
  partitioned into the exact stream groups ``ops/fusion.
  plan_layer_groups`` would register (the same partition the tuner
  prices), each segment freeing its group's cotangents for the wire.
- **Communication** — every group's collective lowers through the real
  compositor (``topo/compositor.py``): the selected plan's per-stage
  alpha-beta costs are replayed hop by hop, hops modeled as serially
  shared resources, so two-level / split / int8 / ZeRO-1 RS+AG shapes
  price exactly as the planner prices them.
- **Faults** — stragglers come from seeded ``fault/plan.py`` schedules
  (``delay`` actions at the ``step`` site), drawn from the same
  per-(seed, action, rank) decision streams the chaos harness diffs,
  so a simulated incident is byte-reproducible.

Closing the loop both ways (the FlexLink lesson, arXiv:2510.15882 —
measure links, don't assume them):

- :mod:`sim.calibrate` fits per-hop alpha-beta constants from merged
  PR-10 trace data (``tools/trace_merge.py --stats``) into a
  signature-keyed ``calibration.json`` — same staleness-fallback
  discipline as ``tuned.json``: a calibration for a different hop
  ladder warns loudly and falls back to generation defaults.
- ``tools/fleet_sim.py --replay <trace-dir>`` re-simulates an observed
  run and reports per-hop model-vs-measured divergence as
  ``hvd_sim_divergence_ratio{hop}`` so a drifting model is loud, not
  silently wrong.

Simulated runs render as Perfetto traces through ``trace/merge.py``
(one lane per simulated rank, plan/fault instants preserved), so
predicted and observed timelines are inspected with the same tooling.

Everything here is deterministic and never touches an accelerator
backend (jax is imported only for the shared ``plan_layer_groups``
partition — one source of truth with the streamed path — and no device
is ever initialized): two runs from the same seed produce
byte-identical reports, the property ``make sim-smoke`` locks. See
docs/simulation.md.
"""

from __future__ import annotations

from .calibrate import (  # noqa: F401
    Calibration,
    apply_calibration,
    divergence_report,
    fit_calibration,
    load_calibration,
    measured_from_stats,
    model_signature,
    resolve_calibration,
    save_calibration,
)
from .core import (  # noqa: F401
    ServeSimConfig,
    SimConfig,
    SimGroup,
    SimProgram,
    SimResult,
    program_from_layers,
    program_from_spec,
    simulate,
    simulate_serve,
    straggler_sensitivity,
    tp_fixed_comm_us,
)
