"""Small MNIST CNN — parity with the reference's ``examples/keras_mnist.py``
model (conv32-conv64-pool-dense128-dense10), used by the end-to-end MNIST
example and tests."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn


class MnistCNN(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x
