"""Decoder-only Transformer LM (flax), TPU-first, with pluggable attention.

The long-context flagship: ``attn_fn`` can be the dense reference, ring
attention, or Ulysses (``horovod_tpu.parallel.ring_attention``), letting the
same module run single-chip or sequence-parallel inside a shard_map without
code changes. bfloat16 compute with fp32 logits; positions are passed in so
sequence-sharded shards can feed their global offsets.

Every submodule is EXPLICITLY named (``block_0/attention/query/kernel``,
``mlp/up/bias``, ``ln_f/scale``, ...) so the param tree is a stable,
meaningful namespace the sharding-rules engine can place by regex
(``parallel/rules.py``; the shipped DP x TP table is
``analysis.sharding_rules.EXAMPLE_GPT_RULES``). :func:`tp_apply` is the
tensor-parallel functional forward of the SAME tree: it consumes the
leaves as (possibly TP-local) shards through ``parallel/tp.py``'s
column-/row-parallel layers — one psum per Megatron half-block — with
attention on the local heads through the Pallas flash kernel.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.pallas_attention import flash_attention_bthd


class Attention(nn.Module):
    """Multi-head self-attention with separate q/k/v projections — the
    layout the TP rules shard: a contiguous feature slice of one
    projection is whole heads, so ``P(None, "model")`` on each kernel is
    exactly Megatron head sharding."""

    n_heads: int
    dtype: Any = jnp.bfloat16
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        B, T, C = x.shape
        H = self.n_heads
        D = C // H
        # Default attention is the fused Pallas flash kernel (interpret
        # mode off-TPU); callers plug ring/Ulysses attention in via
        # attn_fn for sequence parallelism.
        attn = self.attn_fn or partial(flash_attention_bthd, causal=True)
        q = nn.Dense(C, use_bias=False, dtype=self.dtype, name="query")(x)
        k = nn.Dense(C, use_bias=False, dtype=self.dtype, name="key")(x)
        v = nn.Dense(C, use_bias=False, dtype=self.dtype, name="value")(x)
        shape = (B, T, H, D)
        a = attn(q.reshape(shape), k.reshape(shape), v.reshape(shape))
        a = a.reshape(B, T, C)
        return nn.Dense(C, use_bias=False, dtype=self.dtype, name="out")(a)


class Mlp(nn.Module):
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        C = x.shape[-1]
        h = nn.Dense(self.mlp_ratio * C, dtype=self.dtype, name="up")(x)
        h = nn.gelu(h)
        return nn.Dense(C, dtype=self.dtype, name="down")(h)


class Block(nn.Module):
    d_model: int
    n_heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype, name="ln_1")(x)
        x = x + Attention(
            n_heads=self.n_heads, dtype=self.dtype, attn_fn=self.attn_fn,
            name="attention",
        )(h)
        h = nn.LayerNorm(dtype=self.dtype, name="ln_2")(x)
        x = x + Mlp(
            mlp_ratio=self.mlp_ratio, dtype=self.dtype, name="mlp"
        )(h)
        return x


class TransformerLM(nn.Module):
    vocab_size: int
    d_model: int = 256
    n_heads: int = 8
    n_layers: int = 4
    max_len: int = 2048
    dtype: Any = jnp.bfloat16
    attn_fn: Optional[Callable] = None
    remat: bool = False

    @nn.compact
    def __call__(self, tokens, positions=None):
        """tokens: [B, T_local]; positions: [B, T_local] global positions
        (defaults to arange — only valid unsharded)."""
        B, T = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(T), (B, T))
        tok_emb = nn.Embed(self.vocab_size, self.d_model,
                           dtype=self.dtype, name="embeddings")(tokens)
        pos_emb = nn.Embed(self.max_len, self.d_model,
                           dtype=self.dtype, name="pos_embeddings")(positions)
        x = tok_emb + pos_emb
        block = Block
        if self.remat:
            block = nn.remat(Block)
        for i in range(self.n_layers):
            x = block(
                d_model=self.d_model, n_heads=self.n_heads,
                dtype=self.dtype, attn_fn=self.attn_fn,
                name=f"block_{i}",
            )(x)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        logits = nn.Dense(self.vocab_size, use_bias=False,
                          dtype=jnp.float32, name="lm_head")(x)
        return logits


# --- tensor-parallel functional forward --------------------------------------
#
# The composed DP x TP fast path (docs/parallelism.md) cannot run the
# flax module on TP-local shards — flax shape-checks every param against
# the module's declared (full) feature sizes. tp_apply is the functional
# twin: same param NAMES, same math, but each leaf is consumed at
# whatever (local) shape the sharding rules left it, and the two
# row-parallel projections reduce with ONE psum each over the model
# axis (parallel/tp.py). With model_axis=None it is the dense reference
# the composed parity tests compare against.


def _layer_norm(x, p, dtype):
    """nn.LayerNorm parity (eps 1e-6, f32 statistics) on a raw
    {"scale","bias"} param dict."""
    xf = x.astype(jnp.float32)
    mu = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
    y = (xf - mu) * jax.lax.rsqrt(var + 1e-6)
    y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return y.astype(dtype)


def transformer_n_layers(params) -> int:
    return sum(1 for k in params if str(k).startswith("block_"))


def tp_apply(
    params,
    tokens,
    *,
    n_heads: int,
    model_axis: Optional[str] = None,
    positions=None,
    dtype: Any = jnp.bfloat16,
    causal: bool = True,
    tp_overlap: Optional[bool] = None,
):
    """Functional forward of the :class:`TransformerLM` param tree on
    (possibly TP-local) shards.

    ``n_heads`` is the GLOBAL head count (the head dim derives from the
    replicated ``d_model``); with ``model_axis`` bound each rank runs
    its local ``H/n`` heads and ``F/n`` MLP columns through
    ``parallel/tp.py`` — q/k/v and the MLP up-projection are
    column-parallel (no communication), attention-out and MLP-down are
    row-parallel (ONE psum each, biases scattered inside the reduction).
    Embeddings, norms, and the lm head consume replicated leaves. With
    ``model_axis=None`` every shard is full-size and the function is the
    dense single-chip reference (bitwise the same interpretation of the
    same tree).

    ``tp_overlap`` selects the FUSED collective-matmul path
    (docs/parallelism.md "Fused TP overlap"): the residual stream rides
    token-sharded between blocks, q/k/v ride ONE all-gather-matmul,
    attention-out and MLP-down become matmul-reduce-scatters — zero
    model-axis all-reduces inside the blocks. ``None`` defers to
    ``parallel.tp.tp_overlap_enabled()`` (the composed builder's
    ``overlap_scope`` / ``HOROVOD_TP_OVERLAP``)."""
    from ..parallel.tp import column_parallel, row_parallel, tp_block_input
    from ..parallel.tp import tp_overlap_enabled

    B, T = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T), (B, T))
    emb = params["embeddings"]["embedding"]
    pos = params["pos_embeddings"]["embedding"]
    x = (emb[tokens] + pos[positions]).astype(dtype)
    C = emb.shape[-1]
    if C % n_heads:
        raise ValueError(f"d_model {C} not divisible by n_heads {n_heads}")
    head_dim = C // n_heads

    if model_axis is not None and tp_overlap_enabled(tp_overlap):
        from ..common.compat import axis_size as _axis_size

        n = _axis_size(model_axis)
        if n > 1:
            if T % n:
                raise ValueError(
                    f"tp_overlap needs the sequence length ({T}) "
                    f"divisible by the model-axis size ({n}) — the "
                    f"fused path token-shards the residual stream"
                )
            return _tp_apply_fused(
                params, x, model_axis=model_axis, head_dim=head_dim,
                dtype=dtype, causal=causal,
            )

    def f(y):
        # Megatron's `f`: marks the replicated block input feeding
        # column-parallel shards (identity fwd, cotangent psum bwd).
        return y if model_axis is None else tp_block_input(
            y, axis_name=model_axis
        )

    def row(y, w, b=None):
        if model_axis is None:
            out = y @ w
            return out + b if b is not None else out
        return row_parallel(y, w, b, axis_name=model_axis)

    for i in range(transformer_n_layers(params)):
        bp = params[f"block_{i}"]
        h = f(_layer_norm(x, bp["ln_1"], dtype))
        att = bp["attention"]
        q = column_parallel(h, att["query"]["kernel"].astype(dtype))
        k = column_parallel(h, att["key"]["kernel"].astype(dtype))
        v = column_parallel(h, att["value"]["kernel"].astype(dtype))
        if q.shape[-1] % head_dim:
            raise ValueError(
                f"local q/k/v width {q.shape[-1]} is not whole heads of "
                f"dim {head_dim} — n_heads must divide by the model-axis "
                f"size"
            )
        hl = q.shape[-1] // head_dim
        shape = (B, T, hl, head_dim)
        a = flash_attention_bthd(
            q.reshape(shape), k.reshape(shape), v.reshape(shape),
            causal=causal,
        )
        a = a.reshape(B, T, hl * head_dim)
        x = x + row(a, att["out"]["kernel"].astype(dtype))
        h = f(_layer_norm(x, bp["ln_2"], dtype))
        mlp = bp["mlp"]
        u = jax.nn.gelu(column_parallel(
            h, mlp["up"]["kernel"].astype(dtype),
            mlp["up"]["bias"].astype(dtype),
        ))
        x = x + row(
            u, mlp["down"]["kernel"].astype(dtype),
            mlp["down"]["bias"].astype(dtype),
        )
    x = _layer_norm(x, params["ln_f"], dtype)
    w = params["lm_head"]["kernel"].astype(jnp.float32)
    return x.astype(jnp.float32) @ w


def _tp_apply_fused(params, x, *, model_axis, head_dim, dtype, causal):
    """Collective-matmul forward: token-sharded residual stream.

    Per block: LN on the token shard → q/k/v via ONE all-gather-matmul
    over the concatenated kernels (the gather chunks ride the ring while
    the MXU multiplies) → flash attention on full tokens / local heads →
    attention-out via matmul-reduce-scatter → LN → MLP up (all-gather-
    matmul, gelu) → MLP down (matmul-reduce-scatter). Tokens scatter
    once at entry (free slice) and gather once at exit before ln_f, so
    the lm head sees exactly the classic replicated activation —
    ``psum(y@W) == all_gather(reduce_scatter(y@W))`` over tokens makes
    the whole thing block-for-block equivalent to :func:`tp_apply`'s
    classic path with zero model-axis all-reduces in between. Block
    layernorm params route through ``tp_replicated_params`` (their grads
    are per-token-chunk partial on the sharded stream)."""
    from ..parallel.tp import (
        column_parallel_fused,
        row_parallel_fused,
        tp_gather_tokens,
        tp_replicated_params,
        tp_scatter_tokens,
    )

    B, T, C = x.shape
    x = tp_scatter_tokens(x, axis_name=model_axis)  # [B, T/n, C]
    for i in range(transformer_n_layers(params)):
        bp = params[f"block_{i}"]
        ln1 = tp_replicated_params(bp["ln_1"], axis_name=model_axis)
        h = _layer_norm(x, ln1, dtype)
        att = bp["attention"]
        wqkv = jnp.concatenate(
            [
                att["query"]["kernel"].astype(dtype),
                att["key"]["kernel"].astype(dtype),
                att["value"]["kernel"].astype(dtype),
            ],
            axis=-1,
        )
        qkv = column_parallel_fused(h, wqkv, axis_name=model_axis)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        if q.shape[-1] % head_dim:
            raise ValueError(
                f"local q/k/v width {q.shape[-1]} is not whole heads of "
                f"dim {head_dim} — n_heads must divide by the model-axis "
                f"size"
            )
        hl = q.shape[-1] // head_dim
        shape = (B, T, hl, head_dim)
        a = flash_attention_bthd(
            q.reshape(shape), k.reshape(shape), v.reshape(shape),
            causal=causal,
        )
        a = a.reshape(B, T, hl * head_dim)
        x = x + row_parallel_fused(
            a, att["out"]["kernel"].astype(dtype), axis_name=model_axis
        )
        ln2 = tp_replicated_params(bp["ln_2"], axis_name=model_axis)
        h = _layer_norm(x, ln2, dtype)
        mlp = bp["mlp"]
        u = jax.nn.gelu(column_parallel_fused(
            h, mlp["up"]["kernel"].astype(dtype),
            mlp["up"]["bias"].astype(dtype), axis_name=model_axis,
        ))
        x = x + row_parallel_fused(
            u, mlp["down"]["kernel"].astype(dtype),
            mlp["down"]["bias"].astype(dtype), axis_name=model_axis,
        )
    x = tp_gather_tokens(x, axis_name=model_axis)  # [B, T, C] replicated
    x = _layer_norm(x, params["ln_f"], dtype)
    w = params["lm_head"]["kernel"].astype(jnp.float32)
    return x.astype(jnp.float32) @ w


def tp_decode_apply(
    params,
    tokens,
    positions,
    cache,
    page_table,
    *,
    n_heads: int,
    model_axis: Optional[str] = None,
    dtype: Any = jnp.bfloat16,
):
    """One incremental decode step of the SAME param tree over a paged
    KV cache (``serve/kvcache.py``; docs/serving.md).

    ``tokens``/``positions``: [B] current token ids and their global
    positions. ``cache``: the decode-state pytree — per layer,
    ``block_i/attention/cache_k``/``cache_v`` of shape [num_pages,
    page_size, H(local), head_dim]. ``page_table``: [B, max_pages] int32
    page ids mapping each slot's logical positions onto physical pages
    (slot position p lives in page ``page_table[b, p // page_size]`` at
    offset ``p % page_size``); padded slots point every entry at the
    reserved scratch page 0, which the attention mask keeps them from
    ever reading meaningfully.

    Tensor parallelism mirrors :func:`tp_apply` exactly: q/k/v and the
    MLP up-projection are column-parallel (whole local heads — the k/v
    written to the cache are the LOCAL heads, which is why the cache
    rule shards the head dim over "model"), attention-out and MLP-down
    are row-parallel with ONE psum each. With ``model_axis=None`` it is
    the dense single-chip decode the parity tests compare against the
    full-recompute :func:`tp_apply` reference.

    Returns ``(logits [B, vocab] f32, new_cache)``. The new token's k/v
    are written BEFORE attention reads, so position p attends over
    [0..p] inclusive — identical coverage to the causal full recompute.
    """
    from ..parallel.tp import column_parallel, row_parallel, tp_block_input

    B = tokens.shape[0]
    page_size = None
    emb = params["embeddings"]["embedding"]
    pos = params["pos_embeddings"]["embedding"]
    x = (emb[tokens] + pos[positions]).astype(dtype)  # [B, C]
    C = emb.shape[-1]
    if C % n_heads:
        raise ValueError(f"d_model {C} not divisible by n_heads {n_heads}")
    head_dim = C // n_heads

    def f(y):
        return y if model_axis is None else tp_block_input(
            y, axis_name=model_axis
        )

    def row(y, w, b=None):
        if model_axis is None:
            out = y @ w
            return out + b if b is not None else out
        return row_parallel(y, w, b, axis_name=model_axis)

    new_cache = {k: dict(v) for k, v in cache.items()}
    batch_ix = jnp.arange(B)
    for i in range(transformer_n_layers(params)):
        bp = params[f"block_{i}"]
        ck = cache[f"block_{i}"]["attention"]["cache_k"]
        cv = cache[f"block_{i}"]["attention"]["cache_v"]
        page_size = ck.shape[1]
        h = f(_layer_norm(x, bp["ln_1"], dtype))
        att = bp["attention"]
        q = column_parallel(h, att["query"]["kernel"].astype(dtype))
        k = column_parallel(h, att["key"]["kernel"].astype(dtype))
        v = column_parallel(h, att["value"]["kernel"].astype(dtype))
        if q.shape[-1] % head_dim:
            raise ValueError(
                f"local q/k/v width {q.shape[-1]} is not whole heads of "
                f"dim {head_dim} — n_heads must divide by the model-axis "
                f"size"
            )
        hl = q.shape[-1] // head_dim
        q = q.reshape(B, hl, head_dim)
        k = k.reshape(B, hl, head_dim).astype(ck.dtype)
        v = v.reshape(B, hl, head_dim).astype(cv.dtype)
        # Write this position's k/v into its page BEFORE reading.
        page = page_table[batch_ix, positions // page_size]
        off = positions % page_size
        ck = ck.at[page, off].set(k)
        cv = cv.at[page, off].set(v)
        new_cache[f"block_{i}"] = {
            "attention": {"cache_k": ck, "cache_v": cv}
        }
        # Gather each slot's logical cache view through its page table
        # and attend over [0..position].
        keys = ck[page_table]    # [B, MP, page_size, hl, D]
        vals = cv[page_table]
        T = keys.shape[1] * keys.shape[2]
        keys = keys.reshape(B, T, hl, head_dim)
        vals = vals.reshape(B, T, hl, head_dim)
        valid = jnp.arange(T)[None, :] <= positions[:, None]  # [B, T]
        scores = jnp.einsum(
            "bhd,bthd->bth", q.astype(jnp.float32),
            keys.astype(jnp.float32),
        ) / jnp.sqrt(jnp.float32(head_dim))
        scores = jnp.where(valid[:, :, None], scores, jnp.float32(-1e30))
        p = jax.nn.softmax(scores, axis=1)
        a = jnp.einsum(
            "bth,bthd->bhd", p, vals.astype(jnp.float32)
        ).astype(dtype).reshape(B, hl * head_dim)
        x = x + row(a, att["out"]["kernel"].astype(dtype))
        h = f(_layer_norm(x, bp["ln_2"], dtype))
        mlp = bp["mlp"]
        u = jax.nn.gelu(column_parallel(
            h, mlp["up"]["kernel"].astype(dtype),
            mlp["up"]["bias"].astype(dtype),
        ))
        x = x + row(
            u, mlp["down"]["kernel"].astype(dtype),
            mlp["down"]["bias"].astype(dtype),
        )
    x = _layer_norm(x, params["ln_f"], dtype)
    w = params["lm_head"]["kernel"].astype(jnp.float32)
    return x.astype(jnp.float32) @ w, new_cache


def lm_loss(logits, labels):
    """Mean next-token cross entropy (no optax dependency)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)
    return -jnp.mean(ll)


def make_gpt_loss_fn(
    n_heads: int,
    *,
    model_axis: Optional[str] = None,
    dtype: Any = jnp.bfloat16,
    tp_overlap: Optional[bool] = None,
):
    """``loss_fn(params, (tokens, labels))`` over :func:`tp_apply` — the
    loss the composed ``make_train_step(rules=...)`` trains and the
    dense reference (``model_axis=None``) the parity tests compare
    against. ``tp_overlap`` pins the fused collective-matmul path
    (``None`` defers to the builder's ``overlap_scope`` / the
    ``HOROVOD_TP_OVERLAP`` knob)."""

    def loss_fn(params, batch):
        tokens, labels = batch
        logits = tp_apply(
            params, tokens, n_heads=n_heads, model_axis=model_axis,
            dtype=dtype, tp_overlap=tp_overlap,
        )
        return lm_loss(logits, labels)

    return loss_fn
