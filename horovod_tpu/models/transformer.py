"""Decoder-only Transformer LM (flax), TPU-first, with pluggable attention.

The long-context flagship: ``attn_fn`` can be the dense reference, ring
attention, or Ulysses (``horovod_tpu.parallel.ring_attention``), letting the
same module run single-chip or sequence-parallel inside a shard_map without
code changes. bfloat16 compute with fp32 logits; positions are passed in so
sequence-sharded shards can feed their global offsets.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from ..ops.pallas_attention import flash_attention_bthd


class Block(nn.Module):
    d_model: int
    n_heads: int
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16
    attn_fn: Optional[Callable] = None

    @nn.compact
    def __call__(self, x):
        B, T, C = x.shape
        H = self.n_heads
        D = C // H
        # Default attention is the fused Pallas flash kernel (interpret
        # mode off-TPU); callers plug ring/Ulysses attention in via attn_fn
        # for sequence parallelism.
        attn = self.attn_fn or partial(flash_attention_bthd, causal=True)

        h = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * C, use_bias=False, dtype=self.dtype)(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, D)
        k = k.reshape(B, T, H, D)
        v = v.reshape(B, T, H, D)
        a = attn(q, k, v).reshape(B, T, C)
        x = x + nn.Dense(C, use_bias=False, dtype=self.dtype)(a)

        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.mlp_ratio * C, dtype=self.dtype)(h)
        h = nn.gelu(h)
        x = x + nn.Dense(C, dtype=self.dtype)(h)
        return x


class TransformerLM(nn.Module):
    vocab_size: int
    d_model: int = 256
    n_heads: int = 8
    n_layers: int = 4
    max_len: int = 2048
    dtype: Any = jnp.bfloat16
    attn_fn: Optional[Callable] = None
    remat: bool = False

    @nn.compact
    def __call__(self, tokens, positions=None):
        """tokens: [B, T_local]; positions: [B, T_local] global positions
        (defaults to arange — only valid unsharded)."""
        B, T = tokens.shape
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(T), (B, T))
        tok_emb = nn.Embed(self.vocab_size, self.d_model,
                           dtype=self.dtype)(tokens)
        pos_emb = nn.Embed(self.max_len, self.d_model,
                           dtype=self.dtype)(positions)
        x = tok_emb + pos_emb
        block = Block
        if self.remat:
            block = nn.remat(Block)
        for _ in range(self.n_layers):
            x = block(
                d_model=self.d_model, n_heads=self.n_heads,
                dtype=self.dtype, attn_fn=self.attn_fn,
            )(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        logits = nn.Dense(self.vocab_size, use_bias=False,
                          dtype=jnp.float32)(x)
        return logits
