"""ResNet family (flax), TPU-first.

The flagship benchmark model: the reference's headline numbers are ResNet
synthetic-data img/sec (``docs/benchmarks.rst:29-43``,
``examples/tensorflow2_synthetic_benchmark.py`` uses applications.ResNet50).
This implementation is idiomatic flax/XLA: NHWC layout, bfloat16 compute with
fp32 params/batch-stats (MXU-native), no data-dependent control flow.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            self.norm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), self.strides)(y)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1), self.strides, name="conv_proj")(
                residual
            )
            residual = norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: Tuple[int, int] = (1, 1)
    dtype: Any = jnp.bfloat16
    norm: ModuleDef = nn.BatchNorm

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(
            self.norm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )
        residual = x
        y = conv(self.filters, (3, 3), self.strides)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(
                self.filters, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    block: ModuleDef = BottleneckBlock

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        x = nn.Conv(
            self.num_filters,
            (7, 7),
            (2, 2),
            padding=[(3, 3), (3, 3)],
            use_bias=False,
            dtype=self.dtype,
            name="conv_init",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            name="bn_init",
        )(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    dtype=self.dtype,
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3])
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3])
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3])
ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block=BasicBlock)
