"""Model zoo: the reference's headline benchmark families re-implemented
as idiomatic flax modules (bfloat16 compute, fp32 state, NHWC)."""

from __future__ import annotations


def get_model(name: str, **kwargs):
    """Factory keyed by the benchmark names the reference's scripts use
    (``resnet50``, ``vgg16``, ``inception3``, ...)."""
    name = name.lower().replace("-", "").replace("_", "")
    from . import inception, resnet, vgg

    zoo = {
        "resnet18": resnet.ResNet18,
        "resnet34": resnet.ResNet34,
        "resnet50": resnet.ResNet50,
        "resnet101": resnet.ResNet101,
        "resnet152": resnet.ResNet152,
        "vgg11": vgg.VGG11,
        "vgg16": vgg.VGG16,
        "vgg19": vgg.VGG19,
        "inception3": inception.InceptionV3,
        "inceptionv3": inception.InceptionV3,
    }
    if name not in zoo:
        raise ValueError(
            f"unknown model {name!r}; available: {sorted(zoo)}"
        )
    return zoo[name](**kwargs)
