"""Inception V3 (flax), TPU-first.

Inception V3 is one of the reference's three headline scaling benchmarks
(90% efficiency at 512 GPUs, ``README.rst:79`` /
``docs/benchmarks.rst:13``). Structure follows the Szegedy et al. 2015
architecture (stem -> 3x InceptionA -> reduction -> 4x InceptionB ->
reduction -> 2x InceptionC -> pool -> head); bfloat16 compute, fp32
params/logits, NHWC, no aux head (train-time aux classifiers don't change
the throughput benchmark and the reference scripts run synthetic data).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax.numpy as jnp
from flax import linen as nn


class ConvBN(nn.Module):
    features: int
    kernel: Tuple[int, int] = (1, 1)
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(
            self.features, self.kernel, self.strides, padding=self.padding,
            use_bias=False, dtype=self.dtype,
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-3,
            dtype=self.dtype,
        )(x)
        return nn.relu(x)


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = lambda f, k=(1, 1), s=(1, 1): ConvBN(  # noqa: E731
            f, k, s, dtype=self.dtype
        )
        b1 = cbn(64)(x, train)
        b2 = cbn(48)(x, train)
        b2 = cbn(64, (5, 5))(b2, train)
        b3 = cbn(64)(x, train)
        b3 = cbn(96, (3, 3))(b3, train)
        b3 = cbn(96, (3, 3))(b3, train)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = cbn(self.pool_features)(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionA(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = lambda f, k=(1, 1), s=(1, 1), p="SAME": ConvBN(  # noqa: E731
            f, k, s, padding=p, dtype=self.dtype
        )
        b1 = cbn(384, (3, 3), (2, 2), "VALID")(x, train)
        b2 = cbn(64)(x, train)
        b2 = cbn(96, (3, 3))(b2, train)
        b2 = cbn(96, (3, 3), (2, 2), "VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionB(nn.Module):
    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = self.channels_7x7
        cbn = lambda f, k=(1, 1): ConvBN(f, k, dtype=self.dtype)  # noqa: E731
        b1 = cbn(192)(x, train)
        b2 = cbn(c)(x, train)
        b2 = cbn(c, (1, 7))(b2, train)
        b2 = cbn(192, (7, 1))(b2, train)
        b3 = cbn(c)(x, train)
        b3 = cbn(c, (7, 1))(b3, train)
        b3 = cbn(c, (1, 7))(b3, train)
        b3 = cbn(c, (7, 1))(b3, train)
        b3 = cbn(192, (1, 7))(b3, train)
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = cbn(192)(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionB(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = lambda f, k=(1, 1), s=(1, 1), p="SAME": ConvBN(  # noqa: E731
            f, k, s, padding=p, dtype=self.dtype
        )
        b1 = cbn(192)(x, train)
        b1 = cbn(320, (3, 3), (2, 2), "VALID")(b1, train)
        b2 = cbn(192)(x, train)
        b2 = cbn(192, (1, 7))(b2, train)
        b2 = cbn(192, (7, 1))(b2, train)
        b2 = cbn(192, (3, 3), (2, 2), "VALID")(b2, train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = lambda f, k=(1, 1): ConvBN(f, k, dtype=self.dtype)  # noqa: E731
        b1 = cbn(320)(x, train)
        b2 = cbn(384)(x, train)
        b2 = jnp.concatenate(
            [cbn(384, (1, 3))(b2, train), cbn(384, (3, 1))(b2, train)],
            axis=-1,
        )
        b3 = cbn(448)(x, train)
        b3 = cbn(384, (3, 3))(b3, train)
        b3 = jnp.concatenate(
            [cbn(384, (1, 3))(b3, train), cbn(384, (3, 1))(b3, train)],
            axis=-1,
        )
        b4 = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        b4 = cbn(192)(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        d = self.dtype
        x = x.astype(d)
        cbn = lambda f, k, s=(1, 1), p="VALID": ConvBN(  # noqa: E731
            f, k, s, padding=p, dtype=d
        )
        # Stem (299 -> 35 spatial at standard input size).
        x = cbn(32, (3, 3), (2, 2))(x, train)
        x = cbn(32, (3, 3))(x, train)
        x = cbn(64, (3, 3), p="SAME")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = cbn(80, (1, 1))(x, train)
        x = cbn(192, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")

        x = InceptionA(32, dtype=d)(x, train)
        x = InceptionA(64, dtype=d)(x, train)
        x = InceptionA(64, dtype=d)(x, train)
        x = ReductionA(dtype=d)(x, train)
        x = InceptionB(128, dtype=d)(x, train)
        x = InceptionB(160, dtype=d)(x, train)
        x = InceptionB(160, dtype=d)(x, train)
        x = InceptionB(192, dtype=d)(x, train)
        x = ReductionB(dtype=d)(x, train)
        x = InceptionC(dtype=d)(x, train)
        x = InceptionC(dtype=d)(x, train)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)
