"""VGG family (flax), TPU-first.

VGG-16 is one of the reference's three headline scaling benchmarks (68%
efficiency at 512 GPUs, ``README.rst:79`` / ``docs/benchmarks.rst:14``) —
its large dense layers make it the communication-bound stress case for
gradient fusion. bfloat16 compute, fp32 params/logits, NHWC.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn


class VGG(nn.Module):
    stage_sizes: Sequence[int]        # convs per stage (5 stages)
    num_classes: int = 1000
    num_filters: int = 64
    dense_features: int = 4096
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.dtype)
        for i, n_convs in enumerate(self.stage_sizes):
            filters = min(self.num_filters * 2**i, 512)
            for j in range(n_convs):
                x = nn.Conv(
                    filters, (3, 3), padding="SAME", dtype=self.dtype,
                    name=f"conv{i}_{j}",
                )(x)
                x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.dense_features, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(self.dense_features, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


VGG11 = partial(VGG, stage_sizes=[1, 1, 2, 2, 2])
VGG16 = partial(VGG, stage_sizes=[2, 2, 3, 3, 3])
VGG19 = partial(VGG, stage_sizes=[2, 2, 4, 4, 4])
