"""Dependency-free metric primitives: counters, gauges, fixed-bucket
histograms, and the registry that owns them.

Design constraints (docs/metrics.md):

- **No third-party client.** The worker image must not grow a
  ``prometheus_client`` dependency; the text exposition format is tiny and
  is rendered by :mod:`horovod_tpu.metrics.export`.
- **Hot-path cheap.** A counter increment is one dict lookup + one locked
  float add. Histograms use ``bisect`` over a fixed edge tuple — no
  allocation after the first observation of a label set.
- **Snapshot = plain data.** ``Registry.snapshot()`` returns nothing but
  dicts/lists/numbers, so it pickles/JSONs through the KV rendezvous plane
  unchanged and ``hvd.metrics_snapshot()`` can hand it straight to users.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

# Latency edges (seconds): sub-millisecond RPC turnarounds up to
# stall-scale minutes. Histograms are fixed-bucket so cross-rank
# aggregation is a per-bucket sum, never a re-bin.
LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# Payload edges (bytes): one element to past the 64 MB fusion threshold.
BYTE_BUCKETS: Tuple[float, ...] = (
    256.0, 1024.0, 4096.0, 16384.0, 65536.0, 262144.0, 1048576.0,
    4194304.0, 16777216.0, 67108864.0, 268435456.0,
)


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Metric:
    """Base: one named metric holding one series per label set."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: Dict[Tuple[Tuple[str, str], ...], object] = {}

    def _snapshot_series(self) -> List[dict]:
        raise NotImplementedError

    def snapshot(self) -> dict:
        out = {"type": self.kind, "help": self.help,
               "series": self._snapshot_series()}
        return out


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        if value < 0:
            raise ValueError("counters only go up")
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + value

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))

    def _snapshot_series(self) -> List[dict]:
        with self._lock:
            return [
                {"labels": dict(k), "value": float(v)}
                for k, v in sorted(self._series.items())
            ]


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._series[_label_key(labels)] = float(value)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(_label_key(labels), 0.0))

    def _snapshot_series(self) -> List[dict]:
        with self._lock:
            return [
                {"labels": dict(k), "value": float(v)}
                for k, v in sorted(self._series.items())
            ]


class Histogram(Metric):
    """Fixed-bucket histogram. ``buckets[i]`` counts observations with
    ``value <= edges[i]`` exclusively of lower buckets (non-cumulative in
    the snapshot; the Prometheus renderer accumulates). One extra slot at
    the end counts the +Inf overflow."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help)
        edges = tuple(sorted(float(b) for b in (buckets or LATENCY_BUCKETS_S)))
        if not edges:
            raise ValueError("histogram needs at least one bucket edge")
        self.edges = edges

    def observe(self, value: float, **labels) -> None:
        value = float(value)
        idx = bisect_left(self.edges, value)
        key = _label_key(labels)
        with self._lock:
            st = self._series.get(key)
            if st is None:
                st = {"buckets": [0] * (len(self.edges) + 1),
                      "sum": 0.0, "count": 0}
                self._series[key] = st
            st["buckets"][idx] += 1
            st["sum"] += value
            st["count"] += 1

    def count(self, **labels) -> int:
        with self._lock:
            st = self._series.get(_label_key(labels))
            return int(st["count"]) if st else 0

    def _snapshot_series(self) -> List[dict]:
        with self._lock:
            return [
                {"labels": dict(k), "buckets": list(v["buckets"]),
                 "sum": float(v["sum"]), "count": int(v["count"])}
                for k, v in sorted(self._series.items())
            ]

    def snapshot(self) -> dict:
        out = super().snapshot()
        out["bucket_edges"] = list(self.edges)
        return out


class Registry:
    """Thread-safe name → metric table with get-or-create accessors."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: "Dict[str, Metric]" = {}

    def _get_or_create(self, cls, name: str, help: str, **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help=help, **kw)
                self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} is already registered as {m.kind}"
            )
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def snapshot(self) -> Dict[str, dict]:
        with self._lock:
            metrics = sorted(self._metrics.items())
        return {name: m.snapshot() for name, m in metrics}

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()
