"""Snapshot aggregation and Prometheus text exposition.

The driver never scrapes workers: workers PUSH their registry snapshots
over the existing KV rendezvous plane (``MetricsPusher``, one small JSON
PUT per interval), and the driver's ``GET /metrics`` handler merges
whatever snapshots are present with its own registry, stamping each
source's identity labels (``rank="0"`` / ``role="driver"``) onto every
series. Fixed-bucket histograms make the merge a relabeling, never a
re-bin.

``parse_prometheus`` is a deliberately small reader of the subset this
module emits — enough for ``tools/metrics_smoke.py`` and the test suite to
validate the exposition without a prometheus client dependency.
"""

from __future__ import annotations

import json
import logging
import re
import threading
from typing import Dict, Iterable, List, Optional, Tuple

logger = logging.getLogger("horovod_tpu.metrics")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# KV scope worker snapshots are pushed under (driver-side aggregation
# reads the same scope).
KV_SCOPE = "metrics"


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape(v: str) -> str:
    return (
        str(v).replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")
    )


def _labelstr(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def render_prometheus(
    parts: Iterable[Tuple[Dict[str, str], Dict[str, dict]]]
) -> str:
    """Render ``(extra_labels, snapshot)`` parts as one text exposition.

    Series from every part are merged under their metric name with the
    part's extra labels applied; the first part to introduce a name wins
    the HELP/TYPE line (the catalog keeps them identical across ranks
    anyway). A histogram whose bucket edges disagree with the first
    sighting is dropped with a log line rather than corrupting the
    exposition."""
    merged: "Dict[str, dict]" = {}
    for extra, snap in parts:
        for name, metric in (snap or {}).items():
            m = merged.get(name)
            if m is None:
                m = {
                    "type": metric.get("type", "untyped"),
                    "help": metric.get("help", ""),
                    "bucket_edges": metric.get("bucket_edges"),
                    "series": [],
                }
                merged[name] = m
            if metric.get("type") != m["type"]:
                logger.warning(
                    "metric %s: type mismatch across sources (%s vs %s); "
                    "dropping the latecomer", name, metric.get("type"),
                    m["type"],
                )
                continue
            if (m["type"] == "histogram"
                    and metric.get("bucket_edges") != m["bucket_edges"]):
                logger.warning(
                    "metric %s: bucket edges differ across sources; "
                    "dropping the latecomer", name,
                )
                continue
            for s in metric.get("series", []):
                labels = dict(s.get("labels", {}))
                labels.update(extra or {})
                merged[name]["series"].append({**s, "labels": labels})

    lines: List[str] = []
    for name in sorted(merged):
        m = merged[name]
        if m["help"]:
            lines.append(f"# HELP {name} {m['help']}")
        lines.append(f"# TYPE {name} {m['type']}")
        if m["type"] == "histogram":
            edges = m["bucket_edges"] or []
            for s in m["series"]:
                labels = s["labels"]
                cum = 0
                counts = s.get("buckets", [])
                for i, edge in enumerate(edges):
                    cum += counts[i] if i < len(counts) else 0
                    lab = dict(labels)
                    lab["le"] = _fmt(edge)
                    lines.append(
                        f"{name}_bucket{_labelstr(lab)} {cum}"
                    )
                cum += counts[len(edges)] if len(counts) > len(edges) else 0
                lab = dict(labels)
                lab["le"] = "+Inf"
                lines.append(f"{name}_bucket{_labelstr(lab)} {cum}")
                lines.append(
                    f"{name}_sum{_labelstr(labels)} {_fmt(s.get('sum', 0))}"
                )
                lines.append(
                    f"{name}_count{_labelstr(labels)} "
                    f"{_fmt(s.get('count', 0))}"
                )
        else:
            for s in m["series"]:
                lines.append(
                    f"{name}{_labelstr(s['labels'])} "
                    f"{_fmt(s.get('value', 0))}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> Dict[str, dict]:
    """Parse a text exposition into
    ``{name: {"type": t, "samples": [(labels, value), ...]}}``.
    Histogram ``_bucket``/``_sum``/``_count`` samples are filed under
    their base metric name."""
    out: Dict[str, dict] = {}
    types: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            fields = line.split(None, 3)
            if len(fields) >= 4 and fields[1] == "TYPE":
                types[fields[2]] = fields[3].strip()
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"malformed exposition line: {raw!r}")
        name, labelstr, value = m.groups()
        labels = {
            k: v.replace(r"\"", '"').replace(r"\n", "\n").replace(
                "\\\\", "\\"
            )
            for k, v in _LABEL_RE.findall(labelstr or "")
        }
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            stem = name[: -len(suffix)] if name.endswith(suffix) else None
            if stem and types.get(stem) == "histogram":
                base = stem
                break
        entry = out.setdefault(
            base, {"type": types.get(base, "untyped"), "samples": []}
        )
        entry["samples"].append(
            (name, labels, float("inf") if value == "+Inf" else float(value))
        )
    return out


def flatten(snapshot: Dict[str, dict]) -> Dict[str, float]:
    """Human-oriented flat view (``hvd.metrics()``): one
    ``name{label="v"}`` key per series; histograms contribute their
    ``_count`` and ``_sum``."""
    flat: Dict[str, float] = {}
    for name, metric in (snapshot or {}).items():
        for s in metric.get("series", []):
            lab = _labelstr(s.get("labels", {}))
            if metric.get("type") == "histogram":
                flat[f"{name}_count{lab}"] = float(s.get("count", 0))
                flat[f"{name}_sum{lab}"] = float(s.get("sum", 0.0))
            else:
                flat[f"{name}{lab}"] = float(s.get("value", 0.0))
    return flat


class MetricsPusher:
    """Worker-side background publisher: every ``interval`` seconds (and
    once more at stop) the local registry snapshot is PUT to the driver's
    KV store under ``metrics/rank.<rank>``, stamped with this worker's
    identity labels. Push failures are swallowed — metrics must never
    take down training — and the KV client's own bounded retry/backoff
    absorbs transient driver unreachability."""

    def __init__(self, addr: str, port: int, rank: int,
                 interval: Optional[float] = None):
        import os

        from ..run.http_server import KVStoreClient

        self._kv = KVStoreClient(addr, port)
        self._rank = int(rank)
        if interval is None:
            try:
                interval = float(os.environ.get(
                    "HOROVOD_METRICS_PUSH_INTERVAL_S", "") or 2.0)
            except ValueError:
                interval = 2.0
        self._interval = max(float(interval), 0.05)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="hvd_metrics_pusher", daemon=True
        )
        self._thread.start()

    def push_once(self) -> None:
        from . import snapshot as _snapshot

        snap = _snapshot()
        if not snap:
            return
        payload = json.dumps(
            {"labels": {"rank": str(self._rank)}, "snapshot": snap}
        ).encode()
        try:
            self._kv.put(KV_SCOPE, f"rank.{self._rank}", payload)
        except Exception:  # noqa: BLE001 - advisory plane only
            logger.debug("metrics push failed", exc_info=True)

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            self.push_once()

    def stop(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        # Final push so short jobs still land their terminal counts.
        self.push_once()


def aggregate_kv_snapshots(
    kv_entries: Dict[str, bytes],
    local_snapshot: Optional[Dict[str, dict]] = None,
    local_labels: Optional[Dict[str, str]] = None,
) -> str:
    """Driver-side assembly for ``GET /metrics``: decode worker-pushed KV
    payloads (unreadable entries are skipped) and render them with the
    serving process's own snapshot."""
    parts: List[Tuple[Dict[str, str], Dict[str, dict]]] = []
    if local_snapshot:
        parts.append((local_labels or {"role": "driver"}, local_snapshot))
    for key in sorted(kv_entries):
        try:
            payload = json.loads(kv_entries[key].decode())
            parts.append(
                (dict(payload.get("labels", {})),
                 dict(payload.get("snapshot", {})))
            )
        except (ValueError, AttributeError, UnicodeDecodeError):
            logger.warning("unreadable metrics snapshot under %s", key)
    return render_prometheus(parts)
