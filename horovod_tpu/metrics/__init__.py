"""Runtime metrics & introspection.

The observability counterpart of the Chrome-trace timeline
(``utils/timeline.py``): where the timeline reconstructs ONE run post-hoc,
this subsystem keeps low-overhead counters, gauges, and fixed-bucket
histograms that a fleet monitor can scrape continuously — per-op
negotiate/execute latency and bytes, RPC retry/backoff, stall-ladder
escalations, and elastic generation/blacklist/preemption events.

Tap discipline — identical to ``fault/injector.py``: with
``HOROVOD_METRICS`` unset (the production default) the module-level
:data:`ACTIVE` flag is False, :data:`TAP` is the shared no-op singleton
:data:`NULL_TAP`, and instrumented call sites skip their tap entirely
(``if _metrics.ACTIVE: ...`` is the whole overhead). With
``HOROVOD_METRICS=1`` the tap records into a process-local
:class:`~horovod_tpu.metrics.registry.Registry`.

Three consumers (docs/metrics.md):

- ``GET /metrics`` on the driver's rendezvous HTTP server — Prometheus
  text exposition aggregating the driver's own registry with worker
  snapshots pushed over the KV plane, labeled by rank;
- ``hvd.metrics()`` / ``hvd.metrics_snapshot()`` — plain dicts, in
  process;
- ``tools/metrics_dump.py`` — pretty-print or diff snapshots offline.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional

from .registry import (  # noqa: F401 (re-exported)
    BYTE_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    LATENCY_BUCKETS_S,
    Registry,
)

METRICS_ENV = "HOROVOD_METRICS"
METRICS_PORT_ENV = "HOROVOD_METRICS_PORT"
METRICS_PUSH_INTERVAL_ENV = "HOROVOD_METRICS_PUSH_INTERVAL_S"

# Help strings + bucket overrides for the shipped metric catalog
# (docs/metrics.md). Names not listed here still work — they get an empty
# help line and suffix-derived buckets.
_CATALOG: Dict[str, str] = {
    "hvd_op_negotiate_seconds":
        "Per-op latency from submission to coordinator readiness",
    "hvd_op_execute_seconds": "Per-op fused-plan execution latency",
    "hvd_op_bytes": "Per-plan fused payload size in bytes",
    "hvd_ops_submitted_total": "Collectives submitted by this rank",
    "hvd_op_errors_total": "Collectives that completed with an error",
    "hvd_plans_total": "Fused plans executed by this rank",
    "hvd_queue_depth": "Pending tensors in the runtime queue",
    "hvd_cycle_seconds": "Background negotiation-cycle duration",
    "hvd_fusion_buckets": "Fusion buckets planned for one reduction path "
                          "(trace-time; labeled by path)",
    "hvd_fusion_bucket_bytes": "Planned fusion-bucket payload sizes",
    "hvd_overlap_groups": "Streamed-reduction layer groups registered by "
                          "the overlap path (trace-time)",
    "hvd_xla_perf_preset_info": "Resolved XLA perf-flag preset (value is "
                                "always 1; preset/flags in labels)",
    "hvd_xla_cache_hits_total": "Compiled-collective cache hits",
    "hvd_xla_cache_misses_total": "Compiled-collective cache misses",
    "hvd_xla_compile_seconds": "Compiled-collective build time",
    "hvd_rpc_requests_total": "Control-plane RPCs issued",
    "hvd_rpc_retries_total": "Control-plane RPC retries (backoff fired)",
    "hvd_rpc_failures_total": "Control-plane RPCs failed after retries",
    "hvd_rpc_timeouts_total": "Control-plane RPCs answered with a "
                              "server-side phase timeout",
    "hvd_kv_requests_total": "Rendezvous KV requests (client side)",
    "hvd_kv_retries_total": "Rendezvous KV request retries",
    "hvd_kv_server_requests_total": "Rendezvous KV requests served",
    "hvd_stall_warnings_total": "Stall-ladder rung-1 warnings",
    "hvd_stall_aborts_total": "Stall-ladder rung-2 per-tensor aborts",
    "hvd_stall_shutdowns_total": "Stall-ladder rung-3 runtime shutdowns",
    "hvd_elastic_generation": "Current world generation (driver)",
    "hvd_elastic_world_size": "Current world size (driver)",
    "hvd_elastic_generations_total": "World generations published",
    "hvd_elastic_worker_failures_total": "Worker process failures",
    "hvd_elastic_blacklists_total": "Hosts quarantined",
    "hvd_elastic_readmissions_total": "Hosts re-admitted after quarantine",
    "hvd_elastic_blacklisted_hosts": "Hosts currently quarantined",
    "hvd_elastic_preempt_notices_total": "Preemption notices delivered",
    "hvd_elastic_respawn_requests_total": "Worker-requested respawns",
    "hvd_elastic_restarts_total": "Respawn-mode world restarts",
    "hvd_elastic_rollbacks_total": "State rollbacks after collective "
                                   "failure (worker)",
    "hvd_elastic_snapshot_quarantined_total":
        "Unreadable persisted snapshots quarantined to *.corrupt",
    # Elastic resharding (docs/fault_tolerance.md "Elastic resharding").
    "hvd_reshard_total": "Sharded-state reshard executions (labeled by "
                         "trigger: resize/checkpoint/snapshot-restore/"
                         "manual)",
    "hvd_reshard_bytes_total": "Bytes redistributed across ranks by "
                               "reshards (labeled by mesh axis)",
    "hvd_reshard_ef_dropped_elements_total":
        "Error-feedback residual elements dropped or zeroed across a "
        "reshard (labeled by policy; never silent)",
    # Data-plane integrity guard (docs/fault_tolerance.md).
    "hvd_guard_nonfinite_total": "Non-finite gradient detections "
                                 "(labeled by policy and path)",
    "hvd_guard_skipped_steps_total": "Optimizer steps skipped by "
                                     "cross-rank agreement (policy skip)",
    "hvd_guard_metadata_aborts_total": "Collectives aborted by cross-rank "
                                       "metadata validation",
    "hvd_guard_digest_checks_total": "Parameter-digest agreement rounds",
    "hvd_guard_digest_mismatches_total": "Digest rounds that found "
                                         "diverged replicas",
    "hvd_guard_heals_total": "Digest mismatches healed by re-broadcast",
    "hvd_guard_rollbacks_total": "Digest mismatches with no quorum "
                                 "(elastic rollback raised)",
    "hvd_elastic_host_interrupts_total": "Membership-change interrupts "
                                         "(worker)",
    "hvd_elastic_preemptions_total": "Preemption interrupts (worker)",
    "hvd_elastic_rejoins_total": "World rejoins completed (worker)",
    # Compiled-path offline tuning (docs/autotune.md).
    "hvd_tuned_info": "Compiled-path tuned source (value is always 1; "
                      "source=arg/file/env/none, signature hash, "
                      "matched, where in labels)",
    # Fleet simulation (docs/simulation.md).
    "hvd_sim_divergence_ratio": "Replay-mode modeled-over-measured time "
                                "per interconnect hop (hop='step' = "
                                "whole-step scope); drift from 1 means "
                                "the cost model is mispricing links",
    # Topology-aware collective compositor (docs/topology.md).
    "hvd_topo_plan_info": "Selected compositor lowering plan (value is "
                          "always 1; collective/algorithm/op/where in "
                          "labels)",
    "hvd_topo_bytes_per_hop": "Planned per-rank bytes-on-wire per "
                              "interconnect hop for the selected plan",
    "hvd_mesh_fallback_total": "build_mesh degraded to a bare device "
                               "reshape (ICI adjacency lost)",
    # Fleet tracing (docs/timeline.md "Fleet tracing").
    "hvd_timeline_dropped_total": "Timeline events dropped after a "
                                  "writer-thread failure or an "
                                  "undrained shutdown",
    "hvd_step_skew_seconds": "Cross-rank spread of step-end times per "
                             "step (driver-side, raw wall clock)",
    "hvd_straggler_total": "Steps on which this rank finished last with "
                           "skew above the straggler threshold "
                           "(labeled by rank)",
    "hvd_trace_pushes_total": "Trace windows pushed to the driver over "
                              "the KV plane",
    "hvd_trace_collections_total": "Trace windows collected by the "
                                   "driver's supervision loop",
    "hvd_trace_flight_dumps_total": "Flight-recorder dumps written "
                                    "(labeled by reason)",
    "hvd_trace_clock_offset_seconds": "This worker's estimated wall-"
                                      "clock offset vs the driver "
                                      "(KV ping RTT/2; recorded, never "
                                      "applied)",
    # Inference serving (docs/serving.md, docs/metrics.md "Serving").
    "hvd_request_latency_seconds": "End-to-end request latency, "
                                   "admission to completion (the SLO "
                                   "histogram)",
    "hvd_request_total": "Requests finished, labeled by outcome "
                         "(ok/dropped/rejected)",
    "hvd_serve_queue_depth": "Requests waiting in the continuous "
                             "batcher's admission queue",
    "hvd_serve_batch_occupancy": "Live requests in the most recent "
                                 "dispatched batch (padding excluded)",
    "hvd_serve_kv_pages_in_use": "KV-cache pages currently granted to "
                                 "live requests",
    "hvd_serve_replicas": "DP serving replicas currently running",
    "hvd_serve_tokens_total": "Tokens generated across all requests",
    "hvd_serve_requeues_total": "In-flight requests re-queued after a "
                                "replica died mid-batch (each is still "
                                "answered exactly once)",
    "hvd_serve_scale_decisions_total": "Serving autoscale verdicts "
                                       "(labeled by action: "
                                       "scale-out/scale-in)",
}

_BUCKET_OVERRIDES = {
    "hvd_op_bytes": BYTE_BUCKETS,
}

# Counter families pre-seeded at activation so the exposition always
# carries the alerting-relevant zeros (a counter that never fired still
# scrapes as 0, the Prometheus idiom).
_PRESEED_COUNTERS = (
    "hvd_rpc_retries_total",
    "hvd_rpc_failures_total",
    "hvd_kv_retries_total",
    "hvd_stall_warnings_total",
    "hvd_stall_aborts_total",
    "hvd_stall_shutdowns_total",
    "hvd_op_errors_total",
)


class MetricsTap:
    """The live tap: name-keyed get-or-create access into one registry.
    Call sites stay one-liners; metric types are derived from the method
    (``inc`` → counter, ``set`` → gauge, ``observe`` → histogram) and
    histogram buckets from the catalog or the ``_bytes`` name suffix."""

    def __init__(self, registry: Optional[Registry] = None):
        self.registry = registry or Registry()

    def _buckets(self, name: str):
        b = _BUCKET_OVERRIDES.get(name)
        if b is not None:
            return b
        return BYTE_BUCKETS if name.endswith("_bytes") else LATENCY_BUCKETS_S

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        self.registry.counter(name, _CATALOG.get(name, "")).inc(
            value, **labels
        )

    def set(self, name: str, value: float, **labels) -> None:
        self.registry.gauge(name, _CATALOG.get(name, "")).set(value, **labels)

    def observe(self, name: str, value: float, **labels) -> None:
        self.registry.histogram(
            name, _CATALOG.get(name, ""), buckets=self._buckets(name)
        ).observe(value, **labels)

    def snapshot(self) -> Dict[str, dict]:
        return self.registry.snapshot()


class _NullTap:
    """Shared no-op tap installed while metrics are disabled. Sites that
    gate on :data:`ACTIVE` never reach it; sites that hold a tap
    reference pay one empty method call."""

    registry = None

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        pass

    def set(self, name: str, value: float, **labels) -> None:
        pass

    def observe(self, name: str, value: float, **labels) -> None:
        pass

    def snapshot(self) -> Dict[str, dict]:
        return {}


NULL_TAP = _NullTap()

ACTIVE = False
TAP = NULL_TAP

_lock = threading.Lock()


def enabled() -> bool:
    return ACTIVE


def tap():
    """The process-wide tap: the live one when enabled, else the shared
    no-op singleton (``metrics.tap() is metrics.NULL_TAP``)."""
    return TAP


def install(active: bool) -> None:
    """(De)activate metrics for this process."""
    global ACTIVE, TAP
    with _lock:
        if active:
            t = MetricsTap()
            for name in _PRESEED_COUNTERS:
                # inc(0) materializes an unlabeled zero series, so the
                # family scrapes as an explicit 0 before it ever fires.
                t.registry.counter(name, _CATALOG.get(name, "")).inc(0)
            TAP = t
            ACTIVE = True
        else:
            TAP = NULL_TAP
            ACTIVE = False


def activate_from_env() -> bool:
    v = os.environ.get(METRICS_ENV, "").strip().lower()
    install(v not in ("", "0", "false", "no", "off"))
    return ACTIVE


def reset() -> None:
    install(False)


def snapshot() -> Dict[str, dict]:
    """Plain-dict snapshot of every metric in this process ({} when
    disabled)."""
    return TAP.snapshot()


def flat() -> Dict[str, float]:
    """Flat ``{name{label="v"}: value}`` view of :func:`snapshot` — the
    value ``hvd.metrics()`` returns."""
    from .export import flatten

    return flatten(snapshot())


class _CallableModule(type(os)):
    """``hvd.metrics`` must be BOTH this subpackage (``hvd.metrics.TAP``,
    ``hvd.metrics.export``) and the documented ``hvd.metrics()`` API
    returning a plain dict. A module attribute cannot be shadowed by a
    same-named function without breaking ``from .. import metrics`` at
    every instrumented call site, so the module itself is made callable
    (the PEP 562 ``__class__``-swap idiom)."""

    def __call__(self):
        return flat()


import sys as _sys  # noqa: E402

_sys.modules[__name__].__class__ = _CallableModule


# Arm at import (mirrors fault/injector.py): worker processes spawned
# with HOROVOD_METRICS in their environment record without code changes.
if os.environ.get(METRICS_ENV, "").strip():
    activate_from_env()
