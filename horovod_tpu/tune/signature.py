"""Abstract step signatures — the key a pinned ``tuned.json`` is valid for.

A tuned configuration is only meaningful for the program it was tuned on:
the gradient pytree's structure decides the stream-group partition, the
leaf shapes/dtypes decide bucket payloads, and the mesh topology decides
which lowerings exist. The signature captures exactly those inputs and
nothing else (no values, no device ids, no hostnames):

- ``treedef`` — ``str(jax.tree.structure(params))``;
- ``leaves`` — per-leaf ``[shape..., dtype]`` in flatten order;
- ``mesh`` — the mesh axis sizes (``Mesh.shape``) or the interconnect
  model's ``(hop name, size)`` ladder, whichever the caller has.

``signature_hash`` is a SHA-256 prefix over the canonical (sorted-keys)
JSON, so two runs of the tuner on the same program emit byte-identical
keys and a consumer can compare hashes without materializing params.
Works on concrete arrays and ``jax.ShapeDtypeStruct`` avals alike — the
tuner never has to touch a backend to key its output.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, Optional

SIGNATURE_VERSION = 1


def _mesh_component(mesh: Any = None, model: Any = None) -> Dict:
    if mesh is not None:
        shape = getattr(mesh, "shape", None)
        if shape is not None:
            return {"axes": {str(k): int(v) for k, v in dict(shape).items()}}
        return {"axes": {str(k): int(v) for k, v in dict(mesh).items()}}
    if model is not None:
        return {
            "hops": [[h.name, int(h.size)] for h in model.hops],
        }
    return {}


def step_signature(params: Any, mesh: Any = None,
                   model: Any = None) -> Dict:
    """Signature dict for a params pytree (arrays or avals) on a mesh
    (a ``jax.sharding.Mesh``, an ``{axis: size}`` dict, or None) or an
    interconnect model."""
    import jax

    leaves, treedef = jax.tree.flatten(params)
    sig = {
        "version": SIGNATURE_VERSION,
        "treedef": str(treedef),
        "leaves": [
            [list(int(d) for d in getattr(l, "shape", ())),
             str(getattr(l, "dtype", "?"))]
            for l in leaves
        ],
        "mesh": _mesh_component(mesh, model),
    }
    sig["hash"] = signature_hash(sig)
    return sig


def signature_hash(sig: Dict) -> str:
    """Stable 16-hex-digit key over the signature's canonical JSON (the
    ``hash`` field itself excluded)."""
    body = {k: v for k, v in sig.items() if k != "hash"}
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def mesh_axes_hash(sig: Optional[Dict]) -> str:
    """16-hex key over ONLY the mesh component of a signature — what
    lets a consumer say WHY a match failed: same program pinned on a
    different mesh (axes hash differs) vs a different program entirely.
    ``bench.py`` refuses ``--quantized --tuned`` when this half differs
    (the wire-dtype verdict is a function of the mesh's hop ladder)."""
    body = (sig or {}).get("mesh") or {}
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def params_match(tuned_sig: Optional[Dict], live_sig: Dict) -> bool:
    """Whether only the params half (treedef + leaves) agrees."""
    return signatures_match(tuned_sig, live_sig, require_mesh=False)


def signatures_match(tuned_sig: Optional[Dict], live_sig: Dict,
                     require_mesh: bool = True) -> bool:
    """Whether a pinned signature covers the live program. Hash equality
    is the fast path; ``require_mesh=False`` compares only the params
    component (``DistributedOptimizer`` sees gradients but no mesh, so
    it cannot hold the tuning to the mesh half of the key)."""
    if not tuned_sig:
        return False
    if require_mesh:
        return tuned_sig.get("hash") == live_sig.get("hash")
    a = {"treedef": tuned_sig.get("treedef"),
         "leaves": tuned_sig.get("leaves")}
    b = {"treedef": live_sig.get("treedef"),
         "leaves": live_sig.get("leaves")}
    return a == b
