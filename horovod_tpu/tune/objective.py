"""Free (no-hardware) objectives for the compiled-path tuner.

Two cost models the repo already trusts, composed into one scalar score:

1. **Structural overlap** — the streamed path's group partition
   (``ops/fusion.plan_layer_groups``: the same DDP-style reverse-order
   packing ``stream_param_groups`` performs at trace time) gives the
   independent-AR-group count, and the overlappable-compute staircase:
   group ``i`` (reduction order) can hide its transfer behind the
   backward compute of every group still to come. This is the pure-
   python form of what ``tools/tpu_profile_overlap.py --structural``
   measures from HLO — the group partition IS the independent-collective
   structure the HLO analysis counts.
2. **Compositor pricing** — each group's packed payload is priced by the
   topology compositor's exact alpha-beta cost model
   (``topo.compositor.candidate_plans`` / ``select_plan``), honoring the
   pinned topology algorithm and wire dtype.

The scalar the GP maximizes is ``-exposed_us``: per group, the modeled
collective cost discounted by the fraction of backward compute available
to hide it (``cost_us_i * (1 - overlappable_i / total)``), summed. More
groups ⇒ earlier wire starts ⇒ more hiding; cheaper plans / int8 wire ⇒
less to hide. A measured step time (when a backend is reachable) can be
mixed in by the caller via ``measured_us`` — the free model stays the
inner loop either way (HiCCL's framing: the analytic model is the
trustworthy stand-in when hardware is scarce).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..common.quant import WIRE_F32, WIRE_INT8
from ..common.types import ReduceOp


@dataclass(frozen=True)
class ProgramSpec:
    """The abstract training program the tuner scores: top-level layer
    granularity (name, gradient bytes) in FORWARD order — exactly the
    granularity ``stream_param_groups`` partitions at."""

    name: str
    layers: Tuple[Tuple[str, int], ...]
    signature: Dict = field(default_factory=dict)

    @property
    def layer_bytes(self) -> List[int]:
        return [int(b) for _, b in self.layers]

    @property
    def total_bytes(self) -> int:
        return sum(self.layer_bytes)


def _bottleneck_hop(model):
    return min(model.hops, key=lambda h: h.bandwidth_gbps)


def calibrated_model(model, calibration, where: str = "tune"):
    """Resolve a ``calibration`` argument (Calibration / path / dict /
    None = ``HOROVOD_CALIBRATION_FILE``) and apply it to ``model`` with
    the hop-ladder staleness discipline (``sim/calibrate.py``: a stale
    signature warns loudly and keeps generation defaults). Returns
    ``(model, info)`` where ``info`` records what was applied — the
    provenance block ``tuned.json`` carries so a tuning pinned on
    measured constants says so."""
    from ..sim.calibrate import apply_calibration, resolve_calibration

    calib = resolve_calibration(calibration)
    if calib is None:
        return model, {"applied": False, "source": "generation-defaults"}
    patched = apply_calibration(model, calib, where=where)
    return patched, {
        "applied": patched is not model,
        "source": "calibration.json",
        "signature": calib.signature_hash,
        "stale": patched is model,
    }


def plan_for_bucket(model, nbytes: int, config: Dict,
                    op: ReduceOp = ReduceOp.AVERAGE,
                    collective: str = "allreduce"):
    """The plan a bucket of ``nbytes`` would lower with under
    ``config``: the pinned algorithm when the compositor offers it at
    this payload, else the cost-selected plan (the same fallback the
    lowering performs). Returns ``(plan, pinned_honored)``.
    ``collective`` defaults to the allreduce fast path; the zero1
    objective prices ``"reducescatter"`` (int8-eligible) and
    ``"allgather"`` (always full precision — parameters)."""
    from ..topo.compositor import candidate_plans, select_plan

    wire = config.get("wire_dtype", WIRE_F32)
    if (
        op not in (ReduceOp.SUM, ReduceOp.AVERAGE)
        or collective == "allgather"
    ):
        wire = WIRE_F32
    algo = config.get("topo_algorithm") or "auto"
    if algo != "auto":
        cands = candidate_plans(model, collective, nbytes, op=op,
                                wire_dtype=wire)
        if algo in cands:
            return cands[algo], True
    return select_plan(model, collective, nbytes, op=op,
                       wire_dtype=wire), algo == "auto"


def free_objectives(spec: ProgramSpec, config: Dict, model,
                    op: ReduceOp = ReduceOp.AVERAGE,
                    zero1: bool = False,
                    calibration=None,
                    fixed_comm_us: float = 0.0) -> Dict:
    """Score ``config`` on ``spec`` over ``model`` with the two free
    cost models. Returns a plain dict (stable key order for the
    tuned.json record) whose ``score`` the GP maximizes.

    ``zero1=True`` prices the streamed-ZeRO-1 reduction shape: each
    group lowers as reduce-scatter (int8-eligible, hidden behind the
    backward staircase like the allreduce) plus the parameter
    all-gather of the 1/N shard (full precision — parameters; priced
    fully exposed, a conservative stand-in for next-forward overlap).
    This is what lets ``tuned.json`` stop exempting the zero1 mode.

    ``calibration`` (a ``calibration.json`` path, a
    ``sim.calibrate.Calibration``, or None = the
    ``HOROVOD_CALIBRATION_FILE`` knob) prices hops with MEASURED
    alpha-beta constants instead of generation defaults — the FlexLink
    discipline applied to the tuner's objective. A stale hop-ladder
    signature falls back loudly (``calibration.stale`` in the output).

    ``fixed_comm_us`` is the composed program's constant per-step
    communication term OUTSIDE the DP staircase — the tensor-parallel
    in-block psums (``sim.tp_fixed_comm_us``). It shifts every config's
    cost/exposed time identically (the argmax is knob-invariant by
    construction — TP psums are never re-planned), but keeps the
    recorded costs honest for the composed shape."""
    import math as _math

    from ..ops.fusion import plan_layer_groups

    calib_info = None
    if calibration is not None:
        model, calib_info = calibrated_model(
            model, calibration, where="free_objectives"
        )
    layer_bytes = spec.layer_bytes
    total = max(spec.total_bytes, 1)
    groups = plan_layer_groups(
        layer_bytes,
        int(config["fusion_threshold_bytes"]),
        int(config["first_bucket_bytes"]),
    )
    bneck = _bottleneck_hop(model).name
    per_group: List[Dict] = []
    cost_us = 0.0
    exposed_us = 0.0
    wire_bytes = 0
    remaining = total
    pinned_honored = True
    for gi, group in enumerate(groups):
        nb = sum(layer_bytes[i] for i in group)
        remaining -= nb
        if zero1:
            plan, honored = plan_for_bucket(
                model, nb, config, op=op, collective="reducescatter"
            )
            shard = _math.ceil(nb / max(model.size, 1))
            ag_plan, _ = plan_for_bucket(
                model, shard, config, op=op, collective="allgather"
            )
        else:
            plan, honored = plan_for_bucket(model, nb, config, op=op)
            ag_plan = None
        pinned_honored = pinned_honored and honored
        overlappable = remaining / total
        g_exposed = plan.cost_us * (1.0 - overlappable)
        g_wire = int(plan.bytes_per_hop.get(bneck, 0))
        g_cost = plan.cost_us
        if ag_plan is not None:
            g_cost += ag_plan.cost_us
            g_exposed += ag_plan.cost_us  # AG: conservatively exposed
            g_wire += int(ag_plan.bytes_per_hop.get(bneck, 0))
        cost_us += g_cost
        exposed_us += g_exposed
        wire_bytes += g_wire
        entry = {
            "group": gi,
            "layers": [spec.layers[i][0] for i in group],
            "nbytes": nb,
            "algorithm": plan.algorithm,
            "wire_dtype": plan.wire_dtype,
            "cost_us": round(plan.cost_us, 4),
            "overlappable_fraction": round(overlappable, 6),
            "bottleneck_bytes": g_wire,
        }
        if ag_plan is not None:
            entry["ag_algorithm"] = ag_plan.algorithm
            entry["ag_cost_us"] = round(ag_plan.cost_us, 4)
        per_group.append(entry)
    fixed = max(float(fixed_comm_us), 0.0)
    cost_us += fixed
    exposed_us += fixed
    if zero1:
        return {
            "zero1": True,
            **({"calibration": calib_info} if calib_info else {}),
            **({"fixed_comm_us": round(fixed, 4)} if fixed else {}),
            "n_groups": len(groups),
            "cost_us": round(cost_us, 4),
            "exposed_us": round(exposed_us, 4),
            "wire_bytes": int(wire_bytes),
            "bottleneck_hop": bneck,
            "pinned_honored": pinned_honored,
            "per_group": per_group,
            "score": round(-exposed_us, 6),
        }
    return {
        **({"calibration": calib_info} if calib_info else {}),
        **({"fixed_comm_us": round(fixed, 4)} if fixed else {}),
        "n_groups": len(groups),
        "cost_us": round(cost_us, 4),
        "exposed_us": round(exposed_us, 4),
        "wire_bytes": int(wire_bytes),
        "bottleneck_hop": bneck,
        "pinned_honored": pinned_honored,
        "per_group": per_group,
        # The GP maximizes this: hide-adjusted modeled communication
        # time, negated. Rounded so the score (and therefore the whole
        # sample trace) serializes byte-identically.
        "score": round(-exposed_us, 6),
    }


def group_plans(spec: ProgramSpec, config: Dict, model,
                op: ReduceOp = ReduceOp.AVERAGE,
                zero1: bool = False,
                calibration=None) -> List:
    """The concrete compositor plans ``config`` pins for every stream
    group — the artifacts the symbolic verifier checks before the tuner
    is allowed to emit them. ``zero1=True`` yields the RS and AG plan
    for each group (interleaved, reduction order). ``calibration``
    follows :func:`free_objectives` (calibrated constants can flip a
    cost-selected algorithm, so the verified plans must come from the
    same model the objective priced)."""
    import math as _math

    from ..ops.fusion import plan_layer_groups

    if calibration is not None:
        model, _ = calibrated_model(
            model, calibration, where="group_plans"
        )
    layer_bytes = spec.layer_bytes
    groups = plan_layer_groups(
        layer_bytes,
        int(config["fusion_threshold_bytes"]),
        int(config["first_bucket_bytes"]),
    )
    plans = []
    for group in groups:
        nb = sum(layer_bytes[i] for i in group)
        if zero1:
            rs, _ = plan_for_bucket(
                model, nb, config, op=op, collective="reducescatter"
            )
            ag, _ = plan_for_bucket(
                model, _math.ceil(nb / max(model.size, 1)), config,
                op=op, collective="allgather",
            )
            plans.extend([rs, ag])
        else:
            plan, _ = plan_for_bucket(model, nb, config, op=op)
            plans.append(plan)
    return plans
