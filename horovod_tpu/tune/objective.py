"""Free (no-hardware) objectives for the compiled-path tuner.

Two cost models the repo already trusts, composed into one scalar score:

1. **Structural overlap** — the streamed path's group partition
   (``ops/fusion.plan_layer_groups``: the same DDP-style reverse-order
   packing ``stream_param_groups`` performs at trace time) gives the
   independent-AR-group count, and the overlappable-compute staircase:
   group ``i`` (reduction order) can hide its transfer behind the
   backward compute of every group still to come. This is the pure-
   python form of what ``tools/tpu_profile_overlap.py --structural``
   measures from HLO — the group partition IS the independent-collective
   structure the HLO analysis counts.
2. **Compositor pricing** — each group's packed payload is priced by the
   topology compositor's exact alpha-beta cost model
   (``topo.compositor.candidate_plans`` / ``select_plan``), honoring the
   pinned topology algorithm and wire dtype.

The scalar the GP maximizes is ``-exposed_us``: per group, the modeled
collective cost discounted by the fraction of backward compute available
to hide it (``cost_us_i * (1 - overlappable_i / total)``), summed. More
groups ⇒ earlier wire starts ⇒ more hiding; cheaper plans / int8 wire ⇒
less to hide. A measured step time (when a backend is reachable) can be
mixed in by the caller via ``measured_us`` — the free model stays the
inner loop either way (HiCCL's framing: the analytic model is the
trustworthy stand-in when hardware is scarce).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..common.quant import WIRE_F32, WIRE_INT8
from ..common.types import ReduceOp


@dataclass(frozen=True)
class TPTerm:
    """The composed DP x TP program's per-step tensor-parallel
    communication shape, declared so the tuner can price it PER CONFIG
    instead of taking a pre-computed constant: ``degree`` model-axis
    neighbours, ``psum_bytes`` activation payload per in-block psum,
    ``psums_per_step`` psums a step pays (forward AND backward
    conjugates), and ``compute_us`` — the matmul time adjacent to ONE
    psum, i.e. what the fused collective-matmul pair
    (docs/parallelism.md "Fused TP overlap") gets to hide its wire
    behind. ``tp_chunks == 0`` in a config prices the classic exposed
    psum (``sim.tp_fixed_comm_us``); ``tp_chunks >= 1`` prices the
    chunked ring pair via ``topo.compositor.collective_matmul_cost_us``.
    """

    degree: int
    psum_bytes: int
    psums_per_step: int = 1
    compute_us: float = 0.0

    def to_dict(self) -> Dict:
        return {
            "degree": int(self.degree),
            "psum_bytes": int(self.psum_bytes),
            "psums_per_step": int(self.psums_per_step),
            "compute_us": round(float(self.compute_us), 4),
        }


def tp_inner_model(model, degree: int):
    """A single-hop model of the TP axis: the innermost (ICI) hop's
    alpha-beta constants over ``degree`` neighbours — the model the
    fused collective-matmul plans are priced and verified on (the DP
    model's inner hop size is the data-local fanout, not the TP
    degree, so the size must be re-pinned)."""
    import dataclasses as _dc

    hop = model.hops[-1]
    return _dc.replace(model, hops=(_dc.replace(hop, size=int(degree)),))


def tp_term_us(model, tp: TPTerm, chunks: int = 0) -> Dict:
    """Price the per-step TP term under one chunk-count choice.

    ``chunks == 0`` is the classic exposed-psum ring constant
    (``sim.tp_fixed_comm_us`` — fully exposed, nothing overlaps);
    ``chunks >= 1`` replaces each psum with one all_gather_matmul +
    one matmul_reduce_scatter, each priced by the overlap-aware model
    ``cost = max(compute, wire) + ramp`` with half the psum's adjacent
    matmul time to hide behind — only the un-hideable remainder is
    charged. Returns ``{"mode", "chunks", "fixed_comm_us", ...}``."""
    n = int(tp.degree)
    if n <= 1 or int(tp.psum_bytes) <= 0 or int(tp.psums_per_step) <= 0:
        return {"mode": "none", "chunks": 0, "fixed_comm_us": 0.0}
    if int(chunks) <= 0:
        from ..sim.core import tp_fixed_comm_us

        return {
            "mode": "exposed-psum",
            "chunks": 0,
            "fixed_comm_us": tp_fixed_comm_us(
                model, int(tp.psum_bytes), n,
                psums_per_step=int(tp.psums_per_step),
            ),
        }
    from ..topo.compositor import collective_matmul_cost_us

    priced = collective_matmul_cost_us(
        tp_inner_model(model, n), int(tp.psum_bytes),
        chunks=int(chunks), compute_us=float(tp.compute_us) / 2.0,
    )
    fixed = round(
        2.0 * priced["exposed_us"] * int(tp.psums_per_step), 4
    )
    return {
        "mode": "collective_matmul",
        "chunks": int(chunks),
        "fixed_comm_us": fixed,
        "per_primitive": priced,
    }


@dataclass(frozen=True)
class ProgramSpec:
    """The abstract training program the tuner scores: top-level layer
    granularity (name, gradient bytes) in FORWARD order — exactly the
    granularity ``stream_param_groups`` partitions at."""

    name: str
    layers: Tuple[Tuple[str, int], ...]
    signature: Dict = field(default_factory=dict)

    @property
    def layer_bytes(self) -> List[int]:
        return [int(b) for _, b in self.layers]

    @property
    def total_bytes(self) -> int:
        return sum(self.layer_bytes)


def _bottleneck_hop(model):
    return min(model.hops, key=lambda h: h.bandwidth_gbps)


def calibrated_model(model, calibration, where: str = "tune"):
    """Resolve a ``calibration`` argument (Calibration / path / dict /
    None = ``HOROVOD_CALIBRATION_FILE``) and apply it to ``model`` with
    the hop-ladder staleness discipline (``sim/calibrate.py``: a stale
    signature warns loudly and keeps generation defaults). Returns
    ``(model, info)`` where ``info`` records what was applied — the
    provenance block ``tuned.json`` carries so a tuning pinned on
    measured constants says so."""
    from ..sim.calibrate import apply_calibration, resolve_calibration

    calib = resolve_calibration(calibration)
    if calib is None:
        return model, {"applied": False, "source": "generation-defaults"}
    patched = apply_calibration(model, calib, where=where)
    return patched, {
        "applied": patched is not model,
        "source": "calibration.json",
        "signature": calib.signature_hash,
        "stale": patched is model,
    }


def plan_for_bucket(model, nbytes: int, config: Dict,
                    op: ReduceOp = ReduceOp.AVERAGE,
                    collective: str = "allreduce"):
    """The plan a bucket of ``nbytes`` would lower with under
    ``config``: the pinned algorithm when the compositor offers it at
    this payload, else the cost-selected plan (the same fallback the
    lowering performs). Returns ``(plan, pinned_honored)``.
    ``collective`` defaults to the allreduce fast path; the zero1
    objective prices ``"reducescatter"`` (int8/bf16-eligible) and
    ``"allgather"`` (always full precision — parameters). The bf16
    rung is a pure cast, valid for any reduce op; int8's blockwise
    requantization needs SUM/AVERAGE."""
    from ..topo.compositor import candidate_plans, select_plan

    wire = config.get("wire_dtype", WIRE_F32)
    if collective == "allgather":
        wire = WIRE_F32
    elif (
        wire == WIRE_INT8
        and op not in (ReduceOp.SUM, ReduceOp.AVERAGE)
    ):
        wire = WIRE_F32
    algo = config.get("topo_algorithm") or "auto"
    if algo != "auto":
        cands = candidate_plans(model, collective, nbytes, op=op,
                                wire_dtype=wire)
        if algo in cands:
            return cands[algo], True
    return select_plan(model, collective, nbytes, op=op,
                       wire_dtype=wire), algo == "auto"


def free_objectives(spec: ProgramSpec, config: Dict, model,
                    op: ReduceOp = ReduceOp.AVERAGE,
                    zero1: bool = False,
                    calibration=None,
                    fixed_comm_us: float = 0.0,
                    tp: Optional[TPTerm] = None) -> Dict:
    """Score ``config`` on ``spec`` over ``model`` with the two free
    cost models. Returns a plain dict (stable key order for the
    tuned.json record) whose ``score`` the GP maximizes.

    ``zero1=True`` prices the streamed-ZeRO-1 reduction shape: each
    group lowers as reduce-scatter (int8-eligible, hidden behind the
    backward staircase like the allreduce) plus the parameter
    all-gather of the 1/N shard (full precision — parameters; priced
    fully exposed, a conservative stand-in for next-forward overlap).
    This is what lets ``tuned.json`` stop exempting the zero1 mode.

    ``calibration`` (a ``calibration.json`` path, a
    ``sim.calibrate.Calibration``, or None = the
    ``HOROVOD_CALIBRATION_FILE`` knob) prices hops with MEASURED
    alpha-beta constants instead of generation defaults — the FlexLink
    discipline applied to the tuner's objective. A stale hop-ladder
    signature falls back loudly (``calibration.stale`` in the output).

    ``fixed_comm_us`` is a caller-computed constant per-step
    communication term OUTSIDE the DP staircase; it shifts every
    config's cost/exposed time identically (knob-invariant). ``tp``
    (a :class:`TPTerm`) REPLACES that constant with a term priced per
    config from the config's own ``tp_chunks`` choice
    (:func:`tp_term_us`) — the fused collective-matmul path makes the
    TP term knob-DEPENDENT, so the argmax now weighs chunk count
    against the DP knobs. The two are mutually exclusive."""
    import math as _math

    from ..ops.fusion import plan_layer_groups

    if tp is not None and float(fixed_comm_us) > 0.0:
        raise ValueError(
            "pass either tp=TPTerm(...) (the TP term priced per config "
            "from its tp_chunks choice) or the legacy knob-invariant "
            "fixed_comm_us constant — not both"
        )
    calib_info = None
    if calibration is not None:
        model, calib_info = calibrated_model(
            model, calibration, where="free_objectives"
        )
    layer_bytes = spec.layer_bytes
    total = max(spec.total_bytes, 1)
    groups = plan_layer_groups(
        layer_bytes,
        int(config["fusion_threshold_bytes"]),
        int(config["first_bucket_bytes"]),
    )
    bneck = _bottleneck_hop(model).name
    per_group: List[Dict] = []
    cost_us = 0.0
    exposed_us = 0.0
    wire_bytes = 0
    remaining = total
    pinned_honored = True
    for gi, group in enumerate(groups):
        nb = sum(layer_bytes[i] for i in group)
        remaining -= nb
        if zero1:
            plan, honored = plan_for_bucket(
                model, nb, config, op=op, collective="reducescatter"
            )
            shard = _math.ceil(nb / max(model.size, 1))
            ag_plan, _ = plan_for_bucket(
                model, shard, config, op=op, collective="allgather"
            )
        else:
            plan, honored = plan_for_bucket(model, nb, config, op=op)
            ag_plan = None
        pinned_honored = pinned_honored and honored
        overlappable = remaining / total
        g_exposed = plan.cost_us * (1.0 - overlappable)
        g_wire = int(plan.bytes_per_hop.get(bneck, 0))
        g_cost = plan.cost_us
        if ag_plan is not None:
            g_cost += ag_plan.cost_us
            g_exposed += ag_plan.cost_us  # AG: conservatively exposed
            g_wire += int(ag_plan.bytes_per_hop.get(bneck, 0))
        cost_us += g_cost
        exposed_us += g_exposed
        wire_bytes += g_wire
        entry = {
            "group": gi,
            "layers": [spec.layers[i][0] for i in group],
            "nbytes": nb,
            "algorithm": plan.algorithm,
            "wire_dtype": plan.wire_dtype,
            "cost_us": round(plan.cost_us, 4),
            "overlappable_fraction": round(overlappable, 6),
            "bottleneck_bytes": g_wire,
        }
        if ag_plan is not None:
            entry["ag_algorithm"] = ag_plan.algorithm
            entry["ag_cost_us"] = round(ag_plan.cost_us, 4)
        per_group.append(entry)
    tp_info = None
    if tp is not None:
        tp_info = tp_term_us(model, tp, int(config.get("tp_chunks", 0)))
        fixed = float(tp_info["fixed_comm_us"])
    else:
        fixed = max(float(fixed_comm_us), 0.0)
    cost_us += fixed
    exposed_us += fixed
    if zero1:
        return {
            "zero1": True,
            **({"calibration": calib_info} if calib_info else {}),
            **({"tp": tp_info} if tp_info is not None else {}),
            **({"fixed_comm_us": round(fixed, 4)} if fixed else {}),
            "n_groups": len(groups),
            "cost_us": round(cost_us, 4),
            "exposed_us": round(exposed_us, 4),
            "wire_bytes": int(wire_bytes),
            "bottleneck_hop": bneck,
            "pinned_honored": pinned_honored,
            "per_group": per_group,
            "score": round(-exposed_us, 6),
        }
    return {
        **({"calibration": calib_info} if calib_info else {}),
        **({"tp": tp_info} if tp_info is not None else {}),
        **({"fixed_comm_us": round(fixed, 4)} if fixed else {}),
        "n_groups": len(groups),
        "cost_us": round(cost_us, 4),
        "exposed_us": round(exposed_us, 4),
        "wire_bytes": int(wire_bytes),
        "bottleneck_hop": bneck,
        "pinned_honored": pinned_honored,
        "per_group": per_group,
        # The GP maximizes this: hide-adjusted modeled communication
        # time, negated. Rounded so the score (and therefore the whole
        # sample trace) serializes byte-identically.
        "score": round(-exposed_us, 6),
    }


def group_plans(spec: ProgramSpec, config: Dict, model,
                op: ReduceOp = ReduceOp.AVERAGE,
                zero1: bool = False,
                calibration=None) -> List:
    """The concrete compositor plans ``config`` pins for every stream
    group — the artifacts the symbolic verifier checks before the tuner
    is allowed to emit them. ``zero1=True`` yields the RS and AG plan
    for each group (interleaved, reduction order). ``calibration``
    follows :func:`free_objectives` (calibrated constants can flip a
    cost-selected algorithm, so the verified plans must come from the
    same model the objective priced). The TP term's fused plans are
    listed separately (:func:`tp_group_plans`) — they verify on the
    TP-axis model, not this one."""
    import math as _math

    from ..ops.fusion import plan_layer_groups

    if calibration is not None:
        model, _ = calibrated_model(
            model, calibration, where="group_plans"
        )
    layer_bytes = spec.layer_bytes
    groups = plan_layer_groups(
        layer_bytes,
        int(config["fusion_threshold_bytes"]),
        int(config["first_bucket_bytes"]),
    )
    plans = []
    for group in groups:
        nb = sum(layer_bytes[i] for i in group)
        if zero1:
            rs, _ = plan_for_bucket(
                model, nb, config, op=op, collective="reducescatter"
            )
            ag, _ = plan_for_bucket(
                model, _math.ceil(nb / max(model.size, 1)), config,
                op=op, collective="allgather",
            )
            plans.extend([rs, ag])
        else:
            plan, _ = plan_for_bucket(model, nb, config, op=op)
            plans.append(plan)
    return plans


def tp_group_plans(config: Dict, model, tp: Optional[TPTerm]) -> Tuple:
    """The fused TP plans a config pins, with the model they verify on:
    ``(plans, tp_model)``. Empty when there is no TP term or the config
    keeps the classic exposed psum (``tp_chunks == 0`` — nothing fused,
    nothing new to verify; the psum is the long-standing flat ring).
    Both chunked flavors are listed — a step pays one all_gather_matmul
    AND one matmul_reduce_scatter per psum it replaces."""
    chunks = int(config.get("tp_chunks", 0))
    if tp is None or chunks <= 0 or int(tp.degree) <= 1:
        return (), None
    from ..topo.compositor import (
        COLLECTIVE_MATMUL_FLAVORS,
        collective_matmul_plan,
    )

    inner = tp_inner_model(model, int(tp.degree))
    plans = tuple(
        collective_matmul_plan(
            inner, flavor, int(tp.psum_bytes), chunks=chunks,
            compute_us=float(tp.compute_us) / 2.0,
        )
        for flavor in COLLECTIVE_MATMUL_FLAVORS
    )
    return plans, inner
