"""The offline GP/EI tuner for the compiled path, and the ``tuned.json``
artifact it emits.

The loop is the eager engine's (``cpp/src/autotune.cc``) transplanted to
trace-time knobs and free objectives: evaluate the untuned default
first (so "strictly better than default" is always measurable), seed a
few deterministic design points, then fit the GP and walk Expected
Improvement over the candidate grid until the sample budget is spent.
Scoring is the structural-overlap + compositor-cost objective
(``tune/objective.py``); a measured step time can be mixed in by
passing ``measure_fn`` when hardware is reachable.

Before a winner is pinned, every stream-group plan it implies is run
through the symbolic plan verifier (``analysis/plan_verify.py``) — a
tuner must never emit a ``tuned.json`` whose schedule cannot be proven
to realize the collective. Verification failures raise
:class:`TuneVerificationError` instead of writing output.

Everything is seeded and pure-python: two runs from the same inputs
produce byte-identical ``tuned.json`` files (asserted by
``make tune-smoke``).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..common.types import ReduceOp
from . import gp as _gp
from .objective import (
    ProgramSpec,
    TPTerm,
    free_objectives,
    group_plans,
    tp_group_plans,
)
from .signature import signature_hash
from .space import SearchSpace, space_for_model

TUNED_VERSION = 1


class TuneVerificationError(RuntimeError):
    """The winning configuration's plan failed symbolic verification;
    no ``tuned.json`` may be emitted."""

    def __init__(self, findings):
        self.findings = list(findings)
        lines = "\n".join(f"  {f.render()}" for f in self.findings[:8])
        super().__init__(
            f"refusing to pin a tuned configuration: "
            f"{len(self.findings)} plan-verification finding(s)\n{lines}"
        )


@dataclass
class TunedConfig:
    """A pinned compiled-path tuning: the knob values, the step
    signature they are valid for, and the evidence (chosen vs baseline
    objectives, sample history) that justified them."""

    knobs: Dict
    signature: Dict
    objectives: Dict
    baseline: Dict
    program: str = ""
    model: Dict = field(default_factory=dict)
    search: Dict = field(default_factory=dict)
    history: List[Dict] = field(default_factory=list)
    version: int = TUNED_VERSION

    def to_dict(self) -> Dict:
        return {
            "version": self.version,
            "program": self.program,
            "knobs": dict(self.knobs),
            "signature": dict(self.signature),
            "objectives": dict(self.objectives),
            "baseline": dict(self.baseline),
            "model": dict(self.model),
            "search": dict(self.search),
            "history": list(self.history),
        }

    def to_json(self) -> str:
        """Stable serialization — sorted keys, no timestamps — so the CI
        smoke can diff two tuner runs byte-for-byte."""
        return json.dumps(self.to_dict(), sort_keys=True, indent=1) + "\n"

    @staticmethod
    def from_dict(d: Dict) -> "TunedConfig":
        return TunedConfig(
            knobs=dict(d.get("knobs", {})),
            signature=dict(d.get("signature", {})),
            objectives=dict(d.get("objectives", {})),
            baseline=dict(d.get("baseline", {})),
            program=str(d.get("program", "")),
            model=dict(d.get("model", {})),
            search=dict(d.get("search", {})),
            history=list(d.get("history", [])),
            version=int(d.get("version", TUNED_VERSION)),
        )

    @property
    def signature_hash(self) -> str:
        h = self.signature.get("hash")
        return str(h) if h else signature_hash(self.signature)


def save_tuned(cfg: TunedConfig, path: str) -> str:
    with open(path, "w") as f:
        f.write(cfg.to_json())
    return path


def load_tuned(path: str) -> TunedConfig:
    with open(path) as f:
        return TunedConfig.from_dict(json.load(f))


def _round_x(x: Sequence[float]) -> List[float]:
    return [round(float(v), 6) for v in x]


def tune(
    spec: ProgramSpec,
    model,
    *,
    samples: int = 16,
    seed: int = 0,
    space: Optional[SearchSpace] = None,
    allow_int8: bool = True,
    op: ReduceOp = ReduceOp.AVERAGE,
    measure_fn: Optional[Callable[[Dict], float]] = None,
    rounds_fn: Optional[Callable] = None,
    verify: bool = True,
    zero1: bool = False,
    calibration=None,
    fixed_comm_us: float = 0.0,
    tp: Optional[TPTerm] = None,
) -> TunedConfig:
    """Search the joint compiled-path space for ``spec`` on ``model``.

    ``measure_fn(config) -> step_seconds`` (optional) mixes a measured
    objective into the score as ``-1e6 * step_seconds`` (microseconds,
    same unit as the modeled cost) — the free objectives still run so
    the emitted evidence block is always populated. ``rounds_fn`` is
    forwarded to the plan verifier (tests inject corrupted schedules
    through it). ``verify=False`` is for unit tests only.

    ``zero1=True`` tunes the streamed-ZeRO-1 reduction shape: groups
    are priced as per-bucket reduce-scatter + parameter all-gather
    (``free_objectives(zero1=True)``), "split" is dropped from the
    admissible topology choices, and the emitted RS/AG plans are the
    ones symbolically verified before pinning — this is what lets
    ``tuned.json`` stop exempting ``--zero1``.

    ``calibration`` (a ``calibration.json`` path / ``Calibration`` /
    None = the ``HOROVOD_CALIBRATION_FILE`` knob) prices the whole
    search — objectives, emitted plans, and the model recorded in
    ``tuned.json`` — with measured per-hop constants
    (``sim/calibrate.py``); a stale hop-ladder signature warns loudly
    and the search runs on generation defaults, recorded as such in
    ``search.calibration``.

    ``fixed_comm_us`` prices a caller-computed constant per-step
    communication term into every objective — knob-invariant by
    construction, recorded verbatim in ``search.fixed_comm_us``.
    ``tp`` (a :class:`TPTerm`) supersedes it: the TP term is then
    priced PER CONFIG from the config's own ``tp_chunks`` choice
    (``objective.tp_term_us`` — the classic exposed psum at 0, the
    fused collective-matmul pair above), the chunk-count dim joins the
    search, the winner's fused plans are symbolically verified on the
    TP-axis model, and ``search.fixed_comm_us`` records the WINNER's
    computed term instead of a caller constant.
    """
    from .objective import calibrated_model

    if tp is not None and float(fixed_comm_us) > 0.0:
        raise ValueError(
            "pass either tp=TPTerm(...) (priced per config) or the "
            "legacy fixed_comm_us constant — not both"
        )
    calib_info = {"applied": False, "source": "generation-defaults"}
    if calibration is not None:
        model, calib_info = calibrated_model(
            model, calibration, where="tune"
        )
    tp_active = tp is not None and int(tp.degree) > 1
    space = space or space_for_model(model, allow_int8=allow_int8,
                                     zero1=zero1, tp=tp_active)
    grid = space.candidate_grid()
    rng = _gp.Lcg(seed)
    samples = max(int(samples), 1)

    def evaluate(config: Dict) -> Tuple[Dict, float]:
        obj = free_objectives(spec, config, model, op=op, zero1=zero1,
                              fixed_comm_us=fixed_comm_us, tp=tp)
        score = obj["score"]
        if measure_fn is not None:
            measured_s = float(measure_fn(config))
            obj["measured_step_s"] = round(measured_s, 6)
            score = round(-1e6 * measured_s, 6)
            obj["score"] = score
        return obj, score

    xs: List[Tuple[float, ...]] = []
    ys: List[float] = []
    configs: List[Dict] = []
    objs: List[Dict] = []
    seen = set()

    def try_point(x: Tuple[float, ...]) -> None:
        config = space.validate(space.decode(x))
        key = tuple(_round_x(space.encode(config)))
        if key in seen:
            return
        seen.add(key)
        obj, score = evaluate(config)
        xs.append(key)
        ys.append(score)
        configs.append(config)
        objs.append(obj)

    # Sample 0 is ALWAYS the untuned default — the baseline every
    # improvement claim is measured against.
    default = space.default_config()
    try_point(space.encode(default))
    baseline = dict(objs[0])

    # Informed corners before the random design: the small-bucket corner
    # (more stream groups → earlier wire starts) and, when admissible,
    # the int8 default — each teaches the GP one knob axis, so even an
    # ~8-sample smoke budget explores every direction instead of
    # betting the whole budget on random grid cells.
    corners: List[Dict] = [dict(default)]
    corners[-1].update(fusion_threshold_bytes=2 << 20,
                       first_bucket_bytes=256 << 10)
    if space.allow_int8:
        corners.append(dict(default, wire_dtype="int8"))
        corners.append(dict(corners[0], wire_dtype="int8"))
    if getattr(space, "tp", False):
        # The mid-chunk fused corner — teaches the GP the chunk axis
        # against the default's classic exposed psum (tp_chunks=0).
        corners.append(dict(default, tp_chunks=2))
    for c in corners:
        if len(xs) >= samples:
            break
        try_point(space.encode(c))

    # A few seeded random design points before the GP has anything to
    # say (deterministic LCG — byte-stable across runs).
    n_seed = min(3, max(samples - len(xs), 0))
    guard = 0
    while len(xs) < 1 + len(corners) + n_seed and guard < 64:
        if len(xs) >= samples:
            break
        guard += 1
        try_point(grid[rng.next_index(len(grid))])

    while len(xs) < samples:
        model_gp = _gp.fit(xs, ys)
        if model_gp is None:
            break
        # Best unseen EI candidate (strict >, iteration order breaks
        # ties) — the C++ grid scan with a dedupe, since re-sampling a
        # deterministic objective teaches the GP nothing.
        best_ei, best_x = -1.0, None
        for c in grid:
            key = tuple(_round_x(
                space.encode(space.validate(space.decode(c)))
            ))
            if key in seen:
                continue
            ei = _gp.expected_improvement(model_gp, c)
            if ei > best_ei:
                best_ei, best_x = ei, c
        if best_x is None:
            break  # grid exhausted
        try_point(best_x)

    best_i = 0
    for i in range(1, len(ys)):
        if ys[i] > ys[best_i]:
            best_i = i
    best_config = configs[best_i]
    best_obj = objs[best_i]

    tp_plans, tp_model = tp_group_plans(best_config, model, tp)
    findings: List = []
    if verify:
        from ..analysis.plan_verify import verify_plan

        for plan in group_plans(spec, best_config, model, op=op,
                                zero1=zero1):
            findings.extend(verify_plan(plan, model, rounds_fn=rounds_fn))
        for plan in tp_plans:
            findings.extend(
                verify_plan(plan, tp_model, rounds_fn=rounds_fn)
            )
        if findings:
            raise TuneVerificationError(findings)

    history = [
        {"x": _round_x(x), "config": configs[i],
         "score": round(ys[i], 6)}
        for i, x in enumerate(xs)
    ]
    return TunedConfig(
        knobs=dict(best_config),
        signature=dict(spec.signature),
        objectives=best_obj,
        baseline=baseline,
        program=spec.name,
        model=model.to_dict(),
        search={
            "samples": len(xs),
            "requested_samples": samples,
            "seed": int(seed),
            "objective": "measured" if measure_fn is not None else "free",
            "zero1": bool(zero1),
            "calibration": calib_info,
            # With a TP term, this is the WINNER's computed per-step TP
            # time (its tp_chunks choice priced by tp_term_us) — no
            # longer a caller-supplied constant.
            "fixed_comm_us": (
                round(float(best_obj.get("tp", {})
                            .get("fixed_comm_us", 0.0)), 4)
                if tp is not None
                else round(max(float(fixed_comm_us), 0.0), 4)
            ),
            **({"tp": {**tp.to_dict(),
                       "chunks": int(best_config.get("tp_chunks", 0))}}
               if tp is not None else {}),
            "space": {
                "topo_choices": list(space.topo_choices),
                "allow_int8": bool(space.allow_int8),
                **({"tp": True} if getattr(space, "tp", False) else {}),
            },
            "verified_plans": 0 if not verify else len(
                group_plans(spec, best_config, model, op=op, zero1=zero1)
            ) + len(tp_plans),
        },
        history=history,
    )
