"""The joint compiled-path knob space the offline tuner searches.

Six dimensions, extending the eager engine's 2-continuous +
3-categorical shape (``cpp/src/autotune.cc`` — the golden-trace test
depends on the kernel treating the shared dims this way):

- ``x0`` — log2(HOROVOD_FUSION_THRESHOLD) in [16, 28], normalized to
  [0, 1] (the same range the eager tuner sweeps);
- ``x1`` — log2(HOROVOD_FUSION_FIRST_BUCKET_BYTES) in [12, 24],
  normalized (the streamed path's DDP-style small first bucket;
  together with x0 this determines the whole ``stream_param_groups``
  partition);
- ``x2``/``x3`` — the per-collective topology-plan choice for the
  gradient allreduce, two {0,1} embeddings encoding
  ``(auto, flat, two-level, split)``;
- ``x4`` — ``wire_dtype`` at thirds: f32 / bf16 / int8 (docs/overlap.md
  "Quantized wire compression"; the bf16 rung is a pure cast, always
  admissible — int8 stays behind ``allow_int8``);
- ``x5`` — the tensor-parallel chunk count for the fused
  collective-matmul path (docs/parallelism.md "Fused TP overlap"):
  ``0`` = the classic exposed psum, then {1, 2, 4, 8} ring chunks.
  Only live when the program declares a TP term (``tp=True``) —
  otherwise frozen at 0 and absent from decoded configs, so DP-only
  tunings keep their exact historical knob dicts.

Categorical dims that the target topology cannot realize (two-level on a
single-hop model, int8 when the caller pins f32, TP chunks without a TP
term) are FROZEN at their default instead of dropped, exactly like the
C++ engine freezes the hierarchical dims when no (cross, local) grid
exists — the space stays 6-D, the candidate grid just never varies them.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..common.quant import WIRE_BF16, WIRE_DTYPES, WIRE_F32, WIRE_INT8

# log2 bounds, continuous dims (x0 matches autotune.cc kF0/kF1).
FUSION_LOG2_LO, FUSION_LOG2_HI = 16.0, 28.0
FIRST_LOG2_LO, FIRST_LOG2_HI = 12.0, 24.0

# Topology-plan choice encoded in (x2, x3). "auto" = per-bucket
# select_plan (the planner decides by payload); the rest pin one
# algorithm for every bucket.
TOPO_CHOICES: Tuple[str, ...] = ("auto", "flat", "two-level", "split")

# TP chunk-count choice encoded in x5. 0 = the classic exposed psum
# (no fusion); the rest are the fused collective-matmul ring's chunk
# counts (ops/collective_matmul.py caps at 8 — latency rounds scale
# linearly with chunks, so deeper pipelining stops paying).
TP_CHUNK_CHOICES: Tuple[int, ...] = (0, 1, 2, 4, 8)

# Grid resolution for the continuous dims (the C++ engine's 9x9 EI grid).
GRID_POINTS = 9

DEFAULT_FUSION_BYTES = 64 * 1024 * 1024
DEFAULT_FIRST_BUCKET_BYTES = 1024 * 1024


def _norm(log2v: float, lo: float, hi: float) -> float:
    return (min(max(log2v, lo), hi) - lo) / (hi - lo)


def _denorm_bytes(x: float, lo: float, hi: float) -> int:
    return int(round(2.0 ** (lo + min(max(x, 0.0), 1.0) * (hi - lo))))


@dataclass(frozen=True)
class SearchSpace:
    """The admissible slice of the 6-D space for one target topology.

    ``topo_choices`` lists the realizable plan choices (a single-hop
    model lowers natively whatever the label says, so only "auto" is
    offered there); ``allow_int8`` gates the top wire rung (SUM/AVERAGE
    float gradients only — and the tune-smoke pins it off so the tuned
    step stays bitwise-identical to the untuned one; the bf16 cast rung
    is always admissible); ``tp`` activates the TP chunk-count dim —
    only programs that declare a tensor-parallel term
    (``tune(tp=TPTerm(...))``) have anything for it to price."""

    topo_choices: Tuple[str, ...] = TOPO_CHOICES
    allow_int8: bool = True
    tp: bool = False
    dims: int = field(default=6, init=False)

    def encode(self, config: Dict) -> Tuple[float, ...]:
        import math

        topo = config.get("topo_algorithm") or "auto"
        idx = TOPO_CHOICES.index(topo) if topo in TOPO_CHOICES else 0
        wire = config.get("wire_dtype", WIRE_F32)
        chunks = int(config.get("tp_chunks", 0))
        ci = (TP_CHUNK_CHOICES.index(chunks)
              if chunks in TP_CHUNK_CHOICES else 0)
        return (
            _norm(math.log2(max(int(config["fusion_threshold_bytes"]), 1)),
                  FUSION_LOG2_LO, FUSION_LOG2_HI),
            _norm(math.log2(max(int(config["first_bucket_bytes"]), 1)),
                  FIRST_LOG2_LO, FIRST_LOG2_HI),
            float(idx & 1),
            float((idx >> 1) & 1),
            1.0 if wire == WIRE_INT8 else 0.5 if wire == WIRE_BF16
            else 0.0,
            ci / (len(TP_CHUNK_CHOICES) - 1.0),
        )

    def decode(self, x: Sequence[float]) -> Dict:
        idx = (1 if x[2] > 0.5 else 0) | ((1 if x[3] > 0.5 else 0) << 1)
        topo = TOPO_CHOICES[idx]
        if topo not in self.topo_choices:
            topo = "auto"
        if x[4] > 2.0 / 3.0:
            # Top rung falls back to the cast rung when int8 is pinned
            # off — bf16 is the strongest compression still admissible.
            wire = WIRE_INT8 if self.allow_int8 else WIRE_BF16
        elif x[4] > 1.0 / 3.0:
            wire = WIRE_BF16
        else:
            wire = WIRE_F32
        config = {
            "fusion_threshold_bytes": _denorm_bytes(
                x[0], FUSION_LOG2_LO, FUSION_LOG2_HI),
            "first_bucket_bytes": _denorm_bytes(
                x[1], FIRST_LOG2_LO, FIRST_LOG2_HI),
            "topo_algorithm": topo,
            "wire_dtype": wire,
        }
        if self.tp:
            x5 = float(x[5]) if len(x) > 5 else 0.0
            ci = int(round(
                min(max(x5, 0.0), 1.0) * (len(TP_CHUNK_CHOICES) - 1)
            ))
            config["tp_chunks"] = TP_CHUNK_CHOICES[ci]
        return config

    def default_config(self) -> Dict:
        config = {
            "fusion_threshold_bytes": DEFAULT_FUSION_BYTES,
            "first_bucket_bytes": DEFAULT_FIRST_BUCKET_BYTES,
            "topo_algorithm": "auto",
            "wire_dtype": WIRE_F32,
        }
        if self.tp:
            config["tp_chunks"] = 0
        return config

    def _cat_combos(self) -> List[Tuple[float, float, float, float]]:
        wires = (0.0, 0.5, 1.0) if self.allow_int8 else (0.0, 0.5)
        chunk_xs = (
            tuple(i / (len(TP_CHUNK_CHOICES) - 1.0)
                  for i in range(len(TP_CHUNK_CHOICES)))
            if self.tp else (0.0,)
        )
        combos: List[Tuple[float, float, float, float]] = []
        for idx, name in enumerate(TOPO_CHOICES):
            if name not in self.topo_choices:
                continue
            for wire in wires:
                for cx in chunk_xs:
                    combos.append(
                        (float(idx & 1), float((idx >> 1) & 1), wire, cx)
                    )
        return combos

    def candidate_grid(self) -> List[Tuple[float, ...]]:
        """The deterministic EI candidate grid: GRID_POINTS^2 continuous
        cells x the admissible categorical combinations, in a fixed
        iteration order (grid scan, then categories) so EI ties break
        identically on every run."""
        grid: List[Tuple[float, ...]] = []
        for gi in range(GRID_POINTS):
            for gj in range(GRID_POINTS):
                for cat in self._cat_combos():
                    grid.append((
                        gi / (GRID_POINTS - 1.0),
                        gj / (GRID_POINTS - 1.0),
                    ) + cat)
        return grid

    def validate(self, config: Dict) -> Dict:
        if config.get("wire_dtype", WIRE_F32) not in WIRE_DTYPES:
            raise ValueError(
                f"unknown wire_dtype {config.get('wire_dtype')!r}; one of "
                f"{WIRE_DTYPES}"
            )
        topo = config.get("topo_algorithm") or "auto"
        if topo not in TOPO_CHOICES:
            raise ValueError(
                f"unknown topo_algorithm {topo!r}; one of {TOPO_CHOICES}"
            )
        chunks = int(config.get("tp_chunks", 0))
        if chunks not in TP_CHUNK_CHOICES:
            raise ValueError(
                f"unknown tp_chunks {chunks!r}; one of {TP_CHUNK_CHOICES}"
            )
        return config


def space_for_model(model, allow_int8: bool = True,
                    zero1: bool = False, tp: bool = False) -> SearchSpace:
    """The admissible space for an interconnect model: single-hop models
    freeze the topology dims (every label lowers natively flat there);
    two-level models drop "split" unless the FlexLink conditions
    (exactly two hops) hold. ``zero1=True`` (the streamed-ZeRO-1
    reduction shape) additionally drops "split" everywhere — the
    FlexLink concurrent-bucket mode has no reduce-scatter + all-gather
    decomposition — so the tuner never pins an unrealizable plan for a
    zero1 program. ``tp=True`` (a program with a declared
    tensor-parallel term) unfreezes the TP chunk-count dim."""
    if model.levels <= 1:
        choices: Tuple[str, ...] = ("auto",)
    elif model.levels == 2:
        choices = TOPO_CHOICES
    else:
        choices = ("auto", "flat", "two-level")
    if zero1:
        choices = tuple(c for c in choices if c != "split")
    return SearchSpace(topo_choices=choices, allow_int8=bool(allow_int8),
                       tp=bool(tp))
