"""Pure-Python port of the native GP/EI engine (``cpp/src/autotune.cc``).

The eager runtime's autotuner is a dependency-free Gaussian-process
regressor with Expected-Improvement acquisition, re-implemented in C++
inside the native core. The compiled-path offline tuner
(``tune/tuner.py``, ``tools/autotune_compiled.py``) needs the SAME
machinery but runs on a laptop with no native core loaded, so this module
is a line-for-line port: RBF kernel with short length scales on the
continuous dims and a longer one on the categorical {0,1} embeddings,
a hand-rolled Cholesky solve (the design space is 5-D and sample counts
are tens), and EI maximized over a deterministic candidate grid.

Everything is plain Python floats — no numpy, no randomness — so two runs
from the same inputs produce BYTE-identical results, and the math agrees
with the C++ engine to float64 rounding (``tests/test_tune.py`` checks a
golden 5-D trace against an ``hvd_autotune_gp_probe`` build of
``autotune.cc`` itself).

Constants (``kLength``/``kCatLength``/``NOISE``/``XI``) deliberately
mirror ``autotune.cc``; changing one side without the other breaks the
golden-trace agreement test.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

# RBF length scales: continuous dims decorrelate quickly; a categorical
# flip is informative but should not decorrelate totally (autotune.cc
# kLength / kCatLength).
LENGTH = 0.25
CAT_LENGTH = 0.75

# Observation noise added to the kernel diagonal (autotune.cc kNoise) and
# the EI exploration margin (kXi).
NOISE = 0.05
XI = 0.01

# How many leading dims are continuous; the rest use CAT_LENGTH
# (autotune.cc hardcodes 2 continuous + 3 categorical).
N_CONTINUOUS = 2


def kernel(a: Sequence[float], b: Sequence[float],
           n_continuous: int = N_CONTINUOUS) -> float:
    d = 0.0
    for i, (ai, bi) in enumerate(zip(a, b)):
        ls = LENGTH if i < n_continuous else CAT_LENGTH
        d += (ai - bi) * (ai - bi) / (ls * ls)
    return math.exp(-d / 2.0)


def cholesky(a: List[float], n: int) -> bool:
    """In-place Cholesky of a row-major SPD matrix; False if not SPD."""
    for i in range(n):
        for j in range(i + 1):
            s = a[i * n + j]
            for k in range(j):
                s -= a[i * n + k] * a[j * n + k]
            if i == j:
                if s <= 0:
                    return False
                a[i * n + i] = math.sqrt(s)
            else:
                a[i * n + j] = s / a[j * n + j]
    return True


def chol_solve(L: Sequence[float], n: int, b: List[float]) -> List[float]:
    """Solve L L^T x = b in place given the Cholesky factor."""
    for i in range(n):
        s = b[i]
        for k in range(i):
            s -= L[i * n + k] * b[k]
        b[i] = s / L[i * n + i]
    for i in range(n - 1, -1, -1):
        s = b[i]
        for k in range(i + 1, n):
            s -= L[k * n + i] * b[k]
        b[i] = s / L[i * n + i]
    return b


def norm_cdf(z: float) -> float:
    return 0.5 * math.erfc(-z / math.sqrt(2.0))


def norm_pdf(z: float) -> float:
    return math.exp(-0.5 * z * z) / math.sqrt(2.0 * math.pi)


@dataclass
class GP:
    """A fitted GP over normalized observations. ``ys`` are raw scores;
    internally they are max-normalized and mean-centered exactly as the
    C++ Tune() step does, so posterior means are comparable across the
    two implementations."""

    xs: List[Tuple[float, ...]]
    L: List[float]
    alpha: List[float]
    fbest: float
    n_continuous: int = N_CONTINUOUS

    @property
    def n(self) -> int:
        return len(self.xs)


def fit(xs: Sequence[Sequence[float]], ys: Sequence[float],
        n_continuous: int = N_CONTINUOUS) -> Optional[GP]:
    """Fit K = k(X,X) + NOISE*I, alpha = K^-1 y (y mean-centered,
    max-normalized). Returns None when the Cholesky fails (degenerate
    duplicate designs) — the caller falls back to its best-known point,
    like the C++ engine's early return."""
    n = len(xs)
    if n == 0 or len(ys) != n:
        return None
    ymax = 1e-9
    for y in ys:
        ymax = max(ymax, y)
    yn = [y / ymax for y in ys]
    mean = sum(yn) / n
    yn = [y - mean for y in yn]
    pts = [tuple(float(v) for v in x) for x in xs]
    K = [0.0] * (n * n)
    for i in range(n):
        for j in range(n):
            K[i * n + j] = kernel(pts[i], pts[j], n_continuous)
        K[i * n + i] += NOISE
    L = list(K)
    if not cholesky(L, n):
        return None
    alpha = chol_solve(L, n, list(yn))
    return GP(xs=pts, L=L, alpha=alpha, fbest=max(yn),
              n_continuous=n_continuous)


def posterior(gp: GP, c: Sequence[float]) -> Tuple[float, float]:
    """Posterior (mean, variance) at candidate ``c`` (variance includes
    the NOISE prior term, matching autotune.cc)."""
    n = gp.n
    c = tuple(float(v) for v in c)
    k = [kernel(c, gp.xs[i], gp.n_continuous) for i in range(n)]
    mu = 0.0
    for i in range(n):
        mu += k[i] * gp.alpha[i]
    v = chol_solve(gp.L, n, list(k))
    var = kernel(c, c, gp.n_continuous) + NOISE
    for i in range(n):
        var -= k[i] * v[i]
    return mu, max(var, 1e-10)


def expected_improvement(gp: GP, c: Sequence[float]) -> float:
    mu, var = posterior(gp, c)
    sigma = math.sqrt(var)
    z = (mu - gp.fbest - XI) / sigma
    return (mu - gp.fbest - XI) * norm_cdf(z) + sigma * norm_pdf(z)


def ei_argmax(gp: GP, candidates: Sequence[Sequence[float]]) -> int:
    """Index of the EI-maximizing candidate; strict ``>`` comparison in
    iteration order makes ties deterministic (first wins), matching the
    C++ grid scan."""
    best_ei = -1.0
    best = 0
    for idx, c in enumerate(candidates):
        ei = expected_improvement(gp, c)
        if ei > best_ei:
            best_ei = ei
            best = idx
    return best


class Lcg:
    """Tiny deterministic PRNG (numerical-recipes LCG) for seeding the
    initial design — independent of Python's ``random`` so the sample
    sequence is byte-stable across interpreter versions."""

    def __init__(self, seed: int):
        self.state = (int(seed) ^ 0x9E3779B9) & 0xFFFFFFFF

    def next_u32(self) -> int:
        self.state = (1664525 * self.state + 1013904223) & 0xFFFFFFFF
        return self.state

    def next_index(self, n: int) -> int:
        return self.next_u32() % max(int(n), 1)
