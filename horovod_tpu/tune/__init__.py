"""Offline autotuner for the compiled path (docs/autotune.md
"Compiled-path offline tuning").

Where the native core's GP/EI engine (``cpp/src/autotune.cc``) tunes the
*eager* runtime online, this package tunes the *compiled* path offline:
``tools/autotune_compiled.py`` sweeps the joint trace-time knob space —
fusion threshold, streamed first-bucket size (together: the
``stream_param_groups`` partition), per-collective topology-plan choice,
and wire dtype — scored by free cost models (structural overlap +
compositor alpha-beta pricing) and optionally by measured step time,
then freezes the winner as a ``tuned.json`` keyed by an abstract step
signature.

Consumption: ``make_train_step(tuned=...)`` /
``DistributedOptimizer(tuned=...)`` (or the ``HOROVOD_TUNED_FILE`` knob)
apply the pinned knobs when the live program's signature matches; a
mismatch warns loudly and falls back to untuned defaults — stale knobs
are never applied silently.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Dict, Optional, Tuple

from .gp import GP, expected_improvement, fit, posterior  # noqa: F401
from .objective import (  # noqa: F401
    ProgramSpec,
    TPTerm,
    free_objectives,
    group_plans,
    plan_for_bucket,
    tp_group_plans,
    tp_term_us,
)
from .signature import (  # noqa: F401
    mesh_axes_hash,
    params_match,
    signature_hash,
    signatures_match,
    step_signature,
)
from .space import SearchSpace, space_for_model  # noqa: F401
from .tuner import (  # noqa: F401
    TunedConfig,
    TuneVerificationError,
    load_tuned,
    save_tuned,
    tune,
)

_logger = logging.getLogger("horovod_tpu")

# Record of the last tuned-config application attempt in this process —
# the compiled-path analogue of the eager verdict's ``tuned_flags``:
# {"source": "arg"|"file"|"env"|"none", "signature": hash, "matched":
# bool, "where": call site}. Surfaced as the ``hvd_tuned_info`` gauge
# (docs/metrics.md) and stamped into eager plan verdicts by
# ``core/xla_executor.py``.
_applied_info: Optional[Dict] = None


def resolve_tuned(tuned: Any) -> Tuple[Optional[TunedConfig], str]:
    """Resolve a ``tuned`` argument to ``(config, source)``:

    - a :class:`TunedConfig` passes through (source ``"arg"``);
    - a path string loads the file (source ``"file"``);
    - ``None`` consults ``HOROVOD_TUNED_FILE`` (source ``"env"``);
    - ``False`` (or an unset knob) disables tuning (source ``"none"``).

    An unreadable file raises for an explicit path argument but only
    warns for the env knob — a stale env var must not brick a job that
    never asked for tuning in code.
    """
    if tuned is False:
        return None, "none"
    if isinstance(tuned, TunedConfig):
        return tuned, "arg"
    if isinstance(tuned, dict):
        return TunedConfig.from_dict(tuned), "arg"
    if isinstance(tuned, (str, os.PathLike)):
        return load_tuned(os.fspath(tuned)), "file"
    if tuned is not None:
        raise TypeError(
            f"tuned= takes a TunedConfig, a tuned.json path, None, or "
            f"False; got {type(tuned).__name__}"
        )
    from ..common import env as _env

    path = os.environ.get(_env.HOROVOD_TUNED_FILE, "").strip()
    if not path:
        return None, "none"
    try:
        return load_tuned(path), "env"
    except Exception as e:  # noqa: BLE001 - env knob must not brick startup
        _logger.warning(
            "HOROVOD_TUNED_FILE=%s could not be loaded (%r); running "
            "untuned", path, e,
        )
        return None, "none"


def tuned_step_kwargs(cfg: TunedConfig) -> Dict:
    """The ``make_train_step`` knob values a pinned configuration maps
    to — by construction expressible by hand, so a tuned build is
    bitwise-identical to the same knobs passed explicitly:

    - ``fusion_threshold_bytes`` / ``first_bucket_bytes`` verbatim;
    - ``wire_dtype`` ``int8`` → ``quantized=True``;
    - topology choice: ``flat`` pins the flat lowering, ``two-level`` /
      ``split`` ride ``hierarchical="auto"`` with the algorithm pinned
      (``topo_algorithm=``), ``auto`` leaves per-bucket plan selection
      to the compositor. On a flat mesh ``"auto"`` resolves to flat, so
      a pin tuned for a hierarchical mesh can never force an
      unrealizable lowering (and the signature's mesh hash keeps it
      from being applied there in the first place);
    - ``tp_chunks`` (present only on TP-term tunings) → ``tp_overlap``:
      a fused pin (chunks >= 1) maps to ``tp_overlap=True``, the
      classic exposed psum to ``tp_overlap=False``; the chunk count
      itself rides ``HOROVOD_TP_OVERLAP_CHUNKS`` (the fused layers
      resolve it at trace time — docs/parallelism.md "Fused TP
      overlap").
    """
    knobs = cfg.knobs
    topo = knobs.get("topo_algorithm") or "auto"
    if topo == "flat":
        hierarchical: Any = False
        algorithm = None
    elif topo in ("two-level", "split"):
        hierarchical = "auto"
        algorithm = topo
    else:
        hierarchical = "auto"
        algorithm = None
    out = {
        "fusion_threshold_bytes": int(knobs["fusion_threshold_bytes"]),
        "first_bucket_bytes": int(knobs["first_bucket_bytes"]),
        "quantized": knobs.get("wire_dtype") == "int8",
        "hierarchical": hierarchical,
        "topo_algorithm": algorithm,
    }
    if "tp_chunks" in knobs:
        out["tp_overlap"] = int(knobs["tp_chunks"]) > 0
    return out


def note_applied(source: str, signature: str, matched: bool,
                 where: str) -> Dict:
    """Record (and gauge) a tuned-config application attempt."""
    global _applied_info
    _applied_info = {
        "source": str(source),
        "signature": str(signature or "-"),
        "matched": bool(matched),
        "where": str(where),
    }
    try:
        from .. import metrics as _metrics

        if _metrics.ACTIVE:
            _metrics.TAP.set(
                "hvd_tuned_info", 1.0,
                source=_applied_info["source"],
                signature=_applied_info["signature"],
                matched="1" if matched else "0",
                where=_applied_info["where"],
            )
    except Exception:  # noqa: BLE001 - metrics must never block a step build
        pass
    return _applied_info


def applied_tuned_info() -> Optional[Dict]:
    """The last tuned-application record in this process (None before
    any ``tuned=`` / ``HOROVOD_TUNED_FILE`` resolution)."""
    return _applied_info


def current_tuned_source() -> Dict:
    """What the compiled path is tuned from RIGHT NOW, for verdict
    stamping: the last application record if one exists, else the env
    knob's static promise, else ``none``."""
    if _applied_info is not None:
        return dict(_applied_info)
    from ..common import env as _env

    path = os.environ.get(_env.HOROVOD_TUNED_FILE, "").strip()
    if not path:
        return {"source": "none", "signature": "-", "matched": False,
                "where": "-"}
    try:
        cfg = load_tuned(path)
        sig = cfg.signature_hash
    except Exception:  # noqa: BLE001 - unreadable file still reports "env"
        sig = "-"
    return {"source": "env", "signature": sig, "matched": False,
            "where": "-"}


def warn_signature_mismatch(cfg: TunedConfig, live_hash: str,
                            where: str) -> None:
    _logger.warning(
        "tuned configuration (program %r, signature %s) does NOT match "
        "this step's signature %s at %s — the pinned knobs are stale "
        "for this program/mesh; FALLING BACK to untuned defaults. "
        "Re-run tools/autotune_compiled.py against the current program "
        "to refresh tuned.json.",
        cfg.program or "?", cfg.signature_hash, live_hash, where,
    )


def spec_from_params(name: str, params: Any, mesh: Any = None,
                     model: Any = None) -> ProgramSpec:
    """Build a :class:`ProgramSpec` (layer granularity + signature) from
    a real params pytree (arrays or ``ShapeDtypeStruct`` avals) — the
    same top-level-children split ``stream_param_groups`` partitions
    at, so the tuner scores exactly the groups the step would stream."""
    from ..ops.fusion import _top_level_children, _tree_bytes

    split = _top_level_children(params)
    if split is None:
        layers = [("params", _tree_bytes(params))]
    else:
        children, _ = split
        if isinstance(params, dict):
            names = [str(k) for k in params.keys()]
        else:
            names = [str(i) for i in range(len(children))]
        layers = [(n, _tree_bytes(c)) for n, c in zip(names, children)]
    return ProgramSpec(
        name=name,
        layers=tuple((n, int(b)) for n, b in layers),
        signature=step_signature(params, mesh=mesh, model=model),
    )
