"""Data-plane integrity guard (docs/fault_tolerance.md, "Data-plane
integrity").

PRs 2-4 hardened the control plane; this package defends the *data* plane:

- **Non-finite sentinels** around the gradient reduction
  (``HOROVOD_GUARD_NONFINITE=off|warn|zero|skip|abort``): a NaN/Inf
  produced on one rank is detected before (or as) it poisons every
  replica through the allreduce. ``zero`` sanitizes the bad entries
  locally before the wire; ``skip`` reaches cross-rank agreement on a
  skip-step flag so no rank applies a step another rank skipped;
  ``abort`` surfaces a named error the elastic layer can roll back from.
- **Periodic parameter-digest agreement**
  (``HOROVOD_GUARD_DIGEST_STEPS=N``): every N commits each rank hashes
  its tracked state, the digests are compared across ranks, and a
  mismatch self-heals — re-broadcast from the agreeing quorum's
  reference rank, or rollback to the last elastic commit when no quorum
  exists (``HOROVOD_GUARD_NO_QUORUM=rollback|root``).

Tap discipline — identical to ``fault/injector.py`` and ``metrics``:
with no guard knob set (the production default) the module-level
:data:`ACTIVE` flag is False, :data:`TAP` IS the shared no-op singleton
:data:`NULL_TAP`, and instrumented call sites skip the tap entirely
(``if _guard.ACTIVE: ...`` is the whole overhead).

Detections are counted as ``hvd_guard_*`` metrics (when the metrics tap
is live) and appended to the deterministic fault event log (site
``guard``), so seeded chaos runs can assert guard behavior
byte-for-byte.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, Optional

import numpy as np

logger = logging.getLogger("horovod_tpu.guard")

GUARD_NONFINITE_ENV = "HOROVOD_GUARD_NONFINITE"
GUARD_DIGEST_STEPS_ENV = "HOROVOD_GUARD_DIGEST_STEPS"
GUARD_NO_QUORUM_ENV = "HOROVOD_GUARD_NO_QUORUM"

NONFINITE_POLICIES = ("off", "warn", "zero", "skip", "abort")
NO_QUORUM_ACTIONS = ("rollback", "root")


def resolve_policy(explicit: Optional[str] = None) -> str:
    """Resolve the non-finite policy: explicit argument >
    ``HOROVOD_GUARD_NONFINITE`` > ``off``. Raises on unknown values —
    a typoed policy silently meaning "off" would be a disabled guard
    that looks enabled."""
    name = (explicit or os.environ.get(GUARD_NONFINITE_ENV, "")
            or "off").strip().lower()
    if name not in NONFINITE_POLICIES:
        raise ValueError(
            f"unknown {GUARD_NONFINITE_ENV} policy {name!r}; choose from "
            f"{NONFINITE_POLICIES}"
        )
    return name


def digest_steps() -> int:
    """Digest-agreement cadence in commits (0 = disabled)."""
    v = os.environ.get(GUARD_DIGEST_STEPS_ENV, "").strip()
    if not v:
        return 0
    try:
        return max(int(v), 0)
    except ValueError:
        logger.warning(
            "%s=%r is not an integer; digest agreement disabled",
            GUARD_DIGEST_STEPS_ENV, v,
        )
        return 0


def no_quorum_action() -> str:
    """What a digest mismatch with no agreeing majority does:
    ``rollback`` (default — restore the last elastic commit) or ``root``
    (trust the current sync root's replica and re-broadcast from it —
    the only heal available at 2 ranks, where one corruption can never
    be outvoted)."""
    name = (os.environ.get(GUARD_NO_QUORUM_ENV, "")
            or "rollback").strip().lower()
    if name not in NO_QUORUM_ACTIONS:
        logger.warning(
            "unknown %s %r; using 'rollback'", GUARD_NO_QUORUM_ENV, name
        )
        return "rollback"
    return name


def _count(name: str, value: float = 1.0, **labels) -> None:
    """Increment an hvd_guard_* metric when the metrics tap is live."""
    from .. import metrics as _metrics

    if _metrics.ACTIVE:
        _metrics.TAP.inc(name, value, **labels)


def record_guard_event(action: str, detail: str = "") -> None:
    """Append one guard detection to the deterministic fault event log
    (site ``guard``) — seeded chaos runs diff these across runs. Only
    recorded while a fault plan or event-log file is active: a long
    production run with a chatty policy must not grow the in-memory
    event list without bound."""
    from ..fault import injector as _injector

    if not (_injector.ACTIVE
            or os.environ.get(_injector.FAULT_EVENT_LOG_ENV, "")):
        return
    global _guard_event_hits
    with _event_lock:
        _guard_event_hits += 1
        hit = _guard_event_hits
    _injector.record_event("guard", hit, action, detail)


_event_lock = threading.Lock()
_guard_event_hits = 0


class GuardTap:
    """The live tap: eager payload sentinel + counters. Installed only
    while a guard knob is set; call sites gate on :data:`ACTIVE`."""

    def __init__(self, policy: str):
        self.policy = policy

    # --- eager non-finite sentinel (numpy-level, pre-wire) ---
    def check_payload(self, name: str, tensor: Any) -> Any:
        """Apply the non-finite policy to one eager reduction payload
        before it is enqueued. Returns the (possibly sanitized) tensor;
        raises ``HorovodInternalError`` under ``abort``. Non-float
        payloads pass through untouched."""
        if self.policy == "off" or tensor is None:
            return tensor
        dtype = getattr(tensor, "dtype", None)
        if dtype is None or not np.issubdtype(np.dtype(dtype), np.floating):
            return tensor
        arr = np.asarray(tensor)
        finite = np.isfinite(arr)
        if finite.all():
            return tensor
        n_bad = int(arr.size - int(finite.sum()))
        _count("hvd_guard_nonfinite_total", n_bad,
               policy=self.policy, path="eager")
        record_guard_event(
            f"nonfinite-{self.policy}", f"{name} n={n_bad}"
        )
        if self.policy == "abort":
            from .. import HorovodInternalError
            from .. import trace as _trace

            if _trace.ACTIVE:
                # Flight recorder (docs/timeline.md): persist the last
                # moments before the abort unwinds the submitter.
                _trace.TAP.flight_dump("guard-abort")
            raise HorovodInternalError(
                f"non-finite gradient guard (policy abort): tensor "
                f"'{name}' contains {n_bad} non-finite value(s); refusing "
                "to submit it to the collective"
            )
        if self.policy == "warn":
            logger.warning(
                "non-finite guard: tensor '%s' contains %d non-finite "
                "value(s); submitting anyway (policy warn)", name, n_bad,
            )
            return tensor
        # zero — and skip, which degrades to zero on the eager path: a
        # per-submission skip would strand peer ranks inside the
        # collective, and the step-level agreement the compiled path
        # uses has no eager analogue at enqueue granularity.
        if self.policy == "skip":
            logger.warning(
                "non-finite guard: policy 'skip' applies step-level "
                "agreement in the compiled path only; eager tensor '%s' "
                "is sanitized (zeroed) instead", name,
            )
        out = np.array(arr, copy=True)
        out[~finite] = 0
        return out


class _NullGuardTap:
    """Shared no-op tap installed while the guard is disabled."""

    policy = "off"

    def check_payload(self, name: str, tensor: Any) -> Any:
        return tensor


NULL_TAP = _NullGuardTap()

ACTIVE = False
TAP: Any = NULL_TAP

_lock = threading.Lock()


def install(policy: Optional[str] = None,
            digest: Optional[int] = None) -> None:
    """(De)activate the guard for this process. With both the policy
    ``off`` and the digest cadence 0 the no-op singleton is installed."""
    global ACTIVE, TAP
    pol = resolve_policy(policy)
    steps = digest_steps() if digest is None else max(int(digest), 0)
    with _lock:
        if pol == "off" and steps <= 0:
            TAP = NULL_TAP
            ACTIVE = False
        else:
            TAP = GuardTap(pol)
            ACTIVE = True


def activate_from_env() -> bool:
    """(Re)load the guard configuration from the environment."""
    install()
    return ACTIVE


def reset() -> None:
    global ACTIVE, TAP, _guard_event_hits
    with _lock:
        TAP = NULL_TAP
        ACTIVE = False
    with _event_lock:
        _guard_event_hits = 0


# Arm at import (mirrors fault/injector.py and metrics): worker processes
# spawned with guard knobs in their environment are protected without any
# code changes.
if (os.environ.get(GUARD_NONFINITE_ENV, "").strip()
        or os.environ.get(GUARD_DIGEST_STEPS_ENV, "").strip()):
    try:
        activate_from_env()
    except Exception:  # noqa: BLE001 - a malformed knob must not take
        # down production init; surfaced by the guard tools/tests.
        logger.exception("could not arm the data-plane guard from env")
