"""Trace-time non-finite sentinels for the compiled (jit) paths.

The compiled-mode wiring (``horovod_tpu.jax``) applies the policy around
the fused gradient reduction:

- ``zero``  — :func:`sanitize` the local gradients BEFORE the reduce, so
  one rank's NaN never reaches the wire and the healthy ranks'
  contributions survive;
- ``warn``  — detect on the reduced gradients and log via a host
  callback (observability only);
- ``skip``  — compute a local bad-flag, reach cross-rank agreement with
  :func:`agree_flag` (a tiny psum-max — the "agreement seam"), and have
  the step apply NO update on ANY rank when any rank saw a non-finite
  gradient;
- ``abort`` — same agreed flag, surfaced to the host wrapper which
  raises ``HorovodInternalError`` (the elastic layer rolls back).

Everything here is pure jax and safe to trace; the host-side callbacks
(:func:`note_detection`) only fire when a detection actually happened.
"""

from __future__ import annotations

import logging
import threading
from typing import Any

import jax
import jax.numpy as jnp

logger = logging.getLogger("horovod_tpu.guard")

# Per-thread trace ledger for the skip/abort agreement seam: the analysis
# lint (guard-skip-no-agreement) consumes it to catch a streamed-overlap
# step traced under policy "skip" that never emits the agreement
# collective — without the seam, ranks could disagree about skipping and
# deadlock/diverge. Mirrors ops/fusion._stream_trace.
_seam_trace = threading.local()


def _note_seam() -> None:
    d = getattr(_seam_trace, "n", 0)
    _seam_trace.n = d + 1


def take_seam_registrations() -> int:
    """Return and reset this thread's agreement-seam registration count
    since the last take (consumed once per step trace)."""
    n = getattr(_seam_trace, "n", 0)
    _seam_trace.n = 0
    return int(n)


def _float_leaves(tree: Any):
    return [
        l for l in jax.tree.leaves(tree)
        if jnp.issubdtype(jnp.result_type(l), jnp.floating)
    ]


def local_flag(tree: Any) -> jax.Array:
    """1.0 when any float leaf of ``tree`` holds a non-finite value on
    THIS rank, else 0.0 (float32 so it can ride a psum)."""
    leaves = _float_leaves(tree)
    if not leaves:
        return jnp.zeros((), jnp.float32)
    bad = [jnp.any(~jnp.isfinite(l)) for l in leaves]
    flag = bad[0]
    for b in bad[1:]:
        flag = jnp.logical_or(flag, b)
    return flag.astype(jnp.float32)


def sanitize(tree: Any) -> Any:
    """Replace non-finite entries of every float leaf with 0 (policy
    ``zero``). Non-float leaves pass through untouched."""
    def fix(l):
        if not jnp.issubdtype(jnp.result_type(l), jnp.floating):
            return l
        return jnp.where(jnp.isfinite(l), l, jnp.zeros_like(l))

    return jax.tree.map(fix, tree)


def agree_flag(flag: jax.Array, axis_name: Any) -> jax.Array:
    """Cross-rank agreement on the skip/abort flag: psum over the
    reduction axis (or axes) — nonzero on EVERY rank when ANY rank
    flagged, so no rank applies a step another rank skipped. This is the
    agreement seam the collective lint checks for under streamed
    overlap + policy skip."""
    _note_seam()
    axes = axis_name if isinstance(axis_name, (tuple, list)) else (axis_name,)
    out = flag
    for ax in axes:
        out = jax.lax.psum(out, ax)
    return (out > 0).astype(jnp.float32)


def note_detection(policy: str, path: str):
    """Host callback factory: increments the guard counters and appends a
    deterministic guard event when a trace-time detection fired. The
    callback body only runs when ``flag`` is nonzero at runtime."""
    from . import _count, record_guard_event

    def cb(flag):
        if not bool(flag):
            return
        _count("hvd_guard_nonfinite_total", 1.0, policy=policy, path=path)
        if policy == "skip":
            _count("hvd_guard_skipped_steps_total")
        record_guard_event(f"nonfinite-{policy}", path)
        if policy == "warn":
            logger.warning(
                "non-finite guard: non-finite gradients detected in the "
                "%s path (policy warn); the update proceeds", path,
            )
        elif policy == "skip":
            logger.warning(
                "non-finite guard: skipping this optimizer step on every "
                "rank (cross-rank agreed, %s path)", path,
            )

    def emit(flag):
        jax.debug.callback(cb, flag)

    return emit


def select_on_flag(flag: jax.Array, when_set: Any, when_clear: Any) -> Any:
    """Leaf-wise select between two same-structure pytrees on a scalar
    flag (used to keep params/opt-state unchanged on a skipped step)."""
    keep = flag > 0

    def pick(a, b):
        return jnp.where(keep, a, b)

    return jax.tree.map(pick, when_set, when_clear)
