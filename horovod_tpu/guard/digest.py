"""Parameter-digest agreement: detect silently diverged replicas.

Replicated data-parallel state must be bit-identical across ranks between
collectives; SDC (a flipped bit in HBM/host memory), nondeterministic
kernels, or a bad rejoin silently break that invariant and the divergence
compounds every step. The guard hashes each rank's tracked state every
``HOROVOD_GUARD_DIGEST_STEPS`` commits, allgathers the digests (a few
bytes — the payload never moves), and on mismatch:

- an agreeing STRICT MAJORITY exists → the outlier ranks are healed by
  re-broadcasting from the quorum's reference rank (its lowest member);
- no quorum (e.g. a 1-v-1 tie at 2 ranks) → ``HOROVOD_GUARD_NO_QUORUM``
  decides: ``rollback`` (default) raises so the elastic layer restores
  the last commit, ``root`` trusts the current sync root's replica.

The digest is SHA-256 over every array leaf's dtype/shape header and raw
bytes plus a canonical pickle of non-array attributes — a pure function
of the state, identical across ranks exactly when the state is.
"""

from __future__ import annotations

import hashlib
import pickle
from typing import Any, Dict, List, Optional, Sequence, Tuple


def strip_rank_local(tree: Any, specs: Any = None,
                     shard_axes: Sequence[str] = ("model",)) -> Any:
    """Drop tracked-but-RANK-LOCAL subtrees before digesting: the
    error-feedback residual of the quantized wire
    (``ops/quantized.EFState.residual``) legitimately differs across
    ranks — each rank compensates its own quantizer — so hashing it
    would make every digest check a false mismatch. The residual is
    still elastic state (snapshots/sync carry it); only the CROSS-RANK
    agreement ignores it. Everything under ``EFState.inner`` stays
    digest-tracked.

    Streamed-ZeRO-1 state (``parallel/zero.Zero1State``) is sharded BY
    DESIGN: each rank holds only its row of every stacked bucket state
    (and sharded EF residual), so the bytes intentionally diverge across
    ranks. The digest keeps the shard LAYOUT (dtype/shape headers per
    leaf — identical across ranks exactly when the partition is) and
    drops the bytes; a rank whose shard layout drifted still mismatches
    loudly.

    ``specs`` (docs/parallelism.md "Composed DP x TP fast path") is an
    optional PartitionSpec tree mirroring ``tree``: a leaf whose spec
    shards a dim over one of ``shard_axes`` is TENSOR-PARALLEL-sharded —
    each model rank legitimately holds a different shard — so its bytes
    are replaced with a layout token (dtype+shape+spec) and only the
    LAYOUT must agree across ranks. Without this, a composed mesh would
    false-positive a divergence heal on every digest check. A specs tree
    whose leaf count does not match ``tree``'s raises (a stale spec must
    never silently digest the wrong leaves)."""
    import jax

    from ..ops.quantized import EFState
    from ..parallel.zero import Zero1State

    if specs is not None:
        from ..analysis.sharding_rules import normalize_spec
        from jax.sharding import PartitionSpec as P

        leaves, treedef = jax.tree.flatten(tree)
        spec_leaves = jax.tree.leaves(
            specs, is_leaf=lambda x: isinstance(x, P)
        )
        if len(spec_leaves) != len(leaves):
            raise ValueError(
                f"sharding_specs tree has {len(spec_leaves)} leaves but "
                f"the tracked state has {len(leaves)} — stale spec; "
                f"rebuild it from the live step (step.sharding_specs)"
            )

        def tp_sharded(spec) -> bool:
            norm = normalize_spec(spec) or ()
            want = set(shard_axes)
            return any(bool(want.intersection(e)) for e in norm)

        import numpy as np

        out = []
        for leaf, spec in zip(leaves, spec_leaves):
            if tp_sharded(spec) and hasattr(leaf, "shape"):
                out.append(
                    f"tp-shard-layout:"
                    f"{np.dtype(getattr(leaf, 'dtype', type(leaf)))}"
                    f"{tuple(leaf.shape)}:{spec}"
                )
            else:
                out.append(leaf)
        tree = jax.tree.unflatten(treedef, out)

    def is_rank_local(node):
        return isinstance(node, (EFState, Zero1State))

    def strip(node):
        if isinstance(node, EFState):
            return {"inner": strip_rank_local(node.inner)}
        if isinstance(node, Zero1State):
            import numpy as np

            return {"zero1_shard_layout": [
                f"{np.dtype(getattr(l, 'dtype', type(l)))}"
                f"{tuple(getattr(l, 'shape', ()))}"
                for l in jax.tree.leaves(node)
            ]}
        return node

    return jax.tree.map(strip, tree, is_leaf=is_rank_local)


def tree_digest(tree: Any, _h=None) -> str:
    """SHA-256 hex digest of an array-leaf pytree (dtype + shape + raw
    bytes per leaf, in pytree order)."""
    import numpy as np

    import jax

    h = _h or hashlib.sha256()
    leaves = jax.tree.leaves(tree)
    host = jax.device_get(leaves)
    for leaf in host:
        arr = np.ascontiguousarray(np.asarray(leaf))
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    if _h is None:
        return h.hexdigest()
    return ""


def state_digest(state: Any, tracked: Optional[Sequence[str]] = None) -> str:
    """Digest an elastic ``State``'s tracked attributes: array-leaf
    pytrees hash by raw bytes, everything else by pickle (deterministic
    for the plain counters/containers states track).

    Composed DP x TP states set ``state.sharding_specs`` — a mapping of
    tracked-attr name to its PartitionSpec tree (the composed step's
    ``step.sharding_specs``) — so TP-sharded leaves digest per-shard
    (layout tracked, bytes not compared across the model axis)."""
    import jax

    keys = list(tracked if tracked is not None
                else getattr(state, "_tracked", []))
    spec_map = getattr(state, "sharding_specs", None) or {}
    h = hashlib.sha256()
    for k in sorted(keys):
        v = strip_rank_local(getattr(state, k, None),
                             specs=spec_map.get(k))
        h.update(k.encode())
        leaves = jax.tree.leaves(v)
        if leaves and all(hasattr(l, "shape") and hasattr(l, "dtype")
                          for l in leaves):
            tree_digest(v, _h=h)
        else:
            try:
                h.update(pickle.dumps(v, protocol=4))
            except Exception:  # noqa: BLE001 - unpicklable attr: hash repr
                h.update(repr(v).encode())
    return h.hexdigest()


def find_quorum(
    digests: Sequence[str], *, no_quorum: str = "rollback",
    sync_root: int = 0,
) -> Tuple[bool, Optional[int], List[int]]:
    """Decide what a set of per-rank digests means.

    Returns ``(ok, reference_rank, outlier_ranks)``:

    - all digests equal → ``(True, None, [])``;
    - a strict-majority group exists → ``(False, ref, outliers)`` where
      ``ref`` is the lowest rank of the majority and ``outliers`` every
      rank outside it;
    - no strict majority → with ``no_quorum='root'``,
      ``(False, sync_root, ranks disagreeing with sync_root)``; with
      ``'rollback'`` (default), ``(False, None, all ranks)`` — the
      caller must roll back, there is nothing trustworthy to heal from.
    """
    groups: Dict[str, List[int]] = {}
    for r, d in enumerate(digests):
        groups.setdefault(d, []).append(r)
    if len(groups) == 1:
        return True, None, []
    n = len(digests)
    majority = max(groups.values(), key=len)
    if len(majority) * 2 > n:
        ref = min(majority)
        outliers = sorted(set(range(n)) - set(majority))
        return False, ref, outliers
    if no_quorum == "root" and 0 <= sync_root < n:
        ref_digest = digests[sync_root]
        outliers = sorted(
            r for r in range(n) if digests[r] != ref_digest
        )
        return False, sync_root, outliers
    return False, None, list(range(n))
