"""horovod_tpu.mxnet — MXNet binding (gated).

The reference binds MXNet via its dependency engine
(``horovod/mxnet/mpi_ops.cc:132-207``). MXNet has been archived upstream
and is not present in this environment; the binding is gated on import and
raises a clear error with the migration path. The surface mirrors the
reference (``horovod/mxnet/__init__.py:40-108``) so a port is mechanical if
MXNet is installed.
"""

from __future__ import annotations

try:
    import mxnet  # noqa: F401

    _MXNET_AVAILABLE = True
except ImportError:
    _MXNET_AVAILABLE = False

if not _MXNET_AVAILABLE:
    _MSG = (
        "MXNet is not installed in this environment (the project was "
        "archived upstream). Use horovod_tpu.jax (recommended on TPU), "
        "horovod_tpu.torch, or horovod_tpu.tensorflow instead."
    )

    def __getattr__(name):  # noqa: D103
        raise ImportError(_MSG)
else:  # pragma: no cover - exercised only where mxnet exists
    import numpy as _np

    from .. import (  # noqa: F401
        Adasum, Average, Sum, init, is_initialized, local_rank, local_size,
        rank, shutdown, size,
    )
    from .. import allreduce as _allreduce_np
    from .. import broadcast as _broadcast_np

    def allreduce(tensor, average=True, name=None, prescale_factor=1.0,
                  postscale_factor=1.0):
        out = _allreduce_np(tensor.asnumpy(), average=average, name=name,
                            prescale_factor=prescale_factor,
                            postscale_factor=postscale_factor)
        return mxnet.nd.array(_np.asarray(out), ctx=tensor.context,
                              dtype=tensor.dtype)

    def broadcast(tensor, root_rank, name=None):
        out = _broadcast_np(tensor.asnumpy(), root_rank, name=name)
        return mxnet.nd.array(_np.asarray(out), ctx=tensor.context,
                              dtype=tensor.dtype)

    def broadcast_parameters(params, root_rank=0):
        if isinstance(params, dict):
            items = sorted(params.items())
        else:
            items = sorted(
                (name, p.data()) for name, p in params.items()
            )
        for name, p in items:
            p[:] = broadcast(p, root_rank, name=str(name))

    class DistributedOptimizer(mxnet.optimizer.Optimizer):
        """Wraps an mxnet optimizer; allreduces gradients before update
        (reference horovod/mxnet/__init__.py:40-75)."""

        def __init__(self, optimizer):
            self._optimizer = optimizer

        def __getattr__(self, item):
            return getattr(self._optimizer, item)

        def update(self, index, weight, grad, state):
            reduced = allreduce(grad, average=True, name=f"grad.{index}")
            self._optimizer.update(index, weight, reduced, state)

    class DistributedTrainer(mxnet.gluon.Trainer):
        """Gluon trainer that allreduces gradients in ``_allreduce_grads``
        (reference horovod/mxnet/__init__.py:76-108: overrides
        ``_allreduce_grads``; the optimizer's rescale_grad is divided by
        size so the reduced SUM becomes an average)."""

        def __init__(self, params, optimizer, optimizer_params=None):
            if isinstance(optimizer, DistributedOptimizer):
                optimizer = optimizer._optimizer
            super().__init__(
                params, optimizer, optimizer_params, kvstore=None
            )
            # gluon-internal attribute; guard against mxnet version drift.
            if hasattr(self, "_scale"):
                self._scale /= size()
            else:  # pragma: no cover - newer gluon keeps it on the optimizer
                self._optimizer.rescale_grad /= size()

        def _allreduce_grads(self):
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    for grad in param.list_grad():
                        grad[:] = allreduce(
                            grad, average=False,
                            name=f"gradient.{i}.{param.name}",
                        )

    def broadcast_object(obj, root_rank=0, name=None):
        """Object broadcast — delegates to the one core implementation
        (size broadcast + uint8 payload broadcast)."""
        from .. import broadcast_object as _bcast_obj

        return _bcast_obj(obj, root_rank=root_rank, name=name)
