"""Pass 3 — symbolic verification of compositor lowering plans.

PR 7's hierarchical schedules (``topo/compositor.py``) are verifiable
artifacts, not just runnable code (HiCCL's framing, PAPERS.md
arXiv:2408.05962): every :class:`~horovod_tpu.topo.compositor.Plan` is a
finite sequence of single-hop primitives whose combined effect must equal
the collective's spec. This module executes a plan *symbolically* — per
rank, an abstract buffer of ``(source_rank, segment)`` chunk sets — and
checks, with no jax import and no backend:

 - every stage names a real hop/axis of the model and a known primitive
   (:data:`RULE_PLAN_STAGE`);
 - the per-round ``ppermute`` schedules that ring/halving stages stand
   for (``topo.compositor.perm_rounds``) are complete bijections over
   their hop, and the declared round counts match
   (:data:`RULE_PLAN_BIJECTION` / :data:`RULE_PLAN_STAGE`);
 - each stage's declared ``bytes_on_wire`` matches the traffic the
   abstract state implies, to integer-rounding slack
   (:data:`RULE_PLAN_BYTES`);
 - the final abstract state equals the collective's spec — allreduce:
   every rank holds every segment with contributions from every rank;
   allgather / reduce-scatter / broadcast / alltoall likewise
   (:data:`RULE_PLAN_RESULT`).

``verify_plan_grid`` sweeps the whole ``candidate_plans`` grid (all
collectives x all candidate algorithms x the topo-smoke topology ladder)
— the CI stage that makes a corrupted schedule a lint failure instead of
a 2/4/8-rank execution flake.
"""

from __future__ import annotations

import math
from fractions import Fraction
from typing import (
    Any, Callable, Dict, FrozenSet, List, Optional, Sequence, Tuple,
)

from ..common.quant import (
    WIRE_BF16,
    WIRE_DTYPES,
    WIRE_F32,
    WIRE_INT8,
    bf16_wire_bytes,
    int8_wire_bytes,
)
from ..common.types import ReduceOp
from ..topo import compositor as _comp
from ..topo.compositor import Plan, Stage, perm_rounds, stage_kind
from ..topo.model import InterconnectModel, synthetic_model
from .findings import (
    Finding,
    RULE_PLAN_BIJECTION,
    RULE_PLAN_BYTES,
    RULE_PLAN_RESULT,
    RULE_PLAN_STAGE,
    SEVERITY_ERROR,
    apply_suppressions,
)

Coords = Tuple[int, ...]

# The topology ladder the CI smoke sweeps (mirrors tools/topo_smoke.py)
# plus payloads spanning latency-bound to bandwidth-bound selections.
DEFAULT_TOPOLOGIES: Tuple[Tuple[str, Dict[str, int]], ...] = (
    ("1-slice", dict(local=8)),
    ("2-slice", dict(local=4, cross=2)),
    ("4-slice", dict(local=2, cross=4)),
    ("2-pod", dict(local=2, cross=2, pod=2)),
)
DEFAULT_PAYLOADS: Tuple[int, ...] = (1024, 1 << 20, 64 << 20)
DEFAULT_OPS: Tuple[ReduceOp, ...] = (
    ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.MIN, ReduceOp.MAX,
    ReduceOp.PRODUCT,
)


def _digits(idx: int, sizes: Sequence[int]) -> Coords:
    out = []
    for s in reversed(sizes):
        out.append(idx % s)
        idx //= s
    return tuple(reversed(out))


def _index(digits: Coords, sizes: Sequence[int]) -> int:
    idx = 0
    for d, s in zip(digits, sizes):
        idx = idx * s + d
    return idx


def _all_coords(sizes: Sequence[int]) -> List[Coords]:
    coords: List[Coords] = [()]
    for s in sizes:
        coords = [c + (d,) for c in coords for d in range(s)]
    return coords


def _groups(coords: Sequence[Coords],
            levels: Tuple[int, ...]) -> List[List[Coords]]:
    """Partition the rank space into the groups a stage over ``levels``
    communicates within: ranks sharing every coordinate OUTSIDE the
    stage's levels."""
    by_key: Dict[Coords, List[Coords]] = {}
    lv = set(levels)
    for c in coords:
        key = tuple(d for i, d in enumerate(c) if i not in lv)
        by_key.setdefault(key, []).append(c)
    return list(by_key.values())


class _PlanChecker:
    """One plan's verification pass: accumulates findings, never raises."""

    def __init__(self, plan: Plan, model: InterconnectModel,
                 rounds_fn: Optional[Callable] = None):
        self.plan = plan
        self.model = _comp._effective_model(model)
        self.rounds_fn = rounds_fn or perm_rounds
        self.sizes = tuple(h.size for h in self.model.hops)
        self.n = 1
        for s in self.sizes:
            self.n *= s
        self.coords = _all_coords(self.sizes)
        self.findings: List[Finding] = []
        # Rounding slack between declared int()/ceil bookkeeping and the
        # exact Fraction accounting: bounded by one byte per level of
        # ceil-division plus the final truncation, scaled by group size.
        self.byte_tol = 8 + self.n

    # ----------------------------------------------------------- findings
    def _loc(self, i: int, stage: Stage) -> str:
        return (
            f"plan:{self.plan.collective}/{self.plan.algorithm}/"
            f"stage[{i}]:{stage.primitive}@{stage.hop}"
        )

    def _flag(self, rule: str, i: int, stage: Stage, msg: str,
              **details: Any) -> None:
        self.findings.append(Finding(
            rule=rule,
            severity=SEVERITY_ERROR,
            message=msg,
            location=self._loc(i, stage),
            details={
                "stage_index": i,
                "primitive": stage.primitive,
                "hop": stage.hop,
                "axis": stage.axis,
                **details,
            },
        ))

    def _flag_final(self, msg: str, **details: Any) -> None:
        self.findings.append(Finding(
            rule=RULE_PLAN_RESULT,
            severity=SEVERITY_ERROR,
            message=msg,
            location=(
                f"plan:{self.plan.collective}/{self.plan.algorithm}/final"
            ),
            details=details,
        ))

    # ------------------------------------------------------ stage helpers
    def _stage_levels(self, i: int, stage: Stage) -> Optional[Tuple[int, ...]]:
        if stage.hop == "-":
            return ()
        model_axes = tuple(h.axis for h in self.model.hops)
        # Exact single-hop match first: a collapsed ineligible model's
        # one hop legitimately carries a joined "cross+local" axis name.
        for lvl, h in enumerate(self.model.hops):
            if h.axis == stage.axis:
                if h.name != stage.hop:
                    self._flag(
                        RULE_PLAN_STAGE, i, stage,
                        f"stage rides axis {stage.axis!r} which belongs "
                        f"to hop {h.name!r}, not {stage.hop!r}",
                    )
                return (lvl,)
        axes = tuple(a for a in stage.axis.split("+") if a)
        if len(axes) > 1:
            if set(axes) != set(model_axes):
                self._flag(
                    RULE_PLAN_STAGE, i, stage,
                    f"flat stage spans axes {axes} but the model has "
                    f"{model_axes}",
                )
                return None
            return tuple(range(len(self.sizes)))
        self._flag(
            RULE_PLAN_STAGE, i, stage,
            f"stage axis {stage.axis!r} is not an axis of the model "
            f"(axes: {model_axes})",
        )
        return None

    def _group_size(self, levels: Tuple[int, ...]) -> int:
        g = 1
        for lvl in levels:
            g *= self.sizes[lvl]
        return g

    def _check_rounds_and_perm(self, i: int, stage: Stage, g: int) -> None:
        """Round-count + bijectivity checks for one stage over a group of
        size ``g``."""
        kind, variant, _ = stage_kind(stage.primitive)
        if variant in ("ring", "halving", "doubling"):
            rounds = self.rounds_fn(stage.primitive, g)
            if rounds is None:
                rounds = []
            for t, perm in enumerate(rounds):
                srcs = [s for s, _ in perm]
                dsts = [d for _, d in perm]
                ok = (
                    sorted(srcs) == list(range(g))
                    and sorted(dsts) == list(range(g))
                    and all(s != d or g == 1 for s, d in perm)
                )
                if not ok:
                    self._flag(
                        RULE_PLAN_BIJECTION, i, stage,
                        f"{variant} schedule round {t} is not a complete "
                        f"bijection over the hop (size {g}): "
                        f"sources {sorted(set(srcs))}, "
                        f"destinations {sorted(set(dsts))}",
                        round=t, group_size=g,
                    )
                    return
            if stage.rounds != len(rounds):
                self._flag(
                    RULE_PLAN_STAGE, i, stage,
                    f"declares {stage.rounds} rounds but the {variant} "
                    f"schedule over a size-{g} hop has {len(rounds)}",
                    expected_rounds=len(rounds), group_size=g,
                )
            return
        k = max(1, math.ceil(math.log2(max(g, 2))))
        if kind == "allreduce":
            expected = {2 * (g - 1), k}
        elif kind in ("reducescatter", "allgather", "alltoall"):
            expected = {g - 1, k}
        elif kind == "broadcast":
            expected = {k}
        else:
            return
        if g <= 1:
            expected |= {0}
        if stage.rounds not in expected:
            self._flag(
                RULE_PLAN_STAGE, i, stage,
                f"declares {stage.rounds} rounds; a {kind} over a "
                f"size-{g} hop realizes {sorted(expected)}",
                expected_rounds=sorted(expected), group_size=g,
            )

    def _check_bytes(self, i: int, stage: Stage, expected: Fraction,
                     allow_tree: Optional[Fraction] = None) -> None:
        declared = int(stage.bytes_on_wire)
        candidates = [expected]
        if allow_tree is not None:
            candidates.append(allow_tree)
        if getattr(stage, "wire_dtype", WIRE_F32) == WIRE_INT8:
            # Compressed stage: the symbolic state still moves the full-
            # precision payload; what the wire carries is its int8+scales
            # image. A stage claiming int8 with full-size bytes (or the
            # converse — small bytes without the wire_dtype marker, which
            # lands in the plain branch above) fails here.
            candidates = [
                Fraction(int8_wire_bytes(int(c))) for c in candidates
            ]
        elif getattr(stage, "wire_dtype", WIRE_F32) == WIRE_BF16:
            # The cast rung: the wire carries the payload's bf16 image —
            # two bytes per full-precision element, no scales.
            candidates = [
                Fraction(bf16_wire_bytes(int(c))) for c in candidates
            ]
        if any(abs(declared - c) <= self.byte_tol for c in candidates):
            return
        self._flag(
            RULE_PLAN_BYTES, i, stage,
            f"declares {declared} bytes on wire but the symbolic state "
            f"implies {int(candidates[0])}"
            + (f" (or {int(candidates[-1])} for a latency tree)"
               if allow_tree is not None else ""),
            declared_bytes=declared, expected_bytes=int(candidates[0]),
        )

    # -------------------------------------------------- reduction machine
    def _verify_reduction(self, stages: Sequence[Tuple[int, Stage]],
                          nbytes: int, want: str) -> None:
        """allreduce (`want='allreduce'`) and reduce-scatter
        (`want='reducescatter'`): per rank, segment -> contributing
        ranks. Segments are the ``n`` outer-major destination shards."""
        n = self.n
        state: Dict[Coords, Dict[int, FrozenSet[int]]] = {
            c: {seg: frozenset([_index(c, self.sizes)])
                for seg in range(n)}
            for c in self.coords
        }
        for i, stage in stages:
            kind, variant, _ = stage_kind(stage.primitive)
            if kind == "local":
                continue
            if kind not in ("allreduce", "reducescatter", "allgather"):
                self._flag(
                    RULE_PLAN_STAGE, i, stage,
                    f"unexpected primitive in an {want} schedule",
                )
                return
            levels = self._stage_levels(i, stage)
            if levels is None:
                return
            g = self._group_size(levels)
            self._check_rounds_and_perm(i, stage, g)
            frac = Fraction(nbytes)
            for group in _groups(self.coords, levels):
                segsets = {frozenset(state[c].keys()) for c in group}
                held = len(next(iter(segsets)))
                b_pre = Fraction(nbytes) * held / n
                if kind in ("allreduce", "reducescatter"):
                    if len(segsets) != 1:
                        self._flag(
                            RULE_PLAN_STAGE, i, stage,
                            f"group members disagree on held segments "
                            f"before a {kind} stage (SPMD asymmetry)",
                        )
                        return
                if kind == "allreduce":
                    frac = 2 * b_pre * (g - 1) / g if g else Fraction(0)
                    tree = b_pre
                    for seg in next(iter(segsets)):
                        merged = frozenset().union(
                            *(state[c][seg] for c in group)
                        )
                        for c in group:
                            state[c][seg] = merged
                elif kind == "reducescatter":
                    frac = b_pre * (g - 1) / g if g else Fraction(0)
                    tree = None
                    pre = {m: state[m] for m in group}
                    for c in group:
                        mine = tuple(c[lvl] for lvl in levels)
                        kept: Dict[int, FrozenSet[int]] = {}
                        for seg in pre[c]:
                            sd = _digits(seg, self.sizes)
                            if tuple(sd[lvl] for lvl in levels) == mine:
                                kept[seg] = frozenset().union(
                                    *(pre[m][seg] for m in group)
                                )
                        state[c] = kept
                else:  # allgather
                    frac = b_pre * (g - 1)
                    tree = None
                    union: Dict[int, FrozenSet[int]] = {}
                    ok = True
                    for c in group:
                        for seg, contrib in state[c].items():
                            if seg in union and union[seg] != contrib:
                                self._flag(
                                    RULE_PLAN_STAGE, i, stage,
                                    f"gather merges segment {seg} with "
                                    f"conflicting contribution sets",
                                    segment=seg,
                                )
                                ok = False
                            union[seg] = contrib
                    if not ok:
                        return
                    for c in group:
                        state[c] = dict(union)
            self._check_bytes(i, stage, frac, allow_tree=tree
                              if kind == "allreduce" else None)
        everyone = frozenset(range(n))
        for c in self.coords:
            r = _index(c, self.sizes)
            if want == "allreduce":
                missing = sorted(set(range(n)) - set(state[c]))
                if missing:
                    self._flag_final(
                        f"rank {r} is missing segments {missing} after "
                        f"the schedule (allreduce must leave the full "
                        f"buffer everywhere)", rank=r, missing=missing,
                    )
                    return
                for seg, contrib in state[c].items():
                    if contrib != everyone:
                        self._flag_final(
                            f"rank {r} segment {seg} only reduces "
                            f"contributions from ranks "
                            f"{sorted(contrib)}, not all {n}",
                            rank=r, segment=seg,
                            contributors=sorted(contrib),
                        )
                        return
            else:  # reducescatter
                if set(state[c]) != {r}:
                    self._flag_final(
                        f"rank {r} ends holding segments "
                        f"{sorted(state[c])}; reduce-scatter must leave "
                        f"exactly its own shard [{r}]",
                        rank=r, held=sorted(state[c]),
                    )
                    return
                if state[c][r] != everyone:
                    self._flag_final(
                        f"rank {r}'s shard only reduces contributions "
                        f"from ranks {sorted(state[c][r])}, not all {n}",
                        rank=r, contributors=sorted(state[c][r]),
                    )
                    return

    # --------------------------------------------------- movement machines
    def _verify_allgather(self, stages: Sequence[Tuple[int, Stage]],
                          nbytes: int) -> None:
        """Per rank: the set of source blocks held (plan nbytes is the
        per-rank shard size)."""
        state: Dict[Coords, FrozenSet[int]] = {
            c: frozenset([_index(c, self.sizes)]) for c in self.coords
        }
        for i, stage in stages:
            kind, _, _ = stage_kind(stage.primitive)
            if kind == "local":
                continue
            if kind != "allgather":
                self._flag(RULE_PLAN_STAGE, i, stage,
                           "unexpected primitive in an allgather schedule")
                return
            levels = self._stage_levels(i, stage)
            if levels is None:
                return
            g = self._group_size(levels)
            self._check_rounds_and_perm(i, stage, g)
            expected = Fraction(0)
            for group in _groups(self.coords, levels):
                counts = {len(state[c]) for c in group}
                if len(counts) != 1:
                    self._flag(
                        RULE_PLAN_STAGE, i, stage,
                        "group members hold unequal block counts before "
                        "a gather stage (SPMD asymmetry)",
                    )
                    return
                union = frozenset().union(*(state[c] for c in group))
                expected = Fraction(nbytes) * counts.pop() * (g - 1)
                for c in group:
                    state[c] = union
            self._check_bytes(i, stage, expected)
        everyone = frozenset(range(self.n))
        for c in self.coords:
            if state[c] != everyone:
                r = _index(c, self.sizes)
                self._flag_final(
                    f"rank {r} ends holding source blocks "
                    f"{sorted(state[c])}; allgather must deliver all "
                    f"{self.n}", rank=r, held=sorted(state[c]),
                )
                return

    def _verify_collective_matmul(
        self, stages: Sequence[Tuple[int, Stage]], nbytes: int
    ) -> None:
        """Fused TP primitive (``topo.compositor.collective_matmul_plan``):
        one direction stage per ring, each ``hops x chunks`` rounds of
        the same +-1 shift. Per rank, fwd hop k delivers the segment k
        behind (offset ``-k``), bwd hop k the segment k ahead (``+k``) —
        for all_gather_matmul those are activation chunks gathered, for
        matmul_reduce_scatter partial-product contributions reduced; the
        movement algebra is identical. Completeness: the offsets plus
        the rank's own segment must cover all ``n`` — a dropped chunk
        (short round tag) leaves a hole, doubled bytes break the exact
        ``nbytes*hops/n`` accounting, a corrupted round breaks
        bijectivity."""
        plan = self.plan
        algo, sep, tail = plan.algorithm.rpartition("-c")
        if not sep or not tail.isdigit() or algo not in getattr(
            _comp, "COLLECTIVE_MATMUL_FLAVORS",
            ("all_gather_matmul", "matmul_reduce_scatter"),
        ):
            self._flag_final(
                f"unknown collective_matmul algorithm "
                f"{plan.algorithm!r}; expected "
                f"'<flavor>-c<chunks>'",
            )
            return
        chunks = max(int(tail), 1)
        offsets = {0}
        g_seen: Optional[int] = None
        for i, stage in stages:
            kind, _, _ = stage_kind(stage.primitive)
            if kind == "local":
                continue
            if kind != "collmm":
                self._flag(
                    RULE_PLAN_STAGE, i, stage,
                    "unexpected primitive in a collective_matmul "
                    "schedule",
                )
                return
            levels = self._stage_levels(i, stage)
            if levels is None:
                return
            g = self._group_size(levels)
            if g_seen is None:
                g_seen = g
            elif g != g_seen:
                self._flag(
                    RULE_PLAN_STAGE, i, stage,
                    f"direction stages ride hops of different sizes "
                    f"({g_seen} vs {g})",
                )
                return
            self._check_rounds_and_perm(i, stage, g)
            base = stage.primitive
            if base.endswith("-ring"):
                base = base[: -len("-ring")]
            _, r = _comp._rounds_tag(base)
            if r is None or r <= 0 or r % chunks:
                self._flag(
                    RULE_PLAN_STAGE, i, stage,
                    f"round tag {r!r} is not a positive multiple of the "
                    f"chunk count ({chunks})",
                )
                return
            hops = r // chunks
            fwd = "_fwd" in stage.primitive
            for k in range(1, hops + 1):
                offsets.add((-k if fwd else k) % g)
            # Exact symbolic bytes: hops deliveries of the 1/g segment,
            # chunking is byte-invariant.
            self._check_bytes(i, stage, Fraction(nbytes * hops, g))
        if g_seen is None:
            if self.n > 1:
                self._flag_final(
                    "collective_matmul schedule moved nothing over "
                    f"{self.n} ranks",
                )
            return
        missing = sorted(set(range(g_seen)) - offsets)
        if missing:
            self._flag_final(
                f"chunked schedule leaves segment offsets {missing} "
                f"unreached (of {g_seen}) — each rank must see every "
                "chunk exactly once",
                missing_offsets=missing,
            )

    def _verify_broadcast(self, stages: Sequence[Tuple[int, Stage]],
                          nbytes: int) -> None:
        """Per rank: which of the root's L segments are held (L = inner
        size for scatter-allgather, else 1). Root is global rank 0 (the
        planning layer carries no root; lowering decomposes any)."""
        sa = self.plan.algorithm == "two-level-sa"
        L = self.sizes[-1] if sa and self.sizes else 1
        state: Dict[Coords, FrozenSet[int]] = {
            c: frozenset(range(L)) if _index(c, self.sizes) == 0
            else frozenset()
            for c in self.coords
        }
        inner_level = len(self.sizes) - 1
        for i, stage in stages:
            kind, variant, _ = stage_kind(stage.primitive)
            if kind == "local":
                continue
            if kind not in ("broadcast", "allgather"):
                self._flag(RULE_PLAN_STAGE, i, stage,
                           "unexpected primitive in a broadcast schedule")
                return
            levels = self._stage_levels(i, stage)
            if levels is None:
                return
            g = self._group_size(levels)
            self._check_rounds_and_perm(i, stage, g)
            k = max(1, math.ceil(math.log2(max(g, 2))))
            if kind == "broadcast":
                shard_stage = sa and inner_level not in levels
                for group in _groups(self.coords, levels):
                    donor = next(
                        c for c in group
                        if all(c[lvl] == 0 for lvl in levels)
                    )
                    moved = state[donor]
                    if shard_stage:
                        # Only the group's common inner-shard crosses the
                        # outer hop in scatter-allgather mode.
                        shard = group[0][inner_level]
                        moved = moved & frozenset([shard])
                    for c in group:
                        state[c] = state[c] | moved
                if shard_stage:
                    expected = Fraction(math.ceil(nbytes / L)) * k
                else:
                    expected = Fraction(nbytes) * k
            else:  # the reassembly allgather of two-level-sa
                for group in _groups(self.coords, levels):
                    union = frozenset().union(*(state[c] for c in group))
                    for c in group:
                        state[c] = union
                expected = Fraction(nbytes) * (g - 1) / g
            self._check_bytes(i, stage, expected)
        want = frozenset(range(L))
        for c in self.coords:
            if state[c] != want:
                r = _index(c, self.sizes)
                self._flag_final(
                    f"rank {r} never receives the full broadcast payload "
                    f"(holds {len(state[c])}/{L} shards) — a hole the "
                    f"lowered schedule would hang on",
                    rank=r, held=sorted(state[c]),
                )
                return

    def _verify_alltoall(self, stages: Sequence[Tuple[int, Stage]],
                         nbytes: int) -> None:
        """Per rank: the set of (source, destination) blocks held."""
        n = self.n
        state: Dict[Coords, FrozenSet[Tuple[int, int]]] = {
            c: frozenset(
                (_index(c, self.sizes), d) for d in range(n)
            )
            for c in self.coords
        }
        for i, stage in stages:
            kind, _, _ = stage_kind(stage.primitive)
            if kind == "local":
                continue
            if kind != "alltoall":
                self._flag(RULE_PLAN_STAGE, i, stage,
                           "unexpected primitive in an alltoall schedule")
                return
            levels = self._stage_levels(i, stage)
            if levels is None:
                return
            g = self._group_size(levels)
            self._check_rounds_and_perm(i, stage, g)
            new_state: Dict[Coords, set] = {c: set() for c in self.coords}
            for c in self.coords:
                for (s, d) in state[c]:
                    dd = _digits(d, self.sizes)
                    target = tuple(
                        dd[lvl] if lvl in levels else c[lvl]
                        for lvl in range(len(self.sizes))
                    )
                    new_state[target].add((s, d))
            counts = {c: len(v) for c, v in new_state.items()}
            if any(v != n for v in counts.values()):
                bad = next(c for c, v in counts.items() if v != n)
                self._flag(
                    RULE_PLAN_STAGE, i, stage,
                    f"exchange loses or duplicates blocks: rank "
                    f"{_index(bad, self.sizes)} holds "
                    f"{counts[bad]}/{n} after the stage",
                )
                return
            state = {c: frozenset(v) for c, v in new_state.items()}
            self._check_bytes(
                i, stage, Fraction(nbytes) * (g - 1) / g if g else
                Fraction(0),
            )
        for c in self.coords:
            r = _index(c, self.sizes)
            want = frozenset((s, r) for s in range(n))
            if state[c] != want:
                got_src = sorted(s for s, d in state[c] if d == r)
                self._flag_final(
                    f"rank {r} ends with blocks from sources {got_src} "
                    f"(and {len(state[c]) - len(got_src)} misrouted "
                    f"blocks); alltoall must deliver one block from "
                    f"every source", rank=r,
                )
                return

    # ---------------------------------------------------------------- run
    def run(self) -> List[Finding]:
        plan = self.plan
        if tuple(self.sizes) != tuple(plan.hop_sizes):
            self.findings.append(Finding(
                rule=RULE_PLAN_STAGE,
                severity=SEVERITY_ERROR,
                message=(
                    f"plan was selected for hop sizes {plan.hop_sizes} "
                    f"but the model has {tuple(self.sizes)}"
                ),
                location=f"plan:{plan.collective}/{plan.algorithm}",
            ))
            return self.findings
        for i, stage in enumerate(plan.stages):
            kind, _, _ = stage_kind(stage.primitive)
            if kind == "?":
                self._flag(
                    RULE_PLAN_STAGE, i, stage,
                    f"unknown stage primitive {stage.primitive!r}",
                )
                return self.findings
            wd = getattr(stage, "wire_dtype", WIRE_F32)
            if wd not in WIRE_DTYPES:
                self._flag(
                    RULE_PLAN_STAGE, i, stage,
                    f"unknown stage wire_dtype {wd!r}; one of "
                    f"{WIRE_DTYPES}",
                )
                return self.findings
            if wd == WIRE_INT8 and plan.op not in ("SUM", "AVERAGE"):
                self._flag(
                    RULE_PLAN_STAGE, i, stage,
                    f"int8 wire on a {plan.op} schedule: per-hop "
                    f"requantization accumulates in f32, which is only "
                    f"sound for additive reductions",
                )
                return self.findings
        plan_wire = getattr(plan, "wire_dtype", WIRE_F32)
        if (
            plan_wire in (WIRE_INT8, WIRE_BF16)
            and plan.stages
            and not any(
                getattr(s, "wire_dtype", WIRE_F32) == plan_wire
                for s in plan.stages
                if s.hop != "-"
            )
        ):
            # A plan CLAIMING a reduced wire must actually carry it
            # somewhere — otherwise its advertised bytes-on-wire savings
            # are fiction.
            self._flag_final(
                f"plan declares wire_dtype={plan_wire} but no stage "
                f"carries the {plan_wire} wire — reduced-precision "
                "savings claimed without a converting stage",
            )
            return self.findings
        if self.n > 1 and not plan.stages:
            self._flag_final(
                f"empty schedule over {self.n} ranks cannot realize "
                f"{plan.collective}",
            )
            return self.findings
        if plan.algorithm == "split":
            if sum(plan.split_bytes) != plan.nbytes:
                self._flag_final(
                    f"split buckets {plan.split_bytes} do not sum to the "
                    f"payload ({plan.nbytes} bytes)",
                )
                return self.findings
            for b, nb in enumerate(plan.split_bytes):
                bucket = [
                    (i, s) for i, s in enumerate(plan.stages)
                    if stage_kind(s.primitive)[2] == b
                ]
                stray = [
                    i for i, s in enumerate(plan.stages)
                    if stage_kind(s.primitive)[2] is None
                ]
                if stray:
                    s = plan.stages[stray[0]]
                    self._flag(
                        RULE_PLAN_STAGE, stray[0], s,
                        "split schedule contains a stage with no bucket "
                        "suffix",
                    )
                    return self.findings
                self._verify_reduction(bucket, nb, "allreduce")
            return self.findings
        stages = list(enumerate(plan.stages))
        if plan.collective == "allreduce":
            self._verify_reduction(stages, plan.nbytes, "allreduce")
        elif plan.collective == "reducescatter":
            self._verify_reduction(stages, plan.nbytes, "reducescatter")
        elif plan.collective == "allgather":
            self._verify_allgather(stages, plan.nbytes)
        elif plan.collective == "broadcast":
            self._verify_broadcast(stages, plan.nbytes)
        elif plan.collective == "alltoall":
            self._verify_alltoall(stages, plan.nbytes)
        elif plan.collective == "collective_matmul":
            self._verify_collective_matmul(stages, plan.nbytes)
        else:
            self._flag_final(
                f"unknown collective {plan.collective!r}",
            )
        return self.findings


def verify_plan(
    plan: Plan,
    model: InterconnectModel,
    *,
    rounds_fn: Optional[Callable] = None,
    suppress: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Symbolically execute ``plan`` against ``model`` and return the
    rule violations ([] when the schedule provably realizes the
    collective). ``rounds_fn`` overrides the ring/halving round expander
    (tests inject corrupted schedules through it)."""
    checker = _PlanChecker(plan, model, rounds_fn=rounds_fn)
    return apply_suppressions(checker.run(), suppress)


def verify_plan_grid(
    models: Optional[Sequence[Tuple[str, InterconnectModel]]] = None,
    payloads: Sequence[int] = DEFAULT_PAYLOADS,
    ops: Sequence[ReduceOp] = DEFAULT_OPS,
    *,
    suppress: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], int]:
    """Verify every candidate plan ``select_plan`` can emit across the
    topology grid. Returns ``(findings, plans_verified)``; the count is
    reported by the CLI so a silently-shrunken grid is visible."""
    if models is None:
        models = [
            (name, synthetic_model(generation="v5e", **sizes))
            for name, sizes in DEFAULT_TOPOLOGIES
        ]
    findings: List[Finding] = []
    verified = 0
    for topo_name, model in models:
        for collective in _comp.COLLECTIVES:
            op_list = ops if collective == "allreduce" else (ReduceOp.SUM,)
            for op in op_list:
                # Quantized (int8+scales) candidates exist for allreduce
                # SUM/AVERAGE; the bf16 cast rung exists for EVERY
                # collective and op. Sweep them alongside the f32 grid
                # so a corrupted reduced-wire byte declaration is a lint
                # failure too.
                wire_dtypes: Tuple[str, ...] = (WIRE_F32, WIRE_BF16)
                if collective in ("allreduce", "reducescatter") and op in (
                    ReduceOp.SUM, ReduceOp.AVERAGE
                ):
                    # Reduce-scatter joined the int8 grid with streamed
                    # ZeRO-1 (the gradient hop of the RS+AG
                    # decomposition).
                    wire_dtypes = (WIRE_F32, WIRE_BF16, WIRE_INT8)
                for wire_dtype in wire_dtypes:
                    for nbytes in payloads:
                        cands = _comp.candidate_plans(
                            model, collective, nbytes, op=op,
                            wire_dtype=wire_dtype,
                        )
                        for plan in cands.values():
                            fs = verify_plan(plan, model,
                                             suppress=suppress)
                            for f in fs:
                                f.location = f"{topo_name}/{f.location}"
                                f.details.setdefault("topology", topo_name)
                                f.details.setdefault("op", str(op))
                                f.details.setdefault(
                                    "wire_dtype", wire_dtype
                                )
                            findings.extend(fs)
                            verified += 1
        # The fused-TP collective_matmul plan kind (innermost hop):
        # both flavors, f32 + bf16 wire, the chunk counts the tuner
        # searches.
        for flavor in _comp.COLLECTIVE_MATMUL_FLAVORS:
            for wire_dtype in (WIRE_F32, WIRE_BF16):
                for chunks in (1, 2, 4):
                    for nbytes in payloads:
                        plan = _comp.collective_matmul_plan(
                            model, flavor, nbytes, chunks=chunks,
                            wire_dtype=wire_dtype,
                        )
                        fs = verify_plan(plan, model, suppress=suppress)
                        for f in fs:
                            f.location = f"{topo_name}/{f.location}"
                            f.details.setdefault("topology", topo_name)
                            f.details.setdefault("wire_dtype", wire_dtype)
                        findings.extend(fs)
                        verified += 1
    return findings, verified


# --- streamed ZeRO-1: the implied per-bucket RS+AG plan grid -----------------


def zero1_bucket_plans(
    model: InterconnectModel,
    bucket_bytes: Sequence[int],
    *,
    quantized: bool = False,
    op: ReduceOp = ReduceOp.SUM,
) -> List[Tuple[Plan, Plan]]:
    """The compositor plans a streamed-zero1 build implies, per bucket:
    the gradient reduce-scatter (int8 wire when ``quantized``) and the
    parameter all-gather of the 1/N shard that returns after the
    shard-local update. These are the artifacts the symbolic checker
    verifies before a zero1 configuration ships (the same gate
    ``verify_plan_grid`` provides for the allreduce paths)."""
    from ..common.quant import WIRE_INT8 as _I8

    plans: List[Tuple[Plan, Plan]] = []
    n = max(model.size, 1)
    for nb in bucket_bytes:
        rs = _comp.select_plan(
            model, "reducescatter", int(nb), op=op,
            wire_dtype=_I8 if quantized else WIRE_F32,
        )
        shard = math.ceil(int(nb) / n)
        ag = _comp.select_plan(model, "allgather", shard)
        plans.append((rs, ag))
    return plans


def verify_zero1_stream_plans(
    model: InterconnectModel,
    bucket_bytes: Sequence[int],
    *,
    quantized: bool = False,
    op: ReduceOp = ReduceOp.SUM,
    suppress: Optional[Sequence[str]] = None,
) -> Tuple[List[Finding], int]:
    """Symbolically verify every per-bucket RS and AG plan a
    streamed-zero1 build implies on ``model``. Returns
    ``(findings, plans_verified)``."""
    findings: List[Finding] = []
    verified = 0
    for rs, ag in zero1_bucket_plans(
        model, bucket_bytes, quantized=quantized, op=op
    ):
        for plan in (rs, ag):
            fs = verify_plan(plan, model, suppress=suppress)
            for f in fs:
                f.location = f"zero1/{f.location}"
                f.details.setdefault("zero1", True)
            findings.extend(fs)
            verified += 1
    return findings, verified
