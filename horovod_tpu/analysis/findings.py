"""Finding model shared by both analyzer passes.

A Finding is one rule violation with a stable, machine-readable shape: the
CLI emits findings as JSON with deterministic key order so CI diffs stay
meaningful, and the human renderer prints ``severity rule location message``
lines. Rule ids are the vocabulary of the suppression syntax
(``# hvd-analysis: ignore[rule-id]``) and of the docs in
``docs/static_analysis.md``.
"""

from __future__ import annotations

import contextlib
import fnmatch
import json
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

# --- rule ids (Pass 1: collective lint) ---
RULE_UNKNOWN_AXIS = "unknown-axis"
RULE_ORDER_MISMATCH = "cross-rank-order"
RULE_SIGNATURE_MISMATCH = "cross-rank-signature"
RULE_MISSING_COLLECTIVE = "cross-rank-missing"
RULE_PPERMUTE = "ppermute-non-bijective"
RULE_GROUP_DTYPE = "group-dtype-mismatch"
RULE_GROUP_BUDGET = "group-over-budget"
RULE_FUSION_BUDGET = "fusion-over-budget"
# DistributedOptimizer(overlap=True) around a model whose layers were never
# (or only partially) registered for streamed reduction — the silent
# fallback/unreduced-gradient hazard (docs/overlap.md).
RULE_OVERLAP_STREAMING = "overlap-no-streaming"
# Streamed-overlap step traced under HOROVOD_GUARD_NONFINITE=skip without
# the cross-rank skip-agreement collective (guard/nonfinite.agree_flag):
# ranks could disagree about skipping a step and silently diverge
# (docs/fault_tolerance.md "Data-plane integrity").
RULE_GUARD_SKIP_AGREEMENT = "guard-skip-no-agreement"

# --- rule ids (Pass 2: runtime thread-safety lint) ---
RULE_UNGUARDED = "unguarded-shared-state"

# --- rule ids (Pass 3: symbolic plan verifier) ---
# A compositor Plan stage that is malformed: unknown primitive, a hop/axis
# that does not exist on the model, an SPMD asymmetry (group members whose
# abstract buffers disagree where the schedule requires agreement), or a
# declared round count that does not match the stage's expanded schedule.
RULE_PLAN_STAGE = "plan-bad-stage"
# An expanded ppermute round of a ring/halving schedule is not a complete
# bijection over its hop (the silent-hang class jaxpr lint catches for
# traced ppermutes, applied to the *planned* schedule before any trace).
RULE_PLAN_BIJECTION = "plan-non-bijective-permute"
# A stage's declared bytes-on-wire deviates from the symbolically-derived
# traffic beyond integer-rounding slack.
RULE_PLAN_BYTES = "plan-bytes-mismatch"
# The final abstract state does not satisfy the collective's spec
# (allreduce: every rank holds the full reduction; allgather/
# reduce-scatter/broadcast/alltoall likewise).
RULE_PLAN_RESULT = "plan-wrong-result"

# --- rule ids (Pass 4: SPMD rank-divergence analyzer) ---
# A collective reached under control flow (cond/switch/while) whose
# predicate derives from axis_index over an axis the collective reduces
# over: ranks of one group can take different branches and deadlock
# (the Horovod coordination model's classic SPMD killer).
RULE_RANK_DIVERGENCE = "rank-divergent-collective"

# --- rule ids (Pass 5: mesh/sharding-rule validator) ---
RULE_SHARDING_UNKNOWN_AXIS = "sharding-unknown-axis"
RULE_SHARDING_DUP_AXIS = "sharding-duplicate-axis"
RULE_SHARDING_INDIVISIBLE = "sharding-non-divisible"
RULE_SHARDING_UNMATCHED = "sharding-unmatched-param"
RULE_SHARDING_SCALAR = "sharding-scalar-not-replicated"
RULE_SHARDING_BAD_RULE = "sharding-bad-rule"

ALL_RULES = (
    RULE_UNKNOWN_AXIS,
    RULE_ORDER_MISMATCH,
    RULE_SIGNATURE_MISMATCH,
    RULE_MISSING_COLLECTIVE,
    RULE_PPERMUTE,
    RULE_GROUP_DTYPE,
    RULE_GROUP_BUDGET,
    RULE_FUSION_BUDGET,
    RULE_OVERLAP_STREAMING,
    RULE_GUARD_SKIP_AGREEMENT,
    RULE_UNGUARDED,
    RULE_PLAN_STAGE,
    RULE_PLAN_BIJECTION,
    RULE_PLAN_BYTES,
    RULE_PLAN_RESULT,
    RULE_RANK_DIVERGENCE,
    RULE_SHARDING_UNKNOWN_AXIS,
    RULE_SHARDING_DUP_AXIS,
    RULE_SHARDING_INDIVISIBLE,
    RULE_SHARDING_UNMATCHED,
    RULE_SHARDING_SCALAR,
    RULE_SHARDING_BAD_RULE,
)


@dataclass
class Finding:
    rule: str
    severity: str
    message: str
    location: str = ""
    details: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        # Insertion order is the stable JSON key order.
        return {
            "rule": self.rule,
            "severity": self.severity,
            "location": self.location,
            "message": self.message,
            "details": {k: self.details[k] for k in sorted(self.details)},
        }

    def render(self) -> str:
        loc = f" {self.location}" if self.location else ""
        return f"{self.severity}[{self.rule}]{loc}: {self.message}"


class CollectiveSafetyError(RuntimeError):
    """Raised by the opt-in pre-flight (HOROVOD_TPU_STATIC_CHECKS=1) when a
    static check finds an error-severity problem before the collective is
    submitted/traced."""

    def __init__(self, findings: Sequence[Finding]):
        self.findings = list(findings)
        super().__init__(
            "collective-safety pre-flight failed:\n"
            + "\n".join(f"  {f.render()}" for f in self.findings)
        )


def sort_findings(findings: Sequence[Finding]) -> List[Finding]:
    """Deterministic order: errors first, then by rule, location, message."""
    sev_rank = {SEVERITY_ERROR: 0, SEVERITY_WARNING: 1}
    return sorted(
        findings,
        key=lambda f: (
            sev_rank.get(f.severity, 2), f.rule, f.location, f.message
        ),
    )


def findings_to_json(findings: Sequence[Finding], **extra: Any) -> str:
    ordered = sort_findings(findings)
    doc = {
        "findings": [f.to_dict() for f in ordered],
        "summary": {
            "total": len(ordered),
            "errors": sum(
                1 for f in ordered if f.severity == SEVERITY_ERROR
            ),
            "warnings": sum(
                1 for f in ordered if f.severity == SEVERITY_WARNING
            ),
        },
    }
    doc.update(extra)
    return json.dumps(doc, indent=2, sort_keys=False)


def errors(findings: Sequence[Finding]) -> List[Finding]:
    return [f for f in findings if f.severity == SEVERITY_ERROR]


# --- call-site suppressions -------------------------------------------------
#
# The AST pass suppresses with an in-source comment; jaxpr-level and
# divergence findings have no source line to hang a comment on — their
# "call site" is the lint/preflight call. A suppression spec is
# ``"rule-id"`` (everywhere) or ``"rule-id@location-glob"`` (only where
# the finding's location matches the fnmatch pattern), so one sanctioned
# false positive never forces a global rule disable. Specs come in via
# the ``suppress=`` kwarg on the analyzers or the :func:`suppressions`
# context manager (thread-local, nestable) around a lint/preflight call.

_suppress_local = threading.local()


def _parse_spec(spec: str) -> Tuple[str, str]:
    rule, _, loc = str(spec).partition("@")
    return rule.strip(), (loc.strip() or "*")


def _active_specs() -> List[Tuple[str, str]]:
    return list(getattr(_suppress_local, "stack", ()))


@contextlib.contextmanager
def suppressions(*specs: str):
    """Suppress matching findings from any analyzer run inside the block
    (the call-site analogue of ``# hvd-analysis: ignore[rule]``)."""
    parsed = [_parse_spec(s) for s in specs]
    stack = getattr(_suppress_local, "stack", [])
    _suppress_local.stack = stack + parsed
    try:
        yield
    finally:
        _suppress_local.stack = stack


def _suppressed(finding: Finding, specs: Iterable[Tuple[str, str]]) -> bool:
    for rule, loc in specs:
        if rule and rule != finding.rule:
            continue
        if fnmatch.fnmatchcase(finding.location or "", loc):
            return True
    return False


def apply_suppressions(
    findings: Sequence[Finding],
    suppress: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Filter ``findings`` through the explicit ``suppress`` specs plus
    any :func:`suppressions` context active on this thread."""
    specs = [_parse_spec(s) for s in (suppress or ())]
    specs.extend(_active_specs())
    if not specs:
        return list(findings)
    return [f for f in findings if not _suppressed(f, specs)]
