"""Finding model shared by both analyzer passes.

A Finding is one rule violation with a stable, machine-readable shape: the
CLI emits findings as JSON with deterministic key order so CI diffs stay
meaningful, and the human renderer prints ``severity rule location message``
lines. Rule ids are the vocabulary of the suppression syntax
(``# hvd-analysis: ignore[rule-id]``) and of the docs in
``docs/static_analysis.md``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Sequence

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

# --- rule ids (Pass 1: collective lint) ---
RULE_UNKNOWN_AXIS = "unknown-axis"
RULE_ORDER_MISMATCH = "cross-rank-order"
RULE_SIGNATURE_MISMATCH = "cross-rank-signature"
RULE_MISSING_COLLECTIVE = "cross-rank-missing"
RULE_PPERMUTE = "ppermute-non-bijective"
RULE_GROUP_DTYPE = "group-dtype-mismatch"
RULE_GROUP_BUDGET = "group-over-budget"
RULE_FUSION_BUDGET = "fusion-over-budget"
# DistributedOptimizer(overlap=True) around a model whose layers were never
# (or only partially) registered for streamed reduction — the silent
# fallback/unreduced-gradient hazard (docs/overlap.md).
RULE_OVERLAP_STREAMING = "overlap-no-streaming"
# Streamed-overlap step traced under HOROVOD_GUARD_NONFINITE=skip without
# the cross-rank skip-agreement collective (guard/nonfinite.agree_flag):
# ranks could disagree about skipping a step and silently diverge
# (docs/fault_tolerance.md "Data-plane integrity").
RULE_GUARD_SKIP_AGREEMENT = "guard-skip-no-agreement"

# --- rule ids (Pass 2: runtime thread-safety lint) ---
RULE_UNGUARDED = "unguarded-shared-state"

ALL_RULES = (
    RULE_UNKNOWN_AXIS,
    RULE_ORDER_MISMATCH,
    RULE_SIGNATURE_MISMATCH,
    RULE_MISSING_COLLECTIVE,
    RULE_PPERMUTE,
    RULE_GROUP_DTYPE,
    RULE_GROUP_BUDGET,
    RULE_FUSION_BUDGET,
    RULE_OVERLAP_STREAMING,
    RULE_GUARD_SKIP_AGREEMENT,
    RULE_UNGUARDED,
)


@dataclass
class Finding:
    rule: str
    severity: str
    message: str
    location: str = ""
    details: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        # Insertion order is the stable JSON key order.
        return {
            "rule": self.rule,
            "severity": self.severity,
            "location": self.location,
            "message": self.message,
            "details": {k: self.details[k] for k in sorted(self.details)},
        }

    def render(self) -> str:
        loc = f" {self.location}" if self.location else ""
        return f"{self.severity}[{self.rule}]{loc}: {self.message}"


class CollectiveSafetyError(RuntimeError):
    """Raised by the opt-in pre-flight (HOROVOD_TPU_STATIC_CHECKS=1) when a
    static check finds an error-severity problem before the collective is
    submitted/traced."""

    def __init__(self, findings: Sequence[Finding]):
        self.findings = list(findings)
        super().__init__(
            "collective-safety pre-flight failed:\n"
            + "\n".join(f"  {f.render()}" for f in self.findings)
        )


def sort_findings(findings: Sequence[Finding]) -> List[Finding]:
    """Deterministic order: errors first, then by rule, location, message."""
    sev_rank = {SEVERITY_ERROR: 0, SEVERITY_WARNING: 1}
    return sorted(
        findings,
        key=lambda f: (
            sev_rank.get(f.severity, 2), f.rule, f.location, f.message
        ),
    )


def findings_to_json(findings: Sequence[Finding], **extra: Any) -> str:
    ordered = sort_findings(findings)
    doc = {
        "findings": [f.to_dict() for f in ordered],
        "summary": {
            "total": len(ordered),
            "errors": sum(
                1 for f in ordered if f.severity == SEVERITY_ERROR
            ),
            "warnings": sum(
                1 for f in ordered if f.severity == SEVERITY_WARNING
            ),
        },
    }
    doc.update(extra)
    return json.dumps(doc, indent=2, sort_keys=False)


def errors(findings: Sequence[Finding]) -> List[Finding]:
    return [f for f in findings if f.severity == SEVERITY_ERROR]
