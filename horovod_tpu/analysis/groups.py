"""Grouped-allreduce / fusion-bucket static checks.

First-class groups (``hvd.grouped_allreduce``) are threshold-exempt: the
coordinator holds every member until the whole group is ready on every
rank, then fuses them into one plan per signature. Two latent hazards are
checkable before submission:

 - **mixed dtypes** split the group into one plan per signature, silently
   breaking the "one collective" expectation (and the fused-buffer
   bandwidth shape) — :data:`RULE_GROUP_DTYPE`;
 - **total size over the fusion-buffer budget** forces a carrier larger
   than the configured fusion buffer, the memory spike runtime fusion was
   designed to avoid — :data:`RULE_GROUP_BUDGET`.

The same check validates compiled-mode fusion bucket plans
(``ops/fusion.plan_buckets``) so a planner regression can never silently
produce an over-budget or mixed-dtype bucket.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from .findings import (
    Finding,
    RULE_GROUP_BUDGET,
    RULE_GROUP_DTYPE,
    SEVERITY_ERROR,
    SEVERITY_WARNING,
)


def _spec(tensor: Any) -> Tuple[str, int]:
    """(dtype, nbytes) of an array-like or an already-made spec tuple."""
    if isinstance(tensor, tuple) and len(tensor) == 2:
        return str(tensor[0]), int(tensor[1])
    import numpy as np

    dtype = getattr(tensor, "dtype", None)
    shape = getattr(tensor, "shape", None)
    if dtype is None or shape is None:
        arr = np.asarray(tensor)
        dtype, shape = arr.dtype, arr.shape
    size = 1
    for d in shape:
        size *= int(d)
    itemsize = getattr(dtype, "itemsize", None) or np.dtype(dtype).itemsize
    return str(dtype), size * itemsize


def check_group(
    tensors: Sequence[Any],
    *,
    threshold_bytes: Optional[int] = None,
    name: str = "group",
) -> List[Finding]:
    """Lint one declared collective group (tensors, arrays, or
    ``(dtype, nbytes)`` spec tuples)."""
    specs = [_spec(t) for t in tensors]
    findings: List[Finding] = []
    dtypes = sorted({d for d, _ in specs})
    loc = f"group:{name}"
    if len(dtypes) > 1:
        findings.append(
            Finding(
                rule=RULE_GROUP_DTYPE,
                severity=SEVERITY_ERROR,
                message=(
                    f"grouped collective '{name}' mixes dtypes {dtypes}: "
                    "the group will execute as one plan per dtype, not "
                    "one fused collective"
                ),
                location=loc,
                details={"dtypes": dtypes, "members": len(specs)},
            )
        )
    total = sum(nbytes for _, nbytes in specs)
    if threshold_bytes and total > threshold_bytes:
        findings.append(
            Finding(
                rule=RULE_GROUP_BUDGET,
                severity=SEVERITY_WARNING,
                message=(
                    f"grouped collective '{name}' totals {total} bytes, "
                    f"over the {threshold_bytes}-byte fusion-buffer "
                    "budget (groups are threshold-exempt, so the carrier "
                    "allocates the full size at once)"
                ),
                location=loc,
                details={
                    "total_bytes": total,
                    "threshold_bytes": threshold_bytes,
                    "members": len(specs),
                },
            )
        )
    return findings


def check_fusion_plan(
    leaves: Sequence[Any],
    threshold_bytes: int,
    *,
    name: str = "gradients",
) -> List[Finding]:
    """Validate what ``ops/fusion.plan_buckets`` would produce for a
    gradient pytree's leaves: every multi-leaf bucket must be single-dtype
    and within budget. (Single big leaves legally exceed the budget in a
    bucket of their own.)"""
    from ..ops.fusion import plan_buckets

    findings: List[Finding] = []
    buckets = plan_buckets(list(leaves), threshold_bytes)
    for bi, bucket in enumerate(buckets):
        if len(bucket) < 2:
            continue
        members = [leaves[i] for i in bucket]
        findings.extend(
            check_group(
                members,
                threshold_bytes=threshold_bytes,
                name=f"{name}.bucket{bi}",
            )
        )
    return findings
