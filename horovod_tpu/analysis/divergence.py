"""Pass 4 — SPMD rank-divergence analysis over jaxprs.

The Horovod paper's coordination model (arXiv:1802.05799) exists because
of one failure class: ranks disagreeing about *whether* to issue a
collective. Under XLA the SPMD program is identical on every rank, so the
only way ranks can diverge is data-dependent control flow on a
rank-dependent value — a collective inside a ``lax.cond`` / ``switch`` /
``while_loop`` whose predicate derives from ``axis_index``. One group
member takes the collective branch, its peers take the other, and every
rank deadlocks at scale (the stall inspector's ~60 s silence, caught here
at trace time).

The analysis is a taint-propagating abstract interpretation:

 - **sources** — ``axis_index(axis)`` taints its output with ``{axis}``;
 - **propagation** — any equation with a tainted operand taints its
   outputs with the union of operand taints, through ``pjit`` / ``scan``
   / ``shard_map`` / custom-vjp sub-jaxprs;
 - **convergence (the sanctioned seam)** — ``psum`` / ``pmax`` / ``pmin``
   / ``all_gather`` over an axis REMOVE that axis from the taint: after
   the reduction every member of the axis group holds the same value.
   This is exactly the guard package's skip-agreement pattern
   (``guard/nonfinite.agree_flag`` — a psum over the reduction axes), so
   guard-skip steps lint clean by construction;
 - **sinks** — a ``cond``/``switch`` whose predicate is tainted over axis
   A, or a ``while`` whose continuation predicate is, flags every
   collective in its branches/body that communicates over A
   (:data:`RULE_RANK_DIVERGENCE`). Divergence over a *disjoint* axis is
   fine: all members of the collective's group share the predicate value.

Wired into :func:`~horovod_tpu.analysis.jaxpr_lint.lint_step`, the CLI
``examples``/``divergence`` targets, and the preflight. Suppress a
sanctioned site with ``analysis.suppressions("rank-divergent-collective")``
or the ``suppress=`` kwarg (``docs/static_analysis.md``).
"""

from __future__ import annotations

from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Tuple

from .findings import (
    Finding,
    RULE_RANK_DIVERGENCE,
    SEVERITY_ERROR,
    apply_suppressions,
)
from .jaxpr_lint import COLLECTIVE_PRIMITIVES, _axis_names, _sub_jaxprs

Taint = FrozenSet[str]
_EMPTY: Taint = frozenset()

# Collectives whose *output* is uniform across the reduced axes: the
# convergence seam. ppermute/all_to_all/reduce_scatter outputs stay
# rank-dependent (each rank receives different data).
_CONVERGING = {"psum", "psum2", "pmax", "pmin", "all_gather"}


def _jaxpr_of(obj: Any) -> Any:
    return obj.jaxpr if hasattr(obj, "jaxpr") else obj


class _TaintEnv:
    """Var -> taint mapping keyed by object identity (jaxpr Vars are
    unique per jaxpr; Literals are always clean)."""

    def __init__(self) -> None:
        self._m: Dict[int, Taint] = {}

    def get(self, var: Any) -> Taint:
        return self._m.get(id(var), _EMPTY)

    def set(self, var: Any, taint: Taint) -> None:
        if taint:
            self._m[id(var)] = frozenset(taint)
        else:
            self._m.pop(id(var), None)


def _collect_collectives_shallow(
    jaxpr: Any, path: str
) -> List[Tuple[str, Tuple[str, ...], str]]:
    """Every collective (primitive, axes, path) inside ``jaxpr``,
    recursively — used to report what a tainted guard would strand."""
    jaxpr = _jaxpr_of(jaxpr)
    out: List[Tuple[str, Tuple[str, ...], str]] = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMITIVES and name != "axis_index":
            out.append((name, _axis_names(eqn.params), path))
        child = f"{path}/{name}" if path else name
        for value in eqn.params.values():
            for sub in _sub_jaxprs(value):
                out.extend(_collect_collectives_shallow(sub, child))
    return out


class _Analyzer:
    def __init__(self) -> None:
        self.findings: List[Finding] = []

    def _flag(self, guard: str, path: str, pred_taint: Taint,
              collectives: Sequence[Tuple[str, Tuple[str, ...], str]],
              ) -> None:
        for prim, axes, cpath in collectives:
            overlap = sorted(set(axes) & pred_taint)
            if not overlap:
                continue
            self.findings.append(Finding(
                rule=RULE_RANK_DIVERGENCE,
                severity=SEVERITY_ERROR,
                message=(
                    f"{COLLECTIVE_PRIMITIVES[prim]} over axis "
                    f"{overlap if len(overlap) > 1 else overlap[0]!r} is "
                    f"guarded by a {guard} whose predicate derives from "
                    f"axis_index over the same axis — group members can "
                    f"take different branches and deadlock every rank; "
                    f"converge the predicate first (psum it over the "
                    f"axis, the guard skip-agreement pattern) or lift "
                    f"the collective out of the branch"
                ),
                location=f"jaxpr:{cpath}/{prim}" if cpath
                else f"jaxpr:{prim}",
                details={
                    "guard": guard,
                    "guard_path": path,
                    "tainted_axes": sorted(pred_taint),
                    "collective_axes": list(axes),
                },
            ))

    def _run_jaxpr(self, jaxpr: Any, in_taints: Sequence[Taint],
                   path: str) -> List[Taint]:
        """Propagate taints through one (open) jaxpr; returns the taints
        of its outvars."""
        jaxpr = _jaxpr_of(jaxpr)
        env = _TaintEnv()
        for var, t in zip(jaxpr.invars, in_taints):
            env.set(var, t)
        for eqn in jaxpr.eqns:
            self._run_eqn(eqn, env, path)
        return [env.get(v) for v in jaxpr.outvars]

    def _invar_taints(self, eqn: Any, env: _TaintEnv) -> List[Taint]:
        return [env.get(v) for v in eqn.invars]

    def _run_eqn(self, eqn: Any, env: _TaintEnv, path: str) -> None:
        name = eqn.primitive.name
        ins = self._invar_taints(eqn, env)
        joined: Taint = frozenset().union(*ins) if ins else _EMPTY

        if name == "axis_index":
            axes = _axis_names(eqn.params)
            for v in eqn.outvars:
                env.set(v, frozenset(axes))
            return

        if name in _CONVERGING:
            axes = frozenset(_axis_names(eqn.params))
            # axis_index_groups restrict the agreement to subgroups; stay
            # conservative and keep the taint in that case.
            if eqn.params.get("axis_index_groups") is None:
                out_taint = joined - axes
            else:
                out_taint = joined
            for v in eqn.outvars:
                env.set(v, out_taint)
            return

        if name == "cond":
            self._run_cond(eqn, env, ins, path)
            return
        if name == "while":
            self._run_while(eqn, env, ins, path)
            return
        if name == "scan":
            self._run_scan(eqn, env, ins, path)
            return

        # Generic sub-jaxpr call (pjit, shard_map, closed_call,
        # custom_jvp/vjp, remat, ...): map operand taints through when
        # arities line up, else degrade to the joined taint.
        subs = [s for v in eqn.params.values() for s in _sub_jaxprs(v)]
        if subs:
            out_taints: Optional[List[Taint]] = None
            for sub in subs:
                sj = _jaxpr_of(sub)
                n_in = len(sj.invars)
                if n_in == len(ins):
                    sub_ins = ins
                elif n_in < len(ins):
                    # Leading operands are consts/tokens for some
                    # primitives; align from the right.
                    sub_ins = ins[len(ins) - n_in:]
                else:
                    sub_ins = list(ins) + [_EMPTY] * (n_in - len(ins))
                child = f"{path}/{name}" if path else name
                outs = self._run_jaxpr(sub, sub_ins, child)
                if out_taints is None:
                    out_taints = outs
                else:
                    out_taints = [
                        a | b for a, b in zip(out_taints, outs)
                    ]
            if out_taints is not None and len(out_taints) == len(
                eqn.outvars
            ):
                for v, t in zip(eqn.outvars, out_taints):
                    env.set(v, t)
                return
        for v in eqn.outvars:
            env.set(v, joined)

    def _run_cond(self, eqn: Any, env: _TaintEnv, ins: List[Taint],
                  path: str) -> None:
        branches = eqn.params.get("branches") or ()
        pred_taint = ins[0] if ins else _EMPTY
        child = f"{path}/cond" if path else "cond"
        if pred_taint:
            for br in branches:
                self._flag(
                    "cond/switch", child, pred_taint,
                    _collect_collectives_shallow(br, child),
                )
        op_ins = ins[1:]
        out_taints: Optional[List[Taint]] = None
        for br in branches:
            outs = self._run_jaxpr(br, op_ins, child)
            if out_taints is None:
                out_taints = outs
            else:
                out_taints = [a | b for a, b in zip(out_taints, outs)]
        for v, t in zip(eqn.outvars, out_taints or []):
            # Branch selection on a tainted predicate taints the result.
            env.set(v, t | pred_taint)

    def _run_while(self, eqn: Any, env: _TaintEnv, ins: List[Taint],
                   path: str) -> None:
        cond_j = eqn.params.get("cond_jaxpr")
        body_j = eqn.params.get("body_jaxpr")
        cn = int(eqn.params.get("cond_nconsts", 0))
        bn = int(eqn.params.get("body_nconsts", 0))
        cond_consts = ins[:cn]
        body_consts = ins[cn:cn + bn]
        carry = list(ins[cn + bn:])
        child = f"{path}/while" if path else "while"
        # Fixpoint on the carry taint (the body may launder axis_index
        # into the carry that feeds the next iteration's predicate).
        for _ in range(len(carry) + 2):
            outs = self._run_jaxpr(body_j, body_consts + carry, child)
            new_carry = [a | b for a, b in zip(carry, outs)]
            if new_carry == carry:
                break
            carry = new_carry
        pred = self._run_jaxpr(cond_j, cond_consts + carry, child)
        pred_taint: Taint = frozenset().union(*pred) if pred else _EMPTY
        if pred_taint:
            # Rank-dependent trip count: every collective in the body
            # runs a different number of times per rank.
            self._flag(
                "while", child, pred_taint,
                _collect_collectives_shallow(body_j, child),
            )
        for v, t in zip(eqn.outvars, carry):
            env.set(v, t | pred_taint)

    def _run_scan(self, eqn: Any, env: _TaintEnv, ins: List[Taint],
                  path: str) -> None:
        body = eqn.params.get("jaxpr")
        n_consts = int(eqn.params.get("num_consts", 0))
        n_carry = int(eqn.params.get("num_carry", 0))
        consts = ins[:n_consts]
        carry = list(ins[n_consts:n_consts + n_carry])
        xs = ins[n_consts + n_carry:]
        child = f"{path}/scan" if path else "scan"
        for _ in range(len(carry) + 2):
            outs = self._run_jaxpr(body, consts + carry + list(xs), child)
            new_carry = [
                a | b for a, b in zip(carry, outs[:n_carry])
            ]
            if new_carry == carry:
                break
            carry = new_carry
        outs = self._run_jaxpr(body, consts + carry + list(xs), child)
        out_taints = list(outs[:n_carry]) + list(outs[n_carry:])
        for v, t in zip(eqn.outvars, out_taints):
            env.set(v, t)


def analyze_divergence(
    closed_jaxpr: Any,
    *,
    suppress: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Analyze an already-traced jaxpr (``jax.make_jaxpr`` output or any
    Jaxpr/ClosedJaxpr) for collectives guarded by rank-divergent control
    flow. Returns findings ([] when every collective is reached
    uniformly)."""
    analyzer = _Analyzer()
    jaxpr = _jaxpr_of(closed_jaxpr)
    analyzer._run_jaxpr(jaxpr, [_EMPTY] * len(jaxpr.invars), "")
    seen = set()
    unique: List[Finding] = []
    for f in analyzer.findings:
        key = (f.location, f.details.get("guard_path"), f.message)
        if key in seen:
            continue
        seen.add(key)
        unique.append(f)
    return apply_suppressions(unique, suppress)


def analyze_step(
    fn: Any,
    *args: Any,
    suppress: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Trace ``fn(*args)`` and run :func:`analyze_divergence` on the
    result (the standalone entry the CLI ``divergence`` target uses;
    ``lint_step`` already folds this pass in)."""
    import jax

    return analyze_divergence(
        jax.make_jaxpr(fn)(*args), suppress=suppress
    )
