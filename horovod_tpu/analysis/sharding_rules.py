"""Pass 5 — mesh/sharding-rule validation.

ROADMAP item 4's sharding-rules engine adopts the declarative
regex -> PartitionSpec table pattern (SNIPPETS.md
``match_partition_rules``): param names are matched against ordered
``(pattern, spec)`` rules and the first hit decides the leaf's
placement. A typo'd axis name, a doubled mesh axis, or a dim the mesh
cannot divide only surfaces deep inside pjit today — this validator
rejects the table *before* anything is traced, so the engine lands on a
checked foundation ("rules validated against the mesh by the static
analyzer").

Everything is backend-free: a "spec" is any PartitionSpec-shaped value —
``None`` (replicated), a string axis name, or a sequence whose entries
are ``None`` / axis name / tuple of axis names (one entry per array
dim). jax's actual ``PartitionSpec`` duck-types through unchanged, so
the future engine and the tests can hand either in.

Rules checked (docs/static_analysis.md has the table):

 - :data:`RULE_SHARDING_BAD_RULE` — a rule's regex does not compile or
   its spec is not PartitionSpec-shaped;
 - :data:`RULE_SHARDING_UNKNOWN_AXIS` — a spec names an axis the mesh
   does not have;
 - :data:`RULE_SHARDING_DUP_AXIS` — one spec uses the same mesh axis for
   two different dims (an axis can shard at most one dim of a leaf);
 - :data:`RULE_SHARDING_INDIVISIBLE` — with a param table: a matched
   dim's size is not divisible by the product of its axis sizes, or the
   spec has more entries than the param has dims;
 - :data:`RULE_SHARDING_UNMATCHED` — with a param table: a non-scalar
   param no rule matches (the engine would have to raise mid-init);
 - :data:`RULE_SHARDING_SCALAR` (warning) — a rule shards a scalar
   param; the canonical engine silently replicates scalars, so the rule
   is dead weight or a misunderstanding.
"""

from __future__ import annotations

import re
from typing import (
    Any, Dict, List, Mapping, Optional, Sequence, Tuple, Union,
)

from .findings import (
    Finding,
    RULE_SHARDING_BAD_RULE,
    RULE_SHARDING_DUP_AXIS,
    RULE_SHARDING_INDIVISIBLE,
    RULE_SHARDING_SCALAR,
    RULE_SHARDING_UNKNOWN_AXIS,
    RULE_SHARDING_UNMATCHED,
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    apply_suppressions,
)

SpecEntry = Union[None, str, Sequence[str]]
Spec = Union[None, str, Sequence[SpecEntry]]
Rule = Tuple[str, Spec]


def normalize_spec(spec: Spec) -> Optional[Tuple[Tuple[str, ...], ...]]:
    """Normalize a PartitionSpec-shaped value into one axis tuple per
    dim; None when the value is not spec-shaped. ``None``/empty ->
    ``()`` (replicated), ``"x"`` -> ``(("x",),)``."""
    if spec is None:
        return ()
    if isinstance(spec, str):
        return ((spec,),)
    try:
        entries = tuple(spec)
    except TypeError:
        return None
    out: List[Tuple[str, ...]] = []
    for e in entries:
        if e is None:
            out.append(())
        elif isinstance(e, str):
            out.append((e,))
        else:
            try:
                axes = tuple(e)
            except TypeError:
                return None
            if not all(isinstance(a, str) for a in axes):
                return None
            out.append(axes)
    return tuple(out)


def _mesh_axes(mesh: Any) -> Dict[str, int]:
    """Name -> size for a mesh given as a dict, a jax ``Mesh`` (or
    anything with a ``.shape`` mapping), or a sequence of (name, size)
    pairs."""
    from .jaxpr_lint import _mesh_axis_sizes

    return _mesh_axis_sizes(mesh)


def _spec_repr(spec: Spec) -> str:
    norm = normalize_spec(spec)
    if norm is None:
        return repr(spec)
    return "P(" + ", ".join(
        "None" if not axes else (repr(axes[0]) if len(axes) == 1
                                 else repr(tuple(axes)))
        for axes in norm
    ) + ")"


def _is_scalar(shape: Sequence[int]) -> bool:
    n = 1
    for d in shape:
        n *= int(d)
    return len(shape) == 0 or n == 1


def validate_sharding_rules(
    rules: Sequence[Rule],
    mesh: Any,
    params: Optional[Mapping[str, Sequence[int]]] = None,
    *,
    suppress: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Validate a regex -> PartitionSpec rule table against ``mesh``
    (name -> size), and — when ``params`` maps param names to shapes —
    against the concrete tree the table is meant to place."""
    axes = _mesh_axes(mesh)
    findings: List[Finding] = []
    compiled: List[Tuple[int, Any, Tuple[Tuple[str, ...], ...]]] = []

    for idx, rule in enumerate(rules):
        try:
            pattern, spec = rule
        except (TypeError, ValueError):
            findings.append(Finding(
                rule=RULE_SHARDING_BAD_RULE,
                severity=SEVERITY_ERROR,
                message=f"rule #{idx} is not a (pattern, spec) pair: "
                        f"{rule!r}",
                location=f"sharding:rule[{idx}]",
                details={"rule_index": idx},
            ))
            continue
        loc = f"sharding:rule[{idx}]:{pattern}"
        try:
            rx = re.compile(pattern)
        except re.error as exc:
            findings.append(Finding(
                rule=RULE_SHARDING_BAD_RULE,
                severity=SEVERITY_ERROR,
                message=f"rule #{idx} pattern {pattern!r} does not "
                        f"compile: {exc}",
                location=loc,
                details={"rule_index": idx, "pattern": str(pattern)},
            ))
            continue
        norm = normalize_spec(spec)
        if norm is None:
            findings.append(Finding(
                rule=RULE_SHARDING_BAD_RULE,
                severity=SEVERITY_ERROR,
                message=f"rule #{idx} spec {spec!r} is not "
                        f"PartitionSpec-shaped",
                location=loc,
                details={"rule_index": idx},
            ))
            continue
        used: Dict[str, int] = {}
        for dim, dim_axes in enumerate(norm):
            for a in dim_axes:
                if a not in axes:
                    findings.append(Finding(
                        rule=RULE_SHARDING_UNKNOWN_AXIS,
                        severity=SEVERITY_ERROR,
                        message=(
                            f"rule #{idx} ({pattern!r}) shards dim {dim} "
                            f"over axis {a!r} which is not a mesh axis "
                            f"(mesh: {sorted(axes) or 'empty'})"
                        ),
                        location=loc,
                        details={"rule_index": idx, "axis": a,
                                 "mesh_axes": sorted(axes)},
                    ))
                elif a in used and used[a] != dim:
                    findings.append(Finding(
                        rule=RULE_SHARDING_DUP_AXIS,
                        severity=SEVERITY_ERROR,
                        message=(
                            f"rule #{idx} ({pattern!r}) uses mesh axis "
                            f"{a!r} for both dim {used[a]} and dim "
                            f"{dim} — an axis can shard at most one dim "
                            f"of one leaf"
                        ),
                        location=loc,
                        details={"rule_index": idx, "axis": a,
                                 "dims": [used[a], dim]},
                    ))
                elif a in used:
                    findings.append(Finding(
                        rule=RULE_SHARDING_DUP_AXIS,
                        severity=SEVERITY_ERROR,
                        message=(
                            f"rule #{idx} ({pattern!r}) repeats mesh "
                            f"axis {a!r} within dim {dim}"
                        ),
                        location=loc,
                        details={"rule_index": idx, "axis": a,
                                 "dims": [dim]},
                    ))
                else:
                    used[a] = dim
        compiled.append((idx, rx, norm))

    if params is not None:
        for name in sorted(params):
            shape = tuple(int(d) for d in params[name])
            scalar = _is_scalar(shape)
            match = None
            for idx, rx, norm in compiled:
                if rx.search(name) is not None:
                    match = (idx, norm)
                    break
            if match is None:
                if not scalar:
                    findings.append(Finding(
                        rule=RULE_SHARDING_UNMATCHED,
                        severity=SEVERITY_ERROR,
                        message=(
                            f"param {name!r} (shape {shape}) matches no "
                            f"rule — the engine would raise mid-init; "
                            f"add a rule or a catch-all replicate"
                        ),
                        location=f"sharding:param:{name}",
                        details={"param": name, "shape": list(shape)},
                    ))
                continue
            idx, norm = match
            loc = f"sharding:param:{name}"
            if scalar:
                if any(norm[d] for d in range(len(norm))):
                    findings.append(Finding(
                        rule=RULE_SHARDING_SCALAR,
                        severity=SEVERITY_WARNING,
                        message=(
                            f"rule #{idx} shards scalar param {name!r}; "
                            f"scalars are always replicated (the engine "
                            f"ignores the spec)"
                        ),
                        location=loc,
                        details={"param": name, "rule_index": idx},
                    ))
                continue
            if len(norm) > len(shape):
                findings.append(Finding(
                    rule=RULE_SHARDING_INDIVISIBLE,
                    severity=SEVERITY_ERROR,
                    message=(
                        f"rule #{idx} spec has {len(norm)} entries but "
                        f"param {name!r} has {len(shape)} dims"
                    ),
                    location=loc,
                    details={"param": name, "rule_index": idx,
                             "shape": list(shape)},
                ))
                continue
            for dim, dim_axes in enumerate(norm):
                factor = 1
                for a in dim_axes:
                    factor *= int(axes.get(a, 1))
                if factor > 1 and shape[dim] % factor:
                    findings.append(Finding(
                        rule=RULE_SHARDING_INDIVISIBLE,
                        severity=SEVERITY_ERROR,
                        message=(
                            f"param {name!r} dim {dim} (size "
                            f"{shape[dim]}) is not divisible by "
                            f"{'x'.join(dim_axes)} = {factor} "
                            f"(rule #{idx})"
                        ),
                        location=loc,
                        details={"param": name, "dim": dim,
                                 "size": shape[dim], "factor": factor,
                                 "rule_index": idx},
                    ))
    return apply_suppressions(findings, suppress)


# --- reference DP x TP table (the CLI `sharding` target + tests) -------------
#
# The SHIPPED GPT table: the exact table `parallel/rules.py` exports as
# GPT_RULES and `make_train_step(rules="gpt")` trains the real
# `models/transformer.py` TransformerLM with on a {"data": D, "model":
# T} mesh. Attention q/k/v and the MLP up-projection are column-parallel
# (feature dim over "model"; a contiguous feature slice is whole heads),
# the attention out- and MLP down-projections are row-parallel (ONE psum
# per Megatron half-block; their biases shard with the output and are
# scattered inside the reduction — parallel/tp.py), norms replicate, and
# the embeddings + lm head replicate deliberately: the lookup stays
# local and the vocab softmax needs full logits (Megatron's
# vocab-parallel embedding is a different schedule with its own
# collective). The validator accepting this pair — and rejecting its
# seeded corruptions — is the acceptance gate; `example_gpt_params`
# mirrors the REAL flax param tree (locked by a parity test against
# `TransformerLM.init`, tests/test_rules.py).

EXAMPLE_GPT_MESH: Dict[str, int] = {"data": 4, "model": 2}

EXAMPLE_GPT_RULES: Tuple[Rule, ...] = (
    # Anchored with (^|/): a bare search for "embeddings/embedding$"
    # would also hit "pos_embeddings/embedding" (over-match — harmless
    # here since both replicate, but the anchor keeps the table honest
    # as a first-match-wins example).
    (r"(^|/)embeddings/embedding$", None),
    (r"(^|/)pos_embeddings/embedding$", None),
    (r"attention/(query|key|value)/kernel$", (None, "model")),
    (r"attention/out/kernel$", ("model", None)),
    (r"mlp/up/kernel$", (None, "model")),
    (r"mlp/up/bias$", ("model",)),
    (r"mlp/down/kernel$", ("model", None)),
    (r"mlp/down/bias$", ("model",)),
    (r"(ln|layernorm|norm)[^/]*/(scale|bias)$", None),
    (r"lm_head/kernel$", None),
    (r"bias$", None),
    (r".*", None),  # catch-all: replicate
)


def example_gpt_params(
    d_model: int = 128, n_heads: int = 4, n_layers: int = 2,
    vocab: int = 384, max_len: int = 128, mlp_ratio: int = 4,
) -> Dict[str, Tuple[int, ...]]:
    """The REAL ``models/transformer.py`` param-shape table (name ->
    shape, flax names) the reference rule table must place cleanly.
    Pure python (the linter imports no jax); a parity test asserts it
    matches ``TransformerLM.init``'s actual tree leaf for leaf."""
    d_ff = mlp_ratio * d_model
    out: Dict[str, Tuple[int, ...]] = {
        "embeddings/embedding": (vocab, d_model),
        "pos_embeddings/embedding": (max_len, d_model),
        "ln_f/scale": (d_model,),
        "ln_f/bias": (d_model,),
        "lm_head/kernel": (d_model, vocab),
    }
    for i in range(n_layers):
        b = f"block_{i}"
        out.update({
            f"{b}/ln_1/scale": (d_model,),
            f"{b}/ln_1/bias": (d_model,),
            f"{b}/attention/query/kernel": (d_model, d_model),
            f"{b}/attention/key/kernel": (d_model, d_model),
            f"{b}/attention/value/kernel": (d_model, d_model),
            f"{b}/attention/out/kernel": (d_model, d_model),
            f"{b}/ln_2/scale": (d_model,),
            f"{b}/ln_2/bias": (d_model,),
            f"{b}/mlp/up/kernel": (d_model, d_ff),
            f"{b}/mlp/up/bias": (d_ff,),
            f"{b}/mlp/down/kernel": (d_ff, d_model),
            f"{b}/mlp/down/bias": (d_model,),
        })
    return out
