"""Pass 5 — mesh/sharding-rule validation.

ROADMAP item 4's sharding-rules engine adopts the declarative
regex -> PartitionSpec table pattern (SNIPPETS.md
``match_partition_rules``): param names are matched against ordered
``(pattern, spec)`` rules and the first hit decides the leaf's
placement. A typo'd axis name, a doubled mesh axis, or a dim the mesh
cannot divide only surfaces deep inside pjit today — this validator
rejects the table *before* anything is traced, so the engine lands on a
checked foundation ("rules validated against the mesh by the static
analyzer").

Everything is backend-free: a "spec" is any PartitionSpec-shaped value —
``None`` (replicated), a string axis name, or a sequence whose entries
are ``None`` / axis name / tuple of axis names (one entry per array
dim). jax's actual ``PartitionSpec`` duck-types through unchanged, so
the future engine and the tests can hand either in.

Rules checked (docs/static_analysis.md has the table):

 - :data:`RULE_SHARDING_BAD_RULE` — a rule's regex does not compile or
   its spec is not PartitionSpec-shaped;
 - :data:`RULE_SHARDING_UNKNOWN_AXIS` — a spec names an axis the mesh
   does not have;
 - :data:`RULE_SHARDING_DUP_AXIS` — one spec uses the same mesh axis for
   two different dims (an axis can shard at most one dim of a leaf);
 - :data:`RULE_SHARDING_INDIVISIBLE` — with a param table: a matched
   dim's size is not divisible by the product of its axis sizes, or the
   spec has more entries than the param has dims;
 - :data:`RULE_SHARDING_UNMATCHED` — with a param table: a non-scalar
   param no rule matches (the engine would have to raise mid-init);
 - :data:`RULE_SHARDING_SCALAR` (warning) — a rule shards a scalar
   param; the canonical engine silently replicates scalars, so the rule
   is dead weight or a misunderstanding.
"""

from __future__ import annotations

import re
from typing import (
    Any, Dict, List, Mapping, Optional, Sequence, Tuple, Union,
)

from .findings import (
    Finding,
    RULE_SHARDING_BAD_RULE,
    RULE_SHARDING_DUP_AXIS,
    RULE_SHARDING_INDIVISIBLE,
    RULE_SHARDING_SCALAR,
    RULE_SHARDING_UNKNOWN_AXIS,
    RULE_SHARDING_UNMATCHED,
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    apply_suppressions,
)

SpecEntry = Union[None, str, Sequence[str]]
Spec = Union[None, str, Sequence[SpecEntry]]
Rule = Tuple[str, Spec]


def normalize_spec(spec: Spec) -> Optional[Tuple[Tuple[str, ...], ...]]:
    """Normalize a PartitionSpec-shaped value into one axis tuple per
    dim; None when the value is not spec-shaped. ``None``/empty ->
    ``()`` (replicated), ``"x"`` -> ``(("x",),)``."""
    if spec is None:
        return ()
    if isinstance(spec, str):
        return ((spec,),)
    try:
        entries = tuple(spec)
    except TypeError:
        return None
    out: List[Tuple[str, ...]] = []
    for e in entries:
        if e is None:
            out.append(())
        elif isinstance(e, str):
            out.append((e,))
        else:
            try:
                axes = tuple(e)
            except TypeError:
                return None
            if not all(isinstance(a, str) for a in axes):
                return None
            out.append(axes)
    return tuple(out)


def _mesh_axes(mesh: Any) -> Dict[str, int]:
    """Name -> size for a mesh given as a dict, a jax ``Mesh`` (or
    anything with a ``.shape`` mapping), or a sequence of (name, size)
    pairs."""
    from .jaxpr_lint import _mesh_axis_sizes

    return _mesh_axis_sizes(mesh)


def _spec_repr(spec: Spec) -> str:
    norm = normalize_spec(spec)
    if norm is None:
        return repr(spec)
    return "P(" + ", ".join(
        "None" if not axes else (repr(axes[0]) if len(axes) == 1
                                 else repr(tuple(axes)))
        for axes in norm
    ) + ")"


def _is_scalar(shape: Sequence[int]) -> bool:
    n = 1
    for d in shape:
        n *= int(d)
    return len(shape) == 0 or n == 1


def validate_sharding_rules(
    rules: Sequence[Rule],
    mesh: Any,
    params: Optional[Mapping[str, Sequence[int]]] = None,
    *,
    suppress: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Validate a regex -> PartitionSpec rule table against ``mesh``
    (name -> size), and — when ``params`` maps param names to shapes —
    against the concrete tree the table is meant to place."""
    axes = _mesh_axes(mesh)
    findings: List[Finding] = []
    compiled: List[Tuple[int, Any, Tuple[Tuple[str, ...], ...]]] = []

    for idx, rule in enumerate(rules):
        try:
            pattern, spec = rule
        except (TypeError, ValueError):
            findings.append(Finding(
                rule=RULE_SHARDING_BAD_RULE,
                severity=SEVERITY_ERROR,
                message=f"rule #{idx} is not a (pattern, spec) pair: "
                        f"{rule!r}",
                location=f"sharding:rule[{idx}]",
                details={"rule_index": idx},
            ))
            continue
        loc = f"sharding:rule[{idx}]:{pattern}"
        try:
            rx = re.compile(pattern)
        except re.error as exc:
            findings.append(Finding(
                rule=RULE_SHARDING_BAD_RULE,
                severity=SEVERITY_ERROR,
                message=f"rule #{idx} pattern {pattern!r} does not "
                        f"compile: {exc}",
                location=loc,
                details={"rule_index": idx, "pattern": str(pattern)},
            ))
            continue
        norm = normalize_spec(spec)
        if norm is None:
            findings.append(Finding(
                rule=RULE_SHARDING_BAD_RULE,
                severity=SEVERITY_ERROR,
                message=f"rule #{idx} spec {spec!r} is not "
                        f"PartitionSpec-shaped",
                location=loc,
                details={"rule_index": idx},
            ))
            continue
        used: Dict[str, int] = {}
        for dim, dim_axes in enumerate(norm):
            for a in dim_axes:
                if a not in axes:
                    findings.append(Finding(
                        rule=RULE_SHARDING_UNKNOWN_AXIS,
                        severity=SEVERITY_ERROR,
                        message=(
                            f"rule #{idx} ({pattern!r}) shards dim {dim} "
                            f"over axis {a!r} which is not a mesh axis "
                            f"(mesh: {sorted(axes) or 'empty'})"
                        ),
                        location=loc,
                        details={"rule_index": idx, "axis": a,
                                 "mesh_axes": sorted(axes)},
                    ))
                elif a in used and used[a] != dim:
                    findings.append(Finding(
                        rule=RULE_SHARDING_DUP_AXIS,
                        severity=SEVERITY_ERROR,
                        message=(
                            f"rule #{idx} ({pattern!r}) uses mesh axis "
                            f"{a!r} for both dim {used[a]} and dim "
                            f"{dim} — an axis can shard at most one dim "
                            f"of one leaf"
                        ),
                        location=loc,
                        details={"rule_index": idx, "axis": a,
                                 "dims": [used[a], dim]},
                    ))
                elif a in used:
                    findings.append(Finding(
                        rule=RULE_SHARDING_DUP_AXIS,
                        severity=SEVERITY_ERROR,
                        message=(
                            f"rule #{idx} ({pattern!r}) repeats mesh "
                            f"axis {a!r} within dim {dim}"
                        ),
                        location=loc,
                        details={"rule_index": idx, "axis": a,
                                 "dims": [dim]},
                    ))
                else:
                    used[a] = dim
        compiled.append((idx, rx, norm))

    if params is not None:
        for name in sorted(params):
            shape = tuple(int(d) for d in params[name])
            scalar = _is_scalar(shape)
            match = None
            for idx, rx, norm in compiled:
                if rx.search(name) is not None:
                    match = (idx, norm)
                    break
            if match is None:
                if not scalar:
                    findings.append(Finding(
                        rule=RULE_SHARDING_UNMATCHED,
                        severity=SEVERITY_ERROR,
                        message=(
                            f"param {name!r} (shape {shape}) matches no "
                            f"rule — the engine would raise mid-init; "
                            f"add a rule or a catch-all replicate"
                        ),
                        location=f"sharding:param:{name}",
                        details={"param": name, "shape": list(shape)},
                    ))
                continue
            idx, norm = match
            loc = f"sharding:param:{name}"
            if scalar:
                if any(norm[d] for d in range(len(norm))):
                    findings.append(Finding(
                        rule=RULE_SHARDING_SCALAR,
                        severity=SEVERITY_WARNING,
                        message=(
                            f"rule #{idx} shards scalar param {name!r}; "
                            f"scalars are always replicated (the engine "
                            f"ignores the spec)"
                        ),
                        location=loc,
                        details={"param": name, "rule_index": idx},
                    ))
                continue
            if len(norm) > len(shape):
                findings.append(Finding(
                    rule=RULE_SHARDING_INDIVISIBLE,
                    severity=SEVERITY_ERROR,
                    message=(
                        f"rule #{idx} spec has {len(norm)} entries but "
                        f"param {name!r} has {len(shape)} dims"
                    ),
                    location=loc,
                    details={"param": name, "rule_index": idx,
                             "shape": list(shape)},
                ))
                continue
            for dim, dim_axes in enumerate(norm):
                factor = 1
                for a in dim_axes:
                    factor *= int(axes.get(a, 1))
                if factor > 1 and shape[dim] % factor:
                    findings.append(Finding(
                        rule=RULE_SHARDING_INDIVISIBLE,
                        severity=SEVERITY_ERROR,
                        message=(
                            f"param {name!r} dim {dim} (size "
                            f"{shape[dim]}) is not divisible by "
                            f"{'x'.join(dim_axes)} = {factor} "
                            f"(rule #{idx})"
                        ),
                        location=loc,
                        details={"param": name, "dim": dim,
                                 "size": shape[dim], "factor": factor,
                                 "rule_index": idx},
                    ))
    return apply_suppressions(findings, suppress)


# --- reference DP x TP table (the CLI `sharding` target + tests) -------------
#
# A GPT-class param tree on a {"data": D, "model": T} mesh: embeddings
# and attention/MLP kernels shard their feature dim over "model",
# norms/biases replicate, scalars replicate implicitly. This is the
# shape item 4's engine will ship; the validator accepting it (and
# rejecting its seeded corruptions) is the acceptance gate.

EXAMPLE_GPT_MESH: Dict[str, int] = {"data": 4, "model": 2}

EXAMPLE_GPT_RULES: Tuple[Rule, ...] = (
    (r"embeddings/embedding$", (None, "model")),
    (r"attention/(query|key|value)/kernel$", (None, "model")),
    (r"attention/out/kernel$", ("model", None)),
    (r"mlp/up/kernel$", (None, "model")),
    (r"mlp/down/kernel$", ("model", None)),
    (r"(ln|layernorm|norm)[^/]*/(scale|bias)$", None),
    (r"bias$", None),
    (r".*", None),  # catch-all: replicate
)


def example_gpt_params(
    d_model: int = 128, d_ff: int = 512, vocab: int = 384
) -> Dict[str, Tuple[int, ...]]:
    """A representative GPT-class param-shape table (name -> shape) the
    reference rule table must place cleanly."""
    return {
        "embeddings/embedding": (vocab, d_model),
        "layer_0/attention/query/kernel": (d_model, d_model),
        "layer_0/attention/key/kernel": (d_model, d_model),
        "layer_0/attention/value/kernel": (d_model, d_model),
        "layer_0/attention/out/kernel": (d_model, d_model),
        "layer_0/attention/out/bias": (d_model,),
        "layer_0/mlp/up/kernel": (d_model, d_ff),
        "layer_0/mlp/down/kernel": (d_ff, d_model),
        "layer_0/ln_1/scale": (d_model,),
        "layer_0/ln_1/bias": (d_model,),
        "final_norm/scale": (d_model,),
        "step": (),
    }
