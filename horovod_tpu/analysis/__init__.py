"""horovod_tpu.analysis — collective-safety static analyzers.

Two passes over two layers of the system:

 - **Pass 1 (collective lint)** inspects what a training step *will* do
   before it runs: trace a jitted fn to its jaxpr and check collective
   axis names, ``ppermute`` bijectivity, and fusion-bucket budgets
   (:mod:`.jaxpr_lint`); simulate eager ranks against the tensor-name
   registry and diff their submission orders — the deadlock class the
   dynamic stall inspector only reports after its timeout
   (:mod:`.ordering`); validate grouped-collective dtype/budget
   composition (:mod:`.groups`).
 - **Pass 2 (runtime thread-safety lint)** checks the runtime's own
   sources against its declared lock discipline (:mod:`.runtime_lint`).

``tools/collective_lint.py`` exposes both as a CLI (JSON + human output,
nonzero exit on findings); ``HOROVOD_TPU_STATIC_CHECKS=1`` wires Pass 1
into ``DistributedOptimizer`` / ``allreduce`` setup as a pre-flight
(:mod:`.preflight`). See ``docs/static_analysis.md``.
"""

from __future__ import annotations

from .findings import (
    CollectiveSafetyError,
    Finding,
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    errors,
    findings_to_json,
    sort_findings,
)
from .groups import check_fusion_plan, check_group
from .jaxpr_lint import (
    CollectiveSite,
    collect_collectives,
    lint_jaxpr,
    lint_step,
)
from .ordering import (
    CollectiveCall,
    check_cross_rank_order,
    record_rank_trace,
    simulate_ranks,
)
from .runtime_lint import (
    AttrRule,
    ClassRule,
    DEFAULT_DISCIPLINE,
    lint_file,
    lint_runtime,
    lint_source,
)

__all__ = [
    "AttrRule",
    "ClassRule",
    "CollectiveCall",
    "CollectiveSafetyError",
    "CollectiveSite",
    "DEFAULT_DISCIPLINE",
    "Finding",
    "SEVERITY_ERROR",
    "SEVERITY_WARNING",
    "check_cross_rank_order",
    "check_fusion_plan",
    "check_group",
    "collect_collectives",
    "errors",
    "findings_to_json",
    "lint_file",
    "lint_jaxpr",
    "lint_runtime",
    "lint_source",
    "lint_step",
    "record_rank_trace",
    "simulate_ranks",
    "sort_findings",
]
