"""horovod_tpu.analysis — collective-safety static analyzers.

Five passes over three layers of the system:

 - **Pass 1 (collective lint)** inspects what a training step *will* do
   before it runs: trace a jitted fn to its jaxpr and check collective
   axis names, ``ppermute`` bijectivity, and fusion-bucket budgets
   (:mod:`.jaxpr_lint`); simulate eager ranks against the tensor-name
   registry and diff their submission orders — the deadlock class the
   dynamic stall inspector only reports after its timeout
   (:mod:`.ordering`); validate grouped-collective dtype/budget
   composition (:mod:`.groups`).
 - **Pass 2 (runtime thread-safety lint)** checks the runtime's own
   sources — and, since PR 8, the fault/guard/metrics/journal packages —
   against their declared lock discipline (:mod:`.runtime_lint`).
 - **Pass 3 (symbolic plan verifier)** executes every compositor
   lowering plan over an abstract per-rank chunk state and proves the
   schedule realizes the collective's spec, with no jax import
   (:mod:`.plan_verify`).
 - **Pass 4 (rank-divergence analyzer)** taint-tracks ``axis_index``
   through a jaxpr and flags collectives guarded by rank-divergent
   ``cond``/``switch``/``while`` — the SPMD deadlock the Horovod paper's
   coordinator exists to catch at runtime (:mod:`.divergence`).
 - **Pass 5 (sharding-rule validator)** rejects regex->PartitionSpec
   rule tables a mesh cannot satisfy before anything is traced
   (:mod:`.sharding_rules`).

``tools/collective_lint.py`` exposes all passes as a CLI (versioned JSON
+ human output; exit 1 on findings, 2 on analyzer crash);
``HOROVOD_TPU_STATIC_CHECKS=1`` wires the trace-time passes into
``DistributedOptimizer`` / ``allreduce`` setup as a pre-flight
(:mod:`.preflight`). Findings can be suppressed in-source
(``# hvd-analysis: ignore[rule]``) or at the call site
(:func:`suppressions` / the ``suppress=`` kwarg). See
``docs/static_analysis.md``.
"""

from __future__ import annotations

from .findings import (
    CollectiveSafetyError,
    Finding,
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    apply_suppressions,
    errors,
    findings_to_json,
    sort_findings,
    suppressions,
)
from .groups import check_fusion_plan, check_group
from .jaxpr_lint import (
    CollectiveSite,
    collect_collectives,
    lint_jaxpr,
    lint_step,
)
from .ordering import (
    CollectiveCall,
    check_cross_rank_order,
    record_rank_trace,
    simulate_ranks,
)
from .runtime_lint import (
    AttrRule,
    ClassRule,
    DEFAULT_DISCIPLINE,
    MODULE,
    lint_file,
    lint_runtime,
    lint_source,
)
from .divergence import analyze_divergence, analyze_step
from .plan_verify import verify_plan, verify_plan_grid
from .sharding_rules import normalize_spec, validate_sharding_rules

__all__ = [
    "AttrRule",
    "ClassRule",
    "CollectiveCall",
    "CollectiveSafetyError",
    "CollectiveSite",
    "DEFAULT_DISCIPLINE",
    "Finding",
    "MODULE",
    "SEVERITY_ERROR",
    "SEVERITY_WARNING",
    "analyze_divergence",
    "analyze_step",
    "apply_suppressions",
    "check_cross_rank_order",
    "check_fusion_plan",
    "check_group",
    "collect_collectives",
    "errors",
    "findings_to_json",
    "lint_file",
    "lint_jaxpr",
    "lint_runtime",
    "lint_source",
    "lint_step",
    "normalize_spec",
    "record_rank_trace",
    "simulate_ranks",
    "sort_findings",
    "suppressions",
    "validate_sharding_rules",
    "verify_plan",
    "verify_plan_grid",
]
