"""Opt-in pre-flight hooks (``HOROVOD_TPU_STATIC_CHECKS=1``).

When the knob is set, the framework entry points run the static analyzers
before work is traced/submitted:

 - ``horovod_tpu.jax.allreduce_gradients`` (and therefore
   ``DistributedOptimizer`` / ``make_train_step``) validates the fusion
   bucket plan of the gradient pytree at trace time and that the reduction
   axis is actually bound;
 - eager ``hvd.grouped_allreduce*`` validates group dtype/budget before
   any member is enqueued (a bad group would otherwise strand peers
   holding an incomplete group);
 - every eager named collective is recorded into a per-process submission
   ledger whose entries feed :func:`horovod_tpu.analysis.ordering
   .check_cross_rank_order` — either offline (simulated ranks) or via an
   explicit :func:`verify_cross_rank_order` barrier a job can call at a
   known-quiet point.

Error-severity findings raise :class:`CollectiveSafetyError`; warnings are
logged. The knob is read once and cached — set it before the first
collective.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, List, Optional, Sequence

from .findings import (
    CollectiveSafetyError,
    Finding,
    SEVERITY_ERROR,
    errors,
)
from .ordering import CollectiveCall, check_cross_rank_order

logger = logging.getLogger("horovod_tpu")

ENV_KNOB = "HOROVOD_TPU_STATIC_CHECKS"

_enabled_cache: Optional[bool] = None
_ledger_lock = threading.Lock()
_ledger: List[CollectiveCall] = []


def enabled() -> bool:
    """True when HOROVOD_TPU_STATIC_CHECKS is set truthy (cached after the
    first read)."""
    global _enabled_cache
    if _enabled_cache is None:
        _enabled_cache = os.environ.get(ENV_KNOB, "").strip().lower() in (
            "1", "true", "yes", "on"
        )
    return _enabled_cache


def _reset_for_tests(value: Optional[bool] = None) -> None:
    global _enabled_cache
    _enabled_cache = value
    with _ledger_lock:
        _ledger.clear()


def _raise_or_log(findings: Sequence[Finding]) -> None:
    errs = errors(findings)
    for f in findings:
        if f.severity != SEVERITY_ERROR:
            logger.warning("static check: %s", f.render())
    if errs:
        raise CollectiveSafetyError(errs)


# --- compiled-mode (trace-time) checks ---
def check_gradient_tree(
    grads: Any, threshold_bytes: int, axis_name: Any
) -> None:
    """Trace-time pre-flight for ``allreduce_gradients``: the fusion
    bucket plan must be well-formed and the reduction axis bound."""
    import jax

    from .groups import check_fusion_plan
    from .findings import RULE_UNKNOWN_AXIS

    findings: List[Finding] = []
    axes = axis_name if isinstance(axis_name, (tuple, list)) else (axis_name,)
    for ax in axes:
        try:
            jax.lax.psum(1, ax)
        except NameError:
            findings.append(
                Finding(
                    rule=RULE_UNKNOWN_AXIS,
                    severity=SEVERITY_ERROR,
                    message=(
                        f"allreduce_gradients over axis {ax!r} but no such "
                        "axis is bound — the step is not running inside a "
                        "shard_map/pmap over that mesh axis"
                    ),
                    location="preflight:allreduce_gradients",
                    details={"axis": str(ax)},
                )
            )
    leaves = jax.tree.flatten(grads)[0]
    if leaves:
        findings.extend(check_fusion_plan(leaves, threshold_bytes))
    _raise_or_log(findings)


def check_overlap_streaming(
    registrations: Dict[str, int], n_grad_leaves: int
) -> List[Finding]:
    """Lint for ``DistributedOptimizer(overlap=True)``: the wrapped model's
    layers must have been registered for streamed reduction
    (``reduce_in_backward`` / ``stream_param_groups``) during the loss
    trace, or the overlap promise silently degrades. Returns warning
    findings (the optimizer falls back to the post-hoc reduction when
    NOTHING was registered; a partial registration leaves the unregistered
    leaves unreduced — flagged the loudest)."""
    from .findings import RULE_OVERLAP_STREAMING, SEVERITY_WARNING

    findings: List[Finding] = []
    calls = int(registrations.get("calls", 0))
    leaves = int(registrations.get("leaves", 0))
    if calls == 0:
        findings.append(
            Finding(
                rule=RULE_OVERLAP_STREAMING,
                severity=SEVERITY_WARNING,
                message=(
                    "DistributedOptimizer(overlap=True) but no parameter "
                    "subtree was registered for streamed reduction — wrap "
                    "the params the loss consumes with "
                    "hvd.reduce_in_backward / hvd.stream_param_groups (or "
                    "use make_train_step(overlap=True)); falling back to "
                    "the post-hoc reduction: correct, but with ZERO "
                    "backward overlap"
                ),
                location="preflight:DistributedOptimizer",
                details={"registered_calls": 0,
                         "grad_leaves": int(n_grad_leaves)},
            )
        )
    elif leaves < int(n_grad_leaves):
        findings.append(
            Finding(
                rule=RULE_OVERLAP_STREAMING,
                severity=SEVERITY_WARNING,
                message=(
                    f"overlap=True with a PARTIAL streaming registration: "
                    f"{leaves} of {n_grad_leaves} gradient leaves were "
                    "registered — the unregistered leaves' gradients are "
                    "NOT reduced across ranks; register every layer or "
                    "drop overlap=True"
                ),
                location="preflight:DistributedOptimizer",
                details={"registered_leaves": leaves,
                         "grad_leaves": int(n_grad_leaves),
                         "registered_calls": calls},
            )
        )
    return findings


def check_guard_skip_agreement(
    stream_calls: int, seam_calls: int, policy: Optional[str] = None
) -> List[Finding]:
    """Lint for streamed-overlap training under the non-finite ``skip``
    policy: a step that registers subtrees for streamed reduction but
    never emits the cross-rank skip-agreement collective
    (``guard/nonfinite.agree_flag``) lets ranks disagree about whether a
    step was skipped — the divergence the digest guard exists to catch,
    manufactured by the guard itself. ``make_train_step`` and
    ``DistributedOptimizer`` always emit the seam; the rule catches
    hand-rolled steps using ``reduce_in_backward`` with their own update
    logic. ``policy=None`` resolves ``HOROVOD_GUARD_NONFINITE``."""
    from ..guard import resolve_policy
    from .findings import RULE_GUARD_SKIP_AGREEMENT

    if resolve_policy(policy) != "skip":
        return []
    if stream_calls <= 0 or seam_calls > 0:
        return []
    return [
        Finding(
            rule=RULE_GUARD_SKIP_AGREEMENT,
            severity=SEVERITY_ERROR,
            message=(
                "HOROVOD_GUARD_NONFINITE=skip with streamed-overlap "
                "reduction but NO cross-rank skip-agreement collective "
                "was traced — ranks can disagree about skipping a step "
                "and silently diverge; route the update through "
                "hvd.DistributedOptimizer / hvd.make_train_step (which "
                "emit the agreement seam), or call "
                "guard.nonfinite.agree_flag on your skip flag"
            ),
            location="preflight:guard-skip",
            details={"stream_calls": int(stream_calls),
                     "seam_calls": int(seam_calls)},
        )
    ]


def check_sharding_rules(
    rules: Any,
    mesh: Any,
    params: Optional[Dict[str, Sequence[int]]] = None,
    *,
    suppress: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Pre-flight for the sharding-rules engine (ROADMAP item 4): reject
    a regex->PartitionSpec rule table the mesh cannot satisfy BEFORE any
    placement is traced. Error findings raise
    :class:`CollectiveSafetyError`; warnings (a rule sharding a scalar)
    are logged and returned."""
    from .sharding_rules import validate_sharding_rules

    findings = validate_sharding_rules(
        rules, mesh, params, suppress=suppress
    )
    _raise_or_log(findings)
    return findings


# --- eager checks ---
def check_grouped(
    tensors: Sequence[Any], threshold_bytes: Optional[int], name: str
) -> None:
    from .groups import check_group

    _raise_or_log(
        check_group(tensors, threshold_bytes=threshold_bytes, name=name)
    )


def record_submission(
    op: str,
    name: str,
    process_set_id: int,
    tensor: Any = None,
) -> None:
    """Append one eager submission to this process's ledger."""
    dtype, shape = "", ()
    try:
        dtype = str(tensor.dtype)
        shape = tuple(int(d) for d in tensor.shape)
    except Exception:  # noqa: BLE001 - scalars / None
        pass
    with _ledger_lock:
        _ledger.append(
            CollectiveCall(
                op=op, name=name, process_set_id=int(process_set_id),
                dtype=dtype, shape=shape,
            )
        )


def ledger() -> List[CollectiveCall]:
    with _ledger_lock:
        return list(_ledger)


def clear_ledger() -> None:
    with _ledger_lock:
        _ledger.clear()


def verify_cross_rank_order(
    allgather_object_fn=None,
) -> List[Finding]:
    """Cross-rank agreement check over the recorded ledgers: every rank
    gathers every rank's submission sequence and diffs them. Call at a
    known-quiet point (all ranks must call it, like a barrier). Raises
    :class:`CollectiveSafetyError` on divergence; returns the findings
    list ([] when orders agree)."""
    import horovod_tpu as hvd

    gather = allgather_object_fn or hvd.allgather_object
    mine = ledger()
    payload = [
        (c.op, c.name, c.process_set_id, c.dtype, tuple(c.shape))
        for c in mine
    ]
    all_payloads = gather(payload, name="hvd.analysis.order")
    traces = {
        r: [
            CollectiveCall(
                op=p[0], name=p[1], process_set_id=p[2], dtype=p[3],
                shape=tuple(p[4]),
            )
            for p in rank_payload
        ]
        for r, rank_payload in enumerate(all_payloads)
    }
    findings = check_cross_rank_order(traces)
    if errors(findings):
        raise CollectiveSafetyError(errors(findings))
    return findings
