"""Cross-rank collective-ordering lint (the deadlock class).

The classic Horovod failure mode: ranks submit named collectives in
different orders, or one rank skips a collective its peers submit, and the
job deadlocks until the stall inspector notices ~60 s later
(``StallInspector``). SPMD jaxprs cannot diverge, but the eager named-op
path can — each rank's submission order is user code. This module makes
that order checkable *statically*:

 - :func:`record_rank_trace` runs a user function against a recording
   runtime stub (no collectives execute; every op is an identity/replicate
   simulation) and returns the rank's submission sequence, using the same
   tensor-name registry (``horovod_tpu._auto_name``) production code uses,
   so auto-generated names line up across simulated ranks;
 - :func:`check_cross_rank_order` diffs per-process-set sequences across
   ranks and reports the first divergence, naming both tensors and both
   ranks — the diagnostic the dynamic stall checker can only approximate
   after its timeout.
"""

from __future__ import annotations

import contextlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .findings import (
    Finding,
    RULE_MISSING_COLLECTIVE,
    RULE_ORDER_MISMATCH,
    RULE_SIGNATURE_MISMATCH,
    SEVERITY_ERROR,
)


@dataclass(frozen=True)
class CollectiveCall:
    """One recorded submission: the identity the coordinator would match
    across ranks (reference Request fields, message.h:46-96)."""

    op: str
    name: str
    process_set_id: int = 0
    dtype: str = ""
    shape: Tuple[int, ...] = ()

    def signature(self) -> Tuple[str, str, str, Tuple[int, ...]]:
        return (self.op, self.name, self.dtype, self.shape)


class _RecordingRuntime:
    """Stand-in runtime installed by :func:`record_rank_trace`: records
    every enqueue and simulates completion locally (allreduce/broadcast/
    alltoall return the input; allgather replicates it member-count times
    so payload-size protocols like ``allgather_object`` keep working)."""

    def __init__(self, rank: int, size: int):
        import types

        self.topology = types.SimpleNamespace(
            rank=rank, size=size, local_rank=rank, local_size=size,
            cross_rank=0, cross_size=1, is_homogeneous=True,
        )
        from ..common.env import Config

        self.config = Config()
        self.calls: List[CollectiveCall] = []
        self._results: Dict[int, Any] = {}
        self._process_sets: Dict[int, List[int]] = {}
        self.running = True

    # -- process sets --
    def register_process_set(self, psid: int, ranks) -> None:
        self._process_sets[int(psid)] = sorted(int(r) for r in ranks)

    def remove_process_set(self, psid: int) -> None:
        self._process_sets.pop(int(psid), None)

    def _members(self, psid: int) -> int:
        if psid and psid in self._process_sets:
            return len(self._process_sets[psid])
        return self.topology.size

    # -- enqueue recording --
    def _record(self, op: str, name: str, tensor: Any,
                process_set_id: int = 0, **_kw: Any) -> int:
        import numpy as np

        arr = np.asarray(tensor) if tensor is not None else None
        self.calls.append(
            CollectiveCall(
                op=op,
                name=name,
                process_set_id=int(process_set_id),
                dtype=str(arr.dtype) if arr is not None else "",
                shape=tuple(arr.shape) if arr is not None else (),
            )
        )
        handle = len(self.calls) - 1
        if op == "allgather" and arr is not None:
            n = self._members(process_set_id)
            out = np.concatenate([arr] * n, axis=0) if arr.ndim else arr
        else:
            out = tensor
        self._results[handle] = out
        return handle

    def enqueue_allreduce(self, name, tensor, **kw) -> int:
        return self._record("allreduce", name, tensor, **_psid_only(kw))

    def enqueue_adasum(self, name, tensor, **kw) -> int:
        return self._record("adasum", name, tensor, **_psid_only(kw))

    def enqueue_allgather(self, name, tensor, **kw) -> int:
        return self._record("allgather", name, tensor, **_psid_only(kw))

    def enqueue_broadcast(self, name, tensor, root_rank, **kw) -> int:
        return self._record("broadcast", name, tensor, **_psid_only(kw))

    def enqueue_alltoall(self, name, tensor, **kw) -> int:
        return self._record("alltoall", name, tensor, **_psid_only(kw))

    def enqueue_reducescatter(self, name, tensor, **kw) -> int:
        return self._record("reducescatter", name, tensor, **_psid_only(kw))

    def enqueue_join(self) -> int:
        return self._record("join", f"join.{self.topology.rank}", None)

    # -- sync --
    def poll(self, handle: int) -> bool:
        return True

    def synchronize(self, handle: int, timeout: Optional[float] = None):
        return self._results.get(handle)


def _psid_only(kw: Dict[str, Any]) -> Dict[str, Any]:
    return {"process_set_id": int(kw.get("process_set_id", 0))}


@contextlib.contextmanager
def _simulated_rank(rank: int, size: int):
    """Swap the module-global runtime for a recorder and reset the
    tensor-name registry so auto names are deterministic per simulated
    rank; restore everything on exit."""
    import horovod_tpu as hvd

    saved = (
        hvd._runtime, dict(hvd._name_counters), dict(hvd._process_sets),
        hvd._ps_barrier_seq, hvd._mesh,
    )
    recorder = _RecordingRuntime(rank, size)
    hvd._runtime = recorder
    hvd._name_counters.clear()
    hvd._process_sets.clear()
    hvd._ps_barrier_seq = 0
    try:
        yield recorder
    finally:
        (hvd._runtime, counters, sets, hvd._ps_barrier_seq,
         hvd._mesh) = saved
        hvd._name_counters.clear()
        hvd._name_counters.update(counters)
        hvd._process_sets.clear()
        hvd._process_sets.update(sets)


def record_rank_trace(
    fn: Callable[..., Any], rank: int, size: int, *args: Any, **kwargs: Any
) -> List[CollectiveCall]:
    """Run ``fn(*args, **kwargs)`` as simulated ``rank`` of ``size`` with
    a recording runtime and return its collective-submission sequence.
    ``fn`` may read ``hvd.rank()`` / ``hvd.size()`` — the stub answers
    with the simulated identity."""
    with _simulated_rank(rank, size) as recorder:
        fn(*args, **kwargs)
    return recorder.calls


def simulate_ranks(
    fn: Callable[..., Any], size: int, *args: Any, **kwargs: Any
) -> Dict[int, List[CollectiveCall]]:
    """Record every rank's trace of ``fn`` (called once per simulated
    rank)."""
    return {
        r: record_rank_trace(fn, r, size, *args, **kwargs)
        for r in range(size)
    }


def check_cross_rank_order(
    traces: Dict[int, Sequence[CollectiveCall]],
) -> List[Finding]:
    """Compare per-process-set collective sequences across ranks.

    A divergence is reported at its first occurrence, naming the two
    tensors and the two ranks involved — the exact diagnostic a deadlocked
    job needs, emitted before anything is submitted. Rank membership is
    taken from the traces themselves: a rank that never touches a process
    set is assumed to be a non-member (legal), but a rank whose sequence
    *diverges* from a peer's is an error.
    """
    findings: List[Finding] = []
    psids = sorted(
        {c.process_set_id for calls in traces.values() for c in calls}
    )
    for psid in psids:
        per_rank = {
            r: [c for c in calls if c.process_set_id == psid]
            for r, calls in traces.items()
        }
        # Non-members (no submissions at all for this set) are skipped.
        members = {r: seq for r, seq in per_rank.items() if seq}
        if len(members) < 2:
            continue
        ref_rank = min(members)
        ref = members[ref_rank]
        for r in sorted(members):
            if r == ref_rank:
                continue
            seq = members[r]
            findings.extend(
                _diff_sequences(psid, ref_rank, ref, r, seq)
            )
    return findings


def _diff_sequences(
    psid: int,
    rank_a: int,
    seq_a: Sequence[CollectiveCall],
    rank_b: int,
    seq_b: Sequence[CollectiveCall],
) -> List[Finding]:
    loc = f"order:process_set={psid}"
    for i, (ca, cb) in enumerate(zip(seq_a, seq_b)):
        if ca.name != cb.name or ca.op != cb.op:
            return [
                Finding(
                    rule=RULE_ORDER_MISMATCH,
                    severity=SEVERITY_ERROR,
                    message=(
                        f"collective order diverges at position {i} of "
                        f"process set {psid}: rank {rank_a} submits "
                        f"{ca.op} '{ca.name}' while rank {rank_b} submits "
                        f"{cb.op} '{cb.name}' — these ranks would "
                        "deadlock waiting for each other"
                    ),
                    location=loc,
                    details={
                        "position": i,
                        "process_set_id": psid,
                        "rank_a": rank_a,
                        "rank_b": rank_b,
                        "tensor_a": ca.name,
                        "tensor_b": cb.name,
                    },
                )
            ]
        if (ca.dtype, ca.shape) != (cb.dtype, cb.shape):
            return [
                Finding(
                    rule=RULE_SIGNATURE_MISMATCH,
                    severity=SEVERITY_ERROR,
                    message=(
                        f"'{ca.name}' (position {i}, process set {psid}) "
                        f"has mismatched signatures: rank {rank_a} "
                        f"submits {ca.dtype}{list(ca.shape)} while rank "
                        f"{rank_b} submits {cb.dtype}{list(cb.shape)}"
                    ),
                    location=loc,
                    details={
                        "position": i,
                        "process_set_id": psid,
                        "rank_a": rank_a,
                        "rank_b": rank_b,
                        "tensor": ca.name,
                        "signature_a": f"{ca.dtype}{list(ca.shape)}",
                        "signature_b": f"{cb.dtype}{list(cb.shape)}",
                    },
                )
            ]
    if len(seq_a) != len(seq_b):
        longer_rank, longer, i = (
            (rank_a, seq_a, len(seq_b))
            if len(seq_a) > len(seq_b)
            else (rank_b, seq_b, len(seq_a))
        )
        shorter_rank = rank_b if longer_rank == rank_a else rank_a
        extra = longer[i]
        return [
            Finding(
                rule=RULE_MISSING_COLLECTIVE,
                severity=SEVERITY_ERROR,
                message=(
                    f"rank {longer_rank} submits {extra.op} "
                    f"'{extra.name}' (position {i}, process set {psid}) "
                    f"that rank {shorter_rank} never submits — rank "
                    f"{longer_rank} would hang in it forever"
                ),
                location=loc,
                details={
                    "position": i,
                    "process_set_id": psid,
                    "rank_present": longer_rank,
                    "rank_missing": shorter_rank,
                    "tensor": extra.name,
                },
            )
        ]
    return []
