"""Pass 1 — collective lint over jaxprs.

The coordinator in the reference exists to catch collectives submitted in
different orders or with mismatched shapes at *runtime* (stall inspector,
controller validation). Under XLA the whole collective schedule is visible
*before* execution: ``jax.make_jaxpr`` of a train step exposes every
``psum`` / ``all_gather`` / ``ppermute`` / ``all_to_all`` the step will
issue, including those buried inside ``pjit`` / ``scan`` / ``while`` /
``shard_map`` sub-jaxprs. This module walks that structure and checks:

 - every collective's axis names exist in the active mesh
   (:data:`RULE_UNKNOWN_AXIS`);
 - every ``ppermute`` permutation is a complete bijection over its axis —
   a duplicate source/destination is rejected, and a hole (a rank that
   never receives) is flagged unless every use of the result is masked
   through ``select_n`` (the guarded-partial-permute idiom the in-repo
   binomial-tree broadcast uses) (:data:`RULE_PPERMUTE`);
 - fused allreduce buckets (``concatenate`` feeding a ``psum``) stay
   within the fusion-buffer budget (:data:`RULE_FUSION_BUDGET`).

Cross-rank ordering (the deadlock lint) lives in ``analysis.ordering``:
SPMD jaxprs are order-identical across ranks by construction, so ordering
divergence is a property of the *eager named-op* path, linted by simulating
ranks against the tensor-name registry.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .findings import (
    Finding,
    RULE_FUSION_BUDGET,
    RULE_PPERMUTE,
    RULE_UNKNOWN_AXIS,
    SEVERITY_ERROR,
    SEVERITY_WARNING,
    apply_suppressions,
)

# Primitive-name vocabulary. jax names the replicated-tracing variants of
# psum/pbroadcast with a ``2`` suffix (shard_map check_rep/check_vma), and
# psum_scatter lowers to ``reduce_scatter``.
COLLECTIVE_PRIMITIVES = {
    "psum": "allreduce",
    "psum2": "allreduce",
    "pmax": "allreduce",
    "pmin": "allreduce",
    "ppermute": "ppermute",
    "pbroadcast": "broadcast",
    "all_gather": "allgather",
    "all_to_all": "alltoall",
    "reduce_scatter": "reducescatter",
    "axis_index": "axis_index",
}


@dataclass
class CollectiveSite:
    """One collective equation found in the (possibly nested) jaxpr."""

    primitive: str
    kind: str
    axes: Tuple[str, ...]
    params: Dict[str, Any]
    nbytes: int
    dtype: str
    path: str  # e.g. "pjit/shard_map/scan"
    # The jaxpr the equation lives in plus the equation itself, so checks
    # can inspect producers/consumers (fusion buckets, select_n guards).
    jaxpr: Any = None
    eqn: Any = None
    # Axis sizes visible at this site (from enclosing shard_map meshes).
    axis_sizes: Dict[str, int] = field(default_factory=dict)

    @property
    def location(self) -> str:
        return f"jaxpr:{self.path}/{self.primitive}"


def _axis_names(params: Dict[str, Any]) -> Tuple[str, ...]:
    axes = params.get("axes", params.get("axis_name", ()))
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(str(a) for a in axes)


def _aval_nbytes(aval: Any) -> int:
    try:
        size = int(math.prod(aval.shape)) if aval.shape else 1
        return size * aval.dtype.itemsize
    except Exception:  # noqa: BLE001 - abstract values without shape
        return 0


def _sub_jaxprs(value: Any) -> Iterable[Any]:
    """Yield any jaxpr-like objects inside an eqn param value (handles
    pjit's ClosedJaxpr, scan/shard_map's Jaxpr, cond's branch tuples)."""
    values = value if isinstance(value, (list, tuple)) else (value,)
    for item in values:
        if hasattr(item, "eqns"):
            yield item
        elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
            yield item.jaxpr


def collect_collectives(
    jaxpr: Any,
    path: str = "",
    axis_sizes: Optional[Dict[str, int]] = None,
) -> List[CollectiveSite]:
    """Recursively walk ``jaxpr`` (a Jaxpr or ClosedJaxpr) and return every
    collective equation, annotated with the axis sizes of any enclosing
    ``shard_map`` meshes."""
    if hasattr(jaxpr, "jaxpr"):  # ClosedJaxpr
        jaxpr = jaxpr.jaxpr
    axis_sizes = dict(axis_sizes or {})
    sites: List[CollectiveSite] = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMITIVES and name != "axis_index":
            invar = eqn.invars[0] if eqn.invars else None
            aval = getattr(invar, "aval", None)
            sites.append(
                CollectiveSite(
                    primitive=name,
                    kind=COLLECTIVE_PRIMITIVES[name],
                    axes=_axis_names(eqn.params),
                    params=dict(eqn.params),
                    nbytes=_aval_nbytes(aval) if aval is not None else 0,
                    dtype=str(getattr(aval, "dtype", "")),
                    path=path or "top",
                    jaxpr=jaxpr,
                    eqn=eqn,
                    axis_sizes=dict(axis_sizes),
                )
            )
        inner_sizes = axis_sizes
        mesh = eqn.params.get("mesh")
        if mesh is not None and hasattr(mesh, "shape"):
            inner_sizes = dict(axis_sizes)
            try:
                inner_sizes.update(
                    {str(k): int(v) for k, v in dict(mesh.shape).items()}
                )
            except Exception:  # noqa: BLE001 - AbstractMesh variants
                pass
        child_path = f"{path}/{name}" if path else name
        for sub in _sub_jaxprs_of_eqn(eqn):
            sites.extend(collect_collectives(sub, child_path, inner_sizes))
    return sites


def _sub_jaxprs_of_eqn(eqn: Any) -> Iterable[Any]:
    for value in eqn.params.values():
        yield from _sub_jaxprs(value)


def _mesh_axis_sizes(mesh: Any) -> Dict[str, int]:
    """Normalize a mesh spec — a jax ``Mesh``, a ``{name: size}`` dict, or
    None — into a name→size dict."""
    if mesh is None:
        return {}
    if isinstance(mesh, dict):
        return {str(k): int(v) for k, v in mesh.items()}
    shape = getattr(mesh, "shape", None)
    if shape is not None:
        try:
            return {str(k): int(v) for k, v in dict(shape).items()}
        except Exception:  # noqa: BLE001
            pass
    names = getattr(mesh, "axis_names", None)
    if names is not None:
        sizes = getattr(mesh, "axis_sizes", None) or ()
        return {
            str(n): int(s)
            for n, s in zip(names, sizes or [0] * len(names))
        }
    raise TypeError(f"cannot read axis sizes from mesh spec {mesh!r}")


def _check_axes(
    site: CollectiveSite, known: Dict[str, int]
) -> List[Finding]:
    out: List[Finding] = []
    for axis in site.axes:
        if axis not in known:
            out.append(
                Finding(
                    rule=RULE_UNKNOWN_AXIS,
                    severity=SEVERITY_ERROR,
                    message=(
                        f"{site.kind} over axis {axis!r} which is not an "
                        f"axis of the active mesh "
                        f"(known axes: {sorted(known) or 'none'})"
                    ),
                    location=site.location,
                    details={"axis": axis, "known_axes": sorted(known)},
                )
            )
    return out


def _is_select(eqn: Any) -> bool:
    """select_n, or a pjit wrapper whose body is only select_n — how
    ``jnp.where`` appears in a jaxpr."""
    if eqn.primitive.name == "select_n":
        return True
    if eqn.primitive.name == "pjit":
        for sub in _sub_jaxprs_of_eqn(eqn):
            if any(e.primitive.name != "select_n" for e in sub.eqns):
                return False
        return True
    return False


def _select_guarded(site: CollectiveSite) -> bool:
    """True when every consumer of the ppermute result in its jaxpr is a
    ``select_n`` — the masked-partial-permute idiom (e.g. the binomial
    broadcast), where holes cannot leak unreceived values."""
    outvars = {id(v) for v in site.eqn.outvars}
    consumed = False
    for eqn in site.jaxpr.eqns:
        if eqn is site.eqn:
            continue
        if any(id(v) in outvars for v in eqn.invars):
            consumed = True
            if not _is_select(eqn):
                return False
    # Unconsumed results also can't leak a hole into downstream values,
    # but an output-returned hole can — require at least one select_n
    # consumer OR no consumption at all with no jaxpr output.
    if not consumed:
        return not any(id(v) in outvars for v in site.jaxpr.outvars)
    return True


def _check_ppermute(
    site: CollectiveSite, known: Dict[str, int]
) -> List[Finding]:
    perm = site.params.get("perm") or ()
    pairs = [(int(s), int(d)) for s, d in perm]
    axis = site.axes[0] if site.axes else None
    n = site.axis_sizes.get(axis) or known.get(axis) or 0
    out: List[Finding] = []
    srcs = [s for s, _ in pairs]
    dsts = [d for _, d in pairs]
    problems: List[str] = []
    if len(set(srcs)) != len(srcs):
        dup = sorted({s for s in srcs if srcs.count(s) > 1})
        problems.append(f"duplicate source ranks {dup}")
    if len(set(dsts)) != len(dsts):
        dup = sorted({d for d in dsts if dsts.count(d) > 1})
        problems.append(f"duplicate destination ranks {dup}")
    if n:
        bad = sorted(
            {r for r in srcs + dsts if r < 0 or r >= n}
        )
        if bad:
            problems.append(f"ranks {bad} outside [0, {n})")
        holes = sorted(set(range(n)) - set(dsts))
        if holes and not problems and not _select_guarded(site):
            problems.append(
                f"ranks {holes} never receive (hole ⇒ silent hang on ICI) "
                "and the result is used unmasked"
            )
    if problems:
        out.append(
            Finding(
                rule=RULE_PPERMUTE,
                severity=SEVERITY_ERROR,
                message=(
                    f"ppermute over axis {axis!r} "
                    f"(size {n or 'unknown'}) is not a complete bijection: "
                    + "; ".join(problems)
                ),
                location=site.location,
                details={
                    "axis": axis or "",
                    "axis_size": n,
                    "perm": [list(p) for p in pairs],
                },
            )
        )
    return out


def _check_fusion_budget(
    site: CollectiveSite, threshold_bytes: Optional[int]
) -> List[Finding]:
    if not threshold_bytes or site.kind != "allreduce":
        return []
    # Only flag *fused buckets* (a concatenate feeding the psum): a single
    # large gradient legally owns an over-threshold bucket of its own.
    invar = site.eqn.invars[0] if site.eqn.invars else None
    producer = None
    for eqn in site.jaxpr.eqns:
        if invar is not None and any(v is invar for v in eqn.outvars):
            producer = eqn
            break
    if producer is None or producer.primitive.name != "concatenate":
        return []
    if site.nbytes <= threshold_bytes:
        return []
    return [
        Finding(
            rule=RULE_FUSION_BUDGET,
            severity=SEVERITY_WARNING,
            message=(
                f"fused allreduce bucket is {site.nbytes} bytes, over the "
                f"{threshold_bytes}-byte fusion-buffer budget "
                f"({len(producer.invars)} leaves concatenated)"
            ),
            location=site.location,
            details={
                "bucket_bytes": site.nbytes,
                "threshold_bytes": threshold_bytes,
                "leaves": len(producer.invars),
            },
        )
    ]


def lint_jaxpr(
    closed_jaxpr: Any,
    *,
    mesh: Any = None,
    fusion_threshold_bytes: Optional[int] = None,
    divergence: bool = True,
    suppress: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Lint an already-traced jaxpr (``jax.make_jaxpr(fn)(*args)`` output,
    or any Jaxpr/ClosedJaxpr). ``divergence=True`` folds the Pass 4
    rank-divergence analysis in; ``suppress`` takes call-site suppression
    specs (``"rule"`` or ``"rule@location-glob"``)."""
    known = _mesh_axis_sizes(mesh)
    sites = collect_collectives(closed_jaxpr)
    findings: List[Finding] = []
    for site in sites:
        # Enclosing shard_map meshes extend the known-axis set: an axis
        # bound by the traced fn itself is valid even if the caller's
        # mesh spec doesn't name it — unless a mesh WAS provided, in
        # which case the step's axes must be a subset of it.
        local_known = dict(site.axis_sizes)
        if mesh is not None:
            local_known = known
        else:
            local_known = {**known, **site.axis_sizes}
        findings.extend(_check_axes(site, local_known))
        if site.primitive == "ppermute":
            findings.extend(_check_ppermute(site, local_known))
        findings.extend(_check_fusion_budget(site, fusion_threshold_bytes))
    if divergence:
        from .divergence import analyze_divergence

        findings.extend(analyze_divergence(closed_jaxpr))
    return apply_suppressions(findings, suppress)


def lint_step(
    fn: Any,
    *args: Any,
    mesh: Any = None,
    fusion_threshold_bytes: Optional[int] = None,
    divergence: bool = True,
    suppress: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Trace ``fn(*args)`` to a jaxpr and lint it. A trace-time unbound
    axis (jax's own NameError) is converted into an ``unknown-axis``
    finding instead of propagating, so the CLI reports it uniformly.

    The trace also feeds the guard-skip-agreement rule: the streamed
    registration and skip-agreement-seam ledgers are drained before and
    consumed after, so a step using streamed overlap under
    ``HOROVOD_GUARD_NONFINITE=skip`` without the agreement collective is
    flagged (docs/fault_tolerance.md). The Pass 4 rank-divergence
    analysis runs over the same trace (``divergence=False`` opts out);
    ``suppress`` filters findings at this call site
    (docs/static_analysis.md "Suppressions")."""
    import jax

    from ..guard import nonfinite as _nf
    from ..ops import fusion as _fusion
    from .preflight import check_guard_skip_agreement

    # Drain stale ledgers so this trace's counts are its own.
    _fusion.take_stream_registrations()
    _nf.take_seam_registrations()
    try:
        closed = jax.make_jaxpr(fn)(*args)
    except NameError as exc:
        return apply_suppressions([
            Finding(
                rule=RULE_UNKNOWN_AXIS,
                severity=SEVERITY_ERROR,
                message=(
                    f"tracing failed with an unbound axis name: {exc}"
                ),
                location="trace",
                details={"exception": str(exc)},
            )
        ], suppress)
    stream_calls = _fusion.take_stream_registrations()["calls"]
    seam_calls = _nf.take_seam_registrations()
    findings = lint_jaxpr(
        closed, mesh=mesh, fusion_threshold_bytes=fusion_threshold_bytes,
        divergence=divergence,
    )
    findings.extend(
        check_guard_skip_agreement(stream_calls, seam_calls)
    )
    return apply_suppressions(findings, suppress)
