"""Pass 2 — runtime thread-safety lint (AST-based lock-discipline checker).

The eager runtime is a multi-threaded producer/consumer system: framework
threads enqueue, a background/executor thread consumes, and inline
``synchronize()`` callers may steal the consumer role. Its correctness
rests on a small set of invariants — *this attribute is only ever mutated
under that lock* — that ordinary tests can't pin down (races are timing-
dependent). This checker makes the discipline explicit and machine-checked:

 - :data:`DEFAULT_DISCIPLINE` declares, per runtime class, which
   attributes are shared state and which lock guards them (or which
   methods they are confined to — e.g. state touched only by the
   coordinator thread's cycle loop, or by the plan consumer serialized
   under ``NativeRuntime._consumer_lock``);
 - the checker walks each method's AST, tracks the lexically-held locks
   (``with self._lock:`` blocks, including aliases like
   ``Condition(self._lock)`` exposed as ``self._cv``), and flags any
   mutation of a guarded attribute outside its lock
   (:data:`RULE_UNGUARDED`);
 - a finding can be suppressed in-source with
   ``# hvd-analysis: ignore[unguarded-shared-state]`` on the flagged line
   or the line directly above it.

Lexical, not dynamic: aliased mutations (``q = self._table[k]; q.pop()``)
are out of scope, as is cross-object access (``rt.queue._table``) — the
discipline table names the hot shared state where a missed lock means a
corrupted tensor table or a hung training job.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .findings import Finding, RULE_UNGUARDED, SEVERITY_ERROR

# Method names that mutate common containers in place.
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "clear", "update", "setdefault",
    "add", "discard", "sort", "reverse",
}

_SUPPRESS_RE = re.compile(
    r"#\s*hvd-analysis:\s*ignore(?:\[(?P<rules>[\w\s,-]+)\])?"
)


@dataclass
class AttrRule:
    """Discipline for one shared attribute: guarded by ``lock`` (an
    attribute name on the same object), and/or mutation-confined to the
    listed methods (single-thread confinement, e.g. coordinator-loop-only
    state). ``__init__`` is always exempt — construction is
    single-threaded."""

    lock: Optional[str] = None
    confined_to: Tuple[str, ...] = ()
    note: str = ""


@dataclass
class ClassRule:
    attrs: Dict[str, AttrRule]
    # Lock attributes that wrap/alias another (a Condition built on a
    # Lock): holding the alias counts as holding the canonical lock.
    lock_aliases: Dict[str, str] = field(default_factory=dict)

    def canonical(self, lock_name: str) -> str:
        return self.lock_aliases.get(lock_name, lock_name)

    def lock_names(self) -> Set[str]:
        names = {r.lock for r in self.attrs.values() if r.lock}
        names |= set(self.lock_aliases)
        names |= set(self.lock_aliases.values())
        return names


# The runtime's lock discipline, by source basename. This table IS the
# documentation of which state is shared and how it is protected — see
# docs/static_analysis.md for prose.
DEFAULT_DISCIPLINE: Dict[str, Dict[str, ClassRule]] = {
    "runtime.py": {
        "TensorQueue": ClassRule(
            attrs={
                "_table": AttrRule("_lock"),
                "_pending": AttrRule("_lock"),
            },
        ),
        "HandleManager": ClassRule(
            attrs={
                "_results": AttrRule("_lock"),
                "_next": AttrRule("_lock"),
                "_names": AttrRule("_lock"),
            },
            lock_aliases={"_cv": "_lock"},
        ),
        "Runtime": ClassRule(
            attrs={
                # Mutated by user threads (register/remove/enqueue_join)
                # AND read/cleared on the background thread — must hold
                # _state_lock.
                "_process_sets": AttrRule("_state_lock"),
                "joined": AttrRule("_state_lock"),
                # Background-thread confined: written by the cycle loop
                # before it sets _shutdown, read by the loop's final
                # drain (the Event is the happens-before edge).
                "_drain_status": AttrRule(
                    None, confined_to=("_run_cycle_once",)
                ),
            },
        ),
        "StallInspector": ClassRule(
            attrs={
                # Coordinator-thread confined: only the cycle loop calls
                # these methods (operations.cc keeps the same invariant).
                "_first_seen": AttrRule(
                    None, confined_to=("record", "clear", "check")
                ),
                "_last_warned": AttrRule(
                    None, confined_to=("record", "clear", "check")
                ),
                "should_shutdown": AttrRule(None, confined_to=("check",)),
            },
        ),
    },
    "native_runtime.py": {
        "NativeRuntime": ClassRule(
            attrs={
                "_entries": AttrRule("_entries_lock"),
                "_outputs": AttrRule("_cv"),
                "_ticket_names": AttrRule("_cv"),
                "_done": AttrRule("_cv"),
                "_sync_waiters": AttrRule("_cv"),
            },
        ),
    },
    "xla_executor.py": {
        "XlaPlanExecutor": ClassRule(
            attrs={
                "_fn_cache": AttrRule("_lock"),
                "_sets": AttrRule("_lock"),
                # Plan execution is serialized by NativeRuntime's
                # _consumer_lock (pop+execute is one atomic unit), so the
                # fence state is consumer-confined to execute().
                "_inflight_outs": AttrRule(
                    None, confined_to=("execute",),
                    note="serialized by NativeRuntime._consumer_lock",
                ),
            },
        ),
    },
}


def _base_self_attr(node: ast.AST) -> Optional[str]:
    """Resolve an expression chain (self.X.method(...).other[...]) down to
    the ``self.X`` base attribute name, or None."""
    while True:
        if isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                return node.attr
            node = node.value
        elif isinstance(node, (ast.Subscript, ast.Starred)):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            return None


def _direct_self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _MethodChecker(ast.NodeVisitor):
    """Walks one method body tracking lexically-held locks."""

    def __init__(self, cls_name: str, method: str, rule: ClassRule,
                 filename: str, src_lines: Sequence[str]):
        self.cls_name = cls_name
        self.method = method
        self.rule = rule
        self.filename = filename
        self.src_lines = src_lines
        self.held: Set[str] = set()
        self.findings: List[Finding] = []

    # -- lock tracking --
    def visit_With(self, node: ast.With) -> None:
        acquired: Set[str] = set()
        for item in node.items:
            attr = _direct_self_attr(item.context_expr)
            if attr is not None and attr in self.rule.lock_names():
                acquired.add(self.rule.canonical(attr))
                acquired.add(attr)
        newly = acquired - self.held
        self.held |= newly
        for stmt in node.body:
            self.visit(stmt)
        self.held -= newly

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # A nested def runs later, on whatever thread calls it: locks held
        # at definition time are NOT held at call time.
        saved, self.held = self.held, set()
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    # -- mutation detection --
    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._check_target(target, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_target(node.target, node)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._check_target(target, node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            attr = _base_self_attr(func.value)
            if attr is not None:
                self._flag_if_unguarded(attr, node, f".{func.attr}(...)")
        self.generic_visit(node)

    def _check_target(self, target: ast.AST, node: ast.AST) -> None:
        attr = _direct_self_attr(target)
        how = "assignment"
        if attr is None and isinstance(target, ast.Subscript):
            attr = _base_self_attr(target.value)
            how = "item assignment"
        if attr is None and isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._check_target(elt, node)
            return
        if attr is not None:
            self._flag_if_unguarded(attr, node, how)

    def _flag_if_unguarded(self, attr: str, node: ast.AST,
                           how: str) -> None:
        arule = self.rule.attrs.get(attr)
        if arule is None:
            return
        if self.method == "__init__":
            return
        if arule.confined_to and self.method in arule.confined_to:
            return
        if arule.lock and self.rule.canonical(arule.lock) in {
            self.rule.canonical(h) for h in self.held
        }:
            return
        if arule.lock is None and not arule.confined_to:
            return
        line = getattr(node, "lineno", 0)
        if self._suppressed(line):
            return
        if arule.lock:
            expectation = f"must hold self.{arule.lock}"
        else:
            expectation = (
                "mutation is confined to "
                + "/".join(arule.confined_to)
                + (f" ({arule.note})" if arule.note else "")
            )
        self.findings.append(
            Finding(
                rule=RULE_UNGUARDED,
                severity=SEVERITY_ERROR,
                message=(
                    f"unguarded mutation of shared state "
                    f"self.{attr} ({how}) in "
                    f"{self.cls_name}.{self.method}: {expectation}"
                ),
                location=f"{self.filename}:{line}",
                details={
                    "class": self.cls_name,
                    "method": self.method,
                    "attribute": attr,
                    "expected_lock": arule.lock or "",
                },
            )
        )

    def _suppressed(self, lineno: int) -> bool:
        for ln in (lineno, lineno - 1):
            if 1 <= ln <= len(self.src_lines):
                m = _SUPPRESS_RE.search(self.src_lines[ln - 1])
                if m:
                    rules = m.group("rules")
                    if rules is None:
                        return True
                    wanted = {r.strip() for r in rules.split(",")}
                    if RULE_UNGUARDED in wanted:
                        return True
        return False


def lint_source(
    src: str,
    rules: Dict[str, ClassRule],
    filename: str = "<memory>",
) -> List[Finding]:
    """Lint python source text against a class→discipline mapping."""
    tree = ast.parse(src, filename=filename)
    src_lines = src.splitlines()
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        rule = rules.get(node.name)
        if rule is None:
            continue
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                checker = _MethodChecker(
                    node.name, item.name, rule, filename, src_lines
                )
                for stmt in item.body:
                    checker.visit(stmt)
                findings.extend(checker.findings)
    return findings


def lint_file(
    path: str, rules: Optional[Dict[str, ClassRule]] = None
) -> List[Finding]:
    if rules is None:
        rules = DEFAULT_DISCIPLINE.get(os.path.basename(path), {})
    if not rules:
        return []
    with open(path, "r") as f:
        src = f.read()
    return lint_source(src, rules, filename=os.path.basename(path))


def default_runtime_paths() -> List[str]:
    core = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "core")
    return [
        os.path.join(core, name)
        for name in ("runtime.py", "native_runtime.py", "xla_executor.py")
    ]


def lint_runtime(paths: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the lock-discipline check over the runtime sources (the three
    core modules by default)."""
    findings: List[Finding] = []
    for path in paths or default_runtime_paths():
        findings.extend(lint_file(path))
    return findings
