"""Pass 2 — runtime thread-safety lint (AST-based lock-discipline checker).

The eager runtime is a multi-threaded producer/consumer system: framework
threads enqueue, a background/executor thread consumes, and inline
``synchronize()`` callers may steal the consumer role. Its correctness
rests on a small set of invariants — *this attribute is only ever mutated
under that lock* — that ordinary tests can't pin down (races are timing-
dependent). This checker makes the discipline explicit and machine-checked:

 - :data:`DEFAULT_DISCIPLINE` declares, per source file (keyed by its
   repo-relative path suffix), which attributes are shared state and
   which lock guards them (or which methods they are confined to — e.g.
   state touched only by the coordinator thread's cycle loop, or by the
   plan consumer serialized under ``NativeRuntime._consumer_lock``).
   The pseudo-class name :data:`MODULE` declares the same discipline for
   *module-level* globals (the tap-singleton pattern ``fault/``,
   ``guard/``, and ``metrics/`` share: ``ACTIVE``/``TAP`` flipped under a
   module ``_lock``);
 - the checker walks each method's (or module function's) AST, tracks
   the lexically-held locks (``with self._lock:`` / ``with _lock:``
   blocks, including aliases like ``Condition(self._lock)`` exposed as
   ``self._cv``), and flags any mutation of a guarded attribute outside
   its lock (:data:`RULE_UNGUARDED`);
 - a finding can be suppressed in-source with
   ``# hvd-analysis: ignore[unguarded-shared-state]`` on the flagged line
   or the line directly above it.

Lexical, not dynamic: aliased mutations (``q = self._table[k]; q.pop()``)
are out of scope, as is cross-object access (``rt.queue._table``) — the
discipline table names the hot shared state where a missed lock means a
corrupted tensor table or a hung training job.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .findings import Finding, RULE_UNGUARDED, SEVERITY_ERROR

# Method names that mutate common containers in place.
_MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "clear", "update", "setdefault",
    "add", "discard", "sort", "reverse",
}

_SUPPRESS_RE = re.compile(
    r"#\s*hvd-analysis:\s*ignore(?:\[(?P<rules>[\w\s,-]+)\])?"
)


@dataclass
class AttrRule:
    """Discipline for one shared attribute: guarded by ``lock`` (an
    attribute name on the same object), and/or mutation-confined to the
    listed methods (single-thread confinement, e.g. coordinator-loop-only
    state). ``__init__`` is always exempt — construction is
    single-threaded."""

    lock: Optional[str] = None
    confined_to: Tuple[str, ...] = ()
    note: str = ""


@dataclass
class ClassRule:
    attrs: Dict[str, AttrRule]
    # Lock attributes that wrap/alias another (a Condition built on a
    # Lock): holding the alias counts as holding the canonical lock.
    lock_aliases: Dict[str, str] = field(default_factory=dict)

    def canonical(self, lock_name: str) -> str:
        return self.lock_aliases.get(lock_name, lock_name)

    def lock_names(self) -> Set[str]:
        names = {r.lock for r in self.attrs.values() if r.lock}
        names |= set(self.lock_aliases)
        names |= set(self.lock_aliases.values())
        return names


# Pseudo-class key declaring discipline for module-level globals.
MODULE = "<module>"

# The runtime's lock discipline, keyed by repo-relative source path
# suffix (``core/runtime.py`` matches ``.../horovod_tpu/core/runtime.py``).
# This table IS the documentation of which state is shared and how it is
# protected — see docs/static_analysis.md for prose. An entry with no
# rules (the ``topo/`` planning layer) records, machine-checkably, that
# the file is *supposed* to hold no shared mutable state.
DEFAULT_DISCIPLINE: Dict[str, Dict[str, ClassRule]] = {
    "core/runtime.py": {
        "TensorQueue": ClassRule(
            attrs={
                "_table": AttrRule("_lock"),
                "_pending": AttrRule("_lock"),
            },
        ),
        "HandleManager": ClassRule(
            attrs={
                "_results": AttrRule("_lock"),
                "_next": AttrRule("_lock"),
                "_names": AttrRule("_lock"),
            },
            lock_aliases={"_cv": "_lock"},
        ),
        "Runtime": ClassRule(
            attrs={
                # Mutated by user threads (register/remove/enqueue_join)
                # AND read/cleared on the background thread — must hold
                # _state_lock.
                "_process_sets": AttrRule("_state_lock"),
                "joined": AttrRule("_state_lock"),
                # Background-thread confined: written by the cycle loop
                # before it sets _shutdown, read by the loop's final
                # drain (the Event is the happens-before edge).
                "_drain_status": AttrRule(
                    None, confined_to=("_run_cycle_once",)
                ),
            },
        ),
        "StallInspector": ClassRule(
            attrs={
                # Coordinator-thread confined: only the cycle loop calls
                # these methods (operations.cc keeps the same invariant).
                "_first_seen": AttrRule(
                    None, confined_to=("record", "clear", "check")
                ),
                "_last_warned": AttrRule(
                    None, confined_to=("record", "clear", "check")
                ),
                "should_shutdown": AttrRule(None, confined_to=("check",)),
            },
        ),
    },
    "core/native_runtime.py": {
        "NativeRuntime": ClassRule(
            attrs={
                "_entries": AttrRule("_entries_lock"),
                "_outputs": AttrRule("_cv"),
                "_ticket_names": AttrRule("_cv"),
                "_done": AttrRule("_cv"),
                "_sync_waiters": AttrRule("_cv"),
            },
        ),
    },
    "core/xla_executor.py": {
        "XlaPlanExecutor": ClassRule(
            attrs={
                "_fn_cache": AttrRule("_lock"),
                "_sets": AttrRule("_lock"),
                # Plan execution is serialized by NativeRuntime's
                # _consumer_lock (pop+execute is one atomic unit), so the
                # fence state is consumer-confined to execute().
                "_inflight_outs": AttrRule(
                    None, confined_to=("execute",),
                    note="serialized by NativeRuntime._consumer_lock",
                ),
            },
        ),
    },
    # --- packages added since PR 1 (PR 8 extension) ---
    "fault/injector.py": {
        MODULE: ClassRule(
            attrs={
                # The plan/counters/event-log are hit from framework
                # threads, the runtime background thread, and the driver
                # loop simultaneously (fault_point is called everywhere).
                "_plan": AttrRule("_lock"),
                "_counters": AttrRule("_lock"),
                "_events": AttrRule("_lock"),
                "_seq": AttrRule("_lock"),
                "ACTIVE": AttrRule("_lock"),
            },
        ),
    },
    "guard/__init__.py": {
        MODULE: ClassRule(
            attrs={
                "TAP": AttrRule("_lock"),
                "ACTIVE": AttrRule("_lock"),
                "_guard_event_hits": AttrRule("_event_lock"),
            },
        ),
    },
    "metrics/__init__.py": {
        MODULE: ClassRule(
            attrs={
                "TAP": AttrRule("_lock"),
                "ACTIVE": AttrRule("_lock"),
            },
        ),
    },
    "metrics/registry.py": {
        # Every Metric subclass shares the base-class series table; one
        # rule per class the file defines keeps the mapping lexical.
        "Counter": ClassRule(attrs={"_series": AttrRule("_lock")}),
        "Gauge": ClassRule(attrs={"_series": AttrRule("_lock")}),
        "Histogram": ClassRule(attrs={"_series": AttrRule("_lock")}),
        "Registry": ClassRule(attrs={"_metrics": AttrRule("_lock")}),
    },
    "run/journal.py": {
        "DriverJournal": ClassRule(
            attrs={
                # Supervision-loop confined: only the elastic driver's
                # single control thread records transitions; the HTTP KV
                # threads never touch the journal.
                "_state": AttrRule(
                    None, confined_to=("record", "replay", "_write"),
                    note="elastic-driver supervision loop only",
                ),
                "writes": AttrRule(
                    None, confined_to=("record", "_write"),
                    note="elastic-driver supervision loop only",
                ),
            },
        ),
    },
    # The topo planning layer is deliberately stateless (pure functions
    # over frozen dataclasses): declaring the empty discipline here keeps
    # these files in the scanned set so a future module-level cache shows
    # up as an undeclared-discipline diff in review, not a silent race.
    "topo/model.py": {},
    "topo/compositor.py": {},
}


def _base_self_attr(node: ast.AST) -> Optional[str]:
    """Resolve an expression chain (self.X.method(...).other[...]) down to
    the ``self.X`` base attribute name, or None."""
    while True:
        if isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                return node.attr
            node = node.value
        elif isinstance(node, (ast.Subscript, ast.Starred)):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        else:
            return None


def _direct_self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _MethodChecker(ast.NodeVisitor):
    """Walks one method body tracking lexically-held locks."""

    def __init__(self, cls_name: str, method: str, rule: ClassRule,
                 filename: str, src_lines: Sequence[str]):
        self.cls_name = cls_name
        self.method = method
        self.rule = rule
        self.filename = filename
        self.src_lines = src_lines
        self.held: Set[str] = set()
        self.findings: List[Finding] = []

    # -- lock tracking --
    def visit_With(self, node: ast.With) -> None:
        acquired: Set[str] = set()
        for item in node.items:
            attr = _direct_self_attr(item.context_expr)
            if attr is not None and attr in self.rule.lock_names():
                acquired.add(self.rule.canonical(attr))
                acquired.add(attr)
        newly = acquired - self.held
        self.held |= newly
        for stmt in node.body:
            self.visit(stmt)
        self.held -= newly

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # A nested def runs later, on whatever thread calls it: locks held
        # at definition time are NOT held at call time.
        saved, self.held = self.held, set()
        for stmt in node.body:
            self.visit(stmt)
        self.held = saved

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    # -- mutation detection --
    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._check_target(target, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_target(node.target, node)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._check_target(target, node)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            attr = _base_self_attr(func.value)
            if attr is not None:
                self._flag_if_unguarded(attr, node, f".{func.attr}(...)")
        self.generic_visit(node)

    def _check_target(self, target: ast.AST, node: ast.AST) -> None:
        attr = _direct_self_attr(target)
        how = "assignment"
        if attr is None and isinstance(target, ast.Subscript):
            attr = _base_self_attr(target.value)
            how = "item assignment"
        if attr is None and isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._check_target(elt, node)
            return
        if attr is not None:
            self._flag_if_unguarded(attr, node, how)

    def _flag_if_unguarded(self, attr: str, node: ast.AST,
                           how: str) -> None:
        arule = self.rule.attrs.get(attr)
        if arule is None:
            return
        if self.method == "__init__":
            return
        if arule.confined_to and self.method in arule.confined_to:
            return
        if arule.lock and self.rule.canonical(arule.lock) in {
            self.rule.canonical(h) for h in self.held
        }:
            return
        if arule.lock is None and not arule.confined_to:
            return
        line = getattr(node, "lineno", 0)
        if self._suppressed(line):
            return
        if arule.lock:
            expectation = f"must hold self.{arule.lock}"
        else:
            expectation = (
                "mutation is confined to "
                + "/".join(arule.confined_to)
                + (f" ({arule.note})" if arule.note else "")
            )
        self.findings.append(
            Finding(
                rule=RULE_UNGUARDED,
                severity=SEVERITY_ERROR,
                message=(
                    f"unguarded mutation of shared state "
                    f"self.{attr} ({how}) in "
                    f"{self.cls_name}.{self.method}: {expectation}"
                ),
                location=f"{self.filename}:{line}",
                details={
                    "class": self.cls_name,
                    "method": self.method,
                    "attribute": attr,
                    "expected_lock": arule.lock or "",
                },
            )
        )

    def _suppressed(self, lineno: int) -> bool:
        for ln in (lineno, lineno - 1):
            if 1 <= ln <= len(self.src_lines):
                m = _SUPPRESS_RE.search(self.src_lines[ln - 1])
                if m:
                    rules = m.group("rules")
                    if rules is None:
                        return True
                    wanted = {r.strip() for r in rules.split(",")}
                    if RULE_UNGUARDED in wanted:
                        return True
        return False


class _ModuleChecker(ast.NodeVisitor):
    """Walks one module-level function tracking lexically-held module
    locks (``with _lock:``) and mutations of declared module globals —
    the tap-singleton discipline of ``fault/injector.py`` and friends.
    A bare-name *assignment* only counts as a global mutation when the
    function declares ``global name`` (else it binds a local); in-place
    mutator calls / item assignments on a declared name always count
    unless the name was rebound locally first."""

    def __init__(self, func: str, rule: ClassRule, filename: str,
                 src_lines: Sequence[str]):
        self.func = func
        self.rule = rule
        self.filename = filename
        self.src_lines = src_lines
        self.held: Set[str] = set()
        self.globals: Set[str] = set()
        self.locals: Set[str] = set()
        self.findings: List[Finding] = []

    def visit_Global(self, node: ast.Global) -> None:
        self.globals.update(node.names)

    def visit_With(self, node: ast.With) -> None:
        acquired: Set[str] = set()
        for item in node.items:
            expr = item.context_expr
            if (isinstance(expr, ast.Name)
                    and expr.id in self.rule.lock_names()):
                acquired.add(self.rule.canonical(expr.id))
                acquired.add(expr.id)
        newly = acquired - self.held
        self.held |= newly
        for stmt in node.body:
            self.visit(stmt)
        self.held -= newly

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # A nested def runs later, on whatever thread calls it.
        saved, self.held = self.held, set()
        saved_g, self.globals = self.globals, set()
        for stmt in node.body:
            self.visit(stmt)
        self.held, self.globals = saved, saved_g

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._check_target(target)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target(node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._check_target(target)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute) and func.attr in _MUTATORS:
            base = func.value
            while isinstance(base, (ast.Subscript, ast.Attribute)):
                base = base.value
            if (isinstance(base, ast.Name)
                    and base.id not in self.locals):
                self._flag_if_unguarded(
                    base.id, node, f".{func.attr}(...)"
                )
        self.generic_visit(node)

    def _check_target(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            if target.id in self.globals:
                self._flag_if_unguarded(target.id, target, "assignment")
            else:
                self.locals.add(target.id)
        elif isinstance(target, ast.Subscript):
            base = target.value
            while isinstance(base, (ast.Subscript, ast.Attribute)):
                base = base.value
            if isinstance(base, ast.Name) and base.id not in self.locals:
                self._flag_if_unguarded(base.id, target, "item assignment")
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._check_target(elt)

    def _flag_if_unguarded(self, name: str, node: ast.AST,
                           how: str) -> None:
        arule = self.rule.attrs.get(name)
        if arule is None:
            return
        if arule.confined_to and self.func in arule.confined_to:
            return
        if arule.lock and self.rule.canonical(arule.lock) in {
            self.rule.canonical(h) for h in self.held
        }:
            return
        if arule.lock is None and not arule.confined_to:
            return
        line = getattr(node, "lineno", 0)
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.src_lines):
                m = _SUPPRESS_RE.search(self.src_lines[ln - 1])
                if m:
                    rules = m.group("rules")
                    if rules is None or RULE_UNGUARDED in {
                        r.strip() for r in rules.split(",")
                    }:
                        return
        if arule.lock:
            expectation = f"must hold {arule.lock}"
        else:
            expectation = (
                "mutation is confined to "
                + "/".join(arule.confined_to)
                + (f" ({arule.note})" if arule.note else "")
            )
        self.findings.append(
            Finding(
                rule=RULE_UNGUARDED,
                severity=SEVERITY_ERROR,
                message=(
                    f"unguarded mutation of module state {name} ({how}) "
                    f"in {self.func}: {expectation}"
                ),
                location=f"{self.filename}:{line}",
                details={
                    "class": MODULE,
                    "method": self.func,
                    "attribute": name,
                    "expected_lock": arule.lock or "",
                },
            )
        )


def lint_source(
    src: str,
    rules: Dict[str, ClassRule],
    filename: str = "<memory>",
) -> List[Finding]:
    """Lint python source text against a class→discipline mapping (the
    pseudo-class :data:`MODULE` checks module-level functions against a
    module-globals discipline)."""
    tree = ast.parse(src, filename=filename)
    src_lines = src.splitlines()
    findings: List[Finding] = []
    module_rule = rules.get(MODULE)
    if module_rule is not None:
        for item in tree.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                checker = _ModuleChecker(
                    item.name, module_rule, filename, src_lines
                )
                for stmt in item.body:
                    checker.visit(stmt)
                findings.extend(checker.findings)
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        rule = rules.get(node.name)
        if rule is None:
            continue
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                checker = _MethodChecker(
                    node.name, item.name, rule, filename, src_lines
                )
                for stmt in item.body:
                    checker.visit(stmt)
                findings.extend(checker.findings)
    return findings


def _discipline_for(path: str) -> Dict[str, ClassRule]:
    """Match ``path`` against the discipline table by posix path suffix
    (longest key wins, so ``metrics/__init__.py`` never collides with
    ``guard/__init__.py``)."""
    norm = os.path.abspath(path).replace(os.sep, "/")
    best: Dict[str, ClassRule] = {}
    best_len = -1
    for key, rules in DEFAULT_DISCIPLINE.items():
        if norm.endswith("/" + key) or norm == key:
            if len(key) > best_len:
                best, best_len = rules, len(key)
    if best_len >= 0:
        return best
    # Fallback: unique-basename match, so ad-hoc copies (tests linting a
    # seeded tmp/runtime.py) still pick up their discipline. Ambiguous
    # basenames (the __init__.py entries) never fall back.
    base = os.path.basename(norm)
    candidates = [
        rules for key, rules in DEFAULT_DISCIPLINE.items()
        if os.path.basename(key) == base
    ]
    if len(candidates) == 1:
        return candidates[0]
    return {}


def lint_file(
    path: str, rules: Optional[Dict[str, ClassRule]] = None
) -> List[Finding]:
    if rules is None:
        rules = _discipline_for(path)
    if not rules:
        return []
    with open(path, "r") as f:
        src = f.read()
    return lint_source(src, rules, filename=os.path.basename(path))


def default_runtime_paths() -> List[str]:
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rel = (
        "core/runtime.py",
        "core/native_runtime.py",
        "core/xla_executor.py",
        # PR 8: packages added since the PR 1 pass landed.
        "fault/injector.py",
        "guard/__init__.py",
        "metrics/__init__.py",
        "metrics/registry.py",
        "run/journal.py",
        "topo/model.py",
        "topo/compositor.py",
    )
    return [os.path.join(pkg, *r.split("/")) for r in rel]


def lint_runtime(paths: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the lock-discipline check over the runtime sources (the three
    core modules by default)."""
    findings: List[Finding] = []
    for path in paths or default_runtime_paths():
        findings.extend(lint_file(path))
    return findings
