"""Eager/op-mode runtime: named-tensor async enqueue + background cycle loop.

TPU-native re-design of the reference core (``horovod/common/operations.cc``):
the same architectural invariant is kept — *all collective work happens on one
background thread per process* (``operations.cc:306-326``); framework callers
are async producers into a mutex-guarded ``TensorQueue`` and the loop is the
single consumer, waking every ``cycle_time_ms`` (default 5 ms,
``operations.cc:411-417``) to negotiate readiness, fuse, and execute.

What changes on TPU: the data plane executes fused XLA collectives (jitted
pack → psum/all_gather/ppermute → unpack) instead of NCCL/MPI calls, and GPU
ready-event polling (``operations.cc:261-285``) disappears — JAX arrays are
ready-by-construction once dispatch returns, and completion is observed with
``block_until_ready`` on the executor thread.

Multi-process coordination (the controller protocol of ``controller.cc``)
plugs in behind the ``Coordinator`` interface; the single-process coordinator
declares every tensor immediately ready, matching the reference's size=1
fast path.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common.env import Config
from ..common.topology import Topology
from ..fault import injector as _fault
from .. import guard as _guard
from .. import metrics as _metrics
from .. import trace as _trace
from ..common.types import (
    DUPLICATE_NAME_ERROR_FMT,
    ReduceOp,
    RequestType,
    ResponseType,
    SHUT_DOWN_ERROR,
    Status,
    TensorTableEntry,
    dtype_from_array,
    dtype_size,
)
from ..utils.timeline import (
    Timeline,
    XLA_ALLGATHER,
    XLA_ALLREDUCE,
    XLA_ALLTOALL,
    XLA_BROADCAST,
    XLA_ADASUM,
    XLA_REDUCESCATTER,
)

logger = logging.getLogger("horovod_tpu")

_REQ_TO_TIMELINE = {
    RequestType.ALLREDUCE: XLA_ALLREDUCE,
    RequestType.ALLGATHER: XLA_ALLGATHER,
    RequestType.BROADCAST: XLA_BROADCAST,
    RequestType.ALLTOALL: XLA_ALLTOALL,
    RequestType.REDUCESCATTER: XLA_REDUCESCATTER,
    RequestType.ADASUM: XLA_ADASUM,
}


@dataclass
class Request:
    """Readiness announcement for one named tensor (reference message.h:46-96)."""

    rank: int
    request_type: RequestType
    tensor_name: str
    dtype: int = 0
    shape: Tuple[int, ...] = ()
    root_rank: int = -1
    reduce_op: int = int(ReduceOp.SUM)
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    # First-class grouped collectives: nonzero id ties members; the
    # coordinator holds the group until all group_size members arrive and
    # fuses them into one response, threshold-exempt (same semantics as
    # the native core).
    group_id: int = 0
    group_size: int = 0
    # Process set (later-reference parity). In the single-process runtime
    # any registered set degenerates to {0}; the field still travels so
    # fusion never mixes sets and tests can assert the plumbing.
    process_set_id: int = 0


@dataclass
class Response:
    """Coordinator verdict: a set of tensors to execute together, or an error
    (reference message.h:126-216)."""

    response_type: ResponseType
    tensor_names: List[str] = field(default_factory=list)
    error_message: str = ""


def describe_request(req: "Request") -> str:
    """Human-readable announcement signature for conflict messages."""
    from ..common.types import DataType

    try:
        dtype = DataType(req.dtype).name.lower()
    except ValueError:
        dtype = str(req.dtype)
    parts = [
        req.request_type.name.lower(), f"dtype={dtype}",
        f"shape={tuple(req.shape)}",
    ]
    if req.request_type in (RequestType.ALLREDUCE, RequestType.ADASUM):
        parts.append(f"op={ReduceOp(req.reduce_op).name}")
    if req.request_type == RequestType.BROADCAST:
        parts.append(f"root={req.root_rank}")
    if req.process_set_id:
        parts.append(f"process_set={req.process_set_id}")
    return " ".join(parts)


class NegotiationTable:
    """Cross-rank metadata validation (the coordinator half of upstream's
    ``Controller::ConstructResponse`` error checks, docs/fault_tolerance.md
    "Data-plane integrity").

    Each announcement of a tensor name is checked against the first one
    seen: conflicting operation type, dtype, shape (exact for
    allreduce/broadcast/alltoall, non-first dimensions for allgather),
    broadcast root, reduce op, or process set returns an error message
    NAMING THE TENSOR AND BOTH RANKS — the coordinator turns it into an
    aborted response instead of fusing garbage or stalling until the
    inspector's timeout. The native core performs the same checks on its
    own coordinator thread (cpp/src/core.cc Coordinate); this table is
    the pure-Python seam, also usable offline to validate simulated
    per-rank submission sets."""

    def __init__(self):
        self._first: Dict[str, Request] = {}

    def clear(self, names: Sequence[str]) -> None:
        for n in names:
            self._first.pop(n, None)

    def observe(self, req: Request) -> Optional[str]:
        """Record one announcement; returns a conflict message when it
        contradicts an earlier announcement of the same tensor."""
        if req.request_type == RequestType.JOIN:
            return None
        first = self._first.get(req.tensor_name)
        if first is None:
            self._first[req.tensor_name] = req
            return None
        if first.rank == req.rank:
            # Same rank re-announcing (its previous incarnation completed
            # and was cleared, or a legal per-cycle repeat): re-key so the
            # freshest metadata is what later ranks validate against.
            self._first[req.tensor_name] = req
            return None

        def conflict(kind: str) -> str:
            return (
                f"{kind} for tensor '{req.tensor_name}': rank "
                f"{first.rank} announced [{describe_request(first)}] but "
                f"rank {req.rank} announced [{describe_request(req)}]"
            )

        if req.process_set_id != first.process_set_id:
            return conflict("Mismatched process sets")
        if req.request_type != first.request_type:
            return conflict("Mismatched collective operations")
        if req.dtype != first.dtype:
            return conflict("Mismatched data types")
        if (req.request_type == RequestType.BROADCAST
                and req.root_rank != first.root_rank):
            return conflict("Mismatched root ranks")
        if (req.request_type in (RequestType.ALLREDUCE, RequestType.ADASUM)
                and req.reduce_op != first.reduce_op):
            return conflict("Mismatched reduce operations")
        if req.request_type == RequestType.ALLGATHER:
            if (len(req.shape) != len(first.shape)
                    or req.shape[1:] != first.shape[1:]):
                return conflict("Mismatched allgather dimensions")
        elif tuple(req.shape) != tuple(first.shape):
            return conflict("Mismatched shapes")
        return None

    def validate(self, requests: Sequence[Request]) -> List[Response]:
        """Observe a batch of announcements (possibly spanning ranks) and
        emit one aborted-error Response per conflicting tensor."""
        out: List[Response] = []
        failed: set = set()
        for req in requests:
            if req.tensor_name in failed:
                continue
            msg = self.observe(req)
            if msg is not None:
                failed.add(req.tensor_name)
                out.append(
                    Response(
                        ResponseType.ERROR, [req.tensor_name],
                        error_message=msg,
                    )
                )
                self._first.pop(req.tensor_name, None)
        return out


class TensorQueue:
    """Thread-safe pending-tensor table (reference tensor_queue.cc).

    Rejects duplicate names (reference common.h:160-163) and drains with an
    abort status on shutdown (``operations.cc:511-517``).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._table: "OrderedDict[str, Tuple[Request, TensorTableEntry]]" = OrderedDict()
        self._pending: List[Request] = []

    def add(self, request: Request, entry: TensorTableEntry) -> Status:
        with self._lock:
            if entry.name in self._table:
                op = request.request_type.name.lower()
                return Status.PreconditionError(DUPLICATE_NAME_ERROR_FMT.format(op=op))
            self._table[entry.name] = (request, entry)
            self._pending.append(request)
            return Status.OK()

    def pop_requests(self) -> List[Request]:
        with self._lock:
            out = self._pending
            self._pending = []
            return out

    def take_entry(self, name: str) -> Optional[TensorTableEntry]:
        with self._lock:
            item = self._table.pop(name, None)
            return item[1] if item is not None else None

    def get_request(self, name: str) -> Optional[Request]:
        with self._lock:
            item = self._table.get(name)
            return item[0] if item is not None else None

    def size(self) -> int:
        with self._lock:
            return len(self._table)

    def drain(self, status: Status) -> None:
        with self._lock:
            entries = [e for _, e in self._table.values()]
            self._table.clear()
            self._pending.clear()
        for entry in entries:
            if entry.callback is not None:
                entry.callback(status, None)


class HandleManager:
    """Handle → (status, output) map for the async API
    (reference torch/handle_manager.cc)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._results: Dict[int, Tuple[Status, Any]] = {}
        self._names: Dict[int, str] = {}
        self._cv = threading.Condition(self._lock)

    def allocate(self, name: str = "") -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._results[h] = (Status.InProgress(), None)
            if name:
                self._names[h] = name
            return h

    def name_of(self, handle: int) -> str:
        with self._lock:
            return self._names.get(handle, "")

    def mark_done(self, handle: int, status: Status, output: Any) -> None:
        with self._cv:
            self._results[handle] = (status, output)
            self._cv.notify_all()

    def poll(self, handle: int) -> bool:
        with self._lock:
            if handle not in self._results:
                # Already synchronized-and-released (or never allocated):
                # report complete, matching the reference where PollHandle
                # after WaitAndClear is not an in-progress state.
                return True
            st, _ = self._results[handle]
            return not st.in_progress()

    def wait(self, handle: int, timeout: Optional[float] = None) -> Tuple[Status, Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                st, out = self._results.get(handle, (Status.InProgress(), None))
                if not st.in_progress():
                    self._results.pop(handle, None)
                    self._names.pop(handle, None)
                    return st, out
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    # Descriptive timeout status — NOT a bare InProgress:
                    # callers historically treated (InProgress, None) as
                    # data. The handle stays allocated; the op may still
                    # complete and a later wait() can collect it.
                    name = self._names.get(handle, "")
                    return Status.TimedOut(
                        f"operation "
                        + (f"'{name}' " if name else f"handle {handle} ")
                        + f"did not complete within {timeout}s; it is "
                        "still in progress"
                    ), None
                self._cv.wait(timeout=0.1 if remaining is None else min(0.1, remaining))


@dataclass
class StallReport:
    """One check()'s escalation verdict: tensors (re-)warned about,
    tensors whose waiters must be aborted, and whether the whole runtime
    should shut down for an elastic reset."""

    warned: List[str] = field(default_factory=list)
    aborted: List[str] = field(default_factory=list)
    shutdown: bool = False


class StallInspector:
    """Escalation ladder for tensors sitting in the queue too long
    (reference stall_inspector.cc ships only the first rung):

    1. warn after ``stall_warning_time_seconds`` and RE-warn every
       ``stall_rewarn_seconds`` (default: the warn interval) — a stall is
       a live incident, not a one-shot log line;
    2. abort the individual tensor after ``stall_abort_time_seconds``
       (optional): its waiters receive a named ``Status.Aborted`` instead
       of hanging, and the rest of the queue keeps flowing;
    3. shut the runtime down after ``stall_shutdown_time_seconds``
       (optional): every queued tensor drains with a named abort status,
       which in an elastic job triggers rollback + re-rendezvous.

    Warnings include the set of missing ranks when the coordinator knows
    them (``Coordinator.missing_ranks``)."""

    def __init__(self, config: Config):
        self._config = config
        self._first_seen: Dict[str, float] = {}
        self._last_warned: Dict[str, float] = {}
        self.should_shutdown = False

    def record(self, names: Sequence[str]) -> None:
        now = time.monotonic()
        for n in names:
            self._first_seen.setdefault(n, now)

    def clear(self, names: Sequence[str]) -> None:
        for n in names:
            self._first_seen.pop(n, None)
            self._last_warned.pop(n, None)

    def stalled_names(self) -> List[str]:
        return sorted(self._first_seen)

    def check(
        self, missing_ranks: Optional[Dict[str, List[int]]] = None
    ) -> StallReport:
        report = StallReport()
        if self._config.stall_check_disable:
            return report
        now = time.monotonic()
        rewarn = (
            self._config.stall_rewarn_seconds
            or self._config.stall_warning_time_seconds
        )
        for n, t in self._first_seen.items():
            if now - t <= self._config.stall_warning_time_seconds:
                continue
            last = self._last_warned.get(n)
            if last is None or now - last > rewarn:
                report.warned.append(n)
        if report.warned:
            detail = ""
            if missing_ranks:
                known = {
                    n: missing_ranks[n]
                    for n in report.warned
                    if missing_ranks.get(n)
                }
                if known:
                    detail = " Missing ranks: " + "; ".join(
                        f"{n} <- {sorted(r)}" for n, r in sorted(known.items())
                    )
            logger.warning(
                "One or more tensors were submitted to be reduced, gathered or "
                "broadcasted by subset of ranks and are waiting for remainder of "
                "ranks for more than %d seconds. Stalled ops: %s.%s",
                int(self._config.stall_warning_time_seconds),
                ", ".join(sorted(report.warned)),
                detail,
            )
            for n in report.warned:
                self._last_warned[n] = now
        if self._config.stall_abort_time_seconds > 0:
            report.aborted = [
                n
                for n, t in self._first_seen.items()
                if now - t > self._config.stall_abort_time_seconds
            ]
        if self._config.stall_shutdown_time_seconds > 0:
            for n, t in self._first_seen.items():
                if now - t > self._config.stall_shutdown_time_seconds:
                    self.should_shutdown = True
                    report.shutdown = True
                    break
        return report


class Coordinator:
    """Controller protocol seam (reference controller.h:63-97).

    ``compute_response_list`` receives this rank's newly-announced requests
    and returns globally-agreed fused Responses. The single-process
    implementation marks everything ready immediately; the multi-process
    implementation (C++ core / TCP control plane) gathers requests to rank 0,
    counts readiness, validates, fuses, and broadcasts decisions.
    """

    def compute_response_list(
        self, requests: List[Request], queue: TensorQueue, config: Config
    ) -> List[Response]:
        raise NotImplementedError

    def missing_ranks(self) -> Dict[str, List[int]]:
        """tensor name → ranks that have NOT announced it yet, for tensors
        this coordinator is still holding. Feeds the stall inspector's
        warnings; the single-process coordinator never holds anything, so
        the default is empty."""
        return {}

    def shutdown(self) -> None:
        pass


class SingleProcessCoordinator(Coordinator):
    def __init__(self):
        self._pending: List[Request] = []
        # gid -> buffered members (first-class groups: held until the
        # group is complete, emitted as one threshold-exempt response —
        # the same semantics the native core implements multi-rank).
        self._groups: Dict[int, List[Request]] = {}

    def compute_response_list(
        self, requests: List[Request], queue: TensorQueue, config: Config
    ) -> List[Response]:
        # Everything announced is ready; fuse same-type/dtype/op requests up
        # to the fusion threshold, preserving submission order (reference
        # FuseResponses, controller.cc:626-750). Grouped members are held
        # until the whole group arrives, then fuse together regardless of
        # the threshold.
        emit: List[Request] = []
        for req in requests:
            if req.request_type != RequestType.JOIN and req.group_id:
                members = self._groups.setdefault(req.group_id, [])
                members.append(req)
                if len(members) >= req.group_size:
                    emit.extend(self._groups.pop(req.group_id))
            else:
                emit.append(req)
        responses: List[Response] = []
        current: Optional[Response] = None
        current_key = None
        current_bytes = 0
        for req in emit:
            if req.request_type == RequestType.JOIN:
                responses.append(Response(ResponseType.JOIN, [req.tensor_name]))
                current, current_key = None, None
                continue
            rtype = ResponseType(int(req.request_type))
            nbytes = int(np.prod(req.shape or (1,))) * dtype_size_or(req.dtype)
            key = (rtype, req.dtype, req.reduce_op, req.root_rank,
                   req.prescale_factor, req.postscale_factor, req.group_id,
                   req.process_set_id)
            fusable = rtype in (ResponseType.ALLREDUCE, ResponseType.ADASUM)
            if (
                fusable
                and current is not None
                and key == current_key
                and (req.group_id
                     or current_bytes + nbytes <= config.fusion_threshold_bytes)
            ):
                current.tensor_names.append(req.tensor_name)
                current_bytes += nbytes
            else:
                current = Response(rtype, [req.tensor_name])
                current_key = key if fusable else None
                current_bytes = nbytes
                responses.append(current)
        return responses


def dtype_size_or(dtype: int, default: int = 4) -> int:
    try:
        from ..common.types import DataType

        return dtype_size(DataType(dtype))
    except Exception:
        return default


class DataPlane:
    """Executes one fused Response worth of entries. Implementations:
    ``LocalDataPlane`` (size=1), ``MeshDataPlane`` (in-process device mesh),
    and the multi-process XLA plane (via jax.distributed)."""

    def execute(
        self, response: Response, entries: List[TensorTableEntry], topo: Topology
    ) -> Status:
        raise NotImplementedError


class LocalDataPlane(DataPlane):
    """size=1 data plane: collectives degenerate to (scaled) identity, as in
    the reference running a single rank. Implemented with jitted ops so the
    eager path exercises the same dispatch machinery."""

    def __init__(self):
        self._scale_fns: Dict[Any, Any] = {}

    def _scale(self, x, factor: float):
        if factor == 1.0:
            return x
        import jax
        import jax.numpy as jnp

        key = "scale"
        fn = self._scale_fns.get(key)
        if fn is None:
            fn = jax.jit(lambda t, f: t * f.astype(t.dtype))
            self._scale_fns[key] = fn
        try:
            return fn(x, np.asarray(factor, dtype=np.result_type(x.dtype, np.float32)))
        except Exception:
            return x * factor

    def execute(
        self, response: Response, entries: List[TensorTableEntry], topo: Topology
    ) -> Status:
        for entry in entries:
            t = entry.tensor
            if response.response_type in (
                ResponseType.ALLREDUCE,
                ResponseType.ADASUM,
            ):
                factor = entry.prescale_factor * entry.postscale_factor
                if entry.reduce_op == ReduceOp.AVERAGE:
                    factor /= topo.size  # size == 1, kept for symmetry
                entry.output = self._scale(t, factor)
            elif response.response_type in (
                ResponseType.ALLGATHER,
                ResponseType.BROADCAST,
                ResponseType.ALLTOALL,
                ResponseType.REDUCESCATTER,
            ):
                entry.output = t
            else:
                return Status.UnknownError(
                    f"Unsupported response type {response.response_type}"
                )
        return Status.OK()


class Runtime:
    """Background-loop owner; the analogue of HorovodGlobalState +
    BackgroundThreadLoop (``operations.cc:328-529``, ``global_state.h``)."""

    def __init__(
        self,
        config: Config,
        topology: Topology,
        coordinator: Optional[Coordinator] = None,
        data_plane: Optional[DataPlane] = None,
    ):
        self.config = config
        self.topology = topology
        self.coordinator = coordinator or SingleProcessCoordinator()
        if data_plane is None:
            if topology.size > 1:
                # Refuse to run multi-rank eager collectives on the local
                # (identity) plane — that would return silently wrong
                # numerics. The multi-process XLA plane plugs in here.
                raise NotImplementedError(
                    f"Eager mode for size={topology.size} requires a "
                    "multi-process data plane (coming with the launcher); "
                    "use the compiled mode (horovod_tpu.jax) over a device "
                    "mesh, or run single-process."
                )
            data_plane = LocalDataPlane()
        self.data_plane = data_plane
        self.tensor_queue = TensorQueue()
        self.handle_manager = HandleManager()
        self.timeline = Timeline()
        self.stall_inspector = StallInspector(config)
        # Cross-rank metadata validation: announcements that contradict an
        # earlier one (shape/dtype/op/root/reduce-op/process-set) abort
        # with tensor + ranks named instead of fusing garbage or stalling.
        self.negotiation = NegotiationTable()
        self.joined = False
        # Status used for the final queue drain; replaced with a named
        # abort when the stall ladder (not a user shutdown) kills the
        # loop, so waiters learn WHICH tensors wedged the runtime.
        self._drain_status: Optional[Status] = None
        self._shutdown = threading.Event()
        self._wake = threading.Event()
        self._initialized = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Guards cross-thread runtime state: the process-set table
        # (mutated by user threads, read during enqueue) and the joined
        # flag (set by the caller's enqueue_join, cleared on the
        # background thread). Found by the Pass-2 lock-discipline lint
        # (analysis/runtime_lint.py) — see docs/static_analysis.md.
        self._state_lock = threading.Lock()
        # Registered process sets (id -> sorted ranks). The single-process
        # data plane executes any set containing rank 0 as an identity,
        # matching the reference's size=1 behavior.
        self._process_sets: Dict[int, List[int]] = {}

    # --- process sets ---
    def register_process_set(self, psid: int, ranks) -> None:
        rs = sorted(int(r) for r in ranks)
        if not rs or rs[0] < 0 or rs[-1] >= self.topology.size:
            raise ValueError("process set ranks must lie in [0, size)")
        with self._state_lock:
            self._process_sets[int(psid)] = rs

    def remove_process_set(self, psid: int) -> None:
        with self._state_lock:
            if self._process_sets.pop(int(psid), None) is None:
                raise ValueError(f"process set {psid} is not registered")

    # --- lifecycle ---
    def start(self) -> None:
        if self._thread is not None:
            return
        if self.config.timeline_filename:
            self.timeline.initialize(self.config.timeline_filename, self.topology.rank)
            from ..common import env as _env_mod

            preset = _env_mod.applied_perf_preset()
            if preset is not None:
                self.timeline.metadata("hvd_xla_perf_preset", preset)
            try:
                from ..topo import resolve_model

                # Run fact a trace reader needs to interpret collective
                # timings: the interconnect model plans were priced on.
                self.timeline.metadata(
                    "hvd_topo_model",
                    resolve_model(self.topology).to_dict(),
                )
            except Exception:  # noqa: BLE001 - metadata must not block start
                pass
        self._thread = threading.Thread(
            target=self._background_loop, name="hvd_background", daemon=True
        )
        self._thread.start()
        # Reference spin-waits initialization_done (operations.cc:627-629).
        self._initialized.wait(timeout=60.0)

    def shutdown(self) -> None:
        if self._thread is None:
            return
        self._shutdown.set()
        self._wake.set()
        self._thread.join(timeout=30.0)
        self._thread = None
        self.tensor_queue.drain(SHUT_DOWN_ERROR)
        self.coordinator.shutdown()
        self.timeline.shutdown()

    @property
    def running(self) -> bool:
        return self._thread is not None and not self._shutdown.is_set()

    # --- enqueue API (reference EnqueueTensor*, operations.cc:783-934) ---
    def _enqueue(
        self,
        request_type: RequestType,
        name: str,
        tensor: Any,
        *,
        root_rank: int = -1,
        reduce_op: ReduceOp = ReduceOp.SUM,
        prescale_factor: float = 1.0,
        postscale_factor: float = 1.0,
        callback: Optional[Callable[[Status, Any], None]] = None,
        group_id: int = 0,
        group_size: int = 0,
        process_set_id: int = 0,
    ) -> int:
        if self._shutdown.is_set() or self._thread is None:
            from .. import HorovodInternalError

            raise HorovodInternalError(
                "Horovod runtime is shut down or was never initialized; "
                "call hvd.init() first."
            )
        if process_set_id != 0:
            with self._state_lock:
                members = self._process_sets.get(process_set_id)
            if members is None:
                raise RuntimeError(
                    f"process set {process_set_id} is not registered on "
                    "this rank"
                )
            if self.topology.rank not in members:
                raise RuntimeError(
                    f"rank {self.topology.rank} is not a member of process "
                    f"set {process_set_id}"
                )
        if _fault.ACTIVE:
            # Chaos tap: scheduled kills/delays for this rank's
            # submissions (docs/fault_tolerance.md). Inactive → not
            # reached (the ACTIVE check is the whole overhead).
            _fault.fault_point("enqueue", name)
            # Payload tap: a scheduled nan/corrupt mutates the tensor
            # BEFORE the guard sentinel below, so the seeded chaos runs
            # exercise detection end-to-end.
            tensor = _fault.payload_fault("payload", name, tensor)
        if _guard.ACTIVE and request_type in (
            RequestType.ALLREDUCE, RequestType.ADASUM
        ):
            # Non-finite sentinel (docs/fault_tolerance.md): one rank's
            # NaN/Inf would silently poison every replica through the
            # reduce. Disabled → not reached, same discipline as above.
            tensor = _guard.TAP.check_payload(name, tensor)
        handle = self.handle_manager.allocate(name)

        def _done(status: Status, output: Any) -> None:
            if callback is not None:
                try:
                    callback(status, output)
                except Exception:  # noqa: BLE001
                    logger.exception("callback for %s raised", name)
            self.handle_manager.mark_done(handle, status, output)

        dtype = dtype_from_array(tensor) if tensor is not None else 0
        request = Request(
            rank=self.topology.rank,
            request_type=request_type,
            tensor_name=name,
            dtype=int(dtype),
            shape=tuple(int(d) for d in getattr(tensor, "shape", ())),
            root_rank=root_rank,
            reduce_op=int(reduce_op),
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            group_id=group_id,
            group_size=group_size,
            process_set_id=process_set_id,
        )
        entry = TensorTableEntry(
            name=name,
            tensor=tensor,
            root_rank=root_rank,
            callback=_done,
            reduce_op=reduce_op,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
        )
        status = self.tensor_queue.add(request, entry)
        if not status.ok():
            self.handle_manager.mark_done(handle, status, None)
            return handle
        if _metrics.ACTIVE:
            # Metrics tap (docs/metrics.md): negotiate latency is measured
            # from here to the coordinator's fused response. Disabled →
            # not reached (the ACTIVE check is the whole overhead), same
            # discipline as the fault tap above.
            entry.context["metrics_enqueue_ts"] = time.monotonic()
            _metrics.TAP.inc(
                "hvd_ops_submitted_total", op=request_type.name
            )
        if self.timeline.initialized:
            self.timeline.negotiate_start(name, request_type.name)
        self._wake.set()
        return handle

    def enqueue_allreduce(self, name, tensor, **kw) -> int:
        return self._enqueue(RequestType.ALLREDUCE, name, tensor, **kw)

    def enqueue_adasum(self, name, tensor, **kw) -> int:
        kw.setdefault("reduce_op", ReduceOp.ADASUM)
        return self._enqueue(RequestType.ADASUM, name, tensor, **kw)

    def enqueue_allgather(self, name, tensor, **kw) -> int:
        return self._enqueue(RequestType.ALLGATHER, name, tensor, **kw)

    def enqueue_broadcast(self, name, tensor, root_rank, **kw) -> int:
        return self._enqueue(RequestType.BROADCAST, name, tensor, root_rank=root_rank, **kw)

    def enqueue_alltoall(self, name, tensor, **kw) -> int:
        return self._enqueue(RequestType.ALLTOALL, name, tensor, **kw)

    def enqueue_reducescatter(self, name, tensor, **kw) -> int:
        return self._enqueue(RequestType.REDUCESCATTER, name, tensor, **kw)

    def enqueue_join(self) -> int:
        with self._state_lock:
            self.joined = True
        return self._enqueue(RequestType.JOIN, f"join.{self.topology.rank}", None)

    # --- background loop (reference RunLoopOnce, operations.cc:531-581) ---
    def _background_loop(self) -> None:
        self._initialized.set()
        cycle_s = max(self.config.cycle_time_ms, 0.05) / 1000.0
        while not self._shutdown.is_set():
            self._wake.wait(timeout=cycle_s)
            self._wake.clear()
            if self._shutdown.is_set():
                break
            try:
                self._run_cycle_once()
            except Exception:  # noqa: BLE001
                logger.exception("background cycle raised; draining queue")
                self.tensor_queue.drain(
                    Status.UnknownError("background loop failure")
                )
        # Final drain so no handle hangs.
        self.tensor_queue.drain(self._drain_status or SHUT_DOWN_ERROR)

    def _run_cycle_once(self) -> None:
        cycle_t0 = time.perf_counter() if _metrics.ACTIVE else 0.0
        if self.timeline.initialized and self.config.timeline_mark_cycles:
            self.timeline.mark_cycle_start()
        requests = self.tensor_queue.pop_requests()
        self.stall_inspector.record([r.tensor_name for r in requests])
        # Metadata validation BEFORE negotiation: a conflicting
        # announcement aborts its waiters now (naming tensor + ranks)
        # rather than fusing garbage or stalling to the inspector's
        # timeout. Failed requests never reach the coordinator.
        error_responses = self.negotiation.validate(requests)
        if error_responses:
            failed = {
                n for r in error_responses for n in r.tensor_names
            }
            requests = [
                r for r in requests if r.tensor_name not in failed
            ]
            for response in error_responses:
                self._perform_operation(response)
        responses = self.coordinator.compute_response_list(
            requests, self.tensor_queue, self.config
        )
        for response in responses:
            self._perform_operation(response)
        missing = self.coordinator.missing_ranks()
        report = self.stall_inspector.check(missing)
        if _metrics.ACTIVE:
            _metrics.TAP.set(
                "hvd_queue_depth", float(self.tensor_queue.size())
            )
            _metrics.TAP.observe(
                "hvd_cycle_seconds", time.perf_counter() - cycle_t0
            )
            if report.warned:
                _metrics.TAP.inc(
                    "hvd_stall_warnings_total", len(report.warned)
                )
            if report.aborted:
                _metrics.TAP.inc(
                    "hvd_stall_aborts_total", len(report.aborted)
                )
            if report.shutdown:
                _metrics.TAP.inc("hvd_stall_shutdowns_total")
        if report.aborted and _trace.ACTIVE:
            # Flight recorder (docs/timeline.md): a stall escalation is
            # exactly the moment "what was the fleet doing" matters —
            # persist the last moments before the waiters unwind.
            _trace.TAP.flight_dump("stall-abort")
        for name in report.aborted:
            # Rung 2: abort the individual stalled tensor — hand its
            # waiter a named status instead of letting it hang — and keep
            # the rest of the queue flowing.
            entry = self.tensor_queue.take_entry(name)
            self.stall_inspector.clear([name])
            if entry is None:
                continue
            ranks = missing.get(name) if missing else None
            status = Status.Aborted(
                f"collective '{name}' aborted: waited longer than "
                f"HOROVOD_STALL_ABORT_TIME_SECONDS="
                f"{self.config.stall_abort_time_seconds:g}s for peer ranks"
                + (f" {sorted(ranks)}" if ranks else "")
                + " to submit it"
            )
            logger.error("%s", status.reason)
            if entry.callback is not None:
                entry.callback(status, None)
        if self.stall_inspector.should_shutdown:
            # Rung 3: the whole runtime is wedged — drain every queued
            # tensor with a named abort (elastic waiters roll back and
            # re-rendezvous; see docs/fault_tolerance.md).
            stalled = self.stall_inspector.stalled_names()
            self._drain_status = Status.Aborted(
                "stall shutdown: tensors ["
                + ", ".join(stalled)
                + "] exceeded HOROVOD_STALL_SHUTDOWN_TIME_SECONDS="
                f"{self.config.stall_shutdown_time_seconds:g}s; aborting "
                "the runtime so elastic recovery can re-form the world"
            )
            logger.error("%s", self._drain_status.reason)
            if _trace.ACTIVE:
                _trace.TAP.flight_dump("stall-shutdown")
            self._shutdown.set()

    def _perform_operation(self, response: Response) -> None:
        # Reference PerformOperation (operations.cc:227-304).
        if response.response_type == ResponseType.JOIN:
            with self._state_lock:
                self.joined = False
            self.stall_inspector.clear(response.tensor_names)
            for name in response.tensor_names:
                entry = self.tensor_queue.take_entry(name)
                if entry and entry.callback:
                    entry.callback(Status.OK(), None)
            return
        entries: List[TensorTableEntry] = []
        for name in response.tensor_names:
            entry = self.tensor_queue.take_entry(name)
            if entry is not None:
                entries.append(entry)
        if not entries:
            return
        if _fault.ACTIVE:
            # Chaos tap: delay/abort a fused response before execution.
            _fault.fault_point("response", entries[0].name)
        self.stall_inspector.clear([e.name for e in entries])
        self.negotiation.clear([e.name for e in entries])
        timeline_name = _REQ_TO_TIMELINE.get(
            RequestType(int(response.response_type))
            if int(response.response_type) <= int(RequestType.ADASUM)
            else None,
            "OP",
        )
        if self.timeline.initialized:
            for e in entries:
                self.timeline.negotiate_end(e.name, timeline_name.replace("XLA_", ""))
                self.timeline.start(e.name, timeline_name)
        op_label = timeline_name.replace("XLA_", "")
        if _metrics.ACTIVE:
            now = time.monotonic()
            for e in entries:
                ts = e.context.pop("metrics_enqueue_ts", None)
                if ts is not None:
                    _metrics.TAP.observe(
                        "hvd_op_negotiate_seconds", now - ts, op=op_label
                    )
        exec_t0 = (
            time.perf_counter()
            if (_metrics.ACTIVE or _trace.ACTIVE) else 0.0
        )
        if response.response_type == ResponseType.ERROR:
            # Coordinator-detected metadata conflict (or negotiation
            # failure): a named ABORT, same status class as the stall
            # ladder, so waiters raise HorovodInternalError and the
            # elastic layer can reset through the usual drain.
            status = Status.Aborted(response.error_message)
            logger.error("%s", response.error_message)
            if _metrics.ACTIVE:
                _metrics.TAP.inc("hvd_guard_metadata_aborts_total")
        else:
            try:
                status = self.data_plane.execute(response, entries, self.topology)
            except Exception as exc:  # noqa: BLE001
                logger.exception("data plane failure")
                status = Status.UnknownError(str(exc))
        if _metrics.ACTIVE:
            _metrics.TAP.observe(
                "hvd_op_execute_seconds", time.perf_counter() - exec_t0,
                op=op_label,
            )
            nbytes = sum(
                int(getattr(e.tensor, "nbytes", 0) or 0) for e in entries
            )
            if nbytes:
                _metrics.TAP.observe("hvd_op_bytes", nbytes, op=op_label)
            if not status.ok():
                _metrics.TAP.inc("hvd_op_errors_total", op=op_label)
        if _trace.ACTIVE:
            # Fleet-trace span for the fused response (the eager path's
            # step → plan → collective link; the native core's analogue
            # carries the hvd_plan_<id> correlation id). nbytes rides
            # the span so `trace_merge.py --stats` can hand the
            # calibrator (sim/calibrate.py) per-collective
            # (bytes, duration) samples off a real trace.
            _dur = time.perf_counter() - exec_t0
            _trace.TAP.event(
                "hvd_response", ph="X", cat="op",
                ts=time.time() - _dur, dur=_dur,
                op=op_label, tensors=len(entries),
                nbytes=sum(
                    int(getattr(e.tensor, "nbytes", 0) or 0)
                    for e in entries
                ),
                ok=bool(status.ok()),
            )
        if self.timeline.initialized:
            for e in entries:
                self.timeline.end(e.name, timeline_name)
        if _fault.ACTIVE and status.ok():
            # Output payload tap: a scheduled corrupt bit-flips THIS
            # rank's result only — the SDC model the parameter-digest
            # guard detects and heals.
            for e in entries:
                e.output = _fault.payload_fault("output", e.name, e.output)
        for entry in entries:
            if entry.callback is not None:
                entry.callback(status, entry.output if status.ok() else None)

    # --- runtime timeline control (later-reference API) ---
    def start_timeline(self, file_path: str, mark_cycles: bool = False):
        if self.timeline.initialized:
            raise ValueError("timeline is already active")
        # The writer opens its file on a background thread, so probe
        # writability HERE — otherwise an unwritable path would succeed
        # silently and block any later start ("already active").
        with open(file_path, "w"):
            pass
        self.config.timeline_mark_cycles = bool(mark_cycles)
        self.timeline.initialize(file_path, self.topology.rank)
        if not self.timeline.initialized:
            raise ValueError(f"could not start timeline at {file_path!r}")

    def stop_timeline(self) -> None:
        self.timeline.shutdown()

    # --- sync helpers ---
    def poll(self, handle: int) -> bool:
        return self.handle_manager.poll(handle)

    def synchronize(self, handle: int, timeout: Optional[float] = None) -> Any:
        status, output = self.handle_manager.wait(handle, timeout)
        if status.in_progress():
            raise TimeoutError(
                status.reason or "Horovod operation timed out"
            )
        if not status.ok():
            # HorovodInternalError (a RuntimeError subclass) so elastic
            # rollback can distinguish collective failures from user bugs.
            from .. import HorovodInternalError

            raise HorovodInternalError(status.reason)
        return output
