"""Runtime backed by the native control-plane core (cpp/libhvd_core.so).

Division of labor (TPU-native re-design of the reference architecture):
the C++ core owns the background cycle loop, cross-rank negotiation,
fusion planning, response cache, stall detection, timeline, and autotune —
everything the reference keeps in ``horovod/common/*.cc``. Tensor payloads
never cross the ABI: Python keeps the arrays, receives fused execution
Plans, runs them on the XLA data plane, and reports completion (which feeds
the core's autotuner and timeline).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

import numpy as np

from ..common.basics import NativeCore, _CoreError
from ..common.env import Config
from ..common.topology import Topology
from ..fault import injector as _fault
from .. import guard as _guard
from .. import metrics as _metrics
from .. import trace as _trace
from ..common.types import (
    DataType,
    ReduceOp,
    RequestType,
    Status,
    StatusType,
    TensorTableEntry,
    dtype_from_array,
    dtype_name,
)

logger = logging.getLogger("horovod_tpu")

_PLAN_ERROR = 7  # ResponseType::kError
_PLAN_JOIN = 3

# Plan type → metrics op label (matches ResponseType ordering in the
# native core and the Python runtime's timeline names).
_PLAN_TYPE_NAMES = {
    0: "ALLREDUCE", 1: "ALLGATHER", 2: "BROADCAST", 3: "JOIN",
    4: "ALLTOALL", 5: "REDUCESCATTER", 6: "ADASUM", 7: "ERROR",
}


class PlanExecutor:
    """Executes one fused plan's entries; returns {name: output}."""

    def execute(self, plan: dict, entries, topo: Topology) -> Dict[str, Any]:
        raise NotImplementedError


class LocalPlanExecutor(PlanExecutor):
    """size=1 executor: collectives are (scaled) identities."""

    def execute(self, plan: dict, entries, topo: Topology) -> Dict[str, Any]:
        outputs: Dict[str, Any] = {}
        participants = max(int(plan.get("participants", 1)), 1)
        for entry in entries:
            t = entry.tensor
            if plan["type"] in (0, 6):  # allreduce / adasum
                factor = entry.prescale_factor * entry.postscale_factor
                if entry.reduce_op == ReduceOp.AVERAGE:
                    factor /= participants
                outputs[entry.name] = t if factor == 1.0 else t * factor
            else:
                outputs[entry.name] = t
        return outputs


class NativeRuntime:
    """Drop-in replacement for core.runtime.Runtime, backed by the C++
    core. Same producer API; the executor thread replaces the Python
    background loop."""

    def __init__(
        self,
        config: Config,
        topology: Topology,
        executor: Optional[PlanExecutor] = None,
        coord_addr: str = "",
        coord_port: int = 0,
    ):
        self.config = config
        self.topology = topology
        if executor is None:
            if topology.size > 1:
                raise NotImplementedError(
                    f"Eager mode for size={topology.size} requires a "
                    "multi-process plan executor (launcher-provided); use "
                    "the compiled mode (horovod_tpu.jax) or run "
                    "single-process."
                )
            executor = LocalPlanExecutor()
        self.executor = executor
        self.core = NativeCore()
        self.core.init(config, topology, coord_addr, coord_port)
        # Per-name FIFO: a name may be legally re-enqueued while its
        # predecessor's plan is still executing; the core dispatches plans
        # in acceptance order, so popleft matches plan order.
        self._entries: Dict[str, "deque[TensorTableEntry]"] = {}
        self._entries_lock = threading.Lock()
        self._outputs: Dict[str, 'deque'] = {}  # name -> FIFO of outputs
        self._ticket_names: Dict[int, str] = {}
        self._done: Dict[int, tuple] = {}
        self._cv = threading.Condition()
        # Inline execution fast path (VERDICT r4 #2): a caller blocked in
        # synchronize() is a hot, already-scheduled thread — letting IT
        # pop and run the plan skips the executor-thread wakeup hop
        # entirely, and since every rank's caller spins the same way,
        # the ranks reach the collective aligned instead of paying each
        # other's wake latency inside it. Pop+execute is one atomic unit
        # under this lock, so plans still execute strictly in the core's
        # dispatch order no matter which thread consumes them. RLock:
        # a completion callback may legally synchronize() another handle
        # (nested consumption by the same thread must not deadlock).
        self._consumer_lock = threading.RLock()
        self._inline_sync = os.environ.get(
            "HOROVOD_INLINE_SYNC", "1"
        ) not in ("0", "false")
        self._flush_hint = os.environ.get(
            "HOROVOD_FLUSH_HINT", "1"
        ) not in ("0", "false")
        # Count of threads currently blocked in synchronize(): while any
        # exist, the executor thread parks so the hot thread wins the
        # consumer role (with a plain race, the executor — usually
        # already blocked inside next_plan's C++ wait — would keep
        # winning and the fast path would never engage). _no_waiters is
        # the park signal: set while the count is zero, so the executor
        # blocks on it instead of busy-polling and wakes the moment the
        # last waiter leaves.
        self._sync_waiters = 0
        self._no_waiters = threading.Event()
        self._no_waiters.set()
        # Set by an inline synchronize() that observes next_plan == -1
        # (core down): the parked executor thread must run its
        # orphaned-entry drain NOW, not after every waiter exits — a TF
        # callback-consumer with no handle to fail would otherwise hang
        # until the last handle-waiter left (advisor finding,
        # native_runtime inline-sync drain deferral).
        self._core_down = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._executor_loop, name="hvd_plan_executor", daemon=True
        )
        self._thread.start()

    # --- lifecycle ---
    def start(self) -> None:  # parity with python Runtime
        pass

    @property
    def running(self) -> bool:
        return not self._stop.is_set() and self.core.initialized()

    def shutdown(self) -> None:
        if self._stop.is_set():
            return
        self._stop.set()
        self.core.shutdown()
        self._thread.join(timeout=30.0)
        with self._cv:
            for t, name in list(self._ticket_names.items()):
                if t not in self._done:
                    self._done[t] = (
                        Status.Aborted("Horovod has been shut down."),
                        None,
                    )
            self._cv.notify_all()

    # --- runtime timeline control (later-reference API) ---
    def start_timeline(self, file_path: str, mark_cycles: bool = False):
        code = self.core.start_timeline(file_path, mark_cycles)
        if code:
            raise ValueError(
                f"could not start timeline at {file_path!r} "
                f"(status {code}: already active, or unwritable path)"
            )

    def stop_timeline(self) -> None:
        self.core.stop_timeline()

    # --- enqueue API ---
    def _enqueue(
        self,
        request_type: RequestType,
        name: str,
        tensor: Any,
        *,
        root_rank: int = -1,
        reduce_op: ReduceOp = ReduceOp.SUM,
        prescale_factor: float = 1.0,
        postscale_factor: float = 1.0,
        callback: Optional[Callable] = None,
        group_id: int = 0,
        group_size: int = 0,
        process_set_id: int = 0,
    ) -> int:
        if not self.running:
            from .. import HorovodInternalError

            raise HorovodInternalError(
                "Horovod runtime is shut down or was never initialized; "
                "call hvd.init() first."
            )
        if _fault.ACTIVE:
            # Chaos tap, same site name as the pure-Python runtime so one
            # fault plan drives either core (docs/fault_tolerance.md).
            _fault.fault_point("enqueue", name)
            # Payload tap: scheduled nan/corrupt mutates the tensor
            # BEFORE the guard sentinel, exercising detection end-to-end.
            tensor = _fault.payload_fault("payload", name, tensor)
        if _guard.ACTIVE and request_type in (
            RequestType.ALLREDUCE, RequestType.ADASUM
        ):
            # Non-finite sentinel, same semantics as the pure-Python
            # runtime (docs/fault_tolerance.md "Data-plane integrity").
            tensor = _guard.TAP.check_payload(name, tensor)
        entry = TensorTableEntry(
            name=name,
            tensor=tensor,
            root_rank=root_rank,
            callback=callback,
            reduce_op=reduce_op,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
        )
        if _metrics.ACTIVE:
            # Metrics tap, same metric names as the pure-Python runtime
            # so dashboards are core-agnostic (docs/metrics.md).
            entry.context["metrics_enqueue_ts"] = time.monotonic()
            _metrics.TAP.inc(
                "hvd_ops_submitted_total", op=request_type.name
            )
        with self._entries_lock:
            self._entries.setdefault(name, deque()).append(entry)
        dtype = int(dtype_from_array(tensor)) if tensor is not None else 0
        shape = [int(d) for d in getattr(tensor, "shape", ())]
        try:
            ticket = self.core.enqueue(
                int(request_type), name, dtype, shape, root_rank,
                int(reduce_op), prescale_factor, postscale_factor,
                group_id, group_size, process_set_id,
            )
        except _CoreError as e:
            with self._entries_lock:
                q = self._entries.get(name)
                # The entry may already have been consumed by the
                # executor-exit drain (which fired its callback); only the
                # thread that removes it owns the completion. Identity
                # comparison — dataclass equality would compare tensor
                # payloads (ambiguous for arrays, and an equal-valued
                # sibling entry must not be confused with ours).
                idx = next(
                    (i for i, e in enumerate(q or ()) if e is entry), None
                )
                owned = idx is not None
                if owned:
                    del q[idx]
                    if not q:
                        del self._entries[name]
            status = Status(
                StatusType(e.code if 0 < e.code <= 5 else 1), str(e)
            )
            # Callback-completed consumers (TF async op kernels) wait on
            # the callback, not the handle — fire it or they hang forever
            # when the core is already down.
            if owned and entry.callback is not None:
                try:
                    entry.callback(status, None)
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "error callback for %s raised", entry.name
                    )
            # Surface as a failed handle, like the reference's callback
            # error path.
            with self._cv:
                fake = -int(time.monotonic_ns() % (1 << 62)) - 1
                self._done[fake] = (status, None)
                return fake
        with self._cv:
            self._ticket_names[ticket] = name
        return ticket

    def enqueue_allreduce(self, name, tensor, **kw) -> int:
        return self._enqueue(RequestType.ALLREDUCE, name, tensor, **kw)

    def enqueue_adasum(self, name, tensor, **kw) -> int:
        kw.setdefault("reduce_op", ReduceOp.ADASUM)
        return self._enqueue(RequestType.ADASUM, name, tensor, **kw)

    def enqueue_allgather(self, name, tensor, **kw) -> int:
        return self._enqueue(RequestType.ALLGATHER, name, tensor, **kw)

    def enqueue_broadcast(self, name, tensor, root_rank, **kw) -> int:
        return self._enqueue(
            RequestType.BROADCAST, name, tensor, root_rank=root_rank, **kw
        )

    def enqueue_alltoall(self, name, tensor, **kw) -> int:
        return self._enqueue(RequestType.ALLTOALL, name, tensor, **kw)

    def enqueue_reducescatter(self, name, tensor, **kw) -> int:
        return self._enqueue(RequestType.REDUCESCATTER, name, tensor, **kw)

    def enqueue_join(self) -> int:
        if not self.running:
            from .. import HorovodInternalError

            raise HorovodInternalError("Horovod runtime is shut down.")
        return self.core.enqueue_join()

    # --- process sets (later-reference horovod.ProcessSet parity) ---
    def register_process_set(self, psid: int, ranks) -> None:
        """Register a rank subset in the native core AND the data-plane
        executor (which builds the member sub-mesh). Atomic: an executor
        failure rolls the core registration back, so control plane and
        data plane can never disagree about a set. The caller is
        responsible for the cross-rank registration barrier."""
        self.core.register_process_set(psid, list(ranks))
        reg = getattr(self.executor, "register_process_set", None)
        if reg is not None:
            try:
                reg(psid, ranks)
            except Exception:
                try:
                    self.core.remove_process_set(psid)
                except Exception:  # noqa: BLE001 - keep the original error
                    pass
                raise

    def remove_process_set(self, psid: int) -> None:
        self.core.remove_process_set(psid)
        rem = getattr(self.executor, "remove_process_set", None)
        if rem is not None:
            rem(psid)

    # --- executor loop ---
    def _executor_loop(self) -> None:
        try:
            while not self._stop.is_set() and not self._core_down.is_set():
                if self._sync_waiters > 0:
                    # A synchronize() caller is inline-draining; park so
                    # the hot thread keeps the consumer role. Bounded
                    # wait: _stop has no channel into this Event (but an
                    # inline waiter that sees the core die sets BOTH
                    # _core_down and _no_waiters to break the park).
                    self._no_waiters.wait(timeout=0.05)
                    continue
                with self._consumer_lock:
                    if self._sync_waiters > 0:
                        continue
                    plan = self.core.next_plan(timeout_ms=100)
                    if plan == -1:
                        break
                    if plan in (0, -2):
                        continue
                    self._execute_plan(plan)
        finally:
            # Core is down (peer loss, shutdown) or the loop itself died:
            # entries that never made it into a plan still hold
            # completion callbacks — e.g. TF async op kernels blocked
            # inside a tf.function train step. Fire them with an error so
            # graph-mode training surfaces the failure instead of hanging
            # forever (the handle-based waiters are failed by the core's
            # own FailAll). try/finally: an exception escaping the loop
            # must still drain, or the hang returns.
            self._drain_entry_callbacks(
                Status.Aborted(
                    "Horovod control plane is down (peer loss or "
                    "shutdown)."
                )
            )

    def _drain_entry_callbacks(self, status: Status) -> None:
        with self._entries_lock:
            orphaned = [
                e for q in self._entries.values() for e in q
            ]
            self._entries.clear()
        for entry in orphaned:
            if entry.callback is not None:
                try:
                    entry.callback(status, None)
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "error callback for %s raised", entry.name
                    )
        # drain: nothing further; core fails outstanding tickets itself.

    def _execute_plan(self, plan: dict) -> None:
        t0 = time.perf_counter()
        names = plan.get("names", [])
        shapes = plan.get("shapes", [])
        entries = []
        for i, name in enumerate(names):
            with self._entries_lock:
                q = self._entries.get(name)
                entry = q.popleft() if q else None
                if q is not None and not q:
                    del self._entries[name]
            if entry is None:
                # Join zero-substitution: fabricate a zero tensor of the
                # coordinator-validated shape (reference joined-rank
                # behavior).
                shape = tuple(shapes[i]) if i < len(shapes) else ()
                np_dtype = dtype_name(DataType(plan["dtype"]))
                entry = TensorTableEntry(
                    name=name,
                    tensor=np.zeros(shape, dtype=np_dtype),
                    reduce_op=ReduceOp(plan["op"]) if plan.get("op") else ReduceOp.SUM,
                    prescale_factor=plan.get("prescale", 1.0),
                    postscale_factor=plan.get("postscale", 1.0),
                )
            entries.append(entry)

        op_label = _PLAN_TYPE_NAMES.get(int(plan["type"]), str(plan["type"]))
        if _metrics.ACTIVE:
            now = time.monotonic()
            for entry in entries:
                ts = entry.context.pop("metrics_enqueue_ts", None)
                if ts is not None:
                    _metrics.TAP.observe(
                        "hvd_op_negotiate_seconds", now - ts, op=op_label
                    )
            with self._entries_lock:
                depth = sum(len(q) for q in self._entries.values())
            _metrics.TAP.set("hvd_queue_depth", float(depth))

        status_code = 0
        error = ""
        outputs: Dict[str, Any] = {}
        if plan["type"] == _PLAN_ERROR:
            # Coordinator-detected conflict (mismatched metadata across
            # ranks, poisoned group): a named ABORT — the same status
            # class as the stall ladder — so waiters raise
            # HorovodInternalError and the elastic layer resets through
            # the usual drain instead of treating it as a local bug.
            status_code = int(StatusType.ABORTED)
            error = plan.get("error", "coordinator reported an error")
            logger.error("coordinator abort: %s", error)
            if _metrics.ACTIVE:
                _metrics.TAP.inc("hvd_guard_metadata_aborts_total")
        elif plan["type"] == _PLAN_JOIN:
            pass
        else:
            try:
                # Correlation with on-chip profiles: the same
                # "hvd_plan_<id>" string the C++ timeline stamps on this
                # plan's activity events (Timeline::BeginPlan) annotates
                # the XLA execution in any active jax.profiler trace, so
                # a slow cycle in the catapult timeline can be matched to
                # its device-side profile (SURVEY §5 timeline parity).
                import jax.profiler as _prof

                with _prof.TraceAnnotation(f"hvd_plan_{plan['id']}"):
                    outputs = self.executor.execute(
                        plan, entries, self.topology
                    )
            except Exception as exc:  # noqa: BLE001
                logger.exception("plan execution failed")
                status_code = int(StatusType.UNKNOWN_ERROR)
                error = str(exc)
        if _fault.ACTIVE and status_code == 0:
            # Output payload tap: a scheduled corrupt bit-flips THIS
            # rank's result only — the SDC model the parameter-digest
            # guard detects and heals (docs/fault_tolerance.md).
            for entry in entries:
                if entry.name in outputs:
                    outputs[entry.name] = _fault.payload_fault(
                        "output", entry.name, outputs[entry.name]
                    )
        duration = time.perf_counter() - t0
        status = (
            Status.OK()
            if status_code == 0
            else Status(StatusType(status_code), error)
        )
        if _trace.ACTIVE:
            # Fleet-trace span carrying the SAME hvd_plan_<id> string
            # the C++ timeline stamps on this plan's activity events and
            # the jax.profiler annotation above wraps its execution in —
            # one id links step → plan → collective across all three
            # artifacts (docs/timeline.md).
            _trace.TAP.event(
                "hvd_plan", ph="X", cat="plan",
                ts=time.time() - duration, dur=duration,
                plan=f"hvd_plan_{plan['id']}", op=op_label,
                tensors=len(names),
                bytes=int(plan.get("total_bytes", 0) or 0),
                ok=status_code == 0,
            )
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_plans_total", op=op_label)
            _metrics.TAP.observe(
                "hvd_op_execute_seconds", duration, op=op_label
            )
            nbytes = int(plan.get("total_bytes", 0) or 0)
            if nbytes:
                _metrics.TAP.observe("hvd_op_bytes", nbytes, op=op_label)
            if status_code != 0:
                _metrics.TAP.inc("hvd_op_errors_total", op=op_label)
        for entry in entries:
            out = outputs.get(entry.name)
            if entry.callback is not None:
                try:
                    entry.callback(status, out)
                except Exception:  # noqa: BLE001
                    logger.exception("callback for %s raised", entry.name)
            if status.ok():
                with self._cv:
                    self._outputs.setdefault(entry.name, deque()).append(out)
        self.core.plan_done(
            int(plan["id"]), status_code, error, duration,
            int(plan.get("total_bytes", 0)),
        )
        with self._cv:
            self._cv.notify_all()

    # --- sync helpers ---
    def poll(self, handle: int) -> bool:
        with self._cv:
            if handle in self._done:
                return True
        state, err = self.core.ticket_status(handle)
        if state == 0:
            return False
        with self._cv:
            name = self._ticket_names.pop(handle, None)
            if state == 1:
                out = None
                q = self._outputs.get(name) if name else None
                if q:
                    out = q.popleft()
                    if not q:
                        del self._outputs[name]
                self._done[handle] = (Status.OK(), out)
            else:
                code = -state
                self._done[handle] = (
                    Status(StatusType(code if 0 < code <= 5 else 1), err),
                    None,
                )
        return True

    def synchronize(self, handle: int, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        if self._inline_sync:
            with self._cv:
                self._sync_waiters += 1
                self._no_waiters.clear()
        # This thread is now committed to waiting: anything it was going
        # to submit is already queued, so the core may seal the next
        # cycle immediately instead of holding the fusion grace for
        # companions that are not coming. Independent of the inline-sync
        # knob — a non-inline waiter is equally committed.
        if self._flush_hint:
            try:
                self.core.flush_hint()
            except Exception:  # noqa: BLE001 - hint only
                pass
        try:
            while True:
                if self.poll(handle):
                    with self._cv:
                        status, out = self._done.pop(handle)
                    if not status.ok():
                        # HorovodInternalError so elastic rollback can
                        # distinguish collective failures from user bugs.
                        from .. import HorovodInternalError

                        raise HorovodInternalError(status.reason)
                    return out
                if deadline is not None and time.monotonic() > deadline:
                    with self._cv:
                        name = self._ticket_names.get(handle, "")
                    raise TimeoutError(
                        "operation "
                        + (f"'{name}' " if name else f"handle {handle} ")
                        + f"did not complete within {timeout}s; it is "
                        "still in progress"
                    )
                # Inline fast path: consume the next plan on THIS thread
                # (see _consumer_lock comment). Non-blocking acquire —
                # another synchronize() caller may already be consuming,
                # in which case its _cv notify wakes us below.
                if (self._inline_sync
                        and self._consumer_lock.acquire(blocking=False)):
                    try:
                        if self._stop.is_set():
                            continue
                        plan = self.core.next_plan(timeout_ms=1)
                        if plan == -1:
                            # Core down. The executor thread owns the
                            # orphaned-entry callback drain
                            # (_drain_entry_callbacks); wake it out of
                            # its waiters park so callback-consumers are
                            # failed promptly instead of after every
                            # synchronize() caller exits via FailAll.
                            self._core_down.set()
                            self._no_waiters.set()
                        elif plan not in (0, -2):
                            self._execute_plan(plan)
                        continue
                    finally:
                        self._consumer_lock.release()
                with self._cv:
                    self._cv.wait(
                        timeout=0.001 if self._inline_sync else 0.01
                    )
        finally:
            if self._inline_sync:
                with self._cv:
                    self._sync_waiters -= 1
                    if self._sync_waiters == 0:
                        self._no_waiters.set()
