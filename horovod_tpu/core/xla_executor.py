"""Multi-process XLA plan executor: the eager-mode data plane.

Where the reference executes fused responses through NCCL/MPI/Gloo
(``horovod/common/ops/*_operations.cc``), the TPU build executes them as
jitted XLA collectives over a global device mesh spanning all processes
(``jax.distributed``): pack the fused entries into one flat buffer, build a
global array sharded one-shard-per-rank, run a compiled
``shard_map(psum/all_gather/...)``, and take the local shard back. Compiled
executables are cached per (op, dtype, total-elements) signature, so
steady-state training reuses one executable per fusion bucket.

Fusion-buffer strategy (the analogue of the reference's persistent
``FusionBufferManager``, ``fusion_buffer_manager.cc:21-50``, re-expressed
for XLA's immutable-buffer model):

 - *Host path* (numpy inputs): the packed carrier array is **donated** to
   the compiled executable, so XLA aliases the input buffer into the output
   — steady state runs in one persistent buffer per fusion signature
   instead of allocating a fresh pair every call.
 - *Device path* (jax-array inputs): pack, collective, and unpack are all
   traced into ONE executable — entries go in as device arrays, outputs
   come back as device arrays, and the flat fusion buffer exists only as an
   XLA temporary that the compiler places and reuses. No ``device_put``,
   no ``np.asarray``, zero host↔device traffic.

On a TPU pod the mesh axis rides ICI/DCN; on CPU test clusters it rides the
gloo-backed CPU collectives. Either way the executor code is identical.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import metrics as _metrics
from ..common.topology import Topology
from ..common.types import ReduceOp
from .native_runtime import PlanExecutor

logger = logging.getLogger("horovod_tpu")

_RANK_AXIS = "hvd_ranks"
_CROSS_AXIS = "hvd_cross"
_LOCAL_AXIS = "hvd_local"


def rank_mesh_devices(devices=None) -> list:
    """One device per rank: process r contributes its first local device.

    (TPU pods with multiple chips per process combine eager rank collectives
    with in-process compiled-mode meshes; the eager plane uses the leading
    chip.) Shared by the executor and the micro benchmark so both measure
    the same mesh.
    """
    import jax

    devices = devices if devices is not None else jax.devices()
    by_proc: Dict[int, list] = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    return [
        sorted(by_proc[p], key=lambda d: d.id)[0]
        for p in sorted(by_proc.keys())
    ]


class _SetContext:
    """Per-process-set mesh bundle (later-reference horovod.ProcessSet).

    The TPU-native expression of a process set is a sub-``Mesh`` over the
    member ranks' devices: only member processes execute the compiled
    collective (multi-controller JAX runs a computation on exactly the
    processes whose devices are in the mesh), which is precisely the
    reference's per-set communicator semantics — no per-set NCCL comm
    split, just a smaller mesh."""

    def __init__(self, psid: int, ranks, mesh_devices, my_rank: int):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        self.id = int(psid)
        self.ranks = sorted(int(r) for r in ranks)
        self.size = len(self.ranks)
        # This rank's member position (-1 on non-members, which never
        # receive plans for the set).
        self.index = (
            self.ranks.index(my_rank) if my_rank in self.ranks else -1
        )
        devs = [mesh_devices[r] for r in self.ranks]
        self.mesh = Mesh(np.array(devs), (_RANK_AXIS,))
        self.sharding = NamedSharding(self.mesh, P(_RANK_AXIS))


class XlaPlanExecutor(PlanExecutor):
    def __init__(self, topology: Topology, device=None, config=None):
        import jax
        from jax.sharding import Mesh

        from ..common import env as _env_mod

        # Resolve + record the XLA perf-flag preset for this data plane
        # (idempotent: hvd.init already applied it pre-backend; here the
        # record lands in metrics even for direct executor construction,
        # and a too-late application is marked `late` rather than lied
        # about).
        try:
            self._perf_preset = _env_mod.apply_xla_perf_preset(
                getattr(config, "xla_perf_preset", None)
            )
        except Exception:  # noqa: BLE001 - plumbing must not block the plane
            self._perf_preset = None

        self._jax = jax
        devices = jax.devices()
        if len(devices) < topology.size:
            raise RuntimeError(
                f"XlaPlanExecutor needs one device per rank: "
                f"{len(devices)} global devices < size {topology.size}"
            )
        mesh_devices = rank_mesh_devices(devices)
        if len(mesh_devices) != topology.size:
            raise RuntimeError(
                f"process count {len(mesh_devices)} != horovod size "
                f"{topology.size}"
            )
        self._mesh_devices = mesh_devices
        self._mesh = Mesh(np.array(mesh_devices), (_RANK_AXIS,))
        self._local_device = device or mesh_devices[topology.rank]
        self._topo = topology
        self._config = config
        # Registered process-set sub-meshes (id -> _SetContext); id 0 (the
        # global set) uses the executor's own mesh fields.
        self._sets: Dict[int, _SetContext] = {}
        # Two-level (cross, local) mesh for the hierarchical lowerings —
        # the ICI/DCN analogue of the reference's LOCAL/CROSS communicator
        # pair (nccl_operations.cc:151-346, mpi_operations.cc:168-321).
        # Requires a homogeneous grid with ranks laid out
        # rank = cross_rank * local_size + local_rank.
        self._mesh2 = None
        if (
            topology.is_homogeneous
            and topology.local_size > 1
            and topology.cross_size > 1
            and topology.local_size * topology.cross_size == topology.size
        ):
            self._mesh2 = Mesh(
                np.array(mesh_devices).reshape(
                    topology.cross_size, topology.local_size
                ),
                (_CROSS_AXIS, _LOCAL_AXIS),
            )
        # Interconnect model for the topology compositor: the eager path
        # consults the same planner the streamed/compiled paths use
        # (docs/topology.md). Built once — selection per plan is pure
        # python. topology_plan="auto" lets the planner ENABLE the
        # hierarchical lowerings; otherwise it is advisory (it still
        # picks two-level vs split under the legacy force-knobs and
        # records every verdict in metrics).
        try:
            from ..topo.model import apply_override, model_from_topology

            self._topo_model = apply_override(model_from_topology(topology))
        except Exception:  # noqa: BLE001 - planner must not block the plane
            self._topo_model = None
        self._topo_auto = (
            getattr(config, "topology_plan", "off") == "auto"
            if config else False
        )
        from jax.sharding import NamedSharding, PartitionSpec as P

        self._sharding = NamedSharding(self._mesh, P(_RANK_AXIS))
        self._sharding2 = (
            NamedSharding(self._mesh2, P(_CROSS_AXIS, _LOCAL_AXIS))
            if self._mesh2 is not None else None
        )
        # dim0-sharded grid variant for the zero-copy device path: the
        # local array is its own shard of a (size*d0, *rest) global
        # (cross-major, local-minor). The flat-mesh case reuses
        # self._sharding (P(_RANK_AXIS) shards dim0 either way).
        self._sharding2_dim0 = (
            NamedSharding(self._mesh2, P((_CROSS_AXIS, _LOCAL_AXIS)))
            if self._mesh2 is not None else None
        )
        self._fn_cache: Dict[Tuple, Any] = {}
        self._lock = threading.Lock()
        # Compiled-path tuned source (docs/autotune.md "Compiled-path
        # offline tuning"): the eager verdict already carries the native
        # core's categorical `tuned_flags`; this records what the
        # COMPILED path is tuned from — file/env/none plus the tuned
        # signature hash — stamped into every executed plan (see
        # execute()) and exported as the hvd_tuned_info gauge.
        try:
            from .. import tune as _tune

            self._tuned_info = _tune.current_tuned_source()
        except Exception:  # noqa: BLE001 - tuning must not block the plane
            self._tuned_info = {"source": "none", "signature": "-",
                                "matched": False, "where": "-"}
        if _metrics.ACTIVE:
            _metrics.TAP.set(
                "hvd_tuned_info", 1.0,
                source=str(self._tuned_info.get("source", "none")),
                signature=str(self._tuned_info.get("signature", "-")),
                matched="1" if self._tuned_info.get("matched") else "0",
                where="executor",
            )
        # Device-order fence: the previous plan's output arrays. XLA
        # dispatch is async (CPU included), and plans may be consumed by
        # DIFFERENT threads (the executor thread or an inline
        # synchronize() caller — native_runtime._consumer_lock): without
        # an explicit fence, two in-flight collective executions can
        # reach the backend's rendezvous out of plan order on one rank
        # and deadlock/mismatch against its peers ("received data size
        # doesn't match expected size"). Blocking on plan K's outputs
        # before dispatching K+1 pins the device-side order to the plan
        # order on every rank.
        self._inflight_outs: Optional[list] = None

    # --- process sets ---
    def register_process_set(self, psid: int, ranks) -> None:
        with self._lock:
            self._sets[int(psid)] = _SetContext(
                psid, ranks, self._mesh_devices, self._topo.rank
            )

    def remove_process_set(self, psid: int) -> None:
        with self._lock:
            self._sets.pop(int(psid), None)
            # Compiled plans over the dropped sub-mesh must not outlive it
            # (a re-registered id could carry different membership).
            for key in [k for k in self._fn_cache if k[-1] == ("ps", psid)]:
                self._fn_cache.pop(key, None)

    def _set_ctx(self, plan: dict) -> Optional[_SetContext]:
        psid = int(plan.get("process_set", 0))
        if psid == 0:
            return None
        with self._lock:
            ctx = self._sets.get(psid)
        if ctx is None:
            raise RuntimeError(
                f"process set {psid} is not registered on this rank"
            )
        return ctx

    def _knob(self, name: str) -> bool:
        return bool(getattr(self._config, name, False)) if self._config else False

    def _plan_knob(self, plan: dict, name: str, bit: int) -> bool:
        """Categorical op-selection knob for one plan: the autotuner's
        verdict-stamped flags win (identical on every rank by construction
        — the coordinator broadcasts them with the plan's verdict,
        core.cc tuned_flags); -1 means autotune off, fall back to the env
        config knob."""
        flags = int(plan.get("tuned_flags", -1))
        if flags >= 0:
            return bool(flags & bit)
        return self._knob(name)

    def _wrap(self, body, hier: bool, n_in: int = 1, n_out: int = 1,
              donate: bool = False, dim0: bool = False,
              ctx: Optional[_SetContext] = None):
        """shard_map+jit a plan body over the flat rank mesh, the
        (cross, local) grid, or a process set's sub-mesh. ``donate``
        aliases the carrier buffer into the output (persistent-fusion-
        buffer behavior); only set it when the executor owns the input
        arrays. ``dim0`` selects the zero-copy layout where dim0 itself is
        sharded (the body receives the local block with no leading rank
        axes)."""
        import jax
        from jax.sharding import PartitionSpec as P
        from ..jax import _shard_map

        if hier:
            # dim0 layout shards dim0 by BOTH grid axes (cross-major);
            # the host layout carries explicit (cross, local) lead axes.
            # (Hierarchical lowerings are global-set-only.)
            assert ctx is None, "hierarchical ops run on the global set"
            in_spec = (P((_CROSS_AXIS, _LOCAL_AXIS)) if dim0
                       else P(_CROSS_AXIS, _LOCAL_AXIS))
            mesh = self._mesh2
        else:
            in_spec = P(_RANK_AXIS)
            mesh = ctx.mesh if ctx is not None else self._mesh
        fn = _shard_map(
            body, mesh,
            in_specs=(in_spec,) * n_in,
            out_specs=P() if n_out == 1 else (P(),) * n_out,
        )
        return jax.jit(
            fn, donate_argnums=tuple(range(n_in)) if donate else ()
        )

    # --- helpers ---
    def _global_array(self, local_np: np.ndarray, hierarchical: bool = False,
                      ctx: Optional[_SetContext] = None):
        """Build a global array of shape (size, *local) — or
        (cross, local, *local) on the 2-D mesh — with one shard per rank
        from this process's local data. ``ctx`` narrows "global" to a
        process set's members."""
        import jax

        if hierarchical:
            sharding = self._sharding2
            gshape = (
                self._topo.cross_size, self._topo.local_size
            ) + local_np.shape
            local = jax.device_put(
                local_np[None, None, ...], self._local_device
            )
        else:
            sharding = ctx.sharding if ctx is not None else self._sharding
            n = ctx.size if ctx is not None else self._topo.size
            gshape = (n,) + local_np.shape
            local = jax.device_put(local_np[None, ...], self._local_device)
        return jax.make_array_from_single_device_arrays(
            gshape, sharding, [local]
        )

    def _device_resident(self, t) -> bool:
        """True when ``t`` is a jax array living wholly on this rank's eager
        device — the zero-copy fast path applies."""
        try:
            return (
                isinstance(t, self._jax.Array)
                and not isinstance(t, self._jax.core.Tracer)
                and len(t.devices()) == 1
                and next(iter(t.devices())) == self._local_device
            )
        except Exception:
            return False

    def _global_from_device(self, x, hierarchical: bool = False,
                            ctx: Optional[_SetContext] = None):
        """Wrap this rank's device-resident array as its shard of the global
        array with ZERO device ops: the global shape is (size*d0, *rest)
        sharded on dim0 (cross-major, local-minor on the 2-D grid, matching
        rank = cross*local_size + local), so the local array IS its shard —
        no reshape dispatch, no host round-trip, pure aliasing metadata.
        Scalars take the one-element-reshape slow path."""
        import jax

        if x.ndim == 0:
            x = x.reshape(1)
        n = ctx.size if ctx is not None else self._topo.size
        gshape = (n * x.shape[0],) + tuple(x.shape[1:])
        if ctx is not None:
            sharding = ctx.sharding
        else:
            sharding = (
                self._sharding2_dim0 if hierarchical else self._sharding
            )
        return jax.make_array_from_single_device_arrays(
            gshape, sharding, [x]
        )

    def _compiled(self, key: Tuple, builder):
        with self._lock:
            fn = self._fn_cache.get(key)
            if fn is None:
                t0 = time.perf_counter() if _metrics.ACTIVE else 0.0
                fn = builder()
                self._fn_cache[key] = fn
                if _metrics.ACTIVE:
                    _metrics.TAP.inc("hvd_xla_cache_misses_total",
                                     op=str(key[0]))
                    _metrics.TAP.observe(
                        "hvd_xla_compile_seconds",
                        time.perf_counter() - t0, op=str(key[0]),
                    )
            elif _metrics.ACTIVE:
                _metrics.TAP.inc("hvd_xla_cache_hits_total", op=str(key[0]))
        return fn

    def _local_out(self, garr) -> np.ndarray:
        shard = [s for s in garr.addressable_shards
                 if s.device == self._local_device]
        return np.asarray(shard[0].data if shard else garr.addressable_shards[0].data)

    # --- execution ---
    def tuned_info(self) -> Dict[str, Any]:
        """The compiled-path tuned source this executor records into
        verdicts (`file`/`env`/`none` + signature hash)."""
        return dict(self._tuned_info)

    def execute(self, plan: dict, entries, topo: Topology) -> Dict[str, Any]:
        ptype = plan["type"]
        # Verdict stamp: alongside the eager core's tuned_flags int the
        # plan now names the compiled-path tuned source, so a timeline /
        # test reading executed plans can attribute knob provenance.
        plan.setdefault("tuned_info", dict(self._tuned_info))
        # Device-order fence (see _inflight_outs): the previous plan's
        # collective must be fully done before this one dispatches.
        prev = self._inflight_outs
        if prev is not None:
            self._inflight_outs = None
            try:
                self._jax.block_until_ready(prev)
            except Exception:  # noqa: BLE001 - its plan already reported
                pass
        # Non-members never receive set plans (the core skips them at
        # dispatch), so ctx.index >= 0 here by construction.
        ctx = self._set_ctx(plan)
        if ptype in (0, 6):  # allreduce / adasum
            out = self._allreduce(plan, entries, adasum=(ptype == 6),
                                  ctx=ctx)
        elif ptype == 1:
            out = self._allgather(plan, entries, ctx=ctx)
        elif ptype == 2:
            out = self._broadcast(plan, entries, ctx=ctx)
        elif ptype == 4:
            out = self._alltoall(plan, entries, ctx=ctx)
        elif ptype == 5:
            out = self._reducescatter(plan, entries, ctx=ctx)
        else:
            raise RuntimeError(f"unsupported plan type {ptype}")
        self._inflight_outs = [
            v for v in out.values()
            if v is not None and not isinstance(v, np.ndarray)
        ] or None
        return out

    def _pack(self, entries) -> Tuple[np.ndarray, List[Tuple[int, ...]], str]:
        shapes = [tuple(int(d) for d in e.tensor.shape) for e in entries]
        flat = [np.asarray(e.tensor).reshape(-1) for e in entries]
        buf = flat[0] if len(flat) == 1 else np.concatenate(flat)
        return buf, shapes, str(buf.dtype)

    def _unpack(self, buf: np.ndarray, entries, shapes) -> Dict[str, Any]:
        outputs: Dict[str, Any] = {}
        offset = 0
        for e, shape in zip(entries, shapes):
            n = int(np.prod(shape)) if shape else 1
            outputs[e.name] = buf[offset:offset + n].reshape(shape)
            offset += n
        return outputs

    def _consult_planner(self, collective: str, nbytes: int, op=None):
        """Select (and metrics-record) the compositor's plan for one
        eager collective — None when no model is available."""
        if self._topo_model is None:
            return None
        try:
            from ..topo import compositor as _compositor

            return _compositor.record_plan(
                _compositor.select_plan(
                    self._topo_model, collective, nbytes,
                    op=op if op is not None else ReduceOp.SUM,
                ),
                where="eager",
            )
        except Exception:  # noqa: BLE001 - advisory only
            return None

    @staticmethod
    def _entry_bytes(entries) -> int:
        return int(sum(
            int(np.prod(e.tensor.shape)) * np.dtype(str(e.tensor.dtype)).itemsize
            if len(e.tensor.shape) else np.dtype(str(e.tensor.dtype)).itemsize
            for e in entries
        ))

    def _reduce_flat(self, v, *, op, adasum, hier, pre, post, participants,
                     algorithm="two-level", split_fraction=None):
        """Collective math on one flat per-rank vector; traced inside the
        compiled plan executable by both the host and device paths."""
        from jax import lax
        from ..ops.adasum import adasum_allreduce

        if pre != 1.0:
            v = v * np.asarray(pre, dtype=v.dtype)
        if adasum:
            if hier:
                from ..ops.adasum import hierarchical_adasum_allreduce

                # 1/local_size so the local reduce-scatter yields the
                # node *average* and VHDD of identical inputs is the
                # identity, matching flat VHDD semantics (the
                # reference applies this divisor in the framework
                # layer, tensorflow/__init__.py:98-106).
                v = (v / self._topo.local_size).astype(v.dtype)
                r = hierarchical_adasum_allreduce(
                    v, local_axis=_LOCAL_AXIS, cross_axis=_CROSS_AXIS
                )
            else:
                r = adasum_allreduce(v, axis_name=_RANK_AXIS)
        elif hier:
            from ..topo import compositor as _compositor

            # The planner's verdict picks the hierarchical flavor:
            # two-level (the NCCLHierarchicalAllreduce shape) or the
            # FlexLink split that drives ICI and DCN concurrently.
            r = _compositor.lower_allreduce(
                v, (_CROSS_AXIS, _LOCAL_AXIS), op=ReduceOp.SUM,
                algorithm=algorithm, split_fraction=split_fraction,
            )
            if op == ReduceOp.AVERAGE:
                r = (r / participants).astype(r.dtype)
        elif op == ReduceOp.AVERAGE:
            # Divide by the participant count (Join-aware divisor),
            # not the axis size.
            s = lax.psum(v, _RANK_AXIS)
            r = (s / participants).astype(s.dtype)
        elif op == ReduceOp.MIN:
            r = lax.pmin(v, _RANK_AXIS)
        elif op == ReduceOp.MAX:
            r = lax.pmax(v, _RANK_AXIS)
        else:
            r = lax.psum(v, _RANK_AXIS)
        if post != 1.0:
            r = r * np.asarray(post, dtype=r.dtype)
        return r

    def _allreduce(self, plan, entries, adasum: bool,
                   ctx: Optional[_SetContext] = None) -> Dict[str, Any]:
        op = ReduceOp(plan.get("op", int(ReduceOp.SUM)))
        pre = float(plan.get("prescale", 1.0))
        post = float(plan.get("postscale", 1.0))
        default_n = ctx.size if ctx is not None else self._topo.size
        participants = max(int(plan.get("participants", default_n)), 1)
        adasum = adasum or op == ReduceOp.ADASUM
        # Hierarchical op selection, the analogue of the reference picking
        # NCCLHierarchicalAllreduce / AdasumCudaAllreduce at op-manager build
        # (operations.cc:142-223, nccl_operations.cc:348-355): honored in
        # eager mode whenever the knob is set and a (cross, local) grid
        # exists. MIN/MAX stay flat (reference hierarchy covers sums only).
        # Process-set collectives always run flat on the sub-mesh (a set
        # has no (cross, local) factorization of its own).
        # The compositor's verdict for this payload (recorded in
        # hvd_topo_plan_info either way; authoritative only under
        # HOROVOD_TOPOLOGY_PLAN=auto).
        tplan = None
        if (
            ctx is None and self._mesh2 is not None and not adasum
            and op in (ReduceOp.SUM, ReduceOp.AVERAGE)
            and (self._topo_auto or _metrics.ACTIVE)
        ):
            tplan = self._consult_planner(
                "allreduce", self._entry_bytes(entries), op
            )
        hier = (
            ctx is None
            and self._mesh2 is not None
            and (
                (not adasum
                 and self._plan_knob(plan, "hierarchical_allreduce", 1)
                 and op in (ReduceOp.SUM, ReduceOp.AVERAGE))
                # Adasum on a multi-level grid is always hierarchical, like
                # the reference's CUDA variant (adasum_cuda_operations.cc).
                or adasum
                # Planner-driven: the cost model turned hierarchy on.
                or (self._topo_auto and tplan is not None
                    and tplan.algorithm in ("two-level", "split"))
            )
        )
        algorithm, split_fraction = "two-level", None
        if (
            hier and not adasum and tplan is not None
            and tplan.algorithm == "split" and tplan.nbytes
        ):
            algorithm = "split"
            split_fraction = tplan.split_bytes[0] / tplan.nbytes
        kw = dict(op=op, adasum=adasum, hier=hier, pre=pre, post=post,
                  participants=participants, ctx=ctx,
                  algorithm=algorithm, split_fraction=split_fraction)
        if (
            all(self._device_resident(e.tensor) for e in entries)
            and len({str(e.tensor.dtype) for e in entries}) == 1
        ):
            return self._allreduce_device(entries, **kw)
        return self._allreduce_host(entries, **kw)

    def _allreduce_host(self, entries, *, op, adasum, hier, pre, post,
                        participants, ctx=None, algorithm="two-level",
                        split_fraction=None) -> Dict[str, Any]:
        buf, shapes, dtype = self._pack(entries)
        key = ("ar", dtype, buf.size, int(op), adasum, pre, post,
               participants, hier, algorithm, split_fraction,
               ("ps", ctx.id if ctx else 0))

        def build():
            def body(x):
                # x: local shard — (1, L) flat or (1, 1, L) hierarchical.
                v = x[0] if not hier else x[0, 0]
                return self._reduce_flat(
                    v, op=op, adasum=adasum, hier=hier, pre=pre, post=post,
                    participants=participants, algorithm=algorithm,
                    split_fraction=split_fraction,
                )

            # The carrier is executor-owned: donate it so XLA aliases the
            # buffer across calls (persistent fusion buffer).
            return self._wrap(body, hier, donate=True, ctx=ctx)

        garr = self._global_array(buf, hierarchical=hier, ctx=ctx)
        out = self._compiled(key, build)(garr)
        res = self._local_out(out)
        # jax (x64 disabled) narrows 64-bit wires; restore the caller's
        # dtype (compute happened in 32-bit — values beyond its range
        # wrap, the same contract the framework bindings document).
        if res.dtype != buf.dtype:
            res = res.astype(buf.dtype)
        return self._unpack(res, entries, shapes)

    def _allreduce_device(self, entries, *, op, adasum, hier, pre, post,
                          participants, ctx=None, algorithm="two-level",
                          split_fraction=None) -> Dict[str, Any]:
        """Zero-host-copy path: entries are device-resident jax arrays, so
        pack + collective + unpack trace into one executable and outputs
        stay on device. The flat fusion buffer is an XLA temporary — the
        compiler, not the host, owns its placement and reuse."""
        import jax.numpy as jnp

        shapes = tuple(tuple(int(d) for d in e.tensor.shape) for e in entries)
        dtype = str(entries[0].tensor.dtype)
        key = ("ar_dev", dtype, shapes, int(op), adasum, pre, post,
               participants, hier, algorithm, split_fraction,
               ("ps", ctx.id if ctx else 0))

        def build():
            def body(*xs):
                # dim0 layout: each block is this rank's tensor verbatim.
                vs = [x.reshape(-1) for x in xs]
                v = vs[0] if len(vs) == 1 else jnp.concatenate(vs)
                r = self._reduce_flat(
                    v, op=op, adasum=adasum, hier=hier, pre=pre, post=post,
                    participants=participants, algorithm=algorithm,
                    split_fraction=split_fraction,
                )
                if len(shapes) == 1:
                    return r.reshape(shapes[0])
                outs, off = [], 0
                for shp in shapes:
                    n = int(np.prod(shp)) if shp else 1
                    outs.append(r[off:off + n].reshape(shp))
                    off += n
                return tuple(outs)

            return self._wrap(
                body, hier, n_in=len(entries), n_out=len(entries), dim0=True,
                ctx=ctx,
            )

        garrs = [
            self._global_from_device(e.tensor, hierarchical=hier, ctx=ctx)
            for e in entries
        ]
        outs = self._compiled(key, build)(*garrs)
        if len(entries) == 1:
            outs = (outs,)
        if self._knob("autotune"):
            # Async dispatch is the TPU-native default (consumers block
            # naturally), but the autotuner scores plans by wall time at
            # plan_done — only block when those scores matter.
            self._jax.block_until_ready(outs)
        return {
            e.name: self._local_view(o) for e, o in zip(entries, outs)
        }

    def _local_view(self, garr):
        """This rank's single-device view of a replicated output — a jax
        array, not a host copy."""
        for s in garr.addressable_shards:
            if s.device == self._local_device:
                return s.data
        return garr.addressable_shards[0].data

    def _allgather(self, plan, entries,
                   ctx: Optional[_SetContext] = None) -> Dict[str, Any]:
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P
        from ..jax import _shard_map

        # Per-rank dim0 sizes from the coordinator (the reference's
        # Allgatherv sizes/displacements, mpi_operations.cc:83-162) — in
        # member-position order for a process set. Equal sizes take the
        # direct tiled all_gather; uneven sizes pad to the max, gather,
        # and compact on the host (XLA needs static shapes).
        rank_sizes = [int(s) for s in plan.get("rank_sizes", [])]
        uneven = bool(rank_sizes) and len(set(rank_sizes)) > 1
        tplan = None
        if (
            ctx is None and self._mesh2 is not None
            and (self._topo_auto or _metrics.ACTIVE)
        ):
            tplan = self._consult_planner(
                "allgather", self._entry_bytes(entries)
            )
        hier = (
            ctx is None
            and self._mesh2 is not None
            and (
                self._plan_knob(plan, "hierarchical_allgather", 2)
                or (self._topo_auto and tplan is not None
                    and tplan.algorithm == "two-level")
            )
        )
        n_ranks = ctx.size if ctx is not None else self._topo.size

        outputs: Dict[str, Any] = {}
        for e in entries:
            local = np.asarray(e.tensor)
            max_dim0 = max(rank_sizes) if uneven else (
                local.shape[0] if local.ndim else 0
            )
            if uneven:
                pad = [(0, max_dim0 - local.shape[0])] + [(0, 0)] * (local.ndim - 1)
                send = np.pad(local, pad)
            else:
                send = local
            key = ("ag", str(send.dtype), send.shape, hier,
                   ("ps", ctx.id if ctx else 0))

            def build():
                def body(x):
                    if hier:
                        # Two-stage gather: ICI within the node, DCN across
                        # node leaders — the TPU re-expression of the
                        # reference's MPIHierarchicalAllgather (shared-memory
                        # window + cross-node allgatherv by one rank per
                        # node, mpi_operations.cc:168-321). Rank order
                        # rank = cross*local_size + local keeps the
                        # concatenation identical to the flat op.
                        v = x[0, 0]
                        g = lax.all_gather(v, _LOCAL_AXIS, tiled=True)
                        return lax.all_gather(g, _CROSS_AXIS, tiled=True)
                    return lax.all_gather(x[0], _RANK_AXIS, tiled=True)

                return self._wrap(body, hier, ctx=ctx)

            garr = self._global_array(send, hierarchical=hier, ctx=ctx)
            out = self._compiled(key, build)(garr)
            gathered = self._local_out(out)
            if gathered.dtype != send.dtype:
                gathered = gathered.astype(send.dtype)
            if uneven:
                gathered = np.concatenate([
                    gathered[i * max_dim0: i * max_dim0 + rank_sizes[i]]
                    for i in range(n_ranks)
                ])
            outputs[e.name] = gathered
        return outputs

    def _broadcast(self, plan, entries,
                   ctx: Optional[_SetContext] = None) -> Dict[str, Any]:
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P
        from ..jax import _shard_map
        from ..ops.collectives import broadcast as bcast_op

        if _metrics.ACTIVE and ctx is None:
            # Advisory verdict only (the eager broadcast body runs on the
            # flat rank mesh); surfaces what a hierarchical lowering
            # would save in hvd_topo_bytes_per_hop.
            self._consult_planner("broadcast", self._entry_bytes(entries))
        # root_rank travels as a GLOBAL rank (reference process-set API
        # semantics); on a sub-mesh the lowering wants the member position.
        root = int(plan.get("root", 0))
        if ctx is not None:
            if root not in ctx.ranks:
                raise RuntimeError(
                    f"broadcast root {root} is not a member of process "
                    f"set {ctx.id}"
                )
            root = ctx.ranks.index(root)
        outputs: Dict[str, Any] = {}
        for e in entries:
            local = np.asarray(e.tensor)
            key = ("bc", str(local.dtype), local.shape, root,
                   ("ps", ctx.id if ctx else 0))

            def build():
                def body(x):
                    return bcast_op(x[0], root_rank=root, axis_name=_RANK_AXIS)

                fn = _shard_map(
                    body, ctx.mesh if ctx is not None else self._mesh,
                    in_specs=(P(_RANK_AXIS),), out_specs=P()
                )
                return jax.jit(fn)

            garr = self._global_array(local, ctx=ctx)
            out = self._compiled(key, build)(garr)
            res = self._local_out(out)
            outputs[e.name] = (
                res if res.dtype == local.dtype else res.astype(local.dtype)
            )
        return outputs

    def _reducescatter(self, plan, entries,
                       ctx: Optional[_SetContext] = None) -> Dict[str, Any]:
        """Sum-reduce across ranks and scatter dim0 shards. Even dim0:
        rank r gets rows [r*d0/n, (r+1)*d0/n) of the sum. Uneven dim0
        takes Allgatherv-parity split sizes (the later reference's
        reducescatter semantics, mirroring MPI_Reduce_scatter): rank r
        receives ``d0//n + (1 if r < d0%n else 0)`` rows, earlier ranks
        taking the remainder. TPU-native extension (the reference's op
        set stops at broadcast, message.h:48-50); lowers through the one
        canonical ``ops.collectives.reducescatter`` psum_scatter — the
        uneven case pre-permutes rows with a STATIC gather so each
        rank's uneven shard (zero-padded to the even block size) lands
        in its psum_scatter block, then slices the pad off after the
        collective. AVERAGE divides by the participant count like
        allreduce. Device-resident inputs stay on device."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from ..jax import _shard_map
        from ..ops.collectives import reducescatter as rs_lowering

        if _metrics.ACTIVE and ctx is None:
            self._consult_planner(
                "reducescatter", self._entry_bytes(entries)
            )
        outputs: Dict[str, Any] = {}
        n = ctx.size if ctx is not None else self._topo.size
        my = ctx.index if ctx is not None else self._topo.rank
        participants = int(plan.get("participants", n)) or n
        reduce_op = int(plan.get("op", int(ReduceOp.SUM)))
        if reduce_op not in (int(ReduceOp.SUM), int(ReduceOp.AVERAGE)):
            raise RuntimeError("reducescatter supports SUM/AVERAGE only")
        for e in entries:
            shape = tuple(int(d) for d in e.tensor.shape)
            if not shape:
                raise RuntimeError(
                    "reducescatter needs a tensor with a dim0 to scatter"
                )
            d0 = shape[0]
            base, rem = divmod(d0, n)
            ceil_rows = base + (1 if rem else 0)
            my_count = base + (1 if my < rem else 0)
            if rem:
                # Static row-gather: block r holds rank r's uneven shard
                # (rows [r*base+min(r,rem), +count_r)) then pad slots
                # pointing at one zero row appended at index d0.
                idx = np.full(n * ceil_rows, d0, dtype=np.int32)
                for r in range(n):
                    start = r * base + min(r, rem)
                    cnt = base + (1 if r < rem else 0)
                    idx[r * ceil_rows: r * ceil_rows + cnt] = np.arange(
                        start, start + cnt, dtype=np.int32
                    )
            else:
                idx = None
            on_device = self._device_resident(e.tensor)
            key = ("rs", str(e.tensor.dtype), shape, reduce_op, participants,
                   on_device, ("ps", ctx.id if ctx else 0))

            def build(idx=idx):
                def body(x):
                    # Host layout carries a leading rank axis; the device
                    # (dim0-sharded) layout is the local block verbatim.
                    t = x if on_device else x[0]
                    if idx is not None:
                        zero = jnp.zeros((1,) + t.shape[1:], t.dtype)
                        t = jnp.take(
                            jnp.concatenate([t, zero]), idx, axis=0
                        )
                    out = rs_lowering(t, axis_name=_RANK_AXIS)
                    if reduce_op == int(ReduceOp.AVERAGE):
                        out = (
                            out / np.asarray(participants, dtype=np.float32)
                        ).astype(x.dtype)  # int/int promotes; restore dtype
                    return out

                fn = _shard_map(
                    body, ctx.mesh if ctx is not None else self._mesh,
                    in_specs=(P(_RANK_AXIS),),
                    out_specs=P(_RANK_AXIS),
                )
                return jax.jit(fn)

            if on_device:
                garr = self._global_from_device(e.tensor, ctx=ctx)
                out = self._compiled(key, build)(garr)
                view = self._local_view(out)
                outputs[e.name] = view[:my_count] if rem else view
            else:
                local = np.asarray(e.tensor)
                garr = self._global_array(local, ctx=ctx)
                out = self._compiled(key, build)(garr)
                res = self._local_out(out)
                if rem:
                    res = res[:my_count]
                outputs[e.name] = (
                    res if res.dtype == local.dtype
                    else res.astype(local.dtype)
                )
        return outputs

    def _alltoall(self, plan, entries,
                  ctx: Optional[_SetContext] = None) -> Dict[str, Any]:
        import jax
        from jax import lax
        from jax.sharding import PartitionSpec as P
        from ..jax import _shard_map

        if _metrics.ACTIVE and ctx is None:
            self._consult_planner("alltoall", self._entry_bytes(entries))
        outputs: Dict[str, Any] = {}
        n = ctx.size if ctx is not None else self._topo.size
        for e in entries:
            local = np.asarray(e.tensor)
            if local.shape[0] % n != 0:
                raise RuntimeError(
                    f"alltoall dim0 ({local.shape[0]}) must be divisible by "
                    f"size ({n})"
                )
            key = ("a2a", str(local.dtype), local.shape,
                   ("ps", ctx.id if ctx else 0))

            def build():
                def body(x):
                    return lax.all_to_all(
                        x[0], _RANK_AXIS, split_axis=0, concat_axis=0,
                        tiled=True,
                    )

                fn = _shard_map(
                    body, ctx.mesh if ctx is not None else self._mesh,
                    in_specs=(P(_RANK_AXIS),), out_specs=P()
                )
                return jax.jit(fn)

            garr = self._global_array(local, ctx=ctx)
            out = self._compiled(key, build)(garr)
            res = self._local_out(out)
            outputs[e.name] = (
                res if res.dtype == local.dtype else res.astype(local.dtype)
            )
        return outputs
