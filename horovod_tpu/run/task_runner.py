"""Per-rank entry for the ``horovod_tpu.run.run()`` API: load the pickled
function, execute it, write the pickled result (parity with the reference's
``run/run_task.py`` + KVStore function shipping)."""

from __future__ import annotations

import os
import pickle
import sys


def main() -> int:
    fn_path = os.environ["HOROVOD_RUN_FN_FILE"]
    result_dir = os.environ["HOROVOD_RUN_RESULT_DIR"]
    rank = int(os.environ.get("HOROVOD_RANK", "0"))
    with open(fn_path, "rb") as f:
        fn, args, kwargs = pickle.load(f)
    result = fn(*args, **kwargs)
    tmp = os.path.join(result_dir, f".result.{rank}.tmp")
    with open(tmp, "wb") as f:
        pickle.dump(result, f)
    os.replace(tmp, os.path.join(result_dir, f"result.{rank}.pkl"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
