"""On-disk result cache with TTL.

Role parity with the reference's launcher check cache
(``horovod/run/util/cache.py``, used by the cached SSH reachability
check at ``run/run.py:62-115``): repeated launches skip slow pre-flight
probes while the cached result is fresh. One JSON file, atomic replace,
tolerant of corruption (a broken cache never breaks a launch).
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Optional


class DiskCache:
    def __init__(self, path: str, ttl_seconds: float = 300.0):
        self._path = path
        self._ttl = ttl_seconds

    def _load(self) -> dict:
        try:
            with open(self._path) as f:
                data = json.load(f)
            return data if isinstance(data, dict) else {}
        except (OSError, ValueError):
            return {}

    def get(self, key: str) -> Optional[Any]:
        """Cached value, or None when absent or older than the TTL."""
        entry = self._load().get(key)
        if not isinstance(entry, dict):
            return None
        if time.time() - entry.get("t", 0) > self._ttl:
            return None
        return entry.get("v")

    def put(self, key: str, value: Any) -> None:
        self.put_many({key: value})

    def put_many(self, items: dict) -> None:
        """One read-modify-replace for a batch of keys: concurrent
        per-key puts would lose each other's entries (last writer wins on
        the whole file), so batch writers must use this."""
        data = self._load()
        now = time.time()
        for key, value in items.items():
            data[key] = {"v": value, "t": now}
        try:
            os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
            fd, tmp = tempfile.mkstemp(
                dir=os.path.dirname(self._path) or ".", suffix=".cache"
            )
            with os.fdopen(fd, "w") as f:
                json.dump(data, f)
            os.replace(tmp, self._path)
        except OSError:
            pass  # best-effort: a read-only FS must not break the launch


def default_cache(ttl_seconds: float = 300.0) -> DiskCache:
    base = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache"
    )
    return DiskCache(
        os.path.join(base, "horovod_tpu", "launch_checks.json"), ttl_seconds
    )
