"""HTTP key-value rendezvous server.

Role parity with the reference's ``run/http/http_server.py``
(RendezvousHTTPServer / KVStoreServer): a scoped KV store over HTTP GET/PUT
used by workers to exchange addresses and small blobs at startup, and by the
``horovod_tpu.run.run()`` API to ship pickled functions/results.
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.parse import urlparse

from .. import metrics as _metrics


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet
        pass

    def do_GET(self):  # noqa: N802
        key = urlparse(self.path).path
        if key == "/metrics":
            self._serve_metrics()
            return
        if key == "/clock":
            # Fleet-tracing clock probe (docs/timeline.md "Fleet
            # tracing"): workers ping this at attach and estimate their
            # offset as driver_time - (t_send + t_recv)/2; the estimate
            # is trace METADATA only, never applied to timestamps.
            import json as _json
            import time as _time

            body = _json.dumps({"time": _time.time()}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_kv_server_requests_total", method="GET")
        with self.server.kv_lock:
            value = self.server.kv.get(key)
        if value is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(value)))
        self.end_headers()
        self.wfile.write(value)

    def do_PUT(self):  # noqa: N802
        key = urlparse(self.path).path
        length = int(self.headers.get("Content-Length", 0))
        value = self.rfile.read(length)
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_kv_server_requests_total", method="PUT")
        with self.server.kv_lock:
            self.server.kv[key] = value
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_DELETE(self):  # noqa: N802
        key = urlparse(self.path).path
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_kv_server_requests_total", method="DELETE")
        with self.server.kv_lock:
            self.server.kv.pop(key, None)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def _serve_metrics(self) -> None:
        """Prometheus text exposition (docs/metrics.md): the serving
        process's own registry plus every worker snapshot pushed into the
        KV ``metrics`` scope, each series stamped with its source's
        identity labels (``role="driver"`` / ``rank="N"``)."""
        from ..metrics import export as _export

        prefix = f"/{_export.KV_SCOPE}/"
        with self.server.kv_lock:
            pushed = {
                k[len(prefix):]: v
                for k, v in self.server.kv.items()
                if k.startswith(prefix)
            }
        body = _export.aggregate_kv_snapshots(
            pushed, local_snapshot=_metrics.snapshot()
        ).encode()
        self.send_response(200)
        self.send_header("Content-Type", _export.CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


class _KVServer(ThreadingHTTPServer):
    # Explicit SO_REUSEADDR (http.server defaults to it, but a resumed
    # driver's ability to reclaim its advertised rendezvous port is a
    # correctness requirement here, not an inherited accident): lingering
    # TIME_WAIT connections from the crashed driver's clients must not
    # block the rebind.
    allow_reuse_address = True
    daemon_threads = True


class KVStoreServer:
    """In-process threaded HTTP KV server.

    ``port=0`` picks a free port. A pinned port (``HOROVOD_METRICS_PORT``
    at first launch, or the journal-recorded port on ``--resume``) is
    bound with SO_REUSEADDR; ``reclaim_wait_s`` additionally retries a
    failing bind for that long — a resumed driver racing the OS's
    cleanup of its predecessor's socket reclaims the port instead of
    dying in TIME_WAIT."""

    def __init__(self, port: int = 0, reclaim_wait_s: float = 0.0):
        import errno
        import time as _time

        deadline = _time.monotonic() + max(0.0, reclaim_wait_s)
        while True:
            try:
                self._server = _KVServer(("0.0.0.0", port), _Handler)
                break
            except OSError as exc:
                if (port == 0 or exc.errno != errno.EADDRINUSE
                        or _time.monotonic() >= deadline):
                    raise OSError(
                        exc.errno,
                        f"could not bind rendezvous KV port {port}: "
                        f"{exc.strerror or exc} (pinned port still held; "
                        "waited "
                        f"{max(0.0, reclaim_wait_s):g}s for reclaim)",
                    ) from exc
                _time.sleep(0.1)
        self._server.kv = {}
        self._server.kv_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="hvd_kv_server", daemon=True
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        self._server.shutdown()
        if self._thread:
            self._thread.join(timeout=5)
        self._server.server_close()

    def close(self) -> None:
        """Release the bound port WITHOUT the serve_forever handshake —
        for a server that was constructed but never start()ed
        (``stop()``'s shutdown() would block forever on the event only
        serve_forever sets)."""
        if self._thread is not None:
            self.stop()
        else:
            self._server.server_close()

    def put(self, scope: str, key: str, value: bytes) -> None:
        """In-process store (no HTTP round-trip) under the same lock the
        handler uses — for the owning driver's own writes."""
        with self._server.kv_lock:
            self._server.kv[f"/{scope}/{key}"] = value

    def delete(self, scope: str, key: str) -> None:
        """In-process delete (driver-side retraction of worker signals)."""
        with self._server.kv_lock:
            self._server.kv.pop(f"/{scope}/{key}", None)

    def snapshot(self, scope: str) -> Dict[str, bytes]:
        """In-process read of every key under a scope (driver-side scan
        of worker-written signals)."""
        prefix = f"/{scope}/"
        with self._server.kv_lock:
            return {
                k[len(prefix):]: v
                for k, v in self._server.kv.items()
                if k.startswith(prefix)
            }


class KVHTTPError(Exception):
    """Non-200 KV answer (e.g. 404 for a missing key). Not an OSError on
    purpose — the retry path must not spin on a definitive answer."""

    def __init__(self, message: str, status: int = 0):
        super().__init__(message)
        self.status = status


class KVUnavailableError(ConnectionError):
    """The KV endpoint could not be reached within the retry budget.
    Subclasses ConnectionError so existing transport-failure handling
    still matches, but the message names the endpoint, how long it has
    been down across consecutive failures, and the retry budget spent —
    a dead driver reads as "driver at host:port unreachable for 12.3s",
    not a bare timeout with a phase name."""


class KVStoreClient:
    """Plain-TCP HTTP KV client built on ``http.client.HTTPConnection``.

    Deliberately NOT ``urllib.request.urlopen``: urlopen's default opener
    constructs an HTTPS handler (``ssl.create_default_context`` →
    ``load_default_certs``) even for http:// URLs, and that OpenSSL
    initialization can deadlock in a process forked from a multi-threaded
    parent — exactly the Spark-task fork pattern this client serves.
    A raw HTTPConnection never touches ssl."""

    def __init__(self, addr: str, port: int):
        self._addr = addr
        self._port = port
        # Bounded retry with exponential backoff (HOROVOD_RPC_* knobs):
        # the KV store is the elastic control plane — a dropped GET during
        # a re-rendezvous must cost one backoff, not the generation.
        from ..fault.backoff import Backoff

        self._backoff = Backoff.from_env()
        # First monotonic instant of the CURRENT consecutive-failure
        # streak (None = last request succeeded): errors against a dead
        # driver report elapsed downtime, not just the final attempt.
        self._down_since: Optional[float] = None

    @property
    def endpoint(self) -> str:
        return f"{self._addr}:{self._port}"

    def downtime(self) -> float:
        """Seconds this endpoint has been failing consecutively (0 when
        the last request succeeded)."""
        import time

        return (0.0 if self._down_since is None
                else time.monotonic() - self._down_since)

    def _request(self, method: str, path: str, body=None) -> bytes:
        import http.client
        import time

        from ..fault import injector as _fault
        from ..fault.backoff import retry_call

        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_kv_requests_total", method=method)

        def once() -> bytes:
            if _fault.ACTIVE:
                # Chaos tap: 'drop' raises a ConnectionError before the
                # request leaves, exercising this very retry loop.
                _fault.fault_point("kv", f"{method} {path}")
            conn = http.client.HTTPConnection(
                self._addr, self._port, timeout=30
            )
            try:
                conn.request(method, path, body=body)
                resp = conn.getresponse()
                data = resp.read()
                if resp.status != 200:
                    # Deliberately NOT an OSError: a 404 is an answer
                    # (missing key), not a transport failure to retry.
                    raise KVHTTPError(
                        f"KV {method} {path}: HTTP {resp.status}",
                        status=resp.status,
                    )
                return data
            finally:
                conn.close()

        try:
            data = retry_call(
                once,
                retryable=(OSError, EOFError),
                backoff=self._backoff,
                describe=f"KV {method} {path} to {self.endpoint}",
                on_retry=lambda attempt, exc, delay: (
                    _metrics.TAP.inc("hvd_kv_retries_total", method=method)
                    if _metrics.ACTIVE else None
                ),
            )
        except KVHTTPError:
            self._down_since = None  # the server answered; it is up
            raise
        except (OSError, EOFError) as exc:
            now = time.monotonic()
            if self._down_since is None:
                self._down_since = now
            raise KVUnavailableError(
                f"KV endpoint {self.endpoint} unreachable for "
                f"{now - self._down_since:.1f}s "
                f"({method} {path}; retry budget spent: "
                f"{self._backoff.retries + 1} attempts): {exc}"
            ) from exc
        self._down_since = None
        return data

    def put(self, scope: str, key: str, value: bytes) -> None:
        self._request("PUT", f"/{scope}/{key}", body=value)

    def get(self, scope: str, key: str,
            strict: bool = False) -> Optional[bytes]:
        """Fetch a key. Default (lenient) mode folds EVERY failure into
        None — callers that only care "is the value there yet" keep
        their simple polling loops. ``strict=True`` distinguishes the
        two reasons a value can be absent: a missing key (HTTP 404)
        still returns None, but a transport failure (dead driver)
        raises :class:`KVUnavailableError` so the caller can tell "the
        driver says no such key" from "there is no driver". Only a 404
        means "missing key": any other HTTP status (a listening but
        erroring driver — handler exception, wedged state) is a control
        plane failure, and in strict mode it must count toward the
        driver-lost threshold exactly like a dead endpoint."""
        try:
            return self._request("GET", f"/{scope}/{key}")
        except KVHTTPError as exc:
            if exc.status == 404 or not strict:
                return None
            raise KVUnavailableError(
                f"KV endpoint {self.endpoint} answering but failing: "
                f"HTTP {exc.status} for GET /{scope}/{key}"
            ) from exc
        except Exception:
            if strict:
                raise
            return None

    def wait(self, scope: str, key: str, timeout: float = 60.0) -> bytes:
        import time

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            v = self.get(scope, key)
            if v is not None:
                return v
            time.sleep(0.1)
        raise TimeoutError(f"KV key {scope}/{key} not available")
