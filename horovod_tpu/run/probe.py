"""Per-host NIC probe entry (``python -m horovod_tpu.run.probe <index>
<num_tasks>``) — the counterpart of the reference's
``python -m horovod.run.task_fn`` (``run/task_fn.py:56-67``). Driver
addresses and the HMAC secret arrive via environment, not argv, so the
secret never shows in ``ps``."""

from __future__ import annotations

import os
import sys

from . import network


def main() -> int:
    index = int(sys.argv[1])
    num_tasks = int(sys.argv[2])
    key = network.decode_key(os.environ[network.SECRET_ENV])
    driver_addrs = network.parse_addresses(
        os.environ["HOROVOD_PROBE_DRIVER_ADDRS"]
    )
    network.run_task_probe(index, num_tasks, driver_addrs, key)
    return 0


if __name__ == "__main__":
    sys.exit(main())
