"""``hvdrun`` — the launcher CLI.

Role parity with the reference ``horovodrun`` (``run/run.py``): ``-np``,
``-H``/``--hostfile``, every runtime knob as a flag, YAML ``--config-file``
with CLI-override precedence, ``--check-build``, and a ``run()`` Python API
that ships a pickled function to every rank and gathers results.

TPU-native: no MPI path — ranks are spawned directly (local/ssh) or derived
from TPU pod metadata (``--tpu-pod``); the control plane is the native
core's TCP coordinator and the data plane is XLA.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import Any, Callable, List, Optional

from . import config_parser, launcher


def _preflight_and_nic_probe(hostnames, controller_host, env, args,
                             fatal=True):
    """SSH pre-flight + ring NIC probe shared by the fixed and elastic
    launch paths (reference ``run/run.py:62-115,198-268``).

    Returns the list of hostnames that answered the pre-flight. With
    ``fatal=True`` (fixed path) an unreachable host raises SystemExit 4;
    with ``fatal=False`` (elastic path — an unreachable host is a
    legitimate state the driver handles by blacklisting) it prints the
    per-host error and returns only the reachable hosts, so the driver
    starts from a known-good set instead of discovering dead hosts
    through repeated spawn failures.
    """
    hostnames = sorted(dict.fromkeys(hostnames))
    reachable = list(hostnames)
    from .disk_cache import default_cache

    try:
        launcher.check_hosts_reachable(
            hostnames,
            ssh_port=args.ssh_port,
            cache=None if args.disable_cache else default_cache(),
        )
    except RuntimeError as e:
        if fatal:
            print(str(e), file=sys.stderr)
            raise SystemExit(4)
        print(f"[hvdrun] elastic pre-flight: {e}\n[hvdrun] continuing "
              f"with the reachable subset; the driver will retry/"
              f"blacklist the rest", file=sys.stderr)
        bad = set(getattr(e, "failed_hosts", ()))
        if bad:
            reachable = [h for h in hostnames if h not in bad]

    # NIC selection for the multi-host control plane: explicit flag wins
    # (already exported by the caller); with multiple distinct remote
    # hosts we probe ring-wise over the HMAC-authed services and export
    # the routable intersection.
    if not args.network_interfaces and len(reachable) > 1:
        from . import network

        try:
            common, host_addrs = network.discover_common_interfaces(
                reachable, ssh_port=args.ssh_port, return_addresses=True
            )
            if common:
                env["HOROVOD_IFACE"] = ",".join(common)
                # Controller host's probed address on the first
                # ring-routable interface: lets the launcher dial the
                # controller even when its hostname doesn't resolve
                # from the workers.
                addrs0 = host_addrs.get(controller_host, {})
                for intf in common:
                    if addrs0.get(intf):
                        env["HOROVOD_PROBED_CONTROLLER_ADDR"] = \
                            addrs0[intf][0][0]
                        break
                if args.verbose:
                    print(f"[hvdrun] routable interfaces: {common}")
        except Exception as e:  # probe is best-effort
            print(f"[hvdrun] NIC probe failed ({e}); continuing without",
                  file=sys.stderr)
    return reachable


def parse_args(argv: Optional[List[str]] = None):
    parser = argparse.ArgumentParser(
        "hvdrun", description="Launch a horovod_tpu training job."
    )
    parser.add_argument("-v", "--version", action="store_true")
    parser.add_argument("-np", "--num-proc", type=int, default=None,
                        help="Total number of training processes.")
    parser.add_argument("-H", "--hosts", default=None,
                        help='Host list, e.g. "host1:4,host2:4".')
    parser.add_argument("--hostfile", default=None,
                        help='Hostfile with lines "hostname slots=N".')
    parser.add_argument("--tpu-pod", action="store_true",
                        help="Derive allocation from TPU slice metadata "
                             "(one process per pod host).")
    parser.add_argument("-p", "--ssh-port", type=int, default=None)
    parser.add_argument("--output-dir", default=None,
                        help="Write per-rank stdout/stderr files here.")
    parser.add_argument("--verbose", action="store_true")
    parser.add_argument("--check-build", action="store_true",
                        help="Print build capabilities and exit.")
    parser.add_argument("--config-file", default=None)
    # runtime knobs (reference flag set)
    parser.add_argument("--fusion-threshold-mb", type=int, default=None)
    parser.add_argument("--cycle-time-ms", type=float, default=None)
    parser.add_argument("--cache-capacity", type=int, default=None)
    parser.add_argument("--disable-cache", action="store_true", default=None,
                        help="turn the response cache off entirely "
                             "(reference --disable-cache; same as "
                             "--cache-capacity 0)")
    parser.add_argument("--start-timeout", type=int, default=None,
                        help="seconds to wait for all ranks to register "
                             "with the rendezvous before aborting "
                             "(reference --start-timeout / "
                             "HOROVOD_START_TIMEOUT)")
    parser.add_argument("--hierarchical-allreduce", action="store_true",
                        default=None)
    parser.add_argument("--hierarchical-allgather", action="store_true",
                        default=None)
    parser.add_argument("--autotune", action="store_true", default=None)
    parser.add_argument("--autotune-log-file", default=None)
    parser.add_argument("--autotune-warmup-samples", type=int, default=None)
    parser.add_argument("--autotune-steps-per-sample", type=int, default=None)
    parser.add_argument("--autotune-bayes-opt-max-samples", type=int,
                        default=None)
    parser.add_argument("--autotune-gaussian-process-noise", type=float,
                        default=None)
    parser.add_argument("--timeline-filename", default=None)
    parser.add_argument("--timeline-mark-cycles", action="store_true",
                        default=None)
    parser.add_argument("--stall-check-disable", action="store_true",
                        default=None)
    parser.add_argument("--stall-check-time-seconds", type=float, default=None)
    parser.add_argument("--stall-shutdown-time-seconds", type=float,
                        default=None)
    parser.add_argument("--log-level", default=None,
                        choices=["trace", "debug", "info", "warning", "error"])
    # elastic mode (later-reference horovodrun elastic flags)
    parser.add_argument("--min-np", type=int, default=None,
                        help="Elastic: minimum processes to keep running "
                             "(job fails below this).")
    parser.add_argument("--max-np", type=int, default=None,
                        help="Elastic: cap on processes even when discovery "
                             "offers more slots.")
    parser.add_argument("--host-discovery-script", default=None,
                        help="Elastic: executable printing one "
                             '"host:slots" line per available host; polled '
                             "for membership changes.")
    parser.add_argument("--elastic-discovery-interval", type=float,
                        default=1.0,
                        help="Elastic: seconds between discovery polls.")
    parser.add_argument("--blacklist-threshold", type=int, default=3,
                        help="Elastic: worker failures before a host is "
                             "blacklisted.")
    parser.add_argument("--blacklist-cooldown", type=float, default=None,
                        help="Elastic: seconds a blacklisted host stays "
                             "quarantined before being re-admitted "
                             "(doubles per relapse; 0 = forever; default "
                             "HOROVOD_BLACKLIST_COOLDOWN_S or 300).")
    parser.add_argument("--elastic-timeout", type=float, default=600.0,
                        help="Elastic: seconds a worker waits for a usable "
                             "world generation before giving up.")
    parser.add_argument("--spares", type=int, default=None,
                        help="Elastic: hot-spare workers to keep spawned "
                             "beyond the world — attached to the KV plane "
                             "and heartbeating but excluded from the mesh; "
                             "a quarantine or death promotes one in the "
                             "same generation bump instead of a respawn "
                             "(default HOROVOD_SPARES or 0).")
    parser.add_argument("--resume", action="store_true",
                        help="Elastic: resume a crashed driver from its "
                             "journal (requires the original --output-dir "
                             "or HOROVOD_DRIVER_JOURNAL): replay the "
                             "recorded generation/blacklist/rendezvous "
                             "state, reclaim the advertised port, and "
                             "reattach the surviving workers instead of "
                             "respawning them.")
    parser.add_argument("--auto-resume", action="store_true",
                        help="Elastic: supervise the driver in a child "
                             "process and re-launch it with --resume when "
                             "it dies abnormally (crash/kill), up to "
                             "HOROVOD_DRIVER_MAX_RESTARTS (default 3) "
                             "times. Requires --output-dir for the "
                             "journal.")
    parser.add_argument("--network-interfaces", default=None,
                        help="Comma-separated NICs to use for the control "
                             "plane; skips the automatic ring probe.")
    parser.add_argument("--mesh-axes", default=None,
                        help='Compiled-mode mesh spec, e.g. "data:4,model:2".')
    parser.add_argument("--serve", action="store_true",
                        help="Inference-serving mode (docs/serving.md): "
                             "sets HOROVOD_SERVE=1 for every rank; with "
                             "no command, runs the built-in HTTP serving "
                             "entry point (python -m horovod_tpu.serve).")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="Training command to run on every rank.")
    args = parser.parse_args(argv)

    if args.config_file:
        # CLI flags explicitly provided take precedence over YAML
        # (reference override-tracking): anything non-None was set by CLI.
        overridden = {
            k for k, v in vars(args).items()
            if v is not None and k in config_parser.ARG_TO_ENV
        }
        config_parser.parse_config_file(args.config_file, args, overridden)
    return args


def check_build() -> str:
    from .. import __version__

    lines = [
        f"horovod_tpu v{__version__}:",
        "",
        "Available Frameworks:",
        "    [X] JAX",
        "    [{}] TensorFlow".format("X" if _importable("tensorflow") else " "),
        "    [{}] PyTorch".format("X" if _importable("torch") else " "),
        "    [{}] MXNet".format("X" if _importable("mxnet") else " "),
        "",
        "Available Controllers:",
        "    [X] XLA/TCP (native core)",
        "    [ ] MPI",
        "    [ ] Gloo",
        "",
        "Available Tensor Operations:",
        "    [X] XLA (psum / all_gather / ppermute over ICI+DCN)",
        "    [ ] NCCL",
        "    [ ] DDL",
        "    [ ] MLSL",
        "    [ ] MPI",
        "    [ ] Gloo",
    ]
    return "\n".join(lines)


def _importable(mod: str) -> bool:
    import importlib.util

    try:
        return importlib.util.find_spec(mod) is not None
    except (ImportError, ValueError):
        return False


# Exit code for an UNHANDLED exception in the elastic driver (sysexits
# EX_SOFTWARE). Without it the most common software-crash mode — a
# Python traceback — would exit 1, indistinguishable from the driver's
# deliberate "job failed" verdict, and --auto-resume would refuse to
# resume exactly the crash the journal exists to recover from.
DRIVER_CRASH_RC = 70


def _supervise_driver(argv: List[str],
                      call=None) -> int:
    """``--auto-resume``: run the elastic driver as a child process and
    re-launch it with ``--resume`` whenever it dies abnormally — the
    minimal supervisor that turns the control-plane journal into
    unattended crash recovery. "Abnormal" is any exit the driver does
    not use for deliberate outcomes (0 success, 1 job failure, 2 usage,
    3 config, 4 unreachable hosts); signals, unhandled driver
    exceptions (``DRIVER_CRASH_RC``), and injected/driver-crash codes
    resume. ``HOROVOD_DRIVER_MAX_RESTARTS`` (default 3) bounds a crash
    loop."""
    import subprocess

    call = call or (lambda a: subprocess.call(
        [sys.executable, "-m", "horovod_tpu.run", *a]
    ))
    child_args = [a for a in argv if a != "--auto-resume"]
    try:
        max_restarts = int(
            os.environ.get("HOROVOD_DRIVER_MAX_RESTARTS", "") or 3
        )
    except ValueError:
        max_restarts = 3
    deliberate = (0, 1, 2, 3, 4)
    restarts = 0
    while True:
        rc = call(child_args)
        if rc in deliberate:
            return rc
        if restarts >= max_restarts:
            print(
                f"[hvdrun supervisor] driver died abnormally (exit {rc}) "
                f"and the restart budget ({max_restarts}) is spent",
                file=sys.stderr,
            )
            return rc
        restarts += 1
        print(
            f"[hvdrun supervisor] driver died abnormally (exit {rc}); "
            f"resuming from the journal (restart {restarts}/"
            f"{max_restarts})",
            file=sys.stderr,
        )
        if "--resume" not in child_args:
            child_args = child_args + ["--resume"]


def run_commandline(argv: Optional[List[str]] = None) -> int:
    args = parse_args(argv)
    if args.version:
        from .. import __version__

        print(__version__)
        return 0
    if args.check_build:
        print(check_build())
        return 0
    command = list(args.command)
    if command and command[0] == "--":
        command = command[1:]
    if not command and args.serve:
        # Serving mode's default workload: the built-in HTTP entry point.
        command = [sys.executable, "-m", "horovod_tpu.serve"]
    if not command:
        print("hvdrun: no training command given", file=sys.stderr)
        return 2

    # Runtime-knob env assembly shared by the elastic and fixed paths
    # (--disable-cache, YAML/CLI knobs, explicit NIC pin; the fixed path
    # additionally ring-probes NICs below when none is pinned).
    if args.disable_cache:
        args.cache_capacity = 0
    env = dict(os.environ)
    config_parser.set_env_from_args(env, args)
    if args.network_interfaces:
        env["HOROVOD_IFACE"] = args.network_interfaces
    if args.serve:
        from ..common import env as _env_names

        env[_env_names.HOROVOD_SERVE] = "1"

    # Elastic mode: any elastic flag routes supervision to ElasticDriver
    # (generation-based re-rendezvous) instead of the fixed fan-out.
    if (args.host_discovery_script or args.min_np or args.max_np
            or args.resume):
        if args.auto_resume:
            return _supervise_driver(argv if argv is not None
                                     else sys.argv[1:])
        if args.hostfile:
            hosts = launcher.parse_hostfile(args.hostfile)
        elif args.hosts:
            hosts = launcher.parse_hosts(args.hosts)
        elif args.host_discovery_script:
            hosts = None  # discovery script is the sole source
        elif args.num_proc:
            hosts = [("localhost", args.num_proc)]
        else:
            print("hvdrun: elastic mode needs -np, -H/--hostfile, or "
                  "--host-discovery-script", file=sys.stderr)
            return 2
        # Pre-flight + NIC discovery on the initial host set (ADVICE r4:
        # the elastic branch used to return before both, so multi-host
        # elastic jobs got no HOROVOD_IFACE and dead hosts surfaced only
        # as repeated spawn failures). Unreachable hosts are dropped —
        # not fatal — because elastic semantics tolerate them; the
        # discovery script can bring them (or others) back later.
        probed_hostset = None
        if hosts:
            reachable = _preflight_and_nic_probe(
                [h for h, _ in hosts], hosts[0][0], env, args, fatal=False
            )
            hosts = [(h, c) for h, c in hosts if h in reachable]
            probed_hostset = reachable
            if not hosts:
                print("hvdrun: no initial host is reachable", file=sys.stderr)
                return 4
            # The probed controller address maps the INITIAL hosts[0];
            # the driver re-elects a controller host every generation, so
            # an inherited pin would be stale (and would leak into nested
            # launches, which launch_job pops it to prevent). The IFACE
            # intersection stays — it is host-set-wide, and the driver
            # re-probes when discovery changes the set.
            env.pop("HOROVOD_PROBED_CONTROLLER_ADDR", None)

        from .elastic_driver import ElasticDriver

        driver = ElasticDriver(
            command,
            min_np=args.min_np or args.num_proc or 1,
            max_np=args.max_np or args.num_proc or (1 << 30),
            hosts=hosts,
            discovery_script=args.host_discovery_script,
            discovery_interval=args.elastic_discovery_interval,
            env=env,
            output_dir=args.output_dir,
            verbose=args.verbose,
            host_failure_threshold=args.blacklist_threshold,
            ssh_port=args.ssh_port,
            elastic_timeout=args.elastic_timeout,
            nic_pinned=bool(args.network_interfaces),
            probed_hostset=probed_hostset,
            blacklist_cooldown=args.blacklist_cooldown,
            resume=args.resume,
            spares=args.spares,
        )
        try:
            return driver.run()
        except SystemExit:
            raise
        except Exception:
            import traceback

            traceback.print_exc()
            return DRIVER_CRASH_RC

    if args.tpu_pod:
        slots = launcher.tpu_pod_allocation()
        if slots is None:
            print("hvdrun: --tpu-pod set but TPU_WORKER_HOSTNAMES is empty",
                  file=sys.stderr)
            return 2
    else:
        if args.num_proc is None:
            print("hvdrun: -np is required", file=sys.stderr)
            return 2
        if args.hostfile:
            hosts = launcher.parse_hostfile(args.hostfile)
        elif args.hosts:
            hosts = launcher.parse_hosts(args.hosts)
        else:
            hosts = [("localhost", args.num_proc)]
        slots = launcher.allocate(hosts, args.num_proc)

    # SSH pre-flight (reference run/run.py:62-115) + ring NIC probe
    # (reference run/run.py:198-268), shared with the elastic branch.
    # TPU pods know their topology from slice metadata and have no
    # inter-worker ssh; both steps are only for the generic path.
    if not args.tpu_pod:
        try:
            _preflight_and_nic_probe(
                [s.hostname for s in slots], slots[0].hostname, env, args,
                fatal=True,
            )
        except SystemExit as e:
            return e.code

    return launcher.launch_job(
        command,
        slots,
        env=env,
        ssh_port=args.ssh_port,
        output_dir=args.output_dir,
        verbose=args.verbose,
    )


# ---------------------------------------------------------------- run() API
def run(
    fn: Callable,
    args: tuple = (),
    kwargs: Optional[dict] = None,
    np: int = 1,
    hosts: Optional[str] = None,
    env: Optional[dict] = None,
    verbose: bool = False,
) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``np`` ranks and return the list of
    per-rank results (parity with ``horovod.run.run()``,
    ``run/run.py:863-949``). The function is shipped cloudpickled (as the
    reference does — plain pickle cannot ship closures or
    interactively-defined functions) via a scratch directory and results
    are collected per rank."""
    import pickle
    import tempfile

    try:
        import cloudpickle as _pickler
    except ImportError:  # pragma: no cover - cloudpickle ships with pyspark
        import pickle as _pickler

    kwargs = kwargs or {}
    workdir = tempfile.mkdtemp(prefix="hvdrun_")
    fn_path = os.path.join(workdir, "fn.pkl")
    with open(fn_path, "wb") as f:
        _pickler.dump((fn, args, kwargs), f)

    host_list = launcher.parse_hosts(hosts) if hosts else [("localhost", np)]
    slots = launcher.allocate(host_list, np)
    run_env = dict(os.environ)
    if env:
        run_env.update(env)
    run_env["HOROVOD_RUN_FN_FILE"] = fn_path
    run_env["HOROVOD_RUN_RESULT_DIR"] = workdir
    command = [sys.executable, "-m", "horovod_tpu.run.task_runner"]
    rc = launcher.launch_job(command, slots, env=run_env, verbose=verbose)
    if rc != 0:
        raise RuntimeError(f"hvdrun job failed with exit code {rc}")
    results = []
    for slot in slots:
        with open(os.path.join(workdir, f"result.{slot.rank}.pkl"), "rb") as f:
            results.append(pickle.load(f))
    return results


def main() -> None:
    sys.exit(run_commandline())


if __name__ == "__main__":
    main()
