from .run import main

main()
