"""Elastic job driver: discovery, generations, worker supervision.

Later-reference parity (upstream ``horovod/runner/elastic/driver.py`` +
``discovery.py``, added in v0.20 — absent from the v0.18.2 reference):
``hvdrun --min-np/--max-np/--host-discovery-script`` supervises an elastic
job instead of the fixed fan-out in ``launcher.launch_job``.

Mechanics (TPU-native, see ``horovod_tpu/elastic``):

- The driver owns the HTTP KV rendezvous store. Each world *generation* —
  membership, rank assignments, and fresh controller/JAX-coordinator
  endpoints — is published under ``elastic/world``; workers poll it and
  re-rendezvous in process.
- A host-discovery script (prints ``host:slots`` lines, upstream
  ``--host-discovery-script`` contract) is polled every
  ``discovery_interval`` seconds; membership changes bump the generation.
- A worker process that dies bumps the generation too; its host accrues a
  failure count and is blacklisted at ``host_failure_threshold`` (upstream
  blacklist role), otherwise the slot is re-spawned fresh.
- The job fails when fewer than ``min_np`` slots remain; it caps at
  ``max_np`` even when discovery offers more.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import tempfile
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import signal

from . import journal as _journal_mod
from . import launcher, safe_shell_exec
from . import selfdrive as _selfdrive
from .. import metrics as _metrics
from .. import trace as _trace
from ..fault import injector as _fault
from ..fault.plan import DRIVER_KINDS
from .http_server import KVStoreServer
from .launcher import SlotInfo, _free_port, _is_local


# Worker exit status meaning "respawn me": the worker cannot re-form the
# world in-process (elastic/__init__.py REJOIN_EXIT_CODE — kept as a
# literal on both sides so this launcher never imports the jax-loading
# package). Not a failure: it does not count toward host blacklisting.
REJOIN_EXIT_CODE = 79


def _respawn_drain_grace(env: Dict[str, str], base: float = 15.0) -> float:
    """Drain grace for a respawn-mode world restart, scaled to the
    failure-DETECTION window instead of a fixed constant: a survivor only
    persists-and-exits once its collectives fail, which takes up to the
    coordination heartbeat timeout (2x: one missed beat + the agent's
    confirmation) or the stall abort/shutdown window when one is
    configured — whichever is longest — plus a persistence margin.
    A fixed 15 s grace under a 60 s stall window would SIGTERM survivors
    mid-commit-persist and turn a clean restart into data loss."""

    def _f(name: str, default: float) -> float:
        try:
            return float(env.get(name, "") or default)
        except ValueError:
            return default

    detect = 2.0 * _f("HOROVOD_ELASTIC_HEARTBEAT_S", 10.0)
    for knob in ("HOROVOD_STALL_ABORT_TIME_SECONDS",
                 "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS"):
        v = _f(knob, 0.0)
        if v > 0:
            detect = max(detect, v)
    return max(base, detect + 5.0)


def _inprocess_rejoin_supported() -> bool:
    """Mirror of ``horovod_tpu.elastic._inprocess_rejoin_supported`` (see
    its docstring for the private JAX surfaces probed). The driver
    resolves the rejoin mode once, from its own jax — workers share the
    image — and exports it, so driver orchestration and worker behavior
    always agree."""
    try:
        import jax
        from jax._src import xla_bridge as _xb
        from jax._src.lib import _jax as _jaxlib
    except Exception:  # noqa: BLE001
        return False
    if not callable(getattr(_xb, "_clear_backends", None)):
        return False
    # The driver hosts the coordination service, workers the clients —
    # both factories live on the same jaxlib module, so one probe keeps
    # the exported mode consistent for both sides.
    for factory in (
        "get_distributed_runtime_service", "get_distributed_runtime_client"
    ):
        if not callable(getattr(_jaxlib, factory, None)):
            return False
    try:
        jax.config.jax_enable_recoverability  # noqa: B018
    except Exception:  # noqa: BLE001
        return False
    return True


@dataclass
class _Worker:
    worker_id: str
    host: str
    proc: safe_shell_exec.ManagedProcess
    outfiles: Tuple
    done: bool = False
    spawned_at: float = 0.0


def _run_discovery_script(script: str) -> List[Tuple[str, int]]:
    """Run the host-discovery script; parse ``host`` / ``host:slots``
    lines (the upstream contract)."""
    import subprocess

    out = subprocess.run(
        [script], capture_output=True, text=True, timeout=60, check=True
    ).stdout
    hosts: List[Tuple[str, int]] = []
    for line in out.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if ":" in line:
            name, slots = line.rsplit(":", 1)
            hosts.append((name, int(slots)))
        else:
            hosts.append((line, 1))
    return hosts


class ElasticDriver:
    def __init__(
        self,
        command: List[str],
        min_np: int,
        max_np: int,
        hosts: Optional[List[Tuple[str, int]]] = None,
        discovery_script: Optional[str] = None,
        discovery_interval: float = 1.0,
        env: Optional[Dict[str, str]] = None,
        output_dir: Optional[str] = None,
        verbose: bool = False,
        host_failure_threshold: int = 3,
        ssh_port: Optional[int] = None,
        elastic_timeout: float = 600.0,
        nic_pinned: bool = False,
        probed_hostset: Optional[List[str]] = None,
        blacklist_cooldown: Optional[float] = None,
        resume: bool = False,
        spares: Optional[int] = None,
    ) -> None:
        if not hosts and not discovery_script:
            raise ValueError(
                "elastic mode needs -H/--hostfile or --host-discovery-script"
            )
        self._command = command
        self._min_np = min_np
        self._max_np = max_np
        self._static_hosts = hosts
        self._script = discovery_script
        self._interval = discovery_interval
        self._env = dict(env if env is not None else os.environ)
        self._output_dir = output_dir
        self._verbose = verbose
        self._failure_threshold = host_failure_threshold
        self._ssh_port = ssh_port
        self._elastic_timeout = elastic_timeout

        if output_dir:
            os.makedirs(output_dir, exist_ok=True)
        # Recovery mode for the whole job (VERDICT r4: version-harden the
        # elastic path): explicit HOROVOD_ELASTIC_REJOIN_MODE wins, else
        # probe whether the private JAX surfaces the in-process path
        # needs exist. Exported to every worker so both sides agree.
        forced = self._env.get("HOROVOD_ELASTIC_REJOIN_MODE", "").lower()
        if forced == "inprocess" and not _inprocess_rejoin_supported():
            # Honoring the pin would crash the first rendezvous (the
            # driver-hosted coordination service rides the same private
            # jaxlib surfaces the workers' in-process rejoin does);
            # degrade loudly instead, same policy as
            # elastic.rejoin_mode().
            self._log(
                "HOROVOD_ELASTIC_REJOIN_MODE=inprocess but this jax "
                "lacks the required private surfaces; falling back to "
                "'respawn'"
            )
            self._rejoin_mode = "respawn"
        elif forced in ("inprocess", "respawn"):
            self._rejoin_mode = forced
        else:
            self._rejoin_mode = (
                "inprocess" if _inprocess_rejoin_supported() else "respawn"
            )
        self._env["HOROVOD_ELASTIC_REJOIN_MODE"] = self._rejoin_mode
        # --- durable control-plane journal (docs/fault_tolerance.md
        # "Control-plane availability"): generation, membership,
        # blacklist, and the rendezvous-critical KV keys are
        # write-ahead-logged so a crashed driver can be resumed
        # (--resume) without losing the fleet. Opening the journal bumps
        # the driver EPOCH — the fencing token workers use to reject a
        # stale driver that lost a supervisor race.
        self._resume = bool(resume)
        self._resume_finished = False
        self._resume_world: Optional[Dict] = None
        jpath = _journal_mod.default_path(self._output_dir, self._env)
        if self._resume and jpath is None:
            raise ValueError(
                "--resume needs --output-dir (or HOROVOD_DRIVER_JOURNAL) "
                "to locate the driver journal"
            )
        self._journal = (
            _journal_mod.DriverJournal.open(jpath) if jpath else None
        )
        self._epoch = self._journal.epoch if self._journal else 1
        prior = self._journal.state if self._journal else {}
        if self._resume:
            if not prior.get("gen"):
                raise ValueError(
                    f"--resume: no resumable driver journal at {jpath}"
                )
            if prior.get("finished"):
                # The job completed before the crash-restart raced in;
                # nothing to resume — run() exits 0 without touching the
                # (long gone) fleet.
                self._resume_finished = True
            self._gen = int(prior.get("gen", 0))
            self._resume_world = prior.get("world")
            sd = prior.get("state_dir")
            if sd:
                # The predecessor's snapshot dir, NOT a fresh pid-keyed
                # one: a fallback respawn must find the fleet's last
                # persisted commits.
                self._env["HOROVOD_ELASTIC_STATE_DIR"] = sd
            if _metrics.ACTIVE:
                _metrics.TAP.inc("hvd_driver_journal_replays_total")
        # Per-host snapshot dir for respawn-mode resume (workers write
        # locally; a slot's respawn lands on the same host). The driver
        # pid keys the path so every generation of the job shares it.
        # Owned only when WE invented the path (pid-keyed tmp dir): a
        # user-provided HOROVOD_ELASTIC_STATE_DIR must survive driver
        # exit, ours must not outlive the pid that keys it.
        self._state_dir_owned = "HOROVOD_ELASTIC_STATE_DIR" not in self._env
        self._env.setdefault(
            "HOROVOD_ELASTIC_STATE_DIR",
            os.path.join(
                tempfile.gettempdir(), f"hvd_elastic_state_{os.getpid()}"
            ),
        )
        if self._resume:
            # Ownership (and the cleanup duty that comes with it)
            # transfers from the crashed predecessor.
            self._state_dir_owned = bool(prior.get("state_dir_owned"))
        # The KV rendezvous server doubles as the metrics endpoint
        # (GET /metrics, docs/metrics.md); HOROVOD_METRICS_PORT pins its
        # port so scrapers have a stable target. A resumed driver MUST
        # reclaim the journal-recorded port — every surviving worker
        # dialed it at spawn — so the bind waits out lingering TIME_WAIT
        # state instead of failing (SO_REUSEADDR + bounded retry).
        try:
            kv_port = int(self._env.get("HOROVOD_METRICS_PORT", "") or 0)
        except ValueError:
            kv_port = 0
        if self._resume and prior.get("kv_port"):
            kv_port = int(prior["kv_port"])
        self._kv = KVStoreServer(
            port=kv_port,
            reclaim_wait_s=10.0 if (self._resume and kv_port) else 0.0,
        )
        # --network-interfaces pin: never ring-probe, the user chose.
        self._nic_pinned = nic_pinned
        # Host set most recently ring-probed for NICs — seeded with the
        # set hvdrun probed at launch so the first reconcile doesn't
        # repeat it; None = never probed.
        self._probed_hostset = (
            sorted(probed_hostset) if probed_hostset else None
        )
        # Per-generation jax coordination services as mutable
        # [gen, svc, superseded_monotonic|None, heartbeat_s]; old
        # generations are retired in _retire_services once their drain
        # grace window (two newer generations AND 2x the heartbeat
        # timeout SINCE BEING SUPERSEDED) has passed.
        self._services: List[list] = []
        self._last_hosts: List[Tuple[str, int]] = list(hosts or [])
        self._stop_discovery = threading.Event()
        if not self._resume:
            self._gen = 0
        self._workers: Dict[str, _Worker] = {}
        # Control-plane HA bookkeeping: the last published world doc (the
        # journal's authoritative membership record), the driver-doc beat
        # counter, and — after a resume — the adoption state machine for
        # workers that outlived the previous driver (no process handles;
        # supervised via KV attach/done signals and local pid probes).
        self._last_world: Optional[Dict] = None
        self._beat = 0
        self._adopting = bool(self._resume_world) and not self._resume_finished
        self._attached: Dict[str, int] = {}
        self._adopt_deadline: Optional[float] = None
        self._adopt_drain_pids: Optional[set] = None
        self._adopt_drain_deadline = 0.0
        self._driver_faults_fired: set = set()
        self._last_journaled_kv: Optional[Dict[str, str]] = None
        self._started_at = time.monotonic()
        # Workers dropped from the world, draining toward a voluntary
        # exit (they see the new generation and leave cleanly); value is
        # the terminate-anyway deadline.
        self._removing: List[Tuple[_Worker, float]] = []
        self._removal_grace = 15.0
        # Respawn-mode restarts wait for survivors to DETECT the failure
        # (heartbeat / stall windows) before persisting and exiting, so
        # their drain grace scales with those windows (see
        # _respawn_drain_grace) rather than reusing the fixed scale-down
        # grace above.
        self._restart_grace = _respawn_drain_grace(
            self._env, self._removal_grace
        )
        self._current_ids: List[str] = []
        self._failures: Dict[str, int] = {}
        self._last_failure: Dict[str, float] = {}
        # Quarantine ledger (upstream's blacklist never forgives; here a
        # host that recovers is re-admitted): host -> readmit deadline
        # (None = permanent, when cooldown == 0). Each re-blacklisting of
        # the same host doubles its quarantine. ``_blacklist_reason``
        # distinguishes WHY a host is out ("dead" = worker failures,
        # "slow" = the StragglerPolicy's slowness quarantine), and the
        # two strike ledgers decay independently: a host that crashes is
        # not presumed slow, and vice versa.
        self._blacklist: Dict[str, Optional[float]] = {}
        self._blacklist_reason: Dict[str, str] = {}
        self._quarantine_strikes: Dict[str, int] = {}
        self._slow_strikes: Dict[str, int] = {}
        if blacklist_cooldown is None:
            try:
                blacklist_cooldown = float(
                    self._env.get("HOROVOD_BLACKLIST_COOLDOWN_S", "") or 300.0
                )
            except ValueError:
                blacklist_cooldown = 300.0
        self._blacklist_cooldown = blacklist_cooldown
        try:
            self._quarantine_cooldown = float(
                self._env.get(_selfdrive.QUARANTINE_COOLDOWN_ENV, "")
                or blacklist_cooldown
            )
        except ValueError:
            self._quarantine_cooldown = blacklist_cooldown
        # --- self-driving fleet (docs/fault_tolerance.md "Self-driving
        # fleet"): the slowness-quarantine policy over straggler charges,
        # the live re-plan coordinator, and the hot-spare pool. All three
        # are opt-in (HOROVOD_QUARANTINE_STRIKES / HOROVOD_REPLAN_*
        # unset and --spares 0 keep the driver exactly as before).
        self._policy = _selfdrive.StragglerPolicy.from_env(self._env)
        self._replan_divergence = _selfdrive._env_float(
            self._env, _selfdrive.REPLAN_DIVERGENCE_ENV, 0.0
        )
        self._replan_skew_s = _selfdrive._env_float(
            self._env, _selfdrive.REPLAN_SKEW_ENV, 0.0
        )
        self._replan_check_s = max(_selfdrive._env_float(
            self._env, _selfdrive.REPLAN_CHECK_ENV, 5.0
        ), 0.5)
        self._last_replan_check = 0.0
        self._replan_doc: Optional[Dict] = None
        self._replan_calib_hash: Optional[str] = None
        # Recent per-step cross-rank skews for the trend trigger; one
        # skew-trend re-plan per generation (the deque clears on every
        # publish — fresh world, fresh evidence).
        from collections import deque as _deque

        self._skew_trend: "_deque[float]" = _deque(
            maxlen=max(self._policy.window, 8)
        )
        self._skew_replanned = False
        if spares is None:
            spares = _selfdrive._env_int(
                self._env, _selfdrive.SPARES_ENV, 0
            )
        self._spares_want = max(int(spares), 0)
        self._spares: Dict[str, _Worker] = {}
        self._spare_slots: Dict[str, SlotInfo] = {}
        if self._resume:
            # Quarantines journaled as wall-clock deadlines + remaining
            # budget come back onto THIS process's monotonic clock,
            # skew-clamped (see journal.blacklist_from_journal): healthy
            # hosts are not re-quarantined, active quarantines are not
            # forgotten.
            self._blacklist = _journal_mod.blacklist_from_journal(
                prior.get("blacklist") or {}
            )
            self._blacklist_reason = {
                h: str(r)
                for h, r in (prior.get("blacklist_reasons") or {}).items()
                if h in self._blacklist
            }
            self._quarantine_strikes = {
                h: int(n) for h, n in (prior.get("strikes") or {}).items()
            }
            self._slow_strikes = {
                h: int(n)
                for h, n in (prior.get("slow_strikes") or {}).items()
            }
            self._replan_doc = prior.get("replan") or None
            if self._replan_doc:
                self._replan_calib_hash = self._replan_doc.get("calib")
            self._failures = {
                h: int(n) for h, n in (prior.get("failures") or {}).items()
            }
            self._seed_kv(prior)
            if self._replan_doc:
                # The journaled notice survives the resume, but workers
                # reject any epoch below their fencing baseline — which
                # just rose to THIS incarnation's. Refresh the stamp
                # (same id: already-adopted workers keep their config,
                # not-yet-adopted ones accept now).
                self._replan_doc = dict(self._replan_doc)
                self._replan_doc["epoch"] = self._epoch
                self._kv.put(
                    "elastic", "replan",
                    json.dumps(self._replan_doc, sort_keys=True).encode(),
                )
        self._finishing = False
        # Respawn mode: a world restart is queued behind the drain pool.
        self._restart_pending = False
        # One-shot ledger for fault-plan preemption notices.
        self._preempts_fired: set = set()
        # Deterministic fault injection (docs/fault_tolerance.md): the
        # injector armed itself from HOROVOD_FAULT_PLAN at import. The
        # driver owns the canonical artifacts: the resolved schedule
        # (byte-for-byte reproducible for a seed) and its own event log.
        # Neither path is exported to workers — self._env was snapshotted
        # above, so worker processes log to their own files only if the
        # user pointed them somewhere.
        plan = _fault.active_plan()
        if plan is not None and self._output_dir:
            sched_path = os.path.join(self._output_dir, "fault_schedule.json")
            try:
                with open(sched_path, "w") as f:
                    f.write(plan.canonical_schedule())
            except OSError:
                pass
            os.environ.setdefault(
                _fault.FAULT_EVENT_LOG_ENV,
                os.path.join(self._output_dir, "fault_events.driver.jsonl"),
            )
            self._log(f"fault plan armed (seed {plan.seed}): {sched_path}")
        # Fleet tracing (docs/timeline.md "Fleet tracing"): the driver
        # collects worker-pushed span windows off the KV plane, persists
        # them (+ its own elastic/HA events) next to the worker logs for
        # tools/trace_merge.py, and attributes per-step stragglers into
        # hvd_step_skew_seconds / hvd_straggler_total{rank}.
        self._trace_dir: Optional[str] = None
        self._skew = None
        if _trace.ACTIVE and self._output_dir:
            self._trace_dir = (
                self._env.get(_trace.TRACE_DIR_ENV, "")
                or os.path.join(self._output_dir, "trace")
            )
            os.makedirs(self._trace_dir, exist_ok=True)
            # Workers inherit the dir so flight-recorder dumps land
            # where the postmortem collection can find them (same-host
            # jobs; remote hosts keep their dumps locally).
            self._env.setdefault(_trace.TRACE_DIR_ENV, self._trace_dir)
            os.environ.setdefault(_trace.TRACE_DIR_ENV, self._trace_dir)
            from ..trace.pusher import StepSkewTracker

            self._skew = StepSkewTracker()
            self._trace_event(
                "hvd_driver_start",
                resume=bool(self._resume), epoch=self._epoch,
            )
            self._log(f"fleet trace: collecting into {self._trace_dir}")
        if _metrics.ACTIVE:
            _metrics.TAP.set("hvd_driver_epoch", float(self._epoch))
        if self._journal is not None:
            self._journal_sync(force=True)
            self._log(
                f"driver journal: {self._journal.path} "
                f"(epoch {self._epoch})"
            )
        self._log(f"rejoin mode: {self._rejoin_mode}")

    # ------------------------------------------------------------ pieces
    def _trace_event(self, name: str, **args) -> None:
        """One driver-lane fleet-trace event (generation publishes,
        blacklists, failures, straggler attributions) — rendered on the
        driver's own lane by tools/trace_merge.py. No-op when tracing is
        disabled."""
        if _trace.ACTIVE:
            _trace.TAP.event(name, cat="driver", **args)

    def _trace_collect(self, final: bool = False) -> None:
        """Collect worker-pushed trace windows off the KV plane: persist
        each rank's freshest window (and the driver's own lane) into the
        trace directory, and feed per-step end times into the straggler
        attribution. Runs on the supervision-loop beat; ``final`` also
        bundles surviving flight-recorder dumps."""
        if self._trace_dir is None:
            return
        from ..trace import pusher as _tpush
        from ..utils.checkpoint import _atomic_write

        windows: Dict[int, dict] = {}
        for key, payload in self._kv.snapshot(_trace.KV_SCOPE).items():
            if not key.startswith("rank."):
                continue
            suffix = key.split(".", 1)[1]
            if not suffix.isdigit():
                continue
            doc = _tpush.decode_window(payload)
            if doc is None:
                continue
            rank = int(suffix)
            windows[rank] = doc
            data = json.dumps(doc, sort_keys=True).encode()
            try:
                _atomic_write(
                    os.path.join(self._trace_dir, f"rank.{rank}.json"),
                    lambda f, d=data: f.write(d),
                )
            except OSError:
                pass
        if windows and _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_trace_collections_total")
        if self._skew is not None:
            for idx, skew, worst in self._skew.update(windows):
                charged = skew >= self._skew.threshold_s
                if _metrics.ACTIVE:
                    _metrics.TAP.observe("hvd_step_skew_seconds", skew)
                if charged:
                    if _metrics.ACTIVE:
                        _metrics.TAP.inc(
                            "hvd_straggler_total", rank=str(worst)
                        )
                    self._trace_event(
                        "hvd_straggler", step=idx, rank=worst,
                        skew_s=round(skew, 6),
                    )
                # Feed the self-driving quarantine policy: every emitted
                # step (charged or not) advances its sliding window, so
                # a rank that recovers decays out. The same emission
                # feeds the re-plan skew-trend window.
                if self._policy.enabled:
                    self._policy.observe(idx, skew, worst, charged)
                self._skew_trend.append(skew)
        try:
            data = json.dumps(
                _trace.TAP.window(), sort_keys=True
            ).encode()
            _atomic_write(
                os.path.join(self._trace_dir, "driver.json"),
                lambda f: f.write(data),
            )
        except OSError:
            pass
        if final:
            self._collect_postmortem()

    def _collect_postmortem(self) -> None:
        """Bundle surviving per-rank flight-recorder dumps into
        ``postmortem.json`` — the artifact ``tools/trace_merge.py
        --postmortem`` renders as "the last N seconds before death, all
        ranks, aligned"."""
        import re as _re

        try:
            names = sorted(os.listdir(self._trace_dir))
        except OSError:
            return
        dumps = []
        for fn in names:
            if not _re.fullmatch(r"flight\.rank\d+\.json", fn):
                continue
            try:
                with open(os.path.join(self._trace_dir, fn)) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue
            if isinstance(doc, dict):
                dumps.append(doc)
        if not dumps:
            return
        from ..utils.checkpoint import _atomic_write

        bundle = json.dumps(
            {"schema": 1, "collected_at": time.time(), "dumps": dumps},
            sort_keys=True,
        ).encode()
        try:
            _atomic_write(
                os.path.join(self._trace_dir, "postmortem.json"),
                lambda f: f.write(bundle),
            )
        except OSError:
            return
        self._log(
            f"fleet trace: collected {len(dumps)} flight-recorder "
            "dump(s) into postmortem.json"
        )

    def _log(self, msg: str) -> None:
        line = f"[hvdrun elastic] {msg}"
        print(line, file=sys.stderr, flush=True)
        # Postmortem artifact: with --output-dir, the generation history
        # (publishes, failures, blacklists, drains) persists next to the
        # per-worker logs instead of living only on the driver's stderr.
        # (Dir is created once in __init__; logging must never kill the
        # driver, hence the silent OSError.)
        if self._output_dir:
            try:
                with open(os.path.join(self._output_dir, "driver.log"),
                          "a") as f:
                    f.write(time.strftime("%H:%M:%S ") + line + "\n")
            except OSError:
                pass

    # ------------------------------------------------ control-plane HA
    def _journal_sync(self, force: bool = False) -> None:
        """Write-ahead journal the full control-plane state (atomic
        tmp+fsync+replace). Called with ``force`` at every driver-owned
        transition (publish, blacklist change, resume) and periodically
        from the supervision loop to pick up worker-written KV drift
        (``joined.*``/``rejoin.*`` signals); the periodic path only
        writes when the rendezvous scope actually changed."""
        # getattr: unit tests build bare drivers (__new__) around the
        # blacklist methods without the journal plumbing.
        if getattr(self, "_journal", None) is None:
            return
        kv_snap = {
            k: v.decode("utf-8", "replace")
            for k, v in self._kv.snapshot("elastic").items()
            # The driver doc's beat changes every second and is
            # re-derived on resume anyway — journaling it would turn the
            # change-detection below into an every-second rewrite.
            if k != "driver"
        }
        if not force and kv_snap == self._last_journaled_kv:
            return
        # DriverJournal.open carries prior state — including a completed
        # predecessor's finished=True — forward; every live sync must
        # overwrite it or a fresh job reusing the output dir would look
        # "finished" to --resume after a crash (and --auto-resume would
        # report success over an abandoned fleet). The one exception is
        # the finished-journal resume short-circuit, which must stay
        # finished so repeat resumes keep exiting 0 without touching the
        # (long gone) fleet. getattr: bare __new__ test drivers again.
        self._journal.record(
            finished=bool(getattr(self, "_resume_finished", False)),
            epoch=self._epoch,
            gen=self._gen,
            kv_port=self._kv.port,
            rejoin_mode=self._rejoin_mode,
            state_dir=self._env["HOROVOD_ELASTIC_STATE_DIR"],
            state_dir_owned=self._state_dir_owned,
            world=self._last_world,
            current_ids=list(self._current_ids),
            kv=kv_snap,
            blacklist=_journal_mod.blacklist_to_journal(self._blacklist),
            blacklist_reasons=dict(self._blacklist_reason),
            strikes=dict(self._quarantine_strikes),
            slow_strikes=dict(self._slow_strikes),
            replan=self._replan_doc,
            spare_ids=sorted(self._spares),
            failures=dict(self._failures),
        )
        self._last_journaled_kv = kv_snap
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_driver_journal_writes_total")

    def _seed_kv(self, prior: Dict) -> None:
        """Reload the journal's rendezvous-critical keys into the fresh
        KV store. ``attach.*`` signals are per-epoch (workers must
        re-register under the NEW epoch) and the ``world``/``driver``
        docs are re-stamped with it, so those are excluded/rewritten;
        everything else (``joined.*`` sync-root eligibility, pending
        ``rejoin.*``/``done.*`` signals) replays verbatim."""
        for k, v in (prior.get("kv") or {}).items():
            if k in ("world", "driver") or k.startswith("attach."):
                continue
            self._kv.put("elastic", k, v.encode())

    def _publish_driver_doc(self) -> None:
        """Advertise this driver's identity on the KV plane: the epoch
        (fencing token — workers reject anything lower than they have
        seen) plus the current generation and a liveness beat."""
        self._beat += 1
        self._kv.put(
            "elastic", "driver",
            json.dumps({
                "epoch": self._epoch,
                "gen": self._gen,
                "beat": self._beat,
            }).encode(),
        )

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except OSError:
            pass  # e.g. EPERM: exists but not ours
        return True

    def _enter_adoption(self) -> None:
        """Resume path: re-enter the elastic loop at the journaled
        generation and ADOPT the surviving fleet instead of respawning
        it. In respawn mode the coordination plane (rank 0's controller
        + jax coordinator) outlived the old driver, so the recorded
        world is republished AS IS — same generation, new epoch — and
        workers parked at their commit boundaries reattach in place. In
        in-process mode the old driver hosted the coordination service,
        so its death already failed the workers' collectives: publish a
        FRESH generation (new endpoints) and let the survivors rejoin
        through the existing rollback path — reattach degrades to
        rejoin, never to a respawn of live processes."""
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_driver_restarts_total")
            _metrics.TAP.set("hvd_driver_epoch", float(self._epoch))
        if self._rejoin_mode == "respawn":
            world = dict(self._resume_world)
            world["epoch"] = self._epoch
            self._last_world = world
            self._current_ids = list(world.get("assignments", {}))
            self._journal_sync(force=True)  # WAL before workers can see it
            self._kv.put("elastic", "world", json.dumps(world).encode())
            if _metrics.ACTIVE:
                _metrics.TAP.set(
                    "hvd_elastic_generation", float(self._gen)
                )
                _metrics.TAP.set(
                    "hvd_elastic_world_size",
                    float(len(self._current_ids)),
                )
        else:
            slots = self._slots_from_world(self._resume_world)
            self._publish(slots)  # gen+1, fresh coordination service
            self._current_ids = [self._worker_id(s) for s in slots]
        self._publish_driver_doc()
        self._adopt_deadline = time.monotonic() + max(
            30.0, self._restart_grace
        )
        if _fault.ACTIVE:
            _fault.record_event(
                "driver", 1, "resume",
                f"gen={self._gen} epoch={self._epoch}",
            )
        self._log(
            f"resumed at generation {self._gen} (epoch {self._epoch}); "
            f"awaiting reattach of {sorted(self._current_ids)}"
        )

    @staticmethod
    def _slots_from_world(world: Dict) -> List[SlotInfo]:
        """Rebuild the slot allocation from a journaled world doc (the
        in-process resume path needs real slots to publish fresh
        endpoints for)."""
        slots = []
        for wid, a in (world.get("assignments") or {}).items():
            host = wid.rsplit(":", 1)[0]
            slots.append(SlotInfo(
                hostname=host,
                rank=int(a["rank"]),
                size=int(world.get("size", len(world["assignments"]))),
                local_rank=int(a["local_rank"]),
                local_size=int(a["local_size"]),
                cross_rank=int(a["cross_rank"]),
                cross_size=int(a["cross_size"]),
            ))
        slots.sort(key=lambda s: s.rank)
        return slots

    def _poll_adopted(self) -> Optional[int]:
        """Supervise adopted workers (no process handles — the previous
        driver owned those): reattach via ``attach.<wid>`` KV signals
        stamped with this epoch, completion via ``done.<wid>``, failure
        via ``rejoin.<wid>`` signals, local pid probes, and the
        reattach grace deadline. Returns an exit code when the job is
        finished, else None."""
        snap = self._kv.snapshot("elastic")
        gen_s = str(self._gen)
        for wid in self._current_ids:
            if wid in self._attached:
                continue
            raw = snap.get(f"attach.{wid}")
            if not raw:
                continue
            try:
                a_gen, a_epoch, a_pid = raw.decode().split(":")
            except ValueError:
                continue
            if a_gen == gen_s and int(a_epoch) == self._epoch:
                self._attached[wid] = int(a_pid)
                if _metrics.ACTIVE:
                    _metrics.TAP.inc("hvd_driver_worker_reattaches_total")
                self._log(
                    f"worker {wid} reattached "
                    f"(pid {a_pid}, epoch {self._epoch})"
                )
        done = {
            wid for wid in self._current_ids
            if (snap.get(f"done.{wid}") or b"").decode() == gen_s
        }
        if self._current_ids and done >= set(self._current_ids):
            self._log("all adopted workers completed; job finished")
            return 0
        if any(
            k.startswith("rejoin.") and v.decode() == gen_s
            for k, v in snap.items()
        ):
            self._abandon_adoption(
                "a worker abandoned the adopted generation"
            )
            return None
        dead = [
            wid for wid, pid in self._attached.items()
            if wid not in done and _is_local(wid.rsplit(":", 1)[0])
            and not self._pid_alive(pid)
        ]
        if dead:
            for wid in dead:
                self._record_failure(wid.rsplit(":", 1)[0])
                self._log(f"adopted worker {wid} died")
            self._abandon_adoption(f"adopted workers died: {dead}")
            return None
        if (len(self._attached) < len(self._current_ids)
                and self._adopt_deadline is not None
                and time.monotonic() > self._adopt_deadline):
            missing = sorted(
                set(self._current_ids) - set(self._attached)
            )
            self._abandon_adoption(
                f"workers never reattached within grace: {missing}"
            )
        return None

    def _abandon_adoption(self, why: str) -> None:
        """Adoption failed (a worker died while the driver was down, or
        survivors never reattached): degrade to the existing
        respawn-from-snapshots restart. Attached workers get a SIGTERM
        (their graceful-preemption path persists the last commit) and a
        drain window before the fresh generation is published, so their
        snapshots land before the replacements read them."""
        self._log(
            f"adoption abandoned: {why}; restarting the world from "
            "persisted snapshots"
        )
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_elastic_restarts_total")
        drain = set()
        for wid, pid in self._attached.items():
            if not _is_local(wid.rsplit(":", 1)[0]):
                continue
            if self._pid_alive(pid):
                try:
                    os.kill(pid, signal.SIGTERM)
                    drain.add(pid)
                except OSError:
                    pass
        self._adopting = False
        self._attached = {}
        self._current_ids = []
        self._adopt_drain_pids = drain
        self._adopt_drain_deadline = time.monotonic() + self._restart_grace
        self._journal_sync(force=True)

    def _maybe_fire_driver_faults(self) -> None:
        """Scheduled control-plane faults (docs/fault_tolerance.md):
        ``kill_driver`` hard-exits this process ``after_s`` seconds into
        the run (resume via ``--resume``/supervisor); ``restart_driver``
        runs the full crash-restart cycle in-process. Both fire once,
        and only in the driver incarnation the action's ``epoch``
        selector names (default: the first), so a resumed driver never
        replays its own death."""
        plan = _fault.active_plan()
        if plan is None:
            return
        now = time.monotonic()
        for action in plan.actions:
            if action.kind not in DRIVER_KINDS or action.after_s is None:
                continue
            if not action.matches_driver_epoch(self._epoch):
                continue
            if action.gen is not None and action.gen != self._gen:
                continue
            if action.index in self._driver_faults_fired:
                continue
            if now - self._started_at < action.after_s:
                continue
            self._driver_faults_fired.add(action.index)
            _fault.record_event(
                "driver", 1, action.kind,
                f"gen={self._gen} epoch={self._epoch}",
            )
            if action.kind == "kill_driver":
                self._log(
                    "fault plan: killing driver "
                    f"(exit {action.exit_code})"
                )
                sys.stderr.flush()
                os._exit(action.exit_code)
            else:
                self._simulated_restart()

    def _simulated_restart(self) -> None:
        """The ``restart_driver`` fault: a full crash-restart cycle
        without process death — KV blackout (workers observe driver
        loss and park), journal replay as a fresh driver would perform
        it, epoch bump, rendezvous-port reclaim, republish. Exercises
        every resume mechanism a real ``--resume`` uses, in one
        process, deterministically."""
        if self._journal is None:
            self._log(
                "restart_driver fault ignored: journaling disabled "
                "(no --output-dir and no HOROVOD_DRIVER_JOURNAL)"
            )
            return
        self._log("fault plan: simulating driver crash-restart")
        port = self._kv.port
        self._journal_sync(force=True)
        self._kv.stop()
        try:
            blackout = float(self._env.get(
                "HOROVOD_FAULT_DRIVER_BLACKOUT_S", "") or 3.0)
        except ValueError:
            blackout = 3.0
        time.sleep(blackout)
        self._journal = _journal_mod.DriverJournal.open(self._journal.path)
        prior = self._journal.state
        self._epoch = self._journal.epoch
        self._gen = int(prior.get("gen", self._gen))
        self._blacklist = _journal_mod.blacklist_from_journal(
            prior.get("blacklist") or {}
        )
        self._quarantine_strikes = {
            h: int(n) for h, n in (prior.get("strikes") or {}).items()
        }
        self._slow_strikes = {
            h: int(n) for h, n in (prior.get("slow_strikes") or {}).items()
        }
        self._blacklist_reason = {
            h: str(r)
            for h, r in (prior.get("blacklist_reasons") or {}).items()
            if h in self._blacklist
        }
        self._replan_doc = prior.get("replan") or None
        if self._replan_doc:
            self._replan_calib_hash = self._replan_doc.get("calib")
        self._failures = {
            h: int(n) for h, n in (prior.get("failures") or {}).items()
        }
        self._kv = KVStoreServer(port=port, reclaim_wait_s=10.0)
        self._kv.start()
        self._seed_kv(prior)
        if self._replan_doc:
            # Same epoch refresh as a real --resume (see __init__).
            self._replan_doc = dict(self._replan_doc)
            self._replan_doc["epoch"] = self._epoch
            self._kv.put(
                "elastic", "replan",
                json.dumps(self._replan_doc, sort_keys=True).encode(),
            )
        world = prior.get("world")
        if world:
            world = dict(world)
            world["epoch"] = self._epoch
            self._last_world = world
            self._kv.put("elastic", "world", json.dumps(world).encode())
        self._publish_driver_doc()
        self._journal_sync(force=True)
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_driver_restarts_total")
            _metrics.TAP.inc("hvd_driver_journal_replays_total")
            _metrics.TAP.set("hvd_driver_epoch", float(self._epoch))
        self._log(
            f"driver resumed in-process at generation {self._gen} "
            f"(epoch {self._epoch})"
        )

    def _discovery_loop(self) -> None:
        """Background discovery poller (upstream ElasticDriver runs its
        HostDiscovery on a thread for the same reason): a slow or hung
        discovery script must not stall worker reaping, drain-grace
        enforcement, or generation publishing. The supervision loop only
        ever reads the latest snapshot."""
        while not self._stop_discovery.is_set():
            try:
                self._last_hosts = _run_discovery_script(self._script)
            except Exception as exc:  # noqa: BLE001 - transient failure
                # A flaky discovery script must not take down a healthy
                # job: keep the last known host set and retry next poll.
                self._log(
                    f"host discovery failed ({exc}); keeping last known "
                    f"host set"
                )
            self._stop_discovery.wait(self._interval)

    def _expire_blacklist(self) -> None:
        """Re-admit hosts whose quarantine elapsed. The failure count is
        cleared — the host earned a fresh chance — but its strike count
        persists, so a relapse quarantines it for twice as long."""
        now = time.monotonic()
        changed = False
        for host, deadline in list(self._blacklist.items()):
            if deadline is not None and now >= deadline:
                del self._blacklist[host]
                reason = self._blacklist_reason.pop(host, "dead")
                self._failures.pop(host, None)
                self._last_failure.pop(host, None)
                changed = True
                if _metrics.ACTIVE:
                    _metrics.TAP.inc(
                        "hvd_elastic_readmissions_total", host=host
                    )
                strikes = (
                    self._slow_strikes if reason == "slow"
                    else self._quarantine_strikes
                )
                self._log(
                    f"re-admitting host {host} after {reason} quarantine "
                    f"(strike {strikes.get(host, 1)})"
                )
        if changed:
            self._journal_sync(force=True)

    def _record_failure(self, host: str) -> int:
        """Count one worker failure against ``host``, with decay: a count
        that has been quiet for a full cooldown window is forgiven before
        the new failure lands (old flakiness must not compound with a
        fresh, unrelated incident months later)."""
        now = time.monotonic()
        last = self._last_failure.get(host)
        if (last is not None and self._blacklist_cooldown > 0
                and now - last > self._blacklist_cooldown):
            self._failures[host] = 0
        self._failures[host] = self._failures.get(host, 0) + 1
        self._last_failure[host] = now
        if _metrics.ACTIVE:
            _metrics.TAP.inc(
                "hvd_elastic_worker_failures_total", host=host
            )
        self._journal_sync(force=True)
        return self._failures[host]

    def _blacklist_host(self, host: str) -> None:
        strikes = self._quarantine_strikes.get(host, 0) + 1
        self._quarantine_strikes[host] = strikes
        self._blacklist_reason[host] = "dead"
        self._trace_event("hvd_blacklist", host=host, strikes=strikes)
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_elastic_blacklists_total", host=host)
            _metrics.TAP.inc("hvd_quarantine_total", reason="dead")
        if self._blacklist_cooldown > 0:
            quarantine = self._blacklist_cooldown * (2 ** (strikes - 1))
            self._blacklist[host] = time.monotonic() + quarantine
            self._log(
                f"blacklisted host {host} (strike {strikes}; quarantined "
                f"for {quarantine:g}s)"
            )
        else:
            self._blacklist[host] = None
            self._log(f"blacklisted host {host} (permanently)")
        self._journal_sync(force=True)

    # ---------------------------------------------- self-driving fleet
    def _quarantine_slow_host(
        self, decision: "_selfdrive.QuarantineDecision"
    ) -> None:
        """Quarantine ``decision.host`` for SLOWNESS: same cooldown/
        decay/relapse-doubling machinery as the death blacklist, but on
        the independent ``reason="slow"`` strike ledger — a chronically
        slow host's sentence doubles per slowness relapse without its
        crash history compounding it (and vice versa). Write-ahead
        journaled BEFORE the membership change can publish, so a driver
        crash between decision and publish resumes into the same
        verdict."""
        host = decision.host
        strikes = self._slow_strikes.get(host, 0) + 1
        self._slow_strikes[host] = strikes
        self._blacklist_reason[host] = "slow"
        self._trace_event(
            "hvd_quarantine", host=host, rank=decision.rank,
            strikes=strikes, charges=decision.charges,
            window=decision.window, reason="slow",
        )
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_quarantine_total", reason="slow")
        if self._quarantine_cooldown > 0:
            quarantine = self._quarantine_cooldown * (2 ** (strikes - 1))
            self._blacklist[host] = time.monotonic() + quarantine
            until = f"quarantined for {quarantine:g}s"
        else:
            self._blacklist[host] = None
            until = "quarantined permanently"
        if _fault.ACTIVE:
            # Detail carries only run-invariant fields: the charge count
            # at decision time depends on collection batching, so it
            # stays out of the byte-diffed event log (it is in the
            # driver log and the trace event above).
            _fault.record_event(
                "driver", strikes, "quarantine",
                f"host={host} reason=slow",
            )
        self._log(
            f"slowness quarantine: host {host} (rank {decision.rank} "
            f"charged straggler {decision.charges} of the last "
            f"{decision.window} steps; slow-strike {strikes}; {until}); "
            "re-forming the world without it"
        )
        self._journal_sync(force=True)  # WAL before the publish below

    def _maybe_quarantine_slow(self) -> bool:
        """Run the StragglerPolicy against the current world: at most
        one host per supervision beat, never below --min-np, only ranks
        of the CURRENT generation (the policy re-keys on every publish).
        Returns True when membership changed (caller reconciles)."""
        if not self._policy.enabled or self._adopting:
            return False
        world = self._last_world or {}
        rank_to_host = {
            int(a["rank"]): wid.rsplit(":", 1)[0]
            for wid, a in (world.get("assignments") or {}).items()
        }
        # The min-world veto counts AVAILABLE capacity (discovery minus
        # already-blacklisted hosts) — hot spares and unused slots on
        # healthy hosts are exactly what makes a quarantine affordable.
        slots_by_host = dict(self._discover())
        decision = self._policy.decide(
            rank_to_host, slots_by_host, self._min_np
        )
        if decision is None:
            return False
        if decision.host in self._blacklist:
            return False
        self._quarantine_slow_host(decision)
        return True

    def _maybe_replan(self) -> None:
        """Live re-plan check on the supervision beat
        (docs/fault_tolerance.md "Self-driving fleet"), with two
        triggers: (a) the calibrated per-hop constants
        (HOROVOD_CALIBRATION_FILE — the artifact ``fleet_sim.py
        --calibrate`` fits and ``--replay`` diffs) drift from the
        generation defaults beyond ``HOROVOD_REPLAN_DIVERGENCE``
        (one-shot per calibration signature), or (b) the
        ``StepSkewTracker`` trend — mean cross-rank skew over the
        recent window — stays above ``HOROVOD_REPLAN_SKEW_S`` (one-shot
        per generation). Either way the tuner's free objectives are
        re-priced on the best-available model, every implied plan is
        verified symbolically, the notice is journaled (WAL) and then
        published under ``elastic/replan`` for workers to adopt at
        their next commit boundary."""
        if self._adopting or (self._replan_divergence <= 0
                              and self._replan_skew_s <= 0):
            return
        now = time.monotonic()
        if now - self._last_replan_check < self._replan_check_s:
            return
        self._last_replan_check = now
        if not self._last_world:
            return
        try:
            from ..sim.calibrate import resolve_calibration

            calib = resolve_calibration(None)
        except Exception:  # noqa: BLE001 - a bad file must not kill the loop
            calib = None
        model = _selfdrive.model_for_world(self._last_world)
        trigger = None
        per_hop: Dict[str, float] = {}
        drift = 0.0
        priced_calib = None
        if (self._replan_divergence > 0 and calib is not None
                and calib.signature_hash != self._replan_calib_hash):
            from ..tune.objective import calibrated_model

            drifted, info = calibrated_model(
                model, calib, where="driver-replan"
            )
            if info.get("stale"):
                # Signature mismatch already warned loudly; don't retry
                # every beat against the same stale file.
                self._replan_calib_hash = calib.signature_hash
            else:
                ratios = _selfdrive.divergence_ratios(model, drifted)
                d = _selfdrive.max_divergence(ratios)
                if d >= self._replan_divergence:
                    trigger, per_hop, drift = "divergence", ratios, d
                    priced_calib = calib
                else:
                    self._replan_calib_hash = calib.signature_hash
        if (trigger is None and self._replan_skew_s > 0
                and not self._skew_replanned):
            trend = _selfdrive.skew_trend(self._skew_trend)
            if trend is not None and trend >= self._replan_skew_s:
                trigger, drift = "skew-trend", trend
                priced_calib = calib  # best available; None = defaults
        if trigger is None:
            return
        windows = {
            r: doc for r, doc in self._collected_windows().items()
        }
        try:
            spec = _selfdrive.spec_from_windows(windows)
        except Exception as exc:  # noqa: BLE001 - malformed override
            self._log(f"re-plan: unusable program spec ({exc}); skipping")
            if trigger == "divergence":
                self._replan_calib_hash = calib.signature_hash
            else:
                self._skew_replanned = True
            return
        if spec is None:
            return  # nothing observed to price yet; retry next beat
        current = dict(
            (self._replan_doc or {}).get("config") or {}
        ) or self._current_plan_config(windows)
        proposal = _selfdrive.propose_replan(
            spec, model, current, priced_calib,
            trigger=trigger, per_hop=per_hop, drift=drift,
        )
        if trigger == "divergence":
            self._replan_calib_hash = calib.signature_hash
        else:
            self._skew_replanned = True
        if proposal is None:
            self._log(
                f"re-plan ({trigger}, drift {drift:g}): the current "
                "configuration is already optimal on the observed "
                "model; keeping it"
            )
            return
        findings = _selfdrive.verify_replan(
            spec, proposal.config, model, priced_calib
        )
        if findings:
            self._log(
                f"re-plan REFUSED: {len(findings)} plan-verification "
                f"finding(s) on the proposed configuration "
                f"({findings[0].render() if findings else ''})"
            )
            if _metrics.ACTIVE:
                _metrics.TAP.inc(
                    "hvd_replan_total", trigger="refused-verification"
                )
            return
        notice_id = int((self._replan_doc or {}).get("id", 0)) + 1
        doc = proposal.to_notice(notice_id, self._gen, self._epoch)
        doc["calib"] = (
            priced_calib.signature_hash if priced_calib is not None
            else None
        )
        self._replan_doc = doc
        self._journal_sync(force=True)  # WAL before workers can see it
        self._kv.put(
            "elastic", "replan",
            json.dumps(doc, sort_keys=True).encode(),
        )
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_replan_total", trigger=proposal.trigger)
        self._trace_event(
            "hvd_replan", id=notice_id, trigger=proposal.trigger,
            drift=round(drift, 6), config=dict(proposal.config),
        )
        if _fault.ACTIVE:
            _fault.record_event(
                "driver", notice_id, "replan",
                f"trigger={proposal.trigger} "
                f"wire={proposal.config['wire_dtype']} "
                f"topo={proposal.config['topo_algorithm']}",
            )
        self._log(
            f"re-plan #{notice_id} published (trigger "
            f"{proposal.trigger}, drift {drift:g}): "
            f"{proposal.current} -> {proposal.config}; modeled exposed "
            f"{proposal.current_exposed_us:g}us -> "
            f"{proposal.replanned_exposed_us:g}us"
        )

    def _collected_windows(self) -> Dict[int, dict]:
        """Freshest worker trace windows off the KV plane (current
        generation only — stale-generation windows carry renumbered
        ranks)."""
        from ..trace import pusher as _tpush

        out: Dict[int, dict] = {}
        for key, payload in self._kv.snapshot(_trace.KV_SCOPE).items():
            if not key.startswith("rank."):
                continue
            suffix = key.split(".", 1)[1]
            if not suffix.isdigit():
                continue
            doc = _tpush.decode_window(payload)
            if doc is None:
                continue
            if int(doc.get("gen", 0) or 0) not in (0, self._gen):
                continue
            out[int(suffix)] = doc
        return out

    def _current_plan_config(self, windows: Dict[int, dict]) -> Dict:
        """The fleet's current lowering knobs as the workers reported
        them (trace-tap ``note_plan`` correlation ids); absent fields
        fall back to env/config defaults inside the policy layer."""
        cfg: Dict = {}
        for _, doc in sorted(windows.items()):
            plan = doc.get("plan") or {}
            for src, dst in (("topo_algorithm", "topo_algorithm"),
                             ("wire_dtype", "wire_dtype")):
                if plan.get(src) and dst not in cfg:
                    cfg[dst] = plan[src]
        return cfg

    def _discover(self) -> List[Tuple[str, int]]:
        self._expire_blacklist()
        if _metrics.ACTIVE:
            _metrics.TAP.set(
                "hvd_elastic_blacklisted_hosts", float(len(self._blacklist))
            )
        hosts = (
            self._last_hosts if self._script
            else list(self._static_hosts or [])
        )
        return [(h, c) for h, c in hosts if h not in self._blacklist]

    def _desired_slots(self) -> Optional[List[SlotInfo]]:
        """Allocation over currently-available, non-blacklisted hosts;
        None when below min_np."""
        hosts = self._discover()
        total = sum(c for _, c in hosts)
        if total < self._min_np:
            return None
        return launcher.allocate(hosts, min(total, self._max_np))

    @staticmethod
    def _worker_id(slot: SlotInfo) -> str:
        return f"{slot.hostname}:{slot.local_rank}"

    def _start_coordination_service(
        self, num_processes: int, all_local: bool
    ) -> str:
        """Host this generation's JAX coordination service IN THE DRIVER
        (the reference's elastic driver owns the rendezvous the same way):
        no worker is special, so any worker — including generation rank 0
        — can die without collapsing the coordination plane. The previous
        two generations' services stay alive as the drain grace window —
        answering stale heartbeats from stragglers of a just-abandoned
        generation is what prevents their fatal connection-refused
        aborts — and anything older is shut down: by then a straggler
        has long since either re-rendezvoused or tripped its own
        heartbeat timeout, so unbounded membership churn no longer
        accumulates unbounded gRPC servers/ports in the driver."""
        from jax._src.lib import _jax as _jaxlib

        port = _free_port()
        heartbeat = int(float(self._env.get(
            "HOROVOD_ELASTIC_HEARTBEAT_S", "10"
        )))
        svc = _jaxlib.get_distributed_runtime_service(
            f"[::]:{port}", num_processes,
            heartbeat_timeout=heartbeat, shutdown_timeout=5,
        )
        if self._services:
            # The previous generation is superseded NOW — its drain
            # grace clock starts here, not at its creation (a service
            # hours old can still have stragglers abandoned seconds ago).
            self._services[-1][2] = time.monotonic()
        self._services.append([self._gen, svc, None, heartbeat])
        self._retire_services(keep=2)
        addr = "127.0.0.1" if all_local else socket.gethostname()
        return f"{addr}:{port}"

    def _probe_free_port(self, host: str) -> int:
        """A free port ON THE HOST THAT WILL BIND IT. ``_free_port()``
        probes the driver machine, which is wrong for a remote
        controller/coordinator host (advisor finding: the respawn-mode
        jax coordinator port was probed locally but bound on
        ``controller_addr``). For a remote host, ask it over ssh;
        degrade to the local probe — plus the worker-side
        bind-failure-respawns-with-fresh-ports path — when the probe
        itself fails."""
        if _is_local(host):
            return _free_port()
        import subprocess

        probe = ("import socket; s=socket.socket(); s.bind((\"\", 0)); "
                 "print(s.getsockname()[1])")
        cmd = launcher.ssh_base_cmd(
            host, self._ssh_port, batch=True, connect_timeout=5
        ) + [f"python3 -c '{probe}'"]
        try:
            out = subprocess.run(
                cmd, capture_output=True, text=True, timeout=10,
            )
            port = int(out.stdout.strip().splitlines()[-1])
            if 0 < port < 65536:
                return port
        except Exception as exc:  # noqa: BLE001 - probe is best-effort
            self._log(
                f"remote port probe on {host} failed ({exc}); falling "
                "back to a locally-probed port (a bind collision exits "
                "the worker with the respawn status and retries with "
                "fresh ports)"
            )
        return _free_port()

    def _drain_world_for_restart(self) -> None:
        """Respawn-mode restart: move every remaining live worker into
        the draining pool (grace first — a survivor needs time to persist
        its commit and exit with the rejoin status on its own; only then
        is it terminated) and re-form once the pool empties. Drained
        exits are reaped code-blind, so the follow-on aborts a peer death
        causes in a non-recoverable world never count toward
        blacklisting."""
        if not self._workers:
            self._restart_pending = True
            return
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_elastic_restarts_total")
        deadline = time.monotonic() + self._restart_grace
        for wid in list(self._workers):
            w = self._workers.pop(wid)
            self._removing.append((w, deadline))
            self._log(f"draining {wid} for world restart")
        self._current_ids = []
        self._restart_pending = True

    def _maybe_probe_nics(self, slots: List[SlotInfo]) -> None:
        """Ring NIC probe for elastic worlds whose host set came from (or
        changed through) the discovery script: hvdrun's launch-time probe
        only covers an initial ``-H`` set, so without this a
        discovery-only multi-NIC job would bind the default (possibly
        non-routable) interface. Best-effort, cached per host set; an
        explicit ``HOROVOD_IFACE`` (CLI pin or prior probe over the same
        set) wins."""
        hostnames = sorted({s.hostname for s in slots})
        if (self._nic_pinned
                or len(hostnames) < 2
                or all(_is_local(h) for h in hostnames)
                or hostnames == self._probed_hostset):
            return
        from . import network

        try:
            common = network.discover_common_interfaces(
                hostnames, ssh_port=self._ssh_port
            )
            if common:
                self._env["HOROVOD_IFACE"] = ",".join(common)
                self._log(f"routable interfaces for {hostnames}: {common}")
        except Exception as exc:  # noqa: BLE001 - probe is best-effort
            self._log(f"NIC probe failed ({exc}); continuing without")
        self._probed_hostset = hostnames

    def _maybe_fire_preemptions(self) -> None:
        """Deliver scheduled simulated maintenance notices: a fault-plan
        ``preempt`` action with ``after_s`` SIGTERMs the selected worker
        that long after its spawn — the platform's preemption notice,
        which the worker's graceful drain path turns into commit → drain
        → rejoin. One-shot per (action, worker incarnation)."""
        plan = _fault.active_plan()
        if plan is None:
            return
        now = time.monotonic()
        for action in plan.actions:
            if action.kind != "preempt" or action.after_s is None:
                continue
            if action.gen is not None and action.gen != self._gen:
                continue
            for wid, w in list(self._workers.items()):
                if action.worker is not None and action.worker != wid:
                    continue
                key = (action.index, wid, w.spawned_at)
                if key in self._preempts_fired:
                    continue
                if now - w.spawned_at < action.after_s:
                    continue
                self._preempts_fired.add(key)
                self._trace_event("hvd_preempt_notice", worker=wid)
                if _metrics.ACTIVE:
                    _metrics.TAP.inc("hvd_elastic_preempt_notices_total")
                _fault.record_event(
                    "driver", self._gen, "preempt-notice", wid
                )
                self._log(
                    f"delivering simulated preemption notice (SIGTERM) "
                    f"to {wid}"
                )
                try:
                    os.kill(w.proc.pid, signal.SIGTERM)
                except (ProcessLookupError, OSError):
                    pass

    def _retire_services(self, keep: int) -> None:
        """Shut down all but the newest service and ``keep`` prior
        generations (``keep=0`` drains everything, for driver exit).

        Generation count alone is not a safe drain signal: a failure
        cascade can publish several generations within seconds, while a
        gen-N straggler may legitimately heartbeat the gen-N service for
        a full heartbeat window before noticing and re-rendezvousing —
        shutting its service down mid-rejoin turns a drain into a fatal
        connection-refused abort. So a service is retired only when it is
        BOTH more than ``keep`` generations behind AND twice its
        heartbeat timeout has passed since it was SUPERSEDED (creation
        age is the wrong clock: a service hours old can still have
        stragglers abandoned seconds ago)."""
        limit = keep + 1 if keep else 0
        now = time.monotonic()
        while len(self._services) > limit:
            gen, svc, superseded, heartbeat = self._services[0]
            if keep and (superseded is None
                         or now - superseded < 2 * heartbeat):
                break  # list is supersession-ordered; nothing older
            self._services.pop(0)
            try:
                svc.shutdown()
            except Exception:  # noqa: BLE001
                pass
            self._log(f"retired generation-{gen} coordination service")

    def _publish(self, slots: List[SlotInfo]) -> Dict[str, str]:
        """Publish the next generation; returns env additions for spawns."""
        self._gen += 1
        controller_addr = (
            "127.0.0.1" if _is_local(slots[0].hostname) else slots[0].hostname
        )
        # Both ports are BOUND on rank 0's host, so probe them there
        # (see _probe_free_port), not on the driver machine.
        controller_port = self._probe_free_port(slots[0].hostname)
        if self._rejoin_mode == "respawn":
            # Respawn mode rides the PUBLIC jax.distributed.initialize,
            # whose process 0 hosts the coordination service itself. The
            # driver must NOT also host one: gRPC binds with SO_REUSEPORT,
            # so two services on the port silently load-balance incoming
            # connects and each waits forever for a full house. Rank 0
            # owning the service is fine here — any death restarts the
            # whole generation on a fresh port anyway.
            jax_coordinator = (
                f"{controller_addr}:{self._probe_free_port(slots[0].hostname)}"
            )
        else:
            jax_coordinator = self._start_coordination_service(
                len(slots), all(_is_local(s.hostname) for s in slots)
            )
        # Sync source for the new generation: a surviving worker that has
        # CONFIRMED completing a state sync (it holds live training
        # state) — never a fresh respawn, whose just-constructed state
        # would otherwise overwrite every survivor when it happened to
        # land on rank 0, and not even a running worker that crashed out
        # of its first generation before ever syncing. Fallback order:
        # confirmed survivor, then any running worker, then rank 0.
        joined = self._kv.snapshot("elastic")
        confirmed = {
            wid for wid in self._workers
            if f"joined.{wid}" in joined
        }
        sync_root = 0
        for pool in (confirmed, self._workers):
            chosen = next(
                (s.rank for s in slots if self._worker_id(s) in pool), None
            )
            if chosen is not None:
                sync_root = chosen
                break
        world = {
            "gen": self._gen,
            "epoch": self._epoch,
            "size": len(slots),
            "sync_root": sync_root,
            "controller_addr": controller_addr,
            "controller_port": controller_port,
            "jax_coordinator": jax_coordinator,
            "assignments": {
                self._worker_id(s): {
                    "rank": s.rank,
                    "local_rank": s.local_rank,
                    "local_size": s.local_size,
                    "cross_rank": s.cross_rank,
                    "cross_size": s.cross_size,
                }
                for s in slots
            },
        }
        # A live re-plan notice outlives membership changes: it is
        # RE-STAMPED for the new generation (fresh id, gen, epoch) so a
        # late joiner — a promoted spare, a respawn — adopts the same
        # plan the survivors already run; mismatched lowering knobs
        # across ranks would break the collectives the plan configures.
        # Survivors re-adopt idempotently (same config).
        restamped = None
        if self._replan_doc is not None and int(
            self._replan_doc.get("gen", -1)
        ) != self._gen:
            restamped = dict(self._replan_doc)
            restamped["id"] = int(restamped.get("id", 0)) + 1
            restamped["gen"] = self._gen
            restamped["epoch"] = self._epoch
            self._replan_doc = restamped
        # Write-ahead: the journal records the generation BEFORE any
        # worker can observe it — a crash between the two replays a
        # state the fleet has not outrun.
        self._last_world = world
        self._journal_sync(force=True)
        self._kv.put("elastic", "world", json.dumps(world).encode())
        if restamped is not None:
            self._kv.put(
                "elastic", "replan",
                json.dumps(restamped, sort_keys=True).encode(),
            )
            if _fault.ACTIVE:
                _fault.record_event(
                    "driver", int(restamped["id"]), "replan-restamp",
                    f"id={restamped['id']} gen={self._gen}",
                )
        self._publish_driver_doc()
        # Ranks are renumbered in the new generation: re-key the skew
        # tracker and the quarantine policy (a parked or removed rank
        # must never be charged for the new world's steps) and drop the
        # old generation's pushed trace windows off the KV plane.
        if self._skew is not None:
            self._skew.reset_generation(self._gen)
        self._policy.reset_generation(self._gen)
        self._skew_trend.clear()
        self._skew_replanned = False
        for key in list(self._kv.snapshot(_trace.KV_SCOPE)):
            if key.startswith("rank."):
                self._kv.delete(_trace.KV_SCOPE, key)
        if _metrics.ACTIVE:
            _metrics.TAP.inc("hvd_elastic_generations_total")
            _metrics.TAP.set("hvd_elastic_generation", float(self._gen))
            _metrics.TAP.set("hvd_elastic_world_size", float(len(slots)))
        self._log(
            f"generation {self._gen}: size {len(slots)} over "
            f"{sorted({s.hostname for s in slots})}"
        )
        self._trace_event(
            "hvd_generation_publish", gen=self._gen, size=len(slots),
            epoch=self._epoch, sync_root=sync_root,
        )
        return {
            "controller_addr": controller_addr,
            "controller_port": str(controller_port),
            "jax_coordinator": jax_coordinator,
            "sync_root": str(sync_root),
        }

    def _spawn(self, slot: SlotInfo, endpoints: Dict[str, str]) -> None:
        wid = self._worker_id(slot)
        rank_env = launcher.build_rank_env(
            slot,
            self._env,
            endpoints["controller_addr"],
            int(endpoints["controller_port"]),
            endpoints["jax_coordinator"],
        )
        # The KV rendezvous lives in THIS driver process, not on rank 0's
        # host — remote workers dial the driver's hostname.
        kv_addr = (
            "127.0.0.1" if _is_local(slot.hostname)
            else socket.gethostname()
        )
        rank_env.update(
            {
                "HOROVOD_ELASTIC": "1",
                "HOROVOD_ELASTIC_WORKER_ID": wid,
                "HOROVOD_ELASTIC_GEN": str(self._gen),
                "HOROVOD_DRIVER_EPOCH": str(self._epoch),
                "HOROVOD_ELASTIC_SYNC_ROOT": endpoints["sync_root"],
                "HOROVOD_ELASTIC_KV_ADDR": kv_addr,
                "HOROVOD_ELASTIC_KV_PORT": str(self._kv.port),
                "HOROVOD_ELASTIC_TIMEOUT": str(self._elastic_timeout),
            }
        )
        if _is_local(slot.hostname):
            cmd = self._command
        else:
            cmd = launcher.build_remote_command(
                slot.hostname, rank_env, self._command, self._ssh_port
            )
        stdout = stderr = None
        outfiles: Tuple = ()
        if self._output_dir:
            os.makedirs(self._output_dir, exist_ok=True)
            stdout = open(
                os.path.join(self._output_dir, f"worker.{wid}.out"), "ab"
            )
            stderr = open(
                os.path.join(self._output_dir, f"worker.{wid}.err"), "ab"
            )
            outfiles = (stdout, stderr)
        if self._verbose:
            self._log(f"spawn {wid} rank {slot.rank}: {cmd}")
        if _fault.ACTIVE:
            # Chaos tap: scheduled spawn delays (slow scheduler / image
            # pull); a 'preempt' action with after_s is handled by the
            # supervision loop via _maybe_fire_preemptions.
            _fault.fault_point("spawn", wid)
        # A fresh incarnation must earn its own joined-confirmation: a
        # stale key from a crashed predecessor under the same worker id
        # would otherwise mark this never-synced respawn as a valid
        # sync_root. Same for the HA signals (attach/done are gen- and
        # epoch-stamped, but a dangling value from a dead incarnation
        # has no business outliving it).
        self._kv.delete("elastic", f"joined.{wid}")
        self._kv.delete("elastic", f"rejoin.{wid}")
        self._kv.delete("elastic", f"attach.{wid}")
        self._kv.delete("elastic", f"done.{wid}")
        self._workers[wid] = _Worker(
            wid,
            slot.hostname,
            safe_shell_exec.ManagedProcess(
                cmd, env=rank_env, stdout=stdout, stderr=stderr
            ),
            outfiles,
            spawned_at=time.monotonic(),
        )

    def _reconcile(self, force: bool = False) -> bool:
        """Re-form the world when the desired membership differs from the
        running one — or unconditionally with ``force`` (surviving
        workers abandoned the current generation and need a fresh one
        even though membership is unchanged). Returns False when the job
        must fail (below min_np)."""
        slots = self._desired_slots()
        if slots is None:
            self._log(
                f"available slots fell below --min-np {self._min_np}; "
                "aborting"
            )
            return False
        desired = {self._worker_id(s): s for s in slots}
        desired_ids = [self._worker_id(s) for s in slots]
        if desired_ids == self._current_ids and not force:
            return True
        # A slot whose previous process is still draining must not be
        # re-assigned yet: two live processes claiming the same worker id
        # would both join the new generation as the same rank. Defer the
        # re-formation until the drain completes (exit or grace kill).
        draining = {w.worker_id for w, _ in self._removing}
        if draining & set(desired_ids):
            return True
        # Hot-spare promotion (docs/fault_tolerance.md "Self-driving
        # fleet"): a parked spare whose slot the new world claims joins
        # IN the same generation bump — one resize instead of a
        # respawn-from-snapshot. The spare only leaves its gate on the
        # explicit ``promote.<wid>`` signal (never on the world doc
        # alone), because in respawn mode the FIRST publish after a
        # membership change is only the drain NOTIFICATION — survivors
        # exit 79 and the world re-forms once more. Promotion therefore
        # defers in respawn mode while old-generation workers are still
        # live, and lands on the post-drain restart publish instead;
        # in-process mode promotes immediately (survivors rejoin the
        # same generation the spare enters). KV hygiene runs BEFORE the
        # publish so the promoted spare's attach/joined signals are
        # never clobbered.
        defer_spares = (
            self._rejoin_mode == "respawn" and bool(self._workers)
        )
        promoted = []
        if not defer_spares:
            for wid in desired_ids:
                w = self._spares.get(wid)
                if w is None:
                    continue
                if w.proc.poll() is None:
                    for key in ("joined", "rejoin", "attach", "done"):
                        self._kv.delete("elastic", f"{key}.{wid}")
                    promoted.append(wid)
                else:
                    # Died unnoticed while parked: a fresh spawn takes
                    # the slot below.
                    self._reap_spare(wid, w)
        self._maybe_probe_nics(slots)
        endpoints = self._publish(slots)
        for wid in promoted:
            self._workers[wid] = self._spares.pop(wid)
            self._spare_slots.pop(wid, None)
            self._kv.put(
                "elastic", f"promote.{wid}", str(self._gen).encode()
            )
            if _metrics.ACTIVE:
                _metrics.TAP.inc("hvd_spare_promotions_total")
                _metrics.TAP.set(
                    "hvd_spare_pool_size", float(len(self._spares))
                )
            self._trace_event("hvd_spare_promote", worker=wid,
                              gen=self._gen)
            if _fault.ACTIVE:
                _fault.record_event(
                    "driver", self._gen, "promote", f"worker={wid}"
                )
            self._log(
                f"promoted spare {wid} into generation {self._gen} "
                "(pre-attached: no respawn)"
            )
        # Dropped workers drain gracefully: they poll the KV store, see
        # they are not in the new generation, and exit 0 on their own —
        # SIGTERMing them here would break survivors' in-flight
        # collectives and force a needless rollback. Terminate only after
        # the grace window.
        for wid in list(self._workers):
            if wid not in desired:
                w = self._workers.pop(wid)
                self._removing.append(
                    (w, time.monotonic() + self._removal_grace)
                )
                self._log(f"removed {wid} (draining)")
        for wid, slot in desired.items():
            if wid not in self._workers:
                if wid in self._spares:
                    # Deferred promotion: the parked spare keeps its
                    # claimed slot reserved until the post-drain restart
                    # publish promotes it.
                    continue
                self._spawn(slot, endpoints)
        self._current_ids = desired_ids
        self._reconcile_spares(slots)
        return True

    # ------------------------------------------------------- hot spares
    def _reconcile_spares(self, world_slots: List[SlotInfo]) -> None:
        """Keep ``--spares`` workers spawned BEYOND the world: attached
        to the KV plane and heartbeating, but excluded from the mesh
        (their elastic context parks them before ``hvd.init`` until a
        generation claims their slot — ``elastic.maybe_wait_as_spare``).
        Spare slots are the next slots the allocator would hand out, so
        the pool shrinks honestly when capacity is tight."""
        if not self._spares_want and not self._spares:
            return
        hosts = self._discover()
        total = sum(c for _, c in hosts)
        want = min(self._spares_want, max(total - len(world_slots), 0))
        spare_slots: List[SlotInfo] = []
        if want > 0:
            # allocate() fills hosts in order, so the first
            # len(world_slots) entries are exactly the world allocation
            # and the tail is the spare pool.
            spare_slots = launcher.allocate(
                hosts, len(world_slots) + want
            )[len(world_slots):]
        desired = {self._worker_id(s): s for s in spare_slots}
        for wid in list(self._spares):
            if wid not in desired:
                if wid in self._current_ids:
                    # The world claimed this spare's slot but promotion
                    # was deferred (respawn-mode drain in flight): it
                    # is about to be promoted, not retired.
                    continue
                w = self._spares.pop(wid)
                self._spare_slots.pop(wid, None)
                if w.proc.poll() is None:
                    w.proc.terminate()
                for f in w.outfiles:
                    f.close()
                self._log(f"retired spare {wid}")
        for wid, slot in desired.items():
            if wid not in self._spares:
                self._spawn_spare(slot)
        if _metrics.ACTIVE:
            _metrics.TAP.set(
                "hvd_spare_pool_size", float(len(self._spares))
            )
        self._journal_sync(force=True)

    def _spawn_spare(self, slot: SlotInfo) -> None:
        """Spawn one spare: the training command with the elastic KV
        plumbing but NO rank assignment — ``HOROVOD_ELASTIC_SPARE=1``
        makes the worker-side elastic context hold it at the spare gate
        (heartbeating ``spare.<wid>``) until a published world claims
        its worker id."""
        wid = self._worker_id(slot)
        kv_addr = (
            "127.0.0.1" if _is_local(slot.hostname)
            else socket.gethostname()
        )
        env = dict(self._env)
        env.update({
            "HOROVOD_ELASTIC": "1",
            "HOROVOD_ELASTIC_SPARE": "1",
            "HOROVOD_ELASTIC_WORKER_ID": wid,
            "HOROVOD_ELASTIC_GEN": "0",
            "HOROVOD_DRIVER_EPOCH": str(self._epoch),
            "HOROVOD_ELASTIC_SYNC_ROOT": "0",
            "HOROVOD_ELASTIC_KV_ADDR": kv_addr,
            "HOROVOD_ELASTIC_KV_PORT": str(self._kv.port),
            "HOROVOD_ELASTIC_TIMEOUT": str(self._elastic_timeout),
        })
        if _is_local(slot.hostname):
            cmd = self._command
        else:
            cmd = launcher.build_remote_command(
                slot.hostname, env, self._command, self._ssh_port
            )
        stdout = stderr = None
        outfiles: Tuple = ()
        if self._output_dir:
            stdout = open(
                os.path.join(self._output_dir, f"worker.{wid}.out"), "ab"
            )
            stderr = open(
                os.path.join(self._output_dir, f"worker.{wid}.err"), "ab"
            )
            outfiles = (stdout, stderr)
        for key in ("joined", "rejoin", "attach", "done", "promote",
                    "spare"):
            self._kv.delete("elastic", f"{key}.{wid}")
        self._spares[wid] = _Worker(
            wid,
            slot.hostname,
            safe_shell_exec.ManagedProcess(
                cmd, env=env, stdout=stdout, stderr=stderr
            ),
            outfiles,
            spawned_at=time.monotonic(),
        )
        self._spare_slots[wid] = slot
        self._log(f"spawned spare {wid} (parked until promoted)")

    def _reap_spare(self, wid: str, w: _Worker) -> None:
        """A spare died while parked: count it against its host (a
        crashing spare is still a host signal) and drop it from the
        pool; the supervision loop respawns it while the host stays
        healthy."""
        rc = w.proc.poll()
        self._spares.pop(wid, None)
        for f in w.outfiles:
            f.close()
        count = self._record_failure(w.host)
        if count >= self._failure_threshold:
            self._blacklist_host(w.host)
        self._log(
            f"spare {wid} died while parked (exit {rc}; host failures: "
            f"{count})"
        )
        if _metrics.ACTIVE:
            _metrics.TAP.set(
                "hvd_spare_pool_size", float(len(self._spares))
            )

    def _poll_spares(self) -> None:
        """Supervision-beat spare upkeep: reap dead spares and respawn
        them while their host is still admissible."""
        for wid, w in list(self._spares.items()):
            if w.proc.poll() is None:
                continue
            slot = self._spare_slots.get(wid)
            self._reap_spare(wid, w)
            if (slot is not None and w.host not in self._blacklist
                    and not self._finishing):
                self._spawn_spare(slot)
                if _metrics.ACTIVE:
                    _metrics.TAP.set(
                        "hvd_spare_pool_size", float(len(self._spares))
                    )

    # -------------------------------------------------------------- loop
    def run(self) -> int:
        if self._resume_finished:
            self._log(
                "journal records the job as finished; nothing to resume"
            )
            self._kv.close()
            return 0
        self._kv.start()
        if _metrics.ACTIVE:
            self._log(
                f"metrics: GET /metrics on port {self._kv.port} "
                "(rendezvous KV server)"
            )
        if self._script:
            # Seed synchronously (the first allocation needs hosts when
            # the script is the sole source), then poll on a thread.
            try:
                self._last_hosts = _run_discovery_script(self._script)
            except Exception as exc:  # noqa: BLE001
                self._log(f"initial host discovery failed: {exc}")
            threading.Thread(
                target=self._discovery_loop,
                name="hvd_elastic_discovery", daemon=True,
            ).start()
        try:
            rc = self._run()
            if self._journal is not None:
                try:
                    self._journal.record(finished=(rc == 0))
                except OSError:
                    pass
            return rc
        finally:
            self._stop_discovery.set()
            for w in (list(self._workers.values())
                      + list(self._spares.values())
                      + [w for w, _ in self._removing]):
                if w.proc.poll() is None:
                    w.proc.terminate()
                for f in w.outfiles:
                    f.close()
            # Final fleet-trace collection (the workers' shutdown push
            # landed by now) + the flight-dump postmortem bundle.
            try:
                self._trace_collect(final=True)
            except Exception:  # noqa: BLE001 - teardown must complete
                pass
            self._retire_services(keep=0)
            self._kv.stop()
            # Local respawn snapshots are keyed by this driver's pid —
            # nothing can legitimately read them after it exits. (Remote
            # hosts' dirs are out of reach; they are tmp-reaped. A
            # user-provided dir is theirs to keep.)
            if self._state_dir_owned:
                import shutil

                shutil.rmtree(
                    self._env["HOROVOD_ELASTIC_STATE_DIR"],
                    ignore_errors=True,
                )

    def _run(self) -> int:
        self._started_at = time.monotonic()
        self._publish_driver_doc()
        if self._adopting:
            self._enter_adoption()
        elif not self._reconcile():
            return 1
        last_discovery = time.monotonic()
        last_beat = 0.0
        while True:
            time.sleep(0.1)
            changed = False
            now = time.monotonic()
            if now - last_beat >= 1.0:
                last_beat = now
                # Liveness beat for worker-side driver probes, plus the
                # periodic journal refresh of worker-written KV signals.
                self._publish_driver_doc()
                self._journal_sync()
                self._trace_collect()
                # Self-driving fleet: spare upkeep, the slowness-
                # quarantine decision over the charges _trace_collect
                # just fed, and the calibration-drift re-plan check.
                self._poll_spares()
                if self._maybe_quarantine_slow():
                    changed = True
                self._maybe_replan()
            # Reap draining removed workers (exit code irrelevant);
            # terminate stragglers past the grace window.
            still_removing = []
            for w, deadline in self._removing:
                rc = w.proc.poll()
                if rc is not None:
                    if rc not in (0, REJOIN_EXIT_CODE):
                        # Code-blind for blacklisting, but not for the
                        # postmortem log: a crash reaped during a world
                        # restart (its peer's rejoin exit won the reap
                        # race) must still be attributable in the driver
                        # log, same phrasing as a directly-reaped
                        # failure.
                        self._log(
                            f"{w.worker_id} failed with exit code {rc} "
                            "(reaped while draining for restart)"
                        )
                    for f in w.outfiles:
                        f.close()
                    continue
                if time.monotonic() > deadline:
                    w.proc.terminate()
                    for f in w.outfiles:
                        f.close()
                    continue
                still_removing.append((w, deadline))
            self._removing = still_removing
            # Drain superseded coordination services whose grace window
            # elapsed since the last publish (a cascade can outrun the
            # publish-time retirement's time guard).
            self._retire_services(keep=2)
            if _fault.ACTIVE:
                self._maybe_fire_preemptions()
                self._maybe_fire_driver_faults()
            if self._adopting:
                rc = self._poll_adopted()
                if rc is not None:
                    return rc
                continue
            if self._adopt_drain_pids is not None:
                # Post-adoption drain: wait for SIGTERMed survivors to
                # persist their commits and exit before the replacement
                # generation is spawned over their snapshots.
                alive = {
                    p for p in self._adopt_drain_pids if self._pid_alive(p)
                }
                if alive and time.monotonic() <= self._adopt_drain_deadline:
                    self._adopt_drain_pids = alive
                    continue
                self._adopt_drain_pids = None
                self._restart_pending = True
            if self._restart_pending and not self._removing:
                # Respawn-mode restart: the old generation has fully
                # drained; re-form even if no other event fires.
                self._restart_pending = False
                changed = True
            for wid in list(self._workers):
                # A respawn-mode restart earlier in this sweep drains the
                # dict mid-iteration; drained entries are reaped by the
                # _removing pool instead.
                w = self._workers.get(wid)
                if w is None:
                    continue
                rc = w.proc.poll()
                if rc is None or w.done:
                    continue
                if rc == 0:
                    w.done = True
                    # A clean exit means the training function returned —
                    # the job is completing; stop re-forming the world.
                    self._finishing = True
                    self._log(f"{wid} finished")
                else:
                    requested_respawn = (
                        rc == REJOIN_EXIT_CODE
                        and self._rejoin_mode == "respawn"
                    )
                    if requested_respawn:
                        # Worker-requested respawn (no in-process rejoin
                        # support): not a failure, no blacklist count.
                        # Only honored in respawn mode — the elastic
                        # runtime never emits 79 in-process, so there an
                        # exit 79 is a user program's own status and must
                        # count as a failure (not loop forever).
                        if _metrics.ACTIVE:
                            _metrics.TAP.inc(
                                "hvd_elastic_respawn_requests_total"
                            )
                        self._log(f"{wid} exited requesting respawn")
                    else:
                        count = self._record_failure(w.host)
                        self._log(
                            f"{wid} failed with exit code {rc} "
                            f"(host failures: {count})"
                        )
                        self._trace_event(
                            "hvd_worker_failure", worker=wid, rc=rc,
                            host_failures=count,
                        )
                    if self._finishing:
                        # A straggler crashing while the job winds down is
                        # a real failure — there is no world left to
                        # re-form it into.
                        return 1
                    if (not requested_respawn
                            and self._failures.get(w.host, 0)
                            >= self._failure_threshold):
                        self._blacklist_host(w.host)
                    del self._workers[wid]
                    for f in w.outfiles:
                        f.close()
                    self._current_ids = [
                        i for i in self._current_ids if i != wid
                    ]
                    changed = True
                    if self._rejoin_mode == "respawn":
                        # Any exit dooms the whole generation: peers
                        # cannot re-form in-process, so they will either
                        # persist-and-79 on their own or must be drained.
                        # Batch the restart — draining everyone before
                        # publishing keeps respawned workers from
                        # blocking on transient generations that half the
                        # world never joins.
                        self._drain_world_for_restart()
            if self._finishing:
                if all(w.done for w in self._workers.values()):
                    return 0
                continue
            now = time.monotonic()
            if self._script and now - last_discovery >= self._interval:
                last_discovery = now
                changed = True  # _reconcile no-ops when membership matches
            # Worker-initiated rejoin: a surviving worker abandoned the
            # CURRENT generation (rollback without any process dying —
            # stall shutdown, transient control-plane error). Bump the
            # generation even though membership is unchanged; signals for
            # older generations are stale.
            force = any(
                k.startswith("rejoin.") and v.decode() == str(self._gen)
                for k, v in self._kv.snapshot("elastic").items()
            )
            if force:
                self._log(
                    f"worker abandoned generation {self._gen}; re-forming"
                )
            if (changed or force) and not self._reconcile(force=force):
                return 1
