"""Worker allocation and process fan-out.

Role parity with the reference's Gloo launcher (``run/gloo_run.py``): slot
allocation over hosts → SlotInfo{rank, local_rank, cross_rank, ...}; spawn
each rank (locally or over ssh) with the full ``HOROVOD_*`` env; kill the
remaining ranks when one fails; forward SIGINT/SIGTERM.

TPU-native additions: every rank also receives the JAX distributed
coordinator address (``HOROVOD_JAX_COORDINATOR``) so the eager data plane
can stand up the global device mesh, and ``--tpu-pod`` mode derives the
allocation from TPU slice metadata env (one process per host) instead of
``-H`` host lists.
"""

from __future__ import annotations

import os
import socket
import sys
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from . import safe_shell_exec

LOCAL_HOST_NAMES = ("localhost", "127.0.0.1")


@dataclass
class SlotInfo:
    hostname: str
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int


def parse_hosts(hosts: str) -> List[Tuple[str, int]]:
    """Parse ``host1:4,host2:4`` (reference ``-H`` format)."""
    out = []
    for part in hosts.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            name, slots = part.rsplit(":", 1)
            out.append((name, int(slots)))
        else:
            out.append((part, 1))
    return out


def parse_hostfile(path: str) -> List[Tuple[str, int]]:
    """Parse hostfile lines ``hostname slots=N`` (reference format)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            fields = line.split()
            slots = 1
            for fld in fields[1:]:
                if fld.startswith("slots="):
                    slots = int(fld.split("=", 1)[1])
            out.append((fields[0], slots))
    return out


def allocate(hosts: Sequence[Tuple[str, int]], np_: int) -> List[SlotInfo]:
    """Fill hosts in order (reference _allocate): ranks get consecutive
    local_ranks per host; cross_rank = index of the host among hosts that
    have a worker at that local_rank."""
    slots: List[Tuple[str, int]] = []  # (host, local_rank)
    host_counts: Dict[str, int] = {}
    for host, capacity in hosts:
        for _ in range(capacity):
            if len(slots) >= np_:
                break
            slots.append((host, host_counts.get(host, 0)))
            host_counts[host] = host_counts.get(host, 0) + 1
    if len(slots) < np_:
        total = sum(c for _, c in hosts)
        raise ValueError(
            f"Requested {np_} processes but hosts supply only {total} slots"
        )
    local_sizes: Dict[str, int] = {}
    for host, _ in slots:
        local_sizes[host] = local_sizes.get(host, 0) + 1
    # cross structure: ranks with the same local_rank across hosts
    cross_groups: Dict[int, List[int]] = {}
    infos: List[SlotInfo] = []
    for rank, (host, local_rank) in enumerate(slots):
        cross_groups.setdefault(local_rank, []).append(rank)
    for rank, (host, local_rank) in enumerate(slots):
        group = cross_groups[local_rank]
        infos.append(
            SlotInfo(
                hostname=host,
                rank=rank,
                size=np_,
                local_rank=local_rank,
                local_size=local_sizes[host],
                cross_rank=group.index(rank),
                cross_size=len(group),
            )
        )
    return infos


def tpu_pod_allocation() -> Optional[List[SlotInfo]]:
    """Derive allocation from TPU slice metadata env (one process per host):
    TPU_WORKER_HOSTNAMES + TPU_WORKER_ID, as set by TPU VM runtimes. This
    replaces ssh/MPI rendezvous on pods (BASELINE north star)."""
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES")
    if not hostnames:
        return None
    hosts = [h.strip() for h in hostnames.split(",") if h.strip()]
    n = len(hosts)
    return [
        SlotInfo(hostname=h, rank=i, size=n, local_rank=0, local_size=1,
                 cross_rank=i, cross_size=n)
        for i, h in enumerate(hosts)
    ]


def ssh_base_cmd(host, ssh_port=None, batch=False, connect_timeout=None):
    """The one ssh invocation prefix (options + host) shared by the
    pre-flight probe and the rank fan-out, so a connectivity option added
    for one cannot silently diverge from the other."""
    cmd = ["ssh", "-o", "StrictHostKeyChecking=no"]
    if batch:
        cmd += ["-o", "BatchMode=yes"]
    if connect_timeout:
        cmd += ["-o", f"ConnectTimeout={int(connect_timeout)}"]
    if ssh_port:
        cmd += ["-p", str(ssh_port)]
    return cmd + [host]


def check_hosts_reachable(hostnames, ssh_port=None, timeout=8.0,
                          cache=None):
    """Fail-fast SSH pre-flight (reference ``run/run.py:62-115`` +
    ``run/util/cache.py``): every remote host must answer a BatchMode
    ``ssh <host> true`` before any rank is launched, so a dead host
    produces one clear per-host message instead of a start-timeout
    minutes later. Successful probes are cached on disk with a TTL;
    failures are always re-probed (a fixed host must not stay "down"
    for the cache lifetime).
    """
    import concurrent.futures
    import subprocess

    remote = [h for h in dict.fromkeys(hostnames) if not _is_local(h)]
    if not remote:
        return

    def probe(host):
        key = f"ssh:{host}:{ssh_port or 22}"
        if cache is not None and cache.get(key):
            return host, True
        cmd = ssh_base_cmd(
            host, ssh_port, batch=True, connect_timeout=timeout
        ) + ["true"]
        try:
            ok = subprocess.run(
                cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                timeout=timeout + 4,
            ).returncode == 0
        except Exception:  # noqa: BLE001 - unreachable is unreachable
            ok = False
        return host, ok

    with concurrent.futures.ThreadPoolExecutor(
        max_workers=min(len(remote), 32)
    ) as pool:
        results = list(pool.map(probe, remote))
    if cache is not None:
        # One batched write after the pool joins: concurrent per-host
        # puts would overwrite each other's entries.
        fresh = {
            f"ssh:{h}:{ssh_port or 22}": True for h, ok in results if ok
        }
        if fresh:
            cache.put_many(fresh)
    unreachable = sorted(h for h, ok in results if not ok)
    if unreachable:
        err = RuntimeError(
            "hvdrun: unable to connect over ssh to: "
            + ", ".join(unreachable)
            + ". Verify the host names in -H/--hostfile are reachable and "
            "passwordless ssh (BatchMode) is configured."
        )
        # The elastic path launches with the reachable subset and lets
        # the driver blacklist/retry the rest.
        err.failed_hosts = unreachable
        raise err


def build_remote_command(
    host: str,
    rank_env: Dict[str, str],
    command: List[str],
    ssh_port: Optional[int] = None,
) -> List[str]:
    """The one ssh fan-out command builder (reference get_remote_command):
    env must be inlined since ssh doesn't forward it. Shared by the fixed
    launcher and the elastic driver so the env-prefix filter cannot
    silently diverge between them."""
    env_str = " ".join(
        f"{k}={_shquote(v)}"
        for k, v in rank_env.items()
        if k.startswith(("HOROVOD_", "JAX_", "XLA_", "PATH",
                         "PYTHONPATH", "LD_LIBRARY"))
    )
    return ssh_base_cmd(host, ssh_port) + [
        f"cd {_shquote(os.getcwd())} > /dev/null 2>&1 ; "
        f"{env_str} " + " ".join(_shquote(c) for c in command),
    ]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _is_local(host: str) -> bool:
    return host in LOCAL_HOST_NAMES or host == socket.gethostname()


def build_rank_env(
    slot: SlotInfo,
    base_env: Dict[str, str],
    controller_addr: str,
    controller_port: int,
    jax_coordinator: str,
) -> Dict[str, str]:
    env = dict(base_env)
    env.update(
        {
            "HOROVOD_RANK": str(slot.rank),
            "HOROVOD_SIZE": str(slot.size),
            "HOROVOD_LOCAL_RANK": str(slot.local_rank),
            "HOROVOD_LOCAL_SIZE": str(slot.local_size),
            "HOROVOD_CROSS_RANK": str(slot.cross_rank),
            "HOROVOD_CROSS_SIZE": str(slot.cross_size),
            "HOROVOD_CONTROLLER_ADDR": controller_addr,
            "HOROVOD_CONTROLLER_PORT": str(controller_port),
            "HOROVOD_JAX_COORDINATOR": jax_coordinator,
        }
    )
    return env


def launch_job(
    command: List[str],
    slots: List[SlotInfo],
    env: Optional[Dict[str, str]] = None,
    ssh_port: Optional[int] = None,
    output_dir: Optional[str] = None,
    verbose: bool = False,
) -> int:
    """Spawn every rank; return the first nonzero exit code (0 if all ok).
    On any failure the remaining ranks are terminated (reference gloo_run
    fan-out kill)."""
    base_env = dict(env if env is not None else os.environ)
    controller_addr = (
        slots[0].hostname if not _is_local(slots[0].hostname) else "127.0.0.1"
    )
    if base_env.get("HOROVOD_PROBED_CONTROLLER_ADDR"):
        # Ring-probe result for a *remote* rank 0 (run.py NIC discovery).
        # Deliberately a different variable from the per-rank
        # HOROVOD_CONTROLLER_ADDR export below: ranks inherit that one, and
        # a nested launch must not dial the parent job's controller.
        controller_addr = base_env.pop("HOROVOD_PROBED_CONTROLLER_ADDR")
    elif _is_local(slots[0].hostname):
        # HOROVOD_IFACE (explicit flag or ring-probe result, reference
        # NCCL_SOCKET_IFNAME/gloo-iface role): bind the control plane to
        # the first routable interface's address, not the hostname default.
        iface = base_env.get("HOROVOD_IFACE", "").split(",")[0]
        if iface:
            from . import network as _network

            try:
                addr = _network.interface_address(iface)
            except Exception:
                addr = None  # enumeration unavailable; keep hostname default
            if addr:
                controller_addr = addr
    controller_port = _free_port()
    jax_coordinator = f"{controller_addr}:{_free_port()}"

    procs: List[Tuple[SlotInfo, safe_shell_exec.ManagedProcess]] = []
    outfiles = []
    for slot in slots:
        rank_env = build_rank_env(
            slot, base_env, controller_addr, controller_port, jax_coordinator
        )
        if _is_local(slot.hostname):
            cmd = command
        else:
            cmd = build_remote_command(
                slot.hostname, rank_env, command, ssh_port
            )
        stdout = stderr = None
        if output_dir:
            os.makedirs(output_dir, exist_ok=True)
            stdout = open(os.path.join(output_dir, f"rank.{slot.rank}.out"), "wb")
            stderr = open(os.path.join(output_dir, f"rank.{slot.rank}.err"), "wb")
            outfiles += [stdout, stderr]
        if verbose:
            print(f"[hvdrun] rank {slot.rank} on {slot.hostname}: {cmd}")
        procs.append(
            (slot, safe_shell_exec.ManagedProcess(cmd, env=rank_env,
                                                  stdout=stdout, stderr=stderr))
        )

    exit_code = 0
    try:
        done = set()
        while len(done) < len(procs):
            for slot, mp in procs:
                if slot.rank in done:
                    continue
                rc = mp.poll()
                if rc is not None:
                    done.add(slot.rank)
                    if rc != 0 and exit_code == 0:
                        exit_code = rc
                        print(
                            f"[hvdrun] rank {slot.rank} failed with exit code "
                            f"{rc}; terminating remaining ranks",
                            file=sys.stderr,
                        )
                        for s2, m2 in procs:
                            if s2.rank not in done:
                                m2.terminate()
            time.sleep(0.05)
    except KeyboardInterrupt:
        for _, mp in procs:
            mp.terminate()
        exit_code = 130
    finally:
        for f in outfiles:
            f.close()
    return exit_code


def _shquote(s: str) -> str:
    import shlex

    return shlex.quote(s)
