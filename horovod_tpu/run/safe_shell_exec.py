"""Process spawning with whole-tree cleanup.

Role parity with the reference's ``run/common/util/safe_shell_exec.py``
(middleman process group, graceful terminate then kill): each worker runs in
its own process group; terminate() SIGTERMs the group, escalating to
SIGKILL after a grace period.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import IO, Dict, List, Optional

GRACEFUL_TERMINATION_TIME_S = 5


class ManagedProcess:
    def __init__(
        self,
        command: List[str] | str,
        env: Optional[Dict[str, str]] = None,
        stdout: Optional[IO] = None,
        stderr: Optional[IO] = None,
        shell: bool = False,
    ):
        self.proc = subprocess.Popen(
            command,
            env=env,
            stdout=stdout if stdout is not None else None,
            stderr=stderr if stderr is not None else None,
            shell=shell,
            start_new_session=True,  # own process group for tree-kill
        )

    @property
    def pid(self) -> int:
        return self.proc.pid

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def wait(self, timeout: Optional[float] = None) -> int:
        return self.proc.wait(timeout=timeout)

    def terminate(self) -> None:
        """SIGTERM the process group; SIGKILL after the grace period."""
        try:
            pgid = os.getpgid(self.proc.pid)
        except ProcessLookupError:
            return
        try:
            os.killpg(pgid, signal.SIGTERM)
        except ProcessLookupError:
            return
        deadline = time.monotonic() + GRACEFUL_TERMINATION_TIME_S
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                return
            time.sleep(0.1)
        try:
            os.killpg(pgid, signal.SIGKILL)
        except ProcessLookupError:
            pass


def execute(
    command: List[str] | str,
    env: Optional[Dict[str, str]] = None,
    stdout: Optional[IO] = None,
    stderr: Optional[IO] = None,
    shell: bool = False,
) -> int:
    """Run a command to completion, forwarding SIGINT/SIGTERM to its tree."""
    mp = ManagedProcess(command, env=env, stdout=stdout, stderr=stderr,
                        shell=shell)
    forwarded = []

    def handler(signum, frame):
        forwarded.append(signum)
        mp.terminate()

    old_int = signal.signal(signal.SIGINT, handler)
    old_term = signal.signal(signal.SIGTERM, handler)
    try:
        return mp.wait()
    finally:
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGTERM, old_term)
